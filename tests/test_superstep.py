"""K-step super-step decode tests (8-device CPU mesh via conftest).

The BatchEngine's hot path is the batched device loop
(runtime/device_loop.py make_batched_decode_loop): forward + sampling scan K
steps on device, one host sync per K tokens. Load-bearing properties:

- greedy token PARITY with the sequential Engine.generate loop (bit-exact);
- the dispatch counter drops from ~1/token to ~1/K tokens;
- host-side EOS/stop detection on the returned block with free rollback of
  over-decoded rows (masked slots, position rewind only);
- cancellation and mixed prefill+decode correctness;
- the on-device xorshift* mirrors the host Sampler's RNG bit-for-bit, so
  stochastic decode is one stream whether sampled host- or device-side.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.device_loop import (xorshift_coin,
                                                       xorshift_star_step)
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.sampler import Sampler, _random_u32


def _spec(seq_len=128):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=256, seq_len=seq_len,
                     rope_type=RopeType.LLAMA).resolved()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


@pytest.fixture(scope="module")
def setup():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    eng = Engine(spec, params, tp=2)
    be = BatchEngine(spec, params, slots=2, tp=2, superstep=4)
    yield spec, params, eng, be
    be.close()


# ------------------------------------------------------------- device RNG


def test_device_xorshift_matches_host_sampler_rng():
    """The split-uint32 xorshift* must be bit-exact with sampler._random_u32
    (state evolution AND the high-32 multiply output), so sampler.state can
    round-trip host -> device loop -> host."""
    rs = np.random.RandomState(7)
    states = rs.randint(1, 2**63, size=32, dtype=np.uint64)
    hi = jnp.asarray((states >> np.uint64(32)).astype(np.uint32))
    lo = jnp.asarray((states & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    for _ in range(8):
        hi, lo, out = xorshift_star_step(hi, lo)
        host = [_random_u32(s) for s in states]
        states = np.array([h[0] for h in host], np.uint64)
        outs = np.array([h[1] for h in host], np.uint32)
        got = ((np.asarray(hi).astype(np.uint64) << np.uint64(32))
               | np.asarray(lo).astype(np.uint64))
        assert (got == states).all()
        assert (np.asarray(out) == outs).all()


def test_device_coin_matches_host_coin():
    seed = 987654321
    s = Sampler(16, temperature=1.0, seed=seed)
    want = s._coin()
    _, _, coin = xorshift_coin(jnp.uint32(seed >> 32),
                               jnp.uint32(seed & 0xFFFFFFFF))
    assert np.float32(want) == np.asarray(coin)


# ------------------------------------------------------------- greedy parity


def test_superstep_greedy_parity_with_idle_slot(setup):
    """Single request (second slot rides idle/parked through every scan) with
    K>1 and max_tokens NOT a multiple of K must emit exactly the sequential
    Engine.generate tokens."""
    spec, params, eng, be = setup
    prompt = [1, 7, 23, 5]
    eng.reset()
    want, _ = eng.generate(list(prompt), 11, _greedy(spec))

    req = be.submit(list(prompt), 11, _greedy(spec))
    assert req.wait(timeout=120) == want
    assert req.finish == "length"
    assert req.stats.generated_tokens == 11


def test_superstep_dispatch_counter_one_sync_per_k(setup):
    """Host syncs per decoded token must drop from 1 to ~1/K: n tokens of
    steady-state decode may cost at most ceil(n/K) fused dispatches plus the
    host-sampled boundary token."""
    spec, params, eng, be = setup
    n, k = 25, be.superstep
    base = be.decode_steps
    sbase = be.super_steps
    out = be.submit([1, 3, 5], n, _greedy(spec)).wait(timeout=120)
    assert len(out) == n
    steps = be.decode_steps - base
    # token 1 comes from prefill logits (host-sampled); the remaining n-1
    # ride K-step dispatches
    assert steps <= -(-(n - 1) // k) + 1, (steps, n, k)
    assert be.super_steps - sbase >= (n - 1) // k


# ---------------------------------------------------- rollback / cancellation


def test_mid_superstep_stop_rolls_back(setup):
    """A stop firing mid-block must truncate the output at the stop token and
    rewind the row's position to the verified frontier — the over-decoded
    tail must not leak into the output OR corrupt the slot for prefix reuse."""
    spec, params, eng, be = setup
    prompt = [1, 2, 3]
    full = be.submit(list(prompt), 12, _greedy(spec)).wait(timeout=120)
    stop_at = full[5]  # deep enough to land mid-super-step (K=4)

    req = be.submit(list(prompt), 12, _greedy(spec),
                    stop_check=lambda t: t == stop_at)
    out = req.wait(timeout=120)
    assert out == full[:6]
    assert req.finish == "stop"

    # the slot's history/pos must be consistent after rollback: the same
    # prompt again reuses the prefix and still reproduces the full output
    pre = be.prefilled_tokens
    again = be.submit(list(prompt), 12, _greedy(spec)).wait(timeout=120)
    assert again == full
    assert be.prefilled_tokens - pre <= 1


def test_cancel_during_superstep_block(setup):
    """cancel() observed mid-block delivery stops the stream at the next
    token boundary, discards the over-decoded tail, and frees the slot."""
    spec, params, eng, be = setup
    req_box = []

    def on_token(_t):
        if len(req_box[0].out) == 2:
            req_box[0].cancel()

    req = be.submit([1, 8, 2], 20, _greedy(spec), on_token=on_token)
    req_box.append(req)
    out = req.wait(timeout=120)
    assert req.finish == "cancelled"
    # delivery stops at the token boundary after the cancel flag is seen
    assert len(out) == 2
    # the engine keeps serving after a cancellation
    ok = be.submit([1, 8, 2], 4, _greedy(spec)).wait(timeout=120)
    assert len(ok) == 4


# ------------------------------------------------------ mixed prefill+decode


def test_mixed_prefill_does_not_stall_decode(setup):
    """A request admitted while another decodes must prefill in MIXED steps
    (decode rows riding the prefill dispatch) and both must still emit the
    sequential engine's exact tokens."""
    spec, params, eng, be = setup
    p1 = [1, 7, 23, 5]
    p2 = [1, 9, 2, 40, 41, 42, 43, 44, 45, 46, 47, 48]  # long enough to chunk
    wants = []
    for p in (p1, p2):
        eng.reset()
        out, _ = eng.generate(list(p), 12, _greedy(spec))
        wants.append(out)

    slow_path = []

    def slow_token(_t):
        # keep request 1 decoding long enough for request 2's admission to
        # land mid-generation
        import time
        time.sleep(0.01)
        slow_path.append(_t)

    base_mixed = be.mixed_steps
    r1 = be.submit(list(p1), 12, _greedy(spec), on_token=slow_token)
    import time
    time.sleep(0.05)  # let r1 enter decode before r2 arrives
    r2 = be.submit(list(p2), 12, _greedy(spec))
    assert r1.wait(timeout=120) == wants[0]
    assert r2.wait(timeout=120) == wants[1]
    assert be.mixed_steps > base_mixed, "prefill never rode with decode"


# ------------------------------------------------------------- stochastic


def test_superstep_stochastic_matches_host_sampling():
    """Device-side sampling (xorshift* coins + device_sample_coin) must
    reproduce the host-sampled K=1 scheduler stream token-for-token on the
    f32 CPU mesh, across temperature/top-p regimes, and leave sampler.state
    advanced identically."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    for temp, topp in ((0.8, 0.9), (0.8, 1.0), (1.3, 0.5)):
        outs, states = {}, {}
        for k in (1, 4):
            be = BatchEngine(spec, params, slots=2, tp=2, superstep=k)
            try:
                s = Sampler(spec.vocab_size, temperature=temp, topp=topp,
                            seed=777)
                outs[k] = be.submit([1, 7, 23], 12, s).wait(timeout=120)
                states[k] = int(s.state)
            finally:
                be.close()
        assert outs[1] == outs[4], (temp, topp, outs)
        assert states[1] == states[4], (temp, topp, states)


def test_sampler_state_resync_after_mid_block_stop():
    """A stop mid-block discards the tail, and the discarded tokens' coins
    must NOT advance the caller's sampler: a sampler reused for a second
    request must see one unbroken xorshift* stream, identical between the
    K=1 host-sampled path and the K-step device path."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    results = {}
    for k in (1, 4):
        be = BatchEngine(spec, params, slots=2, tp=2, superstep=k)
        try:
            smp = Sampler(spec.vocab_size, temperature=0.9, topp=0.9, seed=99)
            first = be.submit([1, 7, 23], 16, smp,
                              stop_check=lambda t, seen=[]: (
                                  seen.append(t) or len(seen) >= 6)).wait(120)
            second = be.submit([1, 5, 2], 8, smp).wait(timeout=120)
            results[k] = (first, second, int(smp.state))
        finally:
            be.close()
    assert results[1] == results[4], results


def test_superstep_mixed_greedy_and_stochastic_rows(setup):
    """One greedy and one stochastic request sharing super-steps: the greedy
    row must still be bit-exact with the sequential engine (its lane must not
    consume coins or drift), and the stochastic row must emit valid ids."""
    spec, params, eng, be = setup
    prompt = [1, 7, 23, 5]
    eng.reset()
    want, _ = eng.generate(list(prompt), 10, _greedy(spec))

    g = be.submit(list(prompt), 10, _greedy(spec))
    s = be.submit([1, 9, 2], 10,
                  Sampler(spec.vocab_size, temperature=0.9, topp=0.9, seed=5))
    assert g.wait(timeout=120) == want
    st = s.wait(timeout=120)
    assert len(st) == 10 and all(0 <= t < spec.vocab_size for t in st)


# ------------------------------------------------------------- context end


def test_superstep_budget_clamps_at_context_end():
    """Rows within K of seq_len park mid-scan (budget) and finish 'length'
    without corrupting the cache bounds."""
    spec = _spec(seq_len=16)
    params = init_random_params(spec, FloatType.Q40, seed=3)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=8)
    try:
        req = be.submit([1, 2, 3, 4], 100, _greedy(spec))
        out = req.wait(timeout=120)
        assert req.finish == "length"
        assert 0 < len(out) <= 16
        for slot in be._slots:
            assert slot.pos <= spec.seq_len
            assert len(slot.history) <= spec.seq_len
    finally:
        be.close()
