"""Fused rmsnorm+quantize prologue kernels (ops/pallas_prologue.py), interpret mode.

The prologue collapses the XLA-side rmsnorm + Q80 activation quantization into one
kernel per activation row and feeds the inline-Xexp matvec variants. Its numerics
must match the existing XLA prologue (pallas_q8._quantize_row) bit-for-bit on the
quantize step and the kernel-path forward to float tolerance end-to-end."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import (init_random_params,
                                                 prepare_for_pallas)
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.kernels import rmsnorm
from distributed_llama_tpu.ops.pallas_prologue import (quantize_q80_row,
                                                       rmsnorm_quantize_q80)
from distributed_llama_tpu.ops.pallas_q8 import _quantize_row
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import QK, FloatType


def test_quantize_kernel_matches_xla_quantize():
    rng = np.random.RandomState(0)
    k = 256
    x = jnp.asarray(rng.randn(k).astype(np.float32)) * 3.0
    xq_want, sx_want = _quantize_row(x, k // QK)
    xq, sx = quantize_q80_row(x, interpret=True)
    np.testing.assert_array_equal(np.asarray(xq).ravel(), np.asarray(xq_want))
    np.testing.assert_allclose(np.asarray(sx), np.asarray(sx_want), rtol=1e-7)


def test_quantize_kernel_zero_block():
    """An all-zero block must produce scale 0 and zeros, not NaN (the
    zero-guarded inverse)."""
    x = jnp.zeros((64,), jnp.float32).at[40].set(5.0)
    xq, sx = quantize_q80_row(x, interpret=True)
    assert np.asarray(sx)[0, 0] == 0.0
    assert not np.isnan(np.asarray(sx)).any()
    np.testing.assert_array_equal(np.asarray(xq)[0, :32], 0)


def test_rmsnorm_quantize_matches_separate_ops():
    rng = np.random.RandomState(1)
    k = 512
    x = jnp.asarray(rng.randn(1, 1, k).astype(np.float32))
    w = jnp.asarray(1.0 + 0.1 * rng.randn(k).astype(np.float32))
    eps = 1e-5
    xb = rmsnorm(x, w, eps).reshape(k)
    xq_want, sx_want = _quantize_row(xb, k // QK)
    xq, sx = rmsnorm_quantize_q80(x, w, eps, interpret=True)
    # the kernel normalizes in f32 exactly like ops.kernels.rmsnorm on f32 input
    np.testing.assert_array_equal(np.asarray(xq).ravel(), np.asarray(xq_want))
    np.testing.assert_allclose(np.asarray(sx), np.asarray(sx_want), rtol=1e-6)


def _spec():
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=16,
                     rope_type=RopeType.LLAMA).resolved()


@pytest.mark.parametrize("fuse", [True, False])
def test_forward_prologue_matches_kernel_path(fuse):
    """Decode through the prologue kernels == the plain kernel path (same Q80
    quantization points, so agreement is float-tolerance, not Q80-scale)."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=7)
    rope = RopeTables.create(spec)
    pp = prepare_for_pallas(params, spec=spec, fuse=fuse)

    tok = jnp.asarray([[5]])
    kc, vc = init_kv_cache(spec)
    want, _, _ = forward(pp, spec, rope, tok, kc, vc, jnp.int32(0),
                         use_pallas=True)
    kc, vc = init_kv_cache(spec)
    got, kcp, vcp = forward(pp, spec, rope, tok, kc, vc, jnp.int32(0),
                            use_pallas=True, fused_prologue=True)
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 2e-5, rel


def test_prologue_sharded_decode_matches():
    """tp=2 shard_map decode with the prologue == planar sharded step (Q80
    activation-quantization error scale)."""
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                                   make_sharded_forward,
                                                   shard_params)

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    mesh = make_mesh(tp=2)
    tok = jnp.asarray([[5]])
    rope = RopeTables.create(spec)

    base = shard_params(params, mesh, spec)
    step = make_sharded_forward(spec, mesh, base, donate_cache=False)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    want, _, _ = step(base, rope, tok, kc, vc, jnp.int32(0))

    pp = shard_params(prepare_for_pallas(params, tp=2, spec=spec), mesh, spec)
    stepp = make_sharded_forward(spec, mesh, pp, use_pallas=True,
                                 donate_cache=False, fused_prologue=True)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, _, _ = stepp(pp, rope, tok, kc, vc, jnp.int32(0))
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.03, rel


def test_prologue_engine_generation_matches():
    """End-to-end greedy generation with the prologue engine == without (the
    prologue changes where quantization happens, not its values — greedy tokens
    must be identical)."""
    from distributed_llama_tpu.runtime.engine import Engine
    from distributed_llama_tpu.runtime.sampler import Sampler

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=13)
    base = Engine(spec, params, tp=1, use_pallas=True)
    want, _ = base.generate([1, 7, 3], 8, Sampler(spec.vocab_size, temperature=0.0))

    eng = Engine(spec, params, tp=1, use_pallas=True, fused_prologue=True)
    got, _ = eng.generate([1, 7, 3], 8, Sampler(spec.vocab_size, temperature=0.0))
    assert got == want


def test_q8_inline_matvec_matches_xexp_variant():
    """The new i8 inline-Xexp matvec (scratch scatter) must reproduce the
    Xexp-materializing variant exactly — same int8 dot, same epilogue."""
    import jax

    from distributed_llama_tpu.ops.pallas_q8 import (_q8_matvec,
                                                     _q8_matvec_inline,
                                                     block_diag_scatter)

    rng = np.random.RandomState(3)
    n, k = 48, 256
    nb = k // QK
    xq = jnp.asarray(rng.randint(-127, 128, (1, k)).astype(np.int8))
    sx = jnp.asarray(rng.rand(1, nb).astype(np.float32) * 0.01)
    w8 = jnp.asarray(rng.randint(-8, 8, (n, k)).astype(np.int8))
    scales = jnp.asarray(rng.rand(n, nb).astype(np.float32) * 0.01)

    xexp = block_diag_scatter(xq.reshape(k), nb)
    want = _q8_matvec(xexp, sx, w8, scales, interpret=True)
    got = _q8_matvec_inline(xq, sx, w8, scales, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_prologue_i8_layout_forward_matches():
    """Prologue decode over i8-layout weights (Q80 file type) == plain kernel
    path — exercises qmatmul_q80's i8 inline route end-to-end."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q80, seed=29)
    rope = RopeTables.create(spec)
    pp = prepare_for_pallas(params, spec=spec)
    assert pp["blocks"]["wqkv"].layout == "i8"

    tok = jnp.asarray([[5]])
    kc, vc = init_kv_cache(spec)
    want, _, _ = forward(pp, spec, rope, tok, kc, vc, jnp.int32(0),
                         use_pallas=True)
    kc, vc = init_kv_cache(spec)
    got, _, _ = forward(pp, spec, rope, tok, kc, vc, jnp.int32(0),
                        use_pallas=True, fused_prologue=True)
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 2e-5, rel
