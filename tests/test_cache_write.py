"""Deferred cache-write equivalence: cache_write="deferred" must reproduce the
in-scan discipline's logits and final caches.

The deferred path keeps the KV caches loop-invariant inside the layer scan (reads
committed rows + current-chunk k/v via explicit key positions) and commits all
layers' new rows with one top-level write per cache (models/forward.py). Layer 0's
cache rows are bit-identical across modes; everything downstream of one attention
(later layers' k/v, logits) differs only by float reassociation (the key axis is
[window ++ chunk] instead of in-place), so those compare at ulp-scale tolerance.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import FloatType


def _spec(arch=ArchType.LLAMA, **kw):
    base = dict(arch_type=arch, dim=64, hidden_dim=96, n_layers=3, n_heads=4,
                n_kv_heads=2, vocab_size=128, seq_len=64, rope_type=RopeType.LLAMA)
    base.update(kw)
    return ModelSpec(**base).resolved()


def _run(spec, params, rope, tokens, pos, cache_write, kc, vc, window=None):
    return forward(params, spec, rope, tokens, kc, vc, pos,
                   attn_window=window, cache_write=cache_write)


@pytest.mark.parametrize("window", [None, 16])
def test_deferred_matches_inscan_prefill_and_decode(window):
    spec = _spec()
    params = init_random_params(spec, FloatType.F32, seed=11)
    rope = RopeTables.create(spec)
    prompt = jnp.asarray([[3, 9, 27, 81, 7]])

    kc0, vc0 = init_kv_cache(spec)
    li, kci, vci = _run(spec, params, rope, prompt, jnp.int32(0), "inscan",
                        kc0, vc0, window)
    kc0, vc0 = init_kv_cache(spec)
    ld, kcd, vcd = _run(spec, params, rope, prompt, jnp.int32(0), "deferred",
                        kc0, vc0, window)
    np.testing.assert_allclose(np.asarray(ld), np.asarray(li), atol=1e-5, rtol=1e-5)
    # cache rows: layer 0's are bit-identical; later layers' k/v projections see the
    # reassociated attention output of earlier layers, so ulp-level drift is expected
    np.testing.assert_array_equal(np.asarray(kcd)[0], np.asarray(kci)[0])
    np.testing.assert_allclose(np.asarray(kcd), np.asarray(kci), atol=1e-6, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(vcd), np.asarray(vci), atol=1e-6, rtol=1e-4)

    # decode continuation from the deferred-produced cache, both disciplines
    tok = jnp.asarray([[42]])
    li2, _, _ = _run(spec, params, rope, tok, jnp.int32(5), "inscan", kci, vci, window)
    ld2, _, _ = _run(spec, params, rope, tok, jnp.int32(5), "deferred", kcd, vcd,
                     window)
    np.testing.assert_allclose(np.asarray(ld2), np.asarray(li2), atol=1e-5, rtol=1e-5)
    assert np.argmax(np.asarray(ld2)) == np.argmax(np.asarray(li2))


def test_deferred_pallas_decode_matches_inscan():
    """The PRODUCTION TPU decode glue — deferred + use_pallas + t=1 routes through
    the fused decode-attention kernel (interpret off-TPU) — must match the inscan
    XLA path at reassociation tolerance. Pins the q.reshape head grouping, k_t[0]
    shapes, window wiring, and dtype casts of the integrated branch."""
    spec = _spec(dim=64, hidden_dim=96)
    params = init_random_params(spec, FloatType.Q40, seed=9)
    rope = RopeTables.create(spec)
    from distributed_llama_tpu.models.params import prepare_for_pallas

    pp = prepare_for_pallas(params)

    kc, vc = init_kv_cache(spec)
    _, kc, vc = forward(params, spec, rope, jnp.asarray([[1, 2, 3]]), kc, vc,
                        jnp.int32(0))
    tok = jnp.asarray([[7]])
    want, _, _ = forward(pp, spec, rope, tok, kc, vc, jnp.int32(3),
                         use_pallas=True, cache_write="inscan", attn_window=16)
    got, _, _ = forward(pp, spec, rope, tok, kc, vc, jnp.int32(3),
                        use_pallas=True, cache_write="deferred", attn_window=16)
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 1e-4, rel
    assert np.argmax(got, -1).tolist() == np.argmax(want, -1).tolist()


def test_deferred_matches_inscan_per_row_positions():
    """Continuous-batching shape: per-row start_pos, batch 2, rows at different
    offsets."""
    spec = _spec()
    params = init_random_params(spec, FloatType.F32, seed=5)
    rope = RopeTables.create(spec)

    # seed both rows' caches, then decode with the rows at DIFFERENT depths — the
    # per-row slot masking and the vmap'd per-row commit must each honor its own
    # offset (identical offsets would be indistinguishable from the scalar path)
    kc, vc = init_kv_cache(spec, batch=2)
    seed = jnp.asarray([[1, 2, 3, 11, 12], [4, 5, 6, 13, 14]])
    _, kc, vc = forward(params, spec, rope, seed, kc, vc, jnp.int32(0))
    pos = jnp.asarray([5, 2], jnp.int32)

    tok = jnp.asarray([[7], [8]])
    li, kci, vci = _run(spec, params, rope, tok, pos, "inscan", kc, vc)
    ld, kcd, vcd = _run(spec, params, rope, tok, pos, "deferred", kc, vc)
    np.testing.assert_allclose(np.asarray(ld), np.asarray(li), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(kcd), np.asarray(kci), atol=1e-6, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(vcd), np.asarray(vci), atol=1e-6, rtol=1e-4)


@pytest.mark.parametrize("arch,kw", [
    (ArchType.MIXTRAL, dict(n_experts=4, n_active_experts=2,
                            rope_type=RopeType.FALCON)),
    (ArchType.GROK1, dict(n_experts=4, n_active_experts=2,
                          rope_type=RopeType.FALCON)),
])
def test_deferred_matches_inscan_moe(arch, kw):
    spec = _spec(arch, **kw)
    params = init_random_params(spec, FloatType.F32, seed=2)
    rope = RopeTables.create(spec)
    prompt = jnp.asarray([[3, 9, 27]])
    kc0, vc0 = init_kv_cache(spec)
    li, kci, _ = _run(spec, params, rope, prompt, jnp.int32(0), "inscan", kc0, vc0)
    kc0, vc0 = init_kv_cache(spec)
    ld, kcd, _ = _run(spec, params, rope, prompt, jnp.int32(0), "deferred", kc0, vc0)
    np.testing.assert_allclose(np.asarray(ld), np.asarray(li), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(kcd), np.asarray(kci), atol=1e-6, rtol=1e-4)


def test_deferred_pallas_kv_replicated_mesh():
    """tp=8 > n_kv_heads=2 (the 405B-class GQA shape): deferred + use_pallas decode
    over the KV-replicated mesh must match the replicated single-device model."""
    from distributed_llama_tpu.models.params import prepare_for_pallas
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                                   make_sharded_forward, shard_params)

    spec = _spec(dim=256, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=2,
                 vocab_size=128, seq_len=32)
    params = init_random_params(spec, FloatType.Q40, seed=8)
    rope = RopeTables.create(spec)
    kc, vc = init_kv_cache(spec)
    _, kc, vc = forward(params, spec, rope, jnp.asarray([[1, 2]]), kc, vc,
                        jnp.int32(0))
    tok = jnp.asarray([[5]])
    want, _, _ = forward(params, spec, rope, tok, kc, vc, jnp.int32(2))

    mesh = make_mesh(tp=8)
    pp = shard_params(prepare_for_pallas(params, tp=8), mesh, spec)
    step = make_sharded_forward(spec, mesh, pp, donate_cache=False,
                                use_pallas=True, cache_write="deferred")
    kc8, vc8 = init_sharded_kv_cache(spec, mesh)
    _, kc8, vc8 = step(pp, rope, jnp.asarray([[1, 2]]), kc8, vc8, jnp.int32(0))
    got, _, _ = step(pp, rope, tok, kc8, vc8, jnp.int32(2))
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.03, rel  # Q80 activation-quantization error scale
    assert np.argmax(got, -1).tolist() == np.argmax(want, -1).tolist()


def test_deferred_sharded_step_matches_inscan():
    """tp=2 shard_map: the deferred step over the mesh must match the in-scan step."""
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                                   make_sharded_forward, shard_params)

    spec = _spec(dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
                 vocab_size=128, seq_len=32)
    params = init_random_params(spec, FloatType.Q40, seed=3)
    mesh = make_mesh(tp=2)
    rope = RopeTables.create(spec)
    tokens = jnp.asarray([[1, 2, 3]])
    base = shard_params(params, mesh, spec)

    outs = {}
    for mode in ("inscan", "deferred"):
        step = make_sharded_forward(spec, mesh, base, donate_cache=False,
                                    cache_write=mode)
        kc, vc = init_sharded_kv_cache(spec, mesh)
        logits, kc, vc = step(base, rope, tokens, kc, vc, jnp.int32(0))
        outs[mode] = (np.asarray(logits), np.asarray(kc), np.asarray(vc))
    np.testing.assert_allclose(outs["deferred"][0], outs["inscan"][0],
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(outs["deferred"][1], outs["inscan"][1],
                               atol=1e-6, rtol=1e-4)
    np.testing.assert_allclose(outs["deferred"][2], outs["inscan"][2],
                               atol=1e-6, rtol=1e-4)
