"""Tier-1 wiring for perf/spec_amortize.py (ISSUE 8 satellite, the
test_smoke_lint.py pattern): a (B, T) verify-block dispatch must stay
near-flat in T on the CPU mesh — the amortization that justifies the default
--speculative K and catches regressions where the verify program stops
streaming the weights once per block."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "perf"))

import spec_amortize  # noqa: E402


def test_verify_block_cost_near_flat():
    costs = spec_amortize.measure()
    t_lo, t_hi = spec_amortize.BLOCKS[0], spec_amortize.BLOCKS[-1]
    assert costs[t_lo] > 0 and costs[t_hi] > 0
    ratio = costs[t_hi] / costs[t_lo]
    # T=9 streams the weights once, like T=2: the cost may not scale with
    # the block length (GATE x leaves room for the tiny model's real extra
    # flops + CI-box noise; the measured ratio sits around 1.1-1.5x)
    assert ratio <= spec_amortize.GATE, (ratio, costs)
