"""Merged matvec groups (models/params.py fuse_matvec_groups): wq/wk/wv -> wqkv and
w1/w3 -> w13, row-concatenated with per-TP-group interleaving so plain row sharding
lands each shard its own [q|k|v] / [gate|up] block. One kernel launch per group
replaces one per tensor on the decode path (launch-overhead engineering; the
reference's task lists issue one matmul task per tensor, llama2-tasks.cpp:246-276).

The interleaving is the risky part: these tests pin (a) bit-exact round-trip of the
fused planar tensor against the members, (b) fused == unfused forward on the kernel
path, (c) fused == planar under a real tp=2 shard_map (wrong group order would
scramble heads on shard 1+ and fail loudly here)."""

import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import (fuse_matvec_groups,
                                                 init_random_params,
                                                 prepare_for_pallas)
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import FloatType


def _spec():
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=16,
                     rope_type=RopeType.LLAMA).resolved()


def test_fused_tensor_roundtrip_tp_groups():
    """Dequantized wqkv rows must be exactly the members' rows in TP-group
    interleaved order: [q_g0, k_g0, v_g0, q_g1, k_g1, v_g1] for tp=2."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=5)
    tp = 2
    fused = fuse_matvec_groups(params["blocks"], spec, tp)
    got = fused["wqkv"].to_numpy()

    q = params["blocks"]["wq"].to_numpy()
    k = params["blocks"]["wk"].to_numpy()
    v = params["blocks"]["wv"].to_numpy()
    rows = []
    for g in range(tp):
        for m in (q, k, v):
            r = m.shape[1] // tp
            rows.append(m[:, g * r:(g + 1) * r])
    want = np.concatenate(rows, axis=1)
    np.testing.assert_array_equal(got, want)

    # w13: [w1_g0, w3_g0, w1_g1, w3_g1]
    got13 = fused["w13"].to_numpy()
    w1 = params["blocks"]["w1"].to_numpy()
    w3 = params["blocks"]["w3"].to_numpy()
    rows = []
    for g in range(tp):
        for m in (w1, w3):
            r = m.shape[1] // tp
            rows.append(m[:, g * r:(g + 1) * r])
    np.testing.assert_array_equal(got13, np.concatenate(rows, axis=1))


def test_fused_forward_matches_unfused_kernel_path():
    """Same kernels, merged launches: fused vs unfused pallas decode must agree to
    float tolerance (identical quantized weights, identical activation Q80 path)."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=7)
    rope = RopeTables.create(spec)
    unfused = prepare_for_pallas(params, fuse=False)
    fused = prepare_for_pallas(params, spec=spec)
    assert "wqkv" in fused["blocks"] and "wqkv" not in unfused["blocks"]

    for tokens in (jnp.asarray([[1, 2, 3]]), jnp.asarray([[5]])):
        kc, vc = init_kv_cache(spec)
        want, _, _ = forward(unfused, spec, rope, tokens, kc, vc, jnp.int32(0),
                             use_pallas=True)
        kc, vc = init_kv_cache(spec)
        got, _, _ = forward(fused, spec, rope, tokens, kc, vc, jnp.int32(0),
                            use_pallas=True)
        got, want = np.asarray(got), np.asarray(want)
        rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
        assert rel < 1e-5, rel


def test_fused_sharded_forward_matches_planar():
    """tp=2 shard_map over fused params: wrong group interleaving would hand shard 1
    rows belonging to shard 0's heads and diverge immediately."""
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                                   make_sharded_forward,
                                                   shard_params)

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=3)
    mesh = make_mesh(tp=2)
    tokens = jnp.asarray([[1, 2, 3]])
    rope = RopeTables.create(spec)

    base = shard_params(params, mesh, spec)
    step = make_sharded_forward(spec, mesh, base, donate_cache=False)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    want, _, _ = step(base, rope, tokens, kc, vc, jnp.int32(0))

    pp = shard_params(prepare_for_pallas(params, tp=2, spec=spec), mesh, spec)
    assert "wqkv" in pp["blocks"] and "w13" in pp["blocks"]
    stepp = make_sharded_forward(spec, mesh, pp, donate_cache=False)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, _, _ = stepp(pp, rope, tokens, kc, vc, jnp.int32(0))
    # prefill rides the XLA dequant path: i4p dequant matches planar exactly
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_fused_decode_sharded_kernel_path():
    """tp=2 decode (T=1) through the merged kernels under shard_map vs the planar
    sharded step — kernel path at Q80 activation-quantization error scale."""
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                                   make_sharded_forward,
                                                   shard_params)

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    mesh = make_mesh(tp=2)
    tok = jnp.asarray([[5]])
    rope = RopeTables.create(spec)

    base = shard_params(params, mesh, spec)
    step = make_sharded_forward(spec, mesh, base, donate_cache=False)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    want, _, _ = step(base, rope, tok, kc, vc, jnp.int32(0))

    pp = shard_params(prepare_for_pallas(params, tp=2, spec=spec), mesh, spec)
    stepp = make_sharded_forward(spec, mesh, pp, use_pallas=True,
                                 donate_cache=False)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, _, _ = stepp(pp, rope, tok, kc, vc, jnp.int32(0))
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.03, rel


def test_moe_gu_fused_roundtrip_and_forward():
    """moe_up/moe_gate merge into moe_gu (per-expert [up|gate], TP-group
    interleaved on the hidden axis): bit-exact round-trip and matching Mixtral
    decode through the kernel path."""
    spec = ModelSpec(arch_type=ArchType.MIXTRAL, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=128,
                     seq_len=16, n_experts=4, n_active_experts=2,
                     rope_type=RopeType.FALCON).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=17)

    fused = fuse_matvec_groups(params["blocks"], spec, tp=2)
    got = fused["moe_gu"].to_numpy()  # (L, E, 2h, d)
    up = params["blocks"]["moe_up"].to_numpy()
    gate = params["blocks"]["moe_gate"].to_numpy()
    rows = []
    for g in range(2):
        for m in (up, gate):
            r = m.shape[2] // 2
            rows.append(m[:, :, g * r:(g + 1) * r])
    np.testing.assert_array_equal(got, np.concatenate(rows, axis=2))

    # decode through the kernel path (tp=1): fused == unfused
    rope = RopeTables.create(spec)
    unfused = prepare_for_pallas(params, fuse=False)
    fusedp = prepare_for_pallas(params, spec=spec)
    assert "moe_gu" in fusedp["blocks"]
    tok = jnp.asarray([[5]])
    kc, vc = init_kv_cache(spec)
    want, _, _ = forward(unfused, spec, rope, tok, kc, vc, jnp.int32(0),
                         use_pallas=True)
    kc, vc = init_kv_cache(spec)
    got_l, _, _ = forward(fusedp, spec, rope, tok, kc, vc, jnp.int32(0),
                          use_pallas=True)
    got_l, want = np.asarray(got_l), np.asarray(want)
    rel = np.abs(got_l - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 1e-5, rel


def test_moe_gu_expert_sharded_matches():
    """Expert-parallel mesh (whole experts over tp) with the merged moe_gu
    stack == unsharded planar forward."""
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                                   make_sharded_forward,
                                                   shard_params)

    spec = ModelSpec(arch_type=ArchType.MIXTRAL, dim=128, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=128,
                     seq_len=16, n_experts=4, n_active_experts=2,
                     rope_type=RopeType.FALCON).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=19)
    rope = RopeTables.create(spec)
    tok = jnp.asarray([[5]])

    kc, vc = init_kv_cache(spec)
    want, _, _ = forward(params, spec, rope, tok, kc, vc, jnp.int32(0))

    mesh = make_mesh(tp=4)
    pp = shard_params(prepare_for_pallas(params, tp=4, moe_sharding="expert",
                                         spec=spec),
                      mesh, spec, moe_sharding="expert")
    assert "moe_gu" in pp["blocks"]
    step = make_sharded_forward(spec, mesh, pp, use_pallas=True,
                                donate_cache=False, moe_sharding="expert")
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, _, _ = step(pp, rope, tok, kc, vc, jnp.int32(0))
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.03, rel


def test_row_groups_mismatch_fails_loudly():
    """Fusing for one tp and sharding on another would silently scramble the
    member split — shard_params must refuse (row_groups provenance check)."""
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import shard_params

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=23)
    pp = prepare_for_pallas(params, tp=1, spec=spec)  # interleave for tp=1
    assert pp["blocks"]["wqkv"].row_groups == 1
    mesh = make_mesh(tp=2)
    import pytest

    with pytest.raises(AssertionError, match="row interleave"):
        shard_params(pp, mesh, spec)


def test_fuse_skipped_under_kv_replication():
    """tp > n_kv_heads engages KV-head row replication (parallel/tp.py), which
    rewrites wk/wv AFTER fusion would run — fuse must decline and leave the
    separate tensors for the replication path."""
    spec = _spec()  # n_kv_heads=2
    params = init_random_params(spec, FloatType.Q40, seed=2)
    fused = fuse_matvec_groups(params["blocks"], spec, tp=4)
    assert "wqkv" not in fused and "wq" in fused
    assert "w13" in fused  # gate/up has no replication concern
