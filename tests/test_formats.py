"""File-format tests: .m/.t round trips + byte compatibility with the reference writer."""

import os

import numpy as np
import pytest

from distributed_llama_tpu.formats.mfile import (
    load_model,
    params_file_order,
    read_spec,
    write_model,
)
from distributed_llama_tpu.formats.tfile import TokenizerData, load_tokenizer, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec
from distributed_llama_tpu.quants import FloatType


def tiny_spec(arch=ArchType.LLAMA, **kw):
    d = dict(arch_type=arch, dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
             vocab_size=128, seq_len=32, rope_theta=10000.0)
    if arch != ArchType.LLAMA:
        d.update(n_experts=4, n_active_experts=2)
    if arch == ArchType.GROK1:
        d.update(hidden_act=HiddenAct.GELU)
    d.update(kw)
    return ModelSpec(**d).resolved()


@pytest.mark.parametrize("arch", [ArchType.LLAMA, ArchType.MIXTRAL, ArchType.GROK1])
@pytest.mark.parametrize("ftype", [FloatType.F32, FloatType.Q40])
def test_mfile_roundtrip(tmp_path, arch, ftype):
    spec = tiny_spec(arch)
    params = init_random_params(spec, ftype, seed=1)
    path = str(tmp_path / "model.m")
    write_model(path, spec, params_file_order(spec, params), ftype)

    spec2, params2 = load_model(path)
    assert spec2.arch_type == spec.arch_type
    assert (spec2.dim, spec2.hidden_dim, spec2.n_layers) == (spec.dim, spec.hidden_dim,
                                                             spec.n_layers)
    assert (spec2.n_experts, spec2.n_active_experts) == (spec.n_experts,
                                                         spec.n_active_experts)
    assert spec2.hidden_act == spec.hidden_act
    # tensors survive (through one quantization round for quantized types)
    np.testing.assert_allclose(params2["embedding"], params["embedding"], atol=1e-6)
    for name in params["blocks"]:
        a, b = params["blocks"][name], params2["blocks"][name]
        a = a.to_numpy() if hasattr(a, "to_numpy") else np.asarray(a)
        b = b.to_numpy() if hasattr(b, "to_numpy") else np.asarray(b)
        np.testing.assert_allclose(a, b, atol=1e-6, err_msg=name)


def test_mfile_seq_len_clamp(tmp_path):
    spec = tiny_spec()
    params = init_random_params(spec, FloatType.F32, seed=2)
    path = str(tmp_path / "m.m")
    write_model(path, spec, params_file_order(spec, params), FloatType.F32)
    spec2, _, _ = read_spec(path, max_seq_len=8)
    assert spec2.seq_len == 8 and spec2.orig_seq_len == 32


def test_mfile_wrong_ftype_detected(tmp_path):
    spec = tiny_spec()
    params = init_random_params(spec, FloatType.Q40, seed=3)
    path = str(tmp_path / "m.m")
    write_model(path, spec, params_file_order(spec, params), FloatType.Q40)
    with pytest.raises(ValueError, match="mismatch"):
        load_model(path, weights_ftype=FloatType.F32)


def test_mfile_reference_writer_compatibility(tmp_path):
    """A file produced by the REFERENCE converter's writer must load identically.

    Runs /root/reference/converter/writer.py (public untrusted code, used here only as a
    byte-format oracle) to build a tiny llama .m file.
    """
    torch = pytest.importorskip("torch")
    import sys

    if not os.path.isfile("/root/reference/converter/writer.py"):
        pytest.skip("reference repo not present (byte-format oracle unavailable)")
    sys.path.insert(0, "/root/reference/converter")
    import writer as refwriter  # noqa

    spec = tiny_spec()
    params = init_random_params(spec, FloatType.Q40, seed=4)
    path = str(tmp_path / "ref.m")
    with open(path, "wb") as f:
        refwriter.writeHeader(f, {
            "version": 0, "arch_type": int(spec.arch_type), "dim": spec.dim,
            "hidden_dim": spec.hidden_dim, "n_layers": spec.n_layers,
            "n_heads": spec.n_heads, "n_kv_heads": spec.n_kv_heads,
            "n_experts": 0, "n_active_experts": 0, "vocab_size": spec.vocab_size,
            "max_seq_len": spec.seq_len, "hidden_act": int(spec.hidden_act),
            "rope_theta": int(spec.rope_theta),
            "weights_float_type": int(FloatType.Q40),
        })
        norm_names = {"embedding", "rms_att", "rms_ffn", "rms_final"}
        for name, tensor in params_file_order(spec, params):
            ft = refwriter.FloatType.F32 if name in norm_names else refwriter.FloatType.Q40
            refwriter.writeTensor(f, torch.from_numpy(np.ascontiguousarray(tensor)), ft)

    spec2, params2 = load_model(path)
    assert spec2.dim == spec.dim and spec2.arch_type == ArchType.LLAMA
    np.testing.assert_allclose(params2["embedding"], params["embedding"], atol=1e-6)
    np.testing.assert_allclose(params2["blocks"]["wq"].to_numpy(),
                               params["blocks"]["wq"].to_numpy(), atol=1e-6)
    np.testing.assert_allclose(params2["wcls"].to_numpy(), params["wcls"].to_numpy(),
                               atol=1e-6)


def test_tfile_roundtrip(tmp_path):
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(32, 60)]
    td = TokenizerData(vocab=vocab, scores=[float(-i) for i in range(len(vocab))],
                       bos_id=1, eos_id=2, chat_eos_id=2, max_token_length=6,
                       chat_template="{% if %}<|im_start|>{% endif %}", chat_stop="<|done|>")
    path = str(tmp_path / "tok.t")
    write_tokenizer(path, td)
    td2 = load_tokenizer(path)
    assert td2.vocab == vocab
    assert td2.scores == td.scores
    assert (td2.bos_id, td2.eos_id, td2.chat_eos_id) == (1, 2, 2)
    assert td2.chat_template == td.chat_template
    assert td2.chat_stop == td.chat_stop


def test_tfile_reference_writer_compatibility(tmp_path):
    import sys

    if not os.path.isfile("/root/reference/converter/writer.py"):
        pytest.skip("reference repo not present (byte-format oracle unavailable)")
    sys.path.insert(0, "/root/reference/converter")
    import importlib

    reftw = importlib.import_module("tokenizer-writer")

    vocab = [b"<unk>", b"<s>", b"</s>", b"ab", b"cd"]
    scores = [0.0, 0.0, 0.0, -1.0, -2.0]
    path = str(tmp_path / "ref.t")
    with open(path, "wb") as f:
        reftw.writeTokenizer(f, {"bos_id": 1, "eos_id": 2, "chat_eos_id": 2},
                             vocab, scores, b"<|im_start|>x", None)
    td = load_tokenizer(path)
    assert td.vocab == vocab
    assert td.bos_id == 1 and td.eos_id == 2
    assert td.chat_template == "<|im_start|>x"
