"""Prefill/decode disaggregation (ISSUE 13, docs/DISAGG.md).

Four layers, cheapest first:

- the shared KV wire codec (cache/wire.py): raw mode bit-exact over random
  shapes/dtypes, Q80 mode bounded-error AND bit-identical to the block
  pool's own cold-tier round trip (one arithmetic, two consumers),
  truncation raises;
- role plumbing: healthz role field with back-compat (role-less payloads
  read as "both"), role-preferring pick();
- host-side import machinery: PagedPrefixCache.insert_cold coverage +
  eviction under a full cold tier, KVTransferTable TTL/cap;
- a LIVE disaggregated fleet (in-process prefill-role + decode-role
  replicas behind the real router): long-prompt requests split, ship KV,
  import, admit with ZERO re-prefill of the shipped span, and produce
  byte-identical output to the monolithic path — greedy and
  seeded-stochastic; a broken transfer falls back to local prefill with no
  client-visible failure.
"""

import http.client
import json
import threading

import numpy as np
import pytest

from distributed_llama_tpu.cache.device_pool import DeviceKVPool, PagedPrefixCache
from distributed_llama_tpu.cache.wire import (block_wire_bytes, decode_blocks,
                                              encode_blocks, q80_compress,
                                              q80_compressible, q80_restore)
from distributed_llama_tpu.fleet.disagg import (DECODE_ROLES, PREFILL_ROLES,
                                                DisaggPlanner, KVTransferTable,
                                                estimate_prompt_tokens,
                                                tokens_hash)
from distributed_llama_tpu.fleet.membership import Membership, Replica
from distributed_llama_tpu.fleet.router import RouterState, close_router, serve_router
from distributed_llama_tpu.formats.mfile import (load_model, params_file_order,
                                                 write_model)
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.resilience import faults
from distributed_llama_tpu.resilience.faults import FaultSpec
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.tokenizer import TemplateType
from distributed_llama_tpu.tokenizer.bpe import Tokenizer


# ----------------------------------------------------------------------
# wire codec
# ----------------------------------------------------------------------

def test_wire_codec_property_random_shapes():
    """Round trip over random shapes/dtypes: raw is bit-exact; Q80 is
    bounded-error and EQUALS the block pool's cold-tier reconstruction
    bit-for-bit (the extraction's whole point: the in-RAM tier and the
    wire can never drift)."""
    import ml_dtypes

    rng = np.random.default_rng(7)
    for trial in range(24):
        ndim = int(rng.integers(1, 5))
        shape = tuple(int(rng.integers(1, 7)) for _ in range(ndim))
        dtype = [np.float32, np.float16, ml_dtypes.bfloat16][trial % 3]
        blocks = []
        for _ in range(int(rng.integers(1, 4))):
            k = rng.standard_normal(shape).astype(dtype)
            v = rng.standard_normal(shape).astype(dtype)
            blocks.append((k, v))
        raw = encode_blocks(blocks)
        assert block_wire_bytes(blocks) == len(raw)
        out = decode_blocks(raw)
        assert len(out) == len(blocks)
        for (k, v), (k2, v2) in zip(blocks, out):
            assert k2.dtype == k.dtype and k2.shape == k.shape
            assert np.array_equal(k2, k) and np.array_equal(v2, v)
        q = encode_blocks(blocks, q80=True)
        assert block_wire_bytes(blocks, q80=True) == len(q)
        for (k, v), (k2, v2) in zip(blocks, decode_blocks(q)):
            if q80_compressible(k.shape):
                # identical to the pool's own demote->get reconstruction
                assert np.array_equal(
                    k2, q80_restore(q80_compress(k), k.shape, k.dtype))
                # bounded error: per 32-group absmax/254
                err = np.abs(k2.astype(np.float32) - k.astype(np.float32))
                bound = np.abs(k.astype(np.float32)).max() / 127.0 + 1e-6
                assert err.max() <= bound, (shape, dtype, err.max(), bound)
            else:  # incompressible shapes fall back to raw: bit-exact
                assert np.array_equal(k2, k) and np.array_equal(v2, v)


def test_wire_codec_q80_smaller_and_truncation_raises():
    rng = np.random.default_rng(1)
    k = rng.standard_normal((2, 2, 16, 8)).astype(np.float32)
    blocks = [(k, k.copy())]
    raw, q = encode_blocks(blocks), encode_blocks(blocks, q80=True)
    assert len(q) < len(raw) / 3  # ~34 bytes per 32 f32 values
    with pytest.raises(ValueError):
        decode_blocks(raw[: len(raw) // 2])
    with pytest.raises(ValueError):
        decode_blocks(b"\xff" + raw[1:])  # corrupt count -> over-read


# ----------------------------------------------------------------------
# role plumbing
# ----------------------------------------------------------------------

def test_replica_role_backcompat_old_payload():
    """A role-less healthz block (pre-disagg replica, rolling upgrade) must
    parse as role 'both'; a role-carrying one as advertised; the snapshot
    (what the router /healthz serves) surfaces it."""
    rep = Replica("127.0.0.1", 1)
    assert rep.role == "both"
    rep.apply_poll("ok", True, {"slots": 2, "free_slots": 2,
                                "queue_depth": 0})  # the OLD payload shape
    assert rep.role == "both"
    assert rep.snapshot()["role"] == "both"
    rep.apply_poll("ok", True, {"slots": 2, "role": "prefill"})
    assert rep.role == "prefill"
    assert rep.snapshot()["role"] == "prefill"
    rep.apply_poll("ok", True, {"slots": 2})  # role vanished again
    assert rep.role == "both"


def _fake_membership(roles):
    mem = Membership([f"127.0.0.1:{9000 + i}" for i in range(len(roles))])
    for rep, role in zip(mem.replicas, roles):
        rep.healthy = True
        rep.status = "ok"
        rep.role = role
    return mem


def test_pick_prefers_roles_softly():
    mem = _fake_membership(["prefill", "decode"])
    state = RouterState(mem)
    rep, _ = state.pick(b"k", set(), prefer_roles=DECODE_ROLES)
    assert rep.role == "decode"
    rep, _ = state.pick(b"k", set(), prefer_roles=PREFILL_ROLES)
    assert rep.role == "prefill"
    # soft preference: no candidate in the preferred set -> whole rotation
    rep, _ = state.pick(b"k", {mem.replicas[1].id},
                        prefer_roles=DECODE_ROLES)
    assert rep is not None and rep.role == "prefill"


def test_planner_threshold_and_topology_gates():
    planner = DisaggPlanner(threshold_tokens=32)
    long_body = {"messages": [{"role": "user", "content": "x" * 400}]}
    short_body = {"messages": [{"role": "user", "content": "hi"}]}
    assert estimate_prompt_tokens(long_body) >= 32
    # below threshold / disabled -> no plan, no network
    assert DisaggPlanner(0).plan(_fake_membership(["prefill", "decode"]),
                                 long_body) is None
    assert planner.plan(_fake_membership(["prefill", "decode"]),
                        short_body) is None
    # no distinct decode candidate -> no_topology, no network
    assert planner.plan(_fake_membership(["prefill"]), long_body) is None
    assert planner.plan(_fake_membership(["both"]), long_body) is None
    # homogeneous all-"both" fleets (incl. role-less back-compat payloads)
    # NEVER split — arming the threshold on a monolithic fleet is inert
    assert planner.plan(_fake_membership(["both", "both"]),
                        long_body) is None
    # resume/kv_source bodies never re-split
    assert planner.plan(_fake_membership(["prefill", "decode"]),
                        dict(long_body, resume={"tokens": [1]})) is None
    assert planner.plan(_fake_membership(["prefill", "decode"]),
                        dict(long_body, kv_source={"xfer_id": "x"})) is None
    # role preference: kv_source -> decode; unsplit long -> prefill;
    # short -> decode; homogeneous fleet -> None (no perturbation)
    mem = _fake_membership(["prefill", "decode"])
    assert planner.prefer_roles(dict(long_body, kv_source={}),
                                mem) == DECODE_ROLES
    assert planner.prefer_roles(long_body, mem) == PREFILL_ROLES
    assert planner.prefer_roles(short_body, mem) == DECODE_ROLES
    assert planner.prefer_roles(long_body,
                                _fake_membership(["both", "both"])) is None


def test_planner_warm_skip_follows_resident_prefix():
    """A decode-capable replica that already served the full prefix (per
    the router's affinity map) makes splitting wasteful — the planner
    skips it and prefer_roles follows the warm replica instead of
    steering the long prompt to a prefill replica."""
    from distributed_llama_tpu.fleet.affinity import AffinityMap

    planner = DisaggPlanner(threshold_tokens=32)
    mem = _fake_membership(["prefill", "decode"])
    decode_id = mem.replicas[1].id
    amap = AffinityMap(block_bytes=16)
    key = b"k" * 64
    long_body = {"messages": [{"role": "user", "content": "x" * 400}]}
    # cold key: no warm replica, long prompts prefer prefill-capable
    assert planner.warm_decode(mem, amap, key) is None
    assert planner.prefer_roles(long_body, mem, amap, key) == PREFILL_ROLES
    # the PREFILL replica serving it does not make it warm (not
    # decode-capable), so splitting remains correct
    amap.record(key, mem.replicas[0].id)
    assert planner.warm_decode(mem, amap, key) is None
    # once the DECODE replica served it, the planner skips the split and
    # routing follows the warm cache
    amap.record(key, decode_id)
    assert planner.warm_decode(mem, amap, key) == decode_id
    assert planner.prefer_roles(long_body, mem, amap, key) == DECODE_ROLES
    assert planner.plan(mem, long_body, affinity=amap, key=key) is None


def test_transfer_table_ttl_and_cap():
    table = KVTransferTable(cap=2, ttl=1000.0)
    k = np.zeros((1, 1, 4, 2), np.float32)
    descs = [table.open([1, 2, 3, 4], [(k, k)], 4, "raw") for _ in range(3)]
    assert table.stats()["live"] <= 2
    assert table.get(descs[0]["xfer_id"]) is None  # oldest evicted by cap
    assert table.get(descs[2]["xfer_id"]) is not None
    assert descs[2]["n_tokens"] == 4 and descs[2]["n_blocks"] == 1
    assert descs[2]["tokens_hash"] == tokens_hash([1, 2, 3, 4])
    # TTL expiry
    short = KVTransferTable(cap=2, ttl=0.0)
    d = short.open([1, 2, 3, 4], [(k, k)], 4, "raw")
    assert short.get(d["xfer_id"]) is None
    # consumption: a fetch covering the FINAL block drops the remaining
    # lifetime to consumed_ttl so completed transfers free their slot
    cons = KVTransferTable(cap=2, ttl=1000.0, consumed_ttl=0.0)
    d = cons.open(list(range(8)), [(k, k), (k, k)], 4, "raw")
    t = cons.get(d["xfer_id"])
    cons.note_served(t, 0, 1)  # partial range: still live
    assert cons.get(d["xfer_id"]) is not None
    cons.note_served(t, 1, 1)  # final block served: consumed
    assert cons.get(d["xfer_id"]) is None


# ----------------------------------------------------------------------
# host-side import machinery
# ----------------------------------------------------------------------

def _host_block(rng, bt=4):
    return (rng.standard_normal((1, 1, bt, 2)).astype(np.float32),
            rng.standard_normal((1, 1, bt, 2)).astype(np.float32))


def test_insert_cold_covers_and_lookup_serves():
    rng = np.random.default_rng(3)
    pool = DeviceKVPool(8, 4)
    pc = PagedPrefixCache(pool, 4, cold_blocks=8)
    tokens = list(range(10, 22))  # 3 full blocks
    blocks = [_host_block(rng) for _ in range(3)]
    assert pc.insert_cold(tokens, blocks) == 3
    lease = pc.lookup(tokens + [99])
    assert lease is not None and lease.tokens == 12
    for node, (k, _v) in zip(lease.nodes, blocks):
        tier, h = node.handle
        assert tier == "cold"
        got_k, _got_v = pc.fetch_cold(h)
        assert np.array_equal(got_k, k)
    pc.release(lease)
    # idempotent re-import: existing nodes keep their handles, coverage holds
    assert pc.insert_cold(tokens, [_host_block(rng) for _ in range(3)]) == 3
    assert pc.stats()["cold_blocks"] == 3


def test_insert_cold_full_tier_stops_chain_then_evicts_lru():
    rng = np.random.default_rng(4)
    pool = DeviceKVPool(8, 4)
    pc = PagedPrefixCache(pool, 4, cold_blocks=2)
    # 3 blocks into a 2-block cold tier: the chain being inserted is pinned
    # (its own nodes are not evictable), so coverage stops at 2
    covered = pc.insert_cold(list(range(12)), [_host_block(rng)
                                               for _ in range(3)])
    assert covered == 2
    # a DIFFERENT prefix now evicts the first chain's LRU nodes
    covered = pc.insert_cold(list(range(100, 108)),
                             [_host_block(rng) for _ in range(2)])
    assert covered == 2
    assert pc.stats()["cold_blocks"] == 2
    assert pool.free_blocks() == 7  # imports never touch device blocks


# ----------------------------------------------------------------------
# live disaggregated fleet
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("disagg")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=262,
                     seq_len=192).resolved()
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    return mpath, tpath


@pytest.fixture(scope="module")
def disagg_fleet(model_files):
    from distributed_llama_tpu.apps.api_server import serve

    mpath, tpath = model_files
    reps = []
    for role in ("prefill", "decode"):
        lspec, lparams = load_model(mpath, 0)
        be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), slots=2,
                         tp=1, superstep=4)
        srv = serve(None, host="127.0.0.1", port=0,
                    template_type=TemplateType.CHATML, batch_engine=be,
                    role=role)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        reps.append({"role": role, "be": be, "srv": srv,
                     "port": srv.server_address[1]})
    router = serve_router([f"127.0.0.1:{r['port']}" for r in reps],
                          host="127.0.0.1", port=0, poll_interval=0.15,
                          block_bytes=16, retries=2, try_timeout=60.0,
                          disagg_threshold=24)
    threading.Thread(target=router.serve_forever, daemon=True).start()
    yield {"replicas": reps, "router": router,
           "port": router.server_address[1],
           "state": router.router_state}
    close_router(router)
    for r in reps:
        r["srv"].shutdown()
        r["srv"].server_close()
        r["be"].close()


def _post(port, body, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    return conn.getresponse()


def _long_body(seed=None, stream=False, salt=""):
    body = {"messages": [{"role": "system", "content": "s" * 80},
                         {"role": "user",
                          "content": f"tell me something {salt}"}],
            "max_tokens": 10, "temperature": 0, "stream": stream}
    if seed is not None:
        body.update(temperature=0.9, seed=seed)
    return body


def _completion_text(resp):
    assert resp.status == 200, resp.read()
    data = json.loads(resp.read())
    return data["choices"][0]["message"]["content"]


def _snapshot():
    from distributed_llama_tpu.obs import metrics

    return metrics.snapshot()


def _counter(snap, name, label=None):
    v = snap.get(name) or 0
    if isinstance(v, dict):
        return v.get(label, 0) if label else sum(v.values())
    return v


def _reference(fleet, body):
    """Monolithic reference output: same fleet, split disabled."""
    state = fleet["state"]
    thr = state.disagg.threshold
    state.disagg.threshold = 0
    try:
        return _completion_text(_post(fleet["port"], body))
    finally:
        state.disagg.threshold = thr


def test_disagg_split_byte_identical_and_zero_reprefill(disagg_fleet):
    """The tentpole end-to-end: a long-prompt completion splits (prefill on
    the prefill replica, KV shipped, decode elsewhere), output is
    byte-identical to the monolithic run (raw wire is bit-exact), and the
    decode replica re-prefills ZERO shipped tokens. The DISAGG request
    runs first (a cold affinity key — once a decode replica holds the
    prefix, the planner's warm-skip deliberately stops splitting it)."""
    s0 = _snapshot()
    out = _completion_text(_post(disagg_fleet["port"], _long_body()))
    ref = _reference(disagg_fleet, _long_body())
    assert out == ref
    s1 = _snapshot()
    assert (_counter(s1, "router_disagg_requests_total",
                     '{outcome="split"}')
            > _counter(s0, "router_disagg_requests_total",
                       '{outcome="split"}'))
    assert (_counter(s1, "disagg_import_requests_total",
                     '{outcome="imported"}')
            > _counter(s0, "disagg_import_requests_total",
                       '{outcome="imported"}'))
    assert _counter(s1, "disagg_import_tokens_total") > \
        _counter(s0, "disagg_import_tokens_total")
    assert _counter(s1, "disagg_reprefill_tokens_total") == \
        _counter(s0, "disagg_reprefill_tokens_total"), \
        "shipped KV was re-prefilled"


def test_disagg_seeded_stochastic_identity(disagg_fleet):
    """Stochastic sampling with a pinned seed: the disaggregated decode
    replica draws the SAME xorshift* stream (imported KV is bit-exact raw
    wire), so output matches the monolithic run byte-for-byte."""
    body = _long_body(seed=1234)
    ref = _reference(disagg_fleet, body)
    out = _completion_text(_post(disagg_fleet["port"], body))
    assert out == ref


def test_disagg_stream_parity(disagg_fleet):
    body = _long_body(stream=True)
    ref = _reference(disagg_fleet, _long_body())
    resp = _post(disagg_fleet["port"], body)
    assert resp.status == 200
    text = []
    for line in resp.read().decode().splitlines():
        if line.startswith("data: ") and line != "data: [DONE]":
            payload = json.loads(line[6:])
            assert "error" not in payload, payload
            text.append(payload["choices"][0]["delta"].get("content") or "")
    assert "".join(text) == ref


def test_export_endpoint_ranges_resumable_and_404(disagg_fleet):
    """GET /v1/kv/<id> contract: any range re-fetchable (the resumability
    primitive), bad ranges 400, unknown ids 404."""
    from distributed_llama_tpu.cache.wire import decode_blocks as dec
    from distributed_llama_tpu.fleet.disagg import fetch_kv_blocks

    pre = disagg_fleet["replicas"][0]
    # plant a transfer directly on the prefill replica's table
    rng = np.random.default_rng(5)
    blocks = [(rng.standard_normal((2, 2, 16, 8)).astype(np.float32),
               rng.standard_normal((2, 2, 16, 8)).astype(np.float32))
              for _ in range(3)]
    desc = pre["srv"].api_state.kv_transfers.open(
        list(range(48)), blocks, 16, "raw")
    xid = desc["xfer_id"]
    for _ in range(2):  # same range twice: resumable by construction
        got = fetch_kv_blocks("127.0.0.1", pre["port"], xid, 1, 2)
        assert len(got) == 2
        assert np.array_equal(got[0][0], blocks[1][0])
    conn = http.client.HTTPConnection("127.0.0.1", pre["port"], timeout=30)
    conn.request("GET", f"/v1/kv/{xid}?from=2&n=5")
    assert conn.getresponse().status == 400
    conn.close()
    conn = http.client.HTTPConnection("127.0.0.1", pre["port"], timeout=30)
    conn.request("GET", "/v1/kv/kv-nonexistent?from=0&n=1")
    assert conn.getresponse().status == 404
    conn.close()
    assert dec is not None  # silence unused-import style checks


def test_broken_transfer_falls_back_to_local_prefill(disagg_fleet):
    """Mid-transfer failure (the prefill replica dies between the plan and
    the fetch): the decode replica abandons the import and prefills
    locally — the client sees a normal, byte-identical completion. Unique
    prompt (cold affinity key, so the split actually engages) and the
    faulted request runs before its reference."""
    body = _long_body(salt="broken")
    s0 = _snapshot()
    # every fetch attempt fails (count covers the per-chunk retry too)
    with faults.active(FaultSpec("disagg.fetch", kind="error", count=64)):
        out = _completion_text(_post(disagg_fleet["port"], body))
    faults.uninstall()
    ref = _reference(disagg_fleet, body)
    assert out == ref
    s1 = _snapshot()
    assert (_counter(s1, "disagg_import_requests_total",
                     '{outcome="error"}')
            > _counter(s0, "disagg_import_requests_total",
                       '{outcome="error"}'))


def test_import_seeded_admission_stays_on_manifest():
    """ISSUE 13 satellite (docs/ANALYSIS.md): an import-seeded admission —
    shipped blocks entering as cold directory nodes, promoted to device at
    admission, suffix prefill + scans — must ride the programs
    perf/compile_manifest.json pins (the promotion is an untracked
    single-block pool update; the admission reuses existing programs). And
    a shape drift smuggled in THROUGH the same path must still be caught:
    an off-bucket scan after the import-seeded admission fails the gate
    with the cache key named."""
    from distributed_llama_tpu.analysis import compile_audit
    from distributed_llama_tpu.cache.wire import decode_blocks as dec
    from distributed_llama_tpu.cache.wire import encode_blocks as enc
    from distributed_llama_tpu.runtime.sampler import Sampler

    pinned = compile_audit.load_manifest()
    assert pinned is not None, "perf/compile_manifest.json missing"
    spec = compile_audit.scenario_spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    audit = compile_audit.CompileAudit()
    with audit:
        eng = BatchEngine(spec, params, slots=2, superstep=4, pipeline=True,
                          tp=1, prefix_cache=True)
        try:
            assert eng.kv_pool is not None
            bt = eng._kv_bt
            rng = np.random.default_rng(9)
            L, _n, hk, _bt, hs = eng._eng.k_cache.shape
            blocks = [(rng.standard_normal((L, hk, bt, hs))
                       .astype(np.float32),
                       rng.standard_normal((L, hk, bt, hs))
                       .astype(np.float32))]
            prompt = [(5 * i + 1) % spec.vocab_size for i in range(bt + 1)]
            assert eng.import_kv_blocks(prompt[:bt],
                                        dec(enc(blocks))) == bt
            req = eng.submit(list(prompt), 6, Sampler(spec.vocab_size))
            req.wait(60)
            # the shipped span was reused, not re-prefilled
            assert req.stats.reused_tokens == bt
            clean = compile_audit.diff_manifest(audit.manifest(), pinned)
            assert clean == [], "\n".join(f.message for f in clean)
            eng._batched_loop(7, "greedy", None)  # injected drift
        finally:
            eng.close()
    findings = compile_audit.diff_manifest(audit.manifest(), pinned)
    assert any("batched_scan[k=7,mode=greedy,window=None,paged=16]"
               in f.message for f in findings), \
        [f.message for f in findings]


def test_prefill_leg_carries_tenant_and_class(disagg_fleet):
    """The remote prefill is charged to the requesting tenant at its real
    class (docs/DISAGG.md): POST /v1/kv with relayed X-Tenant/X-Class must
    attribute the prefill request to that tenant, batch class."""
    pre = disagg_fleet["replicas"][0]
    conn = http.client.HTTPConnection("127.0.0.1", pre["port"], timeout=120)
    conn.request("POST", "/v1/kv", json.dumps(
        {"messages": [{"role": "system", "content": "t" * 80},
                      {"role": "user", "content": "attribution"}]}),
        {"Content-Type": "application/json", "X-Tenant": "gold",
         "X-Class": "batch"})
    resp = conn.getresponse()
    desc = json.loads(resp.read())
    conn.close()
    assert resp.status == 200 and desc["n_blocks"] > 0
    from distributed_llama_tpu.obs import metrics

    fam = metrics.snapshot().get("batch_tenant_requests_total") or {}
    assert any("gold" in k and "batch" in k for k in fam), fam


def test_router_strips_client_supplied_kv_source(disagg_fleet):
    """Trust model (docs/DISAGG.md): kv_source is ROUTER-OWNED. A client
    smuggling a descriptor pointing at an arbitrary host must have it
    stripped at the edge — no fetch to the attacker address, no import
    attempt, the request served normally (monolithic: below threshold)."""
    s0 = _snapshot()
    body = {"messages": [{"role": "user", "content": "short q"}],
            "max_tokens": 4, "temperature": 0,
            "kv_source": {"replica": "127.0.0.1:9", "xfer_id": "kv-evil",
                          "n_tokens": 16, "n_blocks": 1,
                          "block_tokens": 16, "tokens_hash": "0" * 16,
                          "wire": "raw"}}
    resp = _post(disagg_fleet["port"], body)
    assert resp.status == 200
    json.loads(resp.read())
    s1 = _snapshot()
    # the descriptor never reached a replica: no import outcome of ANY
    # kind was recorded for it (the fleet is in-process, so the metric
    # family is shared — an attempted fetch/import would show up here)
    assert (_counter(s1, "disagg_import_requests_total")
            == _counter(s0, "disagg_import_requests_total"))


def test_disagg_stats_blocks_surface(disagg_fleet):
    for rep in disagg_fleet["replicas"]:
        conn = http.client.HTTPConnection("127.0.0.1", rep["port"],
                                          timeout=30)
        conn.request("GET", "/v1/stats")
        data = json.loads(conn.getresponse().read())
        conn.close()
        assert data["replica"]["role"] == rep["role"]
        assert data["disagg"]["role"] == rep["role"]
        assert data["disagg"]["kv_wire"] == "raw"
    # router /healthz surfaces the roles in rotation
    conn = http.client.HTTPConnection("127.0.0.1", disagg_fleet["port"],
                                      timeout=30)
    conn.request("GET", "/healthz")
    data = json.loads(conn.getresponse().read())
    conn.close()
    roles = {r["role"] for r in data["replicas"].values()}
    assert roles == {"prefill", "decode"}
