"""Shared-prefix KV cache tests (ISSUE 3, cache/ + BatchEngine/api integration).

Layers under test:
- radix.py against a brute-force longest-prefix oracle (random insert/match/
  evict with refcount invariants — the property test the satellite demands);
- block_pool.py hot/Q80 tiers (bit-exact hot round-trip, near-lossless cold);
- BatchEngine end-to-end: greedy AND seeded-stochastic outputs token-identical
  with the prefix cache enabled vs disabled, cross-slot reuse actually skips
  prefill, clamped-park truncation releases the radix reservation (regression
  for the _park_positions interaction);
- SingleSlotCache (api_server --batch 1 path): cross-conversation reuse after
  the resident conversation was displaced.
"""

import random
import time

import numpy as np
import pytest

from distributed_llama_tpu.cache import PrefixCache
from distributed_llama_tpu.cache.radix import RadixIndex
from distributed_llama_tpu.cache.block_pool import KVBlockPool
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.sampler import Sampler


def _spec(seq_len=128, dim=64):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=dim, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=seq_len, rope_type=RopeType.LLAMA).resolved()


# ---------------------------------------------------------------------------
# radix.py: property test vs a brute-force oracle
# ---------------------------------------------------------------------------


class _Oracle:
    """Brute-force model of the index: a prefix-closed set of block-chains."""

    def __init__(self, bt):
        self.bt = bt
        self.chains: set[tuple] = set()  # each element: tuple of block-tuples

    def blocks(self, toks):
        return tuple(tuple(toks[i:i + self.bt])
                     for i in range(0, len(toks) - self.bt + 1, self.bt))

    def insert(self, toks, landed):
        blks = self.blocks(toks)[:landed]
        for i in range(1, len(blks) + 1):
            self.chains.add(blks[:i])

    def match_len(self, toks):
        blks = self.blocks(toks)
        n = 0
        while n < len(blks) and blks[:n + 1] in self.chains:
            n += 1
        return n


def test_radix_property_vs_oracle():
    rng = random.Random(1234)
    bt = 4
    tree = RadixIndex(block_tokens=bt)
    oracle = _Oracle(bt)
    handles = iter(range(10 ** 9))
    node_of = {}  # chain -> node (for targeted acquire/release bookkeeping)
    acquired = []  # list of chains currently acquired (via match+acquire)

    def rand_tokens():
        # draw from a small alphabet so prefixes actually collide
        return [rng.randrange(1, 6) for _ in range(rng.randrange(0, 20))]

    for step in range(3000):
        op = rng.random()
        toks = rand_tokens()
        if op < 0.4:  # insert
            chain = tree.insert(toks, lambda i: next(handles))
            oracle.insert(toks, len(chain))
            for i, node in enumerate(chain):
                node_of[oracle.blocks(toks)[:i + 1]] = node
        elif op < 0.7:  # match
            got = tree.match(toks)
            assert len(got) == oracle.match_len(toks), (step, toks)
        elif op < 0.85:  # acquire a random cached chain (pins it)
            got = tree.match(toks)
            if got:
                keep = rng.randrange(1, len(got) + 1)
                tree.acquire(got[:keep])
                acquired.append(got[:keep])
        elif acquired and op < 0.95:  # release one acquired chain
            tree.release(acquired.pop(rng.randrange(len(acquired))))
        else:  # evict
            n = rng.randrange(1, 5)
            freed = set(tree.evict(n))
            assert len(freed) <= n
            # oracle removal: chains whose leaf handle was freed
            gone = {c for c, nd in node_of.items() if nd.handle in freed}
            for c in gone:
                oracle.chains.discard(c)
                del node_of[c]
        # global invariants after every op
        assert tree.nodes == len(oracle.chains), step
        assert set(tree.chains()) == oracle.chains, step
        pinned = sum(len(c) for c in acquired)
        assert tree.total_refs() == pinned, step
    for c in acquired:
        tree.release(c)
    assert tree.total_refs() == 0


def test_radix_eviction_respects_refs_and_lru():
    tree = RadixIndex(block_tokens=2)
    h = iter(range(100))
    tree.insert([1, 1, 2, 2], lambda i: next(h))      # chain A (2 blocks)
    tree.insert([9, 9], lambda i: next(h))            # chain B (1 block)
    a = tree.match([1, 1, 2, 2])
    tree.acquire(a)
    # A is pinned: only B is evictable, however much we ask for
    freed = tree.evict(10)
    assert len(freed) == 1 and tree.nodes == 2
    tree.release(a)
    tree.insert([9, 9], lambda i: next(h))  # recreate B, LRU-newer than A
    # A released: eviction cascades leaf -> parent, oldest first
    freed = tree.evict(2)
    assert len(freed) == 2 and tree.nodes == 1
    assert tree.match([1, 1, 2, 2]) == []
    assert len(tree.match([9, 9])) == 1


# ---------------------------------------------------------------------------
# block_pool.py: tiers
# ---------------------------------------------------------------------------


def test_pool_hot_roundtrip_bit_exact_and_capacity():
    pool = KVBlockPool(max_blocks=2)
    k = np.random.default_rng(0).normal(size=(2, 4, 8, 16)).astype(np.float32)
    v = 2 * k + 1
    h = pool.put(k, v)
    k2, v2 = pool.get(h)
    assert k2.dtype == np.float32
    assert np.array_equal(k2, k) and np.array_equal(v2, v)
    assert pool.put(k, v) is not None
    assert pool.put(k, v) is None  # full: pool never evicts on its own
    pool.free(h)
    assert pool.put(k, v) is not None


def test_pool_q80_tier_demotes_lru_and_dequantizes_close():
    pool = KVBlockPool(max_blocks=4, hot_blocks=1, q80=True)
    rng = np.random.default_rng(1)
    blocks = [rng.normal(size=(2, 4, 8, 16)).astype(np.float32)
              for _ in range(3)]
    hs = [pool.put(b, b + 0.25) for b in blocks]
    # hot budget 1: the two LRU blocks were demoted to Q80
    assert pool.is_cold(hs[0]) and pool.is_cold(hs[1]) and not pool.is_cold(hs[2])
    assert pool.hot_count() == 1 and pool.demoted_blocks == 2
    # Q80 is per-32-block absmax/127: reconstruction within ~1% of the range
    k0, v0 = pool.get(hs[0])
    assert k0.shape == blocks[0].shape and k0.dtype == np.float32
    tol = np.abs(blocks[0]).max() / 127 * 1.01
    assert np.abs(k0 - blocks[0]).max() <= tol
    assert np.abs(v0 - (blocks[0] + 0.25)).max() <= tol
    # cold tier is genuinely denser than f32
    assert pool.nbytes() < sum(2 * b.nbytes for b in blocks)


def test_prefix_cache_lookup_fetch_roundtrip():
    """lookup() hands out a lease only; fetch() gathers exactly the requested
    row span — including a skip that starts mid-block."""
    pc = PrefixCache(max_blocks=16, block_tokens=4)
    L, hk, hs = 2, 2, 8
    K = np.arange(L * hk * 12 * hs, dtype=np.float32).reshape(L, hk, 12, hs)
    V = K + 0.5
    toks = list(range(1, 13))
    pc.insert(toks, lambda a, b: (K[:, :, a:b], V[:, :, a:b]))
    lease = pc.lookup(toks + [99])
    assert lease is not None and lease.tokens == 12
    k, v = pc.fetch(lease)
    assert np.array_equal(k, K) and np.array_equal(v, V)
    k5, v5 = pc.fetch(lease, skip=5)  # mid-block skip
    assert np.array_equal(k5, K[:, :, 5:12]) and np.array_equal(v5, V[:, :, 5:12])
    pc.mark_seeded(lease, 12)
    pc.release(lease)
    # a second release must be a no-op (take-and-clear), not an underflow
    pc.release(lease)
    assert pc.total_refs() == 0
    st = pc.stats()
    assert st["hits"] == 1 and st["hit_tokens"] == 12


# ---------------------------------------------------------------------------
# BatchEngine end-to-end: cache on == cache off, cross-slot reuse, eviction
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def engines():
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=17)
    be_off = BatchEngine(spec, params, slots=2, tp=1, prefix_cache=False)
    be_on = BatchEngine(spec, params, slots=2, tp=1, prefix_cache=True,
                        prefix_block_tokens=8)
    yield spec, be_off, be_on
    be_on.close()
    be_off.close()


SHARED = [1] + [10 + (i * 7) % 90 for i in range(33)]  # 34 tokens, 4 blocks of 8


def _run(be, prompt, n, temperature=0.0, seed=0, vocab=256):
    return be.submit(list(prompt),
                     n, Sampler(vocab, temperature=temperature,
                                seed=seed)).wait(timeout=180)


def _settle(pred, timeout=10):
    """wait() returns at done.set(); the scheduler thread harvests the slot
    into the pool just after — poll for the post-finish state."""
    t0 = time.time()
    while not pred() and time.time() - t0 < timeout:
        time.sleep(0.01)
    assert pred()


def test_cache_on_off_token_identical_greedy_and_stochastic(engines):
    spec, be_off, be_on = engines
    prompts = [SHARED + [200 + i] for i in range(3)] + [[1, 99, 98]]
    plans = [(0.0, 0), (0.8, 7), (0.8, 7), (0.0, 0)]  # greedy AND stochastic
    wants = [_run(be_off, p, 8, t, s) for p, (t, s) in zip(prompts, plans)]

    base = be_on.prefilled_tokens
    got = [_run(be_on, prompts[0], 8, *plans[0])]     # warms the radix
    got_unrel = _run(be_on, prompts[3], 8, *plans[3])  # dirties both slots' histories
    mid = be_on.prefilled_tokens
    got.append(_run(be_on, prompts[1], 8, *plans[1]))  # must seed from the pool
    seeded_prefill = be_on.prefilled_tokens - mid
    got.append(_run(be_on, prompts[2], 8, *plans[2]))
    got.append(got_unrel)

    assert got == wants
    # the seeded request prefilled only its uncached suffix: 35-token prompt,
    # 32 tokens (4 full blocks) seeded from the pool
    assert seeded_prefill <= len(prompts[1]) - 32
    st = be_on.prefix_cache.stats()
    # apply-time accounting: prompts[1] seeded from the pool (hit); prompts[2]
    # found its prefix on the slot prompts[1] vacated, so its lookup matched
    # but the copy-free rewind served it (unused_hit, NOT a pool hit)
    assert st["hits"] >= 1 and st["hit_tokens"] >= 30
    assert st["unused_hits"] >= 1
    _settle(lambda: be_on.prefix_cache.total_refs() == 0)  # every lease released


def test_concurrent_shared_prefix_requests_identical(engines):
    spec, be_off, be_on = engines
    prompts = [SHARED + [150 + i] for i in range(4)]
    wants = [_run(be_off, p, 6) for p in prompts]
    _run(be_on, prompts[0], 6)  # warm the cache
    reqs = [be_on.submit(list(p), 6, Sampler(spec.vocab_size, temperature=0.0))
            for p in prompts]
    outs = [r.wait(timeout=180) for r in reqs]
    assert outs == wants
    _settle(lambda: be_on.prefix_cache.total_refs() == 0)


def test_eviction_under_tiny_pool_keeps_outputs_identical(engines):
    """A pool far smaller than the working set must still be correct — every
    miss just prefills (the cache is an optimization, never a correctness
    gate) and eviction churns without corrupting the tree."""
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine

    spec, be_off, _ = engines
    params = init_random_params(spec, FloatType.Q40, seed=17)
    # paged_kv=False: this pins the DENSE host pool's eviction semantics
    # (the --no-paged-kv path); the paged analog lives in test_paged_kv.py
    be = BatchEngine(spec, params, slots=2, tp=1, prefix_cache=True,
                     prefix_block_tokens=8, prefix_cache_blocks=3,
                     paged_kv=False)
    try:
        prompts = [SHARED + [140 + i] for i in range(2)] + [[1, 77] + [30 + i for i in range(20)]]
        wants = [_run(be_off, p, 6) for p in prompts]
        got = [_run(be, p, 6) for p in prompts]
        got2 = [_run(be, p, 6) for p in prompts]  # second pass: churned pool
        assert got == wants and got2 == wants
        _settle(lambda: be.prefix_cache.total_refs() == 0)
        assert len(be.prefix_cache.pool) <= 3
    finally:
        be.close()


def test_context_end_with_cache_matches_off():
    """Drive rows to the context end (exercises the clamped-park and
    super-step history-truncation paths) with the cache enabled; outputs must
    match the cache-off engine exactly."""
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine

    spec = _spec(seq_len=32)
    params = init_random_params(spec, FloatType.Q40, seed=5)
    prompts = [[1, 2, 3, 4, 5, 6, 7, 8, 9, 10], [1, 2, 3, 4, 5, 6, 7, 8, 11]]
    outs = {}
    for on in (False, True):
        be = BatchEngine(spec, params, slots=2, tp=1, prefix_cache=on,
                         prefix_block_tokens=4)
        try:
            if on:
                _run(be, prompts[0], 30)  # warm + insert near-full context
            reqs = [be.submit(list(p), 30, Sampler(spec.vocab_size,
                                                   temperature=0.0))
                    for p in prompts]
            outs[on] = [r.wait(timeout=180) for r in reqs]
            for r in reqs:
                assert r.finish == "length"
            if on:
                _settle(lambda: be.prefix_cache.total_refs() == 0)
                # the clamped super-step destroyed row s-1 mid-scan; the
                # finish harvest must have truncated BEFORE inserting, so no
                # chain may cover the full [0, s) range (block_tokens=4,
                # s=32: max depth 7 blocks = 28 tokens, never 8)
                chains = be.prefix_cache.radix.chains()
                assert chains and max(len(c) for c in chains) <= 7, (
                    max(len(c) for c in chains))
        finally:
            be.close()
    assert outs[True] == outs[False]


def test_clamped_park_releases_radix_reservation():
    """Regression (ISSUE 3 satellite): when a clamped park truncates
    slot.history below a lease's seeded length, the radix reservation must
    shrink with it — the tree must not stay pinned for rows the slot no
    longer holds (a stale pin blocks eviction and misstates what the slot
    can re-insert)."""
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine

    spec = _spec(seq_len=32)
    params = init_random_params(spec, FloatType.Q40, seed=5)
    # paged_kv=False: white-box test of the DENSE lease-shrink machinery
    # (slot.history/lease poking); paged leases shrink through the same
    # _truncate_history path and are covered by test_paged_kv.py
    be = BatchEngine(spec, params, slots=2, tp=1, prefix_cache=True,
                     prefix_block_tokens=4, paged_kv=False)
    try:
        prompt = [1] + list(range(2, 26))  # 25 tokens -> 6 full blocks
        _run(be, prompt, 1)
        pc = be.prefix_cache
        _settle(lambda: pc.radix.nodes >= 6)  # harvest lands post-finish
        # simulate a seeded in-flight slot (as _assign leaves it)
        slot = be._slots[0]
        lease = pc.lookup(prompt)
        assert lease is not None and lease.tokens == 24
        slot.lease = lease
        slot.history = list(prompt[:24])
        slot.pos = 24
        # a 20-wide dispatch parks this row clamped at 32-20=12: rows >= 12
        # are overwritten, history truncates, and the lease MUST follow
        starts = be._park_positions(20)
        assert starts[0] == 12 and slot.history == prompt[:12]
        assert slot.lease.tokens == 12 and len(slot.lease.nodes) == 3
        # exactly the surviving 3 blocks stay pinned
        assert pc.radix.total_refs() == 3
        # the released tail is evictable again; the pinned prefix is not
        freed = pc.radix.evict(100)
        assert len(freed) == 3
        pc.release(slot.lease)
        slot.lease = None
        assert pc.total_refs() == 0
        slot.history, slot.pos = [], 0
    finally:
        be.close()


def test_seeding_into_dp_sharded_cache_matches():
    """dp=2 x tp=2: the seed scatter indexes the dp-SHARDED batch axis and the
    harvest gathers from it — outputs must still match the cache-off engine."""
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=17)
    prompts = [SHARED + [230 + i] for i in range(3)]
    outs = {}
    for on in (False, True):
        be = BatchEngine(spec, params, slots=4, tp=2, dp=2, prefix_cache=on,
                         prefix_block_tokens=8)
        try:
            outs[on] = [_run(be, prompts[0], 6)]  # warm (inserts when on)
            reqs = [be.submit(list(p), 6, Sampler(spec.vocab_size,
                                                  temperature=0.0))
                    for p in prompts[1:]]
            outs[on] += [r.wait(timeout=180) for r in reqs]
            if on:
                _settle(lambda: be.prefix_cache.total_refs() == 0)
                assert be.prefix_cache.hit_tokens >= 32
        finally:
            be.close()
    assert outs[True] == outs[False]


# ---------------------------------------------------------------------------
# SingleSlotCache (api_server --batch 1 path)
# ---------------------------------------------------------------------------


def test_single_slot_cross_conversation_reuse():
    from distributed_llama_tpu.cache import PrefixCache, SingleSlotCache
    from distributed_llama_tpu.runtime.engine import Engine

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=9)
    eng = Engine(spec, params, tp=1)
    ssc = SingleSlotCache(eng, PrefixCache(max_blocks=64, block_tokens=8))
    smp = lambda: Sampler(spec.vocab_size, temperature=0.0)

    conv_a = SHARED + [201]
    conv_b = [1, 60, 61, 62]

    def run(conv):
        reuse = ssc.begin(conv)
        out, _ = eng.generate(conv[reuse:], 6, smp())
        ssc.end((conv + out)[:eng.pos])
        return out, reuse

    want_a, r0 = run(conv_a)
    assert r0 == 0
    run(conv_b)  # displaces the resident conversation
    # return to A: the resident KV holds B, but the radix pool holds A's
    # blocks — reuse must come from the pool, not a fresh prefill
    got_a, reuse = run(conv_a)
    assert reuse >= 32  # 4 full 8-token blocks seeded
    assert got_a == want_a
    # a new conversation sharing only the system prompt also hits
    conv_c = SHARED + [222]
    got_c, reuse_c = run(conv_c)
    assert reuse_c >= 32
    eng.reset()
    cold = Engine(spec, params, tp=1)
    want_c, _ = cold.generate(list(conv_c), 6, smp())
    assert got_c == want_c
    assert ssc.cache.radix.total_refs() == 0


def test_single_slot_invalidate_recovers():
    from distributed_llama_tpu.cache import PrefixCache, SingleSlotCache
    from distributed_llama_tpu.runtime.engine import Engine

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=9)
    eng = Engine(spec, params, tp=1)
    ssc = SingleSlotCache(eng, PrefixCache(max_blocks=64, block_tokens=8))
    prompt = SHARED + [205]
    reuse = ssc.begin(prompt)
    assert reuse == 0
    ssc.invalidate()  # as the api error path would
    assert ssc.resident == [] and ssc.cache.radix.total_refs() == 0
    out, _ = eng.generate(list(prompt), 4, Sampler(spec.vocab_size,
                                                   temperature=0.0))
    ssc.end((prompt + out)[:eng.pos])
    assert ssc.cache.radix.nodes >= 4
