"""Fleet tier tests (ISSUE 6): the prefix-affinity router over 2 in-process
tiny replicas.

- AffinityMap unit/property tests against a brute-force longest-shared-prefix
  oracle (latest-wins per block, walk-up on dead replicas, LRU node cap);
- merge_prometheus label injection + family-header dedup;
- live fleet: shared-prefix requests route sticky to one replica, a draining
  or hard-killed replica is rerouted around with ZERO failed requests, a
  fully-drained fleet sheds with 503 + Retry-After, and streaming vs
  non-streaming parity holds through the proxy;
- membership poller: `router.health` fault injection ejects a replica for the
  round and it rejoins on the next clean poll (the poller thread survives).

Both replicas live in THIS process (two BatchEngines + two api_server
ThreadingHTTPServers on ephemeral ports), so the obs metrics registry is
shared between them — per-replica assertions therefore instrument the
engines directly (submit counters) instead of reading process-global
counters. Full subprocess-per-replica coverage is bench.py --replicas N
(docs/FLEET.md).
"""

import http.client
import json
import random
import threading

import pytest

from distributed_llama_tpu.apps.api_server import serve
from distributed_llama_tpu.fleet.affinity import AffinityMap
from distributed_llama_tpu.fleet.membership import Membership, parse_addr
from distributed_llama_tpu.fleet.router import (close_router, merge_prometheus,
                                                serve_router)
from distributed_llama_tpu.formats.mfile import load_model, params_file_order, write_model
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.resilience import faults
from distributed_llama_tpu.resilience.faults import FaultSpec
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.tokenizer import TemplateType
from distributed_llama_tpu.tokenizer.bpe import Tokenizer


# ----------------------------------------------------------------------
# AffinityMap vs brute-force oracle
# ----------------------------------------------------------------------

class OracleAffinity:
    """Reference semantics: every record stamps ALL full block-prefixes of its
    key with the replica (latest-wins); lookup returns the deepest stamped
    block-prefix of the query whose replica is alive."""

    def __init__(self, block_bytes: int):
        self.bb = block_bytes
        self.owner: dict[bytes, str] = {}

    def _prefixes(self, key: bytes):
        for d in range(self.bb, len(key) + 1, self.bb):
            yield key[:d]

    def record(self, key: bytes, replica: str) -> None:
        for p in self._prefixes(key):
            self.owner[p] = replica

    def lookup(self, key: bytes, alive: set[str]):
        best = (None, 0)
        for depth, p in enumerate(self._prefixes(key), start=1):
            rep = self.owner.get(p)
            if rep is None:
                break
            if rep in alive:
                best = (rep, depth)
        return best


def test_affinity_matches_oracle_randomized():
    rng = random.Random(7)
    bb = 4
    m = AffinityMap(block_bytes=bb, max_nodes=10_000)  # cap never hit here
    oracle = OracleAffinity(bb)
    replicas = ["r0", "r1", "r2"]
    # tiny alphabet + short keys force heavy prefix sharing
    for step in range(600):
        key = bytes(rng.choice(b"ab") for _ in range(rng.randrange(0, 20)))
        if rng.random() < 0.5:
            rep = rng.choice(replicas)
            m.record(key, rep)
            oracle.record(key, rep)
        else:
            alive = {r for r in replicas if rng.random() < 0.7}
            assert m.lookup(key, alive) == oracle.lookup(key, alive), (
                step, key, alive)


def test_affinity_walkup_on_dead_replica():
    m = AffinityMap(block_bytes=2, max_nodes=64)
    m.record(b"aabb", "r1")      # depth-2 chain owned by r1
    m.record(b"aa", "r2")        # depth-1 node re-stamped by r2 (latest wins)
    assert m.lookup(b"aabb", {"r1", "r2"}) == ("r1", 2)
    # r1 dead: walk up to the depth-1 ancestor instead of giving up
    assert m.lookup(b"aabb", {"r2"}) == ("r2", 1)
    assert m.lookup(b"aabb", set()) == (None, 0)
    # partial blocks never match (block granularity, like the replica cache)
    assert m.lookup(b"a", {"r1", "r2"}) == (None, 0)


def test_affinity_node_cap_lru():
    m = AffinityMap(block_bytes=1, max_nodes=8)
    for i in range(64):
        m.record(bytes([i]) * 3, f"r{i}")
    assert m.nodes() <= 8
    # the most recent record survived the LRU sweep
    assert m.lookup(bytes([63]) * 3, {"r63"})[0] == "r63"


# ----------------------------------------------------------------------
# merge_prometheus
# ----------------------------------------------------------------------

def test_merge_prometheus_labels_and_headers():
    own = "# HELP up router up\n# TYPE up gauge\nup 1\n"
    rep = ("# HELP http_total requests\n# TYPE http_total counter\n"
           'http_total{route="/x"} 3\nhttp_total 4\n')
    merged = merge_prometheus([(None, own), ("h1:1", rep), ("h2:2", rep)])
    lines = merged.splitlines()
    # router-own sample stays unlabeled; replica samples get replica="id"
    assert "up 1" in lines
    assert 'http_total{replica="h1:1",route="/x"} 3' in lines
    assert 'http_total{replica="h2:2"} 4' in lines
    # one HELP/TYPE per family even with two sources
    assert sum(ln.startswith("# HELP http_total") for ln in lines) == 1
    assert sum(ln.startswith("# TYPE http_total") for ln in lines) == 1


def test_parse_addr():
    assert parse_addr("127.0.0.1:9990") == ("127.0.0.1", 9990)
    with pytest.raises(ValueError):
        parse_addr("nope")
    with pytest.raises(ValueError):
        Membership([])


# ----------------------------------------------------------------------
# live fleet fixtures
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fleet")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=262,
                     seq_len=192).resolved()
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    return mpath, tpath


class ReplicaHarness:
    """One in-process api_server replica with a submit counter on its engine."""

    def __init__(self, model_files):
        mpath, tpath = model_files
        lspec, lparams = load_model(mpath, 0)
        self.be = BatchEngine(lspec, lparams, Tokenizer.load(tpath),
                              slots=2, tp=1)
        self.submits = 0
        orig = self.be.submit

        def counted(*a, **k):
            self.submits += 1
            return orig(*a, **k)

        self.be.submit = counted
        self.srv = serve(None, host="127.0.0.1", port=0,
                         template_type=TemplateType.CHATML, batch_engine=self.be)
        self.port = self.srv.server_address[1]
        self.id = f"127.0.0.1:{self.port}"
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()
        self.closed = False

    def kill(self):
        if not self.closed:
            self.closed = True
            self.srv.shutdown()
            self.srv.server_close()

    def close(self):
        self.kill()
        self.be.close()


@pytest.fixture(scope="module")
def fleet(model_files):
    reps = [ReplicaHarness(model_files) for _ in range(2)]
    router = serve_router([r.id for r in reps], host="127.0.0.1", port=0,
                          poll_interval=0.15, poll_timeout=2.0,
                          block_bytes=16, retries=2, try_timeout=60.0)
    rport = router.server_address[1]
    threading.Thread(target=router.serve_forever, daemon=True).start()
    yield {"replicas": reps, "router": router, "port": rport,
           "state": router.router_state}
    close_router(router)
    for r in reps:
        r.close()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    return conn.getresponse()


def _post(port, body, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/v1/chat/completions", json.dumps(body),
                 {"Content-Type": "application/json"})
    return conn.getresponse()


def _body(system, user, stream=False, max_tokens=4):
    return {"messages": [{"role": "system", "content": system},
                         {"role": "user", "content": user}],
            "max_tokens": max_tokens, "temperature": 0, "stream": stream}


def _read_sse_text(resp) -> str:
    """Collect content deltas from an SSE completion response."""
    assert "text/event-stream" in resp.getheader("Content-Type", "")
    text, raw = [], resp.read().decode()
    for line in raw.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        payload = json.loads(line[6:])
        assert "error" not in payload, payload
        delta = payload["choices"][0]["delta"]
        text.append(delta.get("content", ""))
    return "".join(text)


def _restore_rotation(fleet):
    """Undo any drain/kill a test left behind and re-poll membership."""
    for r in fleet["replicas"]:
        r.srv.api_state.draining = False
    fleet["state"].membership.poll_once()
    assert len(fleet["state"].membership.in_rotation()) == \
        sum(1 for r in fleet["replicas"] if not r.closed)


# ----------------------------------------------------------------------
# live fleet tests
# ----------------------------------------------------------------------

def test_replica_healthz_block_and_backcompat(fleet):
    """Satellite 1: /healthz keeps `status` (existing probes) and gains the
    identity/load block the membership poller consumes."""
    rep = fleet["replicas"][0]
    payload = json.loads(_get(rep.port, "/healthz").read())
    assert payload["status"] == "ok"  # the pre-fleet probe contract
    block = payload["replica"]
    assert block["id"] == rep.id
    assert block["slots"] == 2 and 0 <= block["free_slots"] <= 2
    assert block["queue_depth"] >= 0 and block["draining"] is False
    assert len(block["model_hash"]) == 12
    # /v1/stats carries the same block
    stats = json.loads(_get(rep.port, "/v1/stats").read())
    assert stats["replica"]["model_hash"] == block["model_hash"]


def test_router_healthz(fleet):
    payload = json.loads(_get(fleet["port"], "/healthz").read())
    assert payload["role"] == "router"
    assert payload["in_rotation"] == 2
    assert set(payload["replicas"]) == {r.id for r in fleet["replicas"]}


def test_shared_prefix_routes_sticky(fleet):
    """Requests sharing a system prompt land on ONE replica (affinity), and
    the streaming path records affinity too."""
    _restore_rotation(fleet)
    before = [r.submits for r in fleet["replicas"]]
    system = "You are a terse assistant. Answer in one word." * 2
    r0 = _post(fleet["port"], _body(system, "first"))
    assert r0.status == 200 and r0.read()
    for i in range(3):
        resp = _post(fleet["port"], _body(system, f"user {i}", stream=True))
        assert resp.status == 200
        _read_sse_text(resp)
    delta = [r.submits - b for r, b in zip(fleet["replicas"], before)]
    assert sorted(delta) == [0, 4], delta  # all four on the same replica
    # the router recorded the route and can look it up
    key = fleet["state"].affinity_key(_body(system, "another"))
    rep_id, depth = fleet["state"].affinity.lookup(
        key, {r.id for r in fleet["replicas"]})
    assert rep_id == fleet["replicas"][delta.index(4)].id and depth >= 1


def test_stream_nonstream_parity_through_router(fleet):
    _restore_rotation(fleet)
    body = _body("parity system prompt", "same question", max_tokens=6)
    r1 = _post(fleet["port"], body)
    assert r1.status == 200
    text1 = json.loads(r1.read())["choices"][0]["message"]["content"]
    r2 = _post(fleet["port"], dict(body, stream=True))
    assert r2.status == 200
    assert _read_sse_text(r2) == text1


def test_drain_reroutes_with_zero_failures(fleet):
    """Drain the replica that owns a shared prefix mid-fleet: every request
    still completes (failover to the survivor), and the affinity map follows
    the traffic to the new replica."""
    _restore_rotation(fleet)
    system = "Drain test system prompt, shared by all requests here."
    assert _post(fleet["port"], _body(system, "warm")).status == 200
    key = fleet["state"].affinity_key(_body(system, "x"))
    owner_id, _ = fleet["state"].affinity.lookup(
        key, {r.id for r in fleet["replicas"]})
    owner = next(r for r in fleet["replicas"] if r.id == owner_id)
    survivor = next(r for r in fleet["replicas"] if r.id != owner_id)
    owner.srv.api_state.draining = True  # SIGTERM's first effect
    try:
        fleet["state"].membership.poll_once()
        assert [r.id for r in fleet["state"].membership.in_rotation()] == \
            [survivor.id]
        before = survivor.submits
        for i in range(3):
            resp = _post(fleet["port"], _body(system, f"after-drain {i}",
                                              stream=(i % 2 == 0)))
            assert resp.status == 200, (i, resp.status, resp.read())
            _read_sse_text(resp) if i % 2 == 0 else resp.read()
        assert survivor.submits - before == 3
        # latest-wins: the prefix now maps to the survivor
        assert fleet["state"].affinity.lookup(
            key, {r.id for r in fleet["replicas"]})[0] == survivor.id
    finally:
        owner.srv.api_state.draining = False
        fleet["state"].membership.poll_once()
    assert len(fleet["state"].membership.in_rotation()) == 2  # rejoined


def test_saturated_fleet_sheds_503_retry_after(fleet):
    _restore_rotation(fleet)
    for r in fleet["replicas"]:
        r.srv.api_state.draining = True
    try:
        fleet["state"].membership.poll_once()
        assert fleet["state"].membership.in_rotation() == []
        resp = _post(fleet["port"], _body("any", "request"))
        assert resp.status == 503
        assert int(resp.getheader("Retry-After")) >= 1
        err = json.loads(resp.read())["error"]
        assert err["type"] in ("overloaded_error", "server_shutting_down")
        # router /healthz reflects the empty rotation
        assert _get(fleet["port"], "/healthz").status == 503
    finally:
        _restore_rotation(fleet)


def test_health_fault_point_ejects_then_rejoins(fleet):
    """router.health chaos: an injected poll error marks replicas unreachable
    for the round; the poller survives and readmits on the next clean poll."""
    _restore_rotation(fleet)
    mem = fleet["state"].membership
    with faults.active(FaultSpec("router.health", kind="error", count=2)):
        mem.poll_once()
        assert mem.in_rotation() == []
        assert all(r.status == "unreachable" for r in mem.replicas)
    mem.poll_once()
    assert len(mem.in_rotation()) == 2


def test_proxy_fault_point_fails_over(fleet):
    """router.proxy chaos on the first try: the request still completes on a
    different replica (pre-first-byte failover), counted as a retry."""
    _restore_rotation(fleet)
    with faults.active(FaultSpec("router.proxy", kind="error", count=1)):
        resp = _post(fleet["port"], _body("proxy fault system", "q"))
        assert resp.status == 200
        assert json.loads(resp.read())["choices"][0]["message"]["content"]


# ----------------------------------------------------------------------
# end-to-end request tracing (ISSUE 7 tentpole)
# ----------------------------------------------------------------------

def _post_traced(port, body, traceparent=None, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    headers = {"Content-Type": "application/json"}
    if traceparent:
        headers["traceparent"] = traceparent
    conn.request("POST", "/v1/chat/completions", json.dumps(body), headers)
    return conn.getresponse()


def test_trace_context_propagates_through_fleet_concurrently(fleet):
    """Satellite 3 acceptance: concurrent requests through the REAL
    2-replica fleet — every engine-side span/instant and every flight
    timeline carries exactly the trace id its request entered with, with no
    cross-request bleed even though one super-step serves many requests."""
    from distributed_llama_tpu.obs import flight as flight_mod
    from distributed_llama_tpu.obs import trace as trace_mod

    _restore_rotation(fleet)
    tr = trace_mod.install(capacity=65536)
    try:
        n = 6
        tids = [f"{i:02x}" * 16 for i in range(1, n + 1)]
        results = [None] * n

        def client(i):
            # distinct shared prefixes spread requests over both replicas
            resp = _post_traced(
                fleet["port"],
                _body(f"system prompt {i % 2}", f"traced user {i}",
                      max_tokens=5),
                traceparent=f"00-{tids[i]}-{'77' * 8}-01")
            rid = resp.getheader("X-Request-Id")
            rep = resp.getheader("X-Replica")
            status = resp.status
            resp.read()
            results[i] = {"status": status, "rid": rid, "replica": rep}

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert all(r and r["status"] == 200 for r in results), results
        # the router relays the replica's identity headers end-to-end
        assert all(r["rid"] and r["replica"] for r in results), results

        rec = flight_mod.current()
        assert rec is not None  # installed by serve()
        by_tid = {}
        for i, r in enumerate(results):
            full = rec.get(r["rid"])
            assert full is not None, r
            # the flight record carries the trace id the CLIENT sent — it
            # crossed router → replica handler → scheduler intact
            assert full["trace_id"] == tids[i], (i, full["trace_id"])
            assert full["finish"] in ("length", "stop")
            names = [e["event"] for e in full["events"]]
            assert "admitted" in names, names
            by_tid[tids[i]] = r["rid"]

        # tracer side: every event stamped with one of our trace ids must be
        # engine-side work (batch.*) or a router proxy span; each request
        # has at least one engine-side event; ids never mix
        evs = tr.events()
        per_tid = {t: [] for t in tids}
        for e in evs:
            t = (e.get("args") or {}).get("trace_id")
            if t in per_tid:
                per_tid[t].append(e["name"])
        for t, names in per_tid.items():
            assert any(nm.startswith("batch.") for nm in names), (t, names)
            assert "router.proxy" in names, (t, names)

        # the slow-request workflow works THROUGH the router: /v1/requests
        # lookups relay to the replica holding the record (clients may not
        # be able to reach replicas directly), listings merge per replica
        r0 = results[0]
        via_router = json.loads(
            _get(fleet["port"], f"/v1/requests/{r0['rid']}").read())
        assert via_router["id"] == r0["rid"]
        assert via_router["trace_id"] == tids[0]
        merged = json.loads(
            _get(fleet["port"], "/v1/requests?slowest=2").read())
        assert set(merged["replicas"]) == {r.id for r in fleet["replicas"]}
        miss = _get(fleet["port"], "/v1/requests/chatcmpl-nonexistent")
        assert miss.status == 404

        # fleet-merged /v1/trace: sources for the router AND both replicas,
        # distinct pids, our spans present (everything shares this process's
        # tracer here — the per-process separation is bench.py --replicas)
        doc = json.loads(_get(fleet["port"], "/v1/trace").read())
        procs = doc["otherData"]["processes"]
        assert len(procs) == 3 and len({p["pid"] for p in procs}) == 3
        assert {p["name"] for p in procs} == {
            "router", *(f"replica {r.id}" for r in fleet["replicas"])}
        stamped = {(e.get("args") or {}).get("trace_id")
                   for e in doc["traceEvents"]}
        assert set(tids) <= stamped
    finally:
        trace_mod.uninstall()


def test_fleet_stats_include_replica_process_identity(fleet):
    """Membership carries the replica's pid/uptime from /healthz into the
    router's snapshot (restart-loop visibility)."""
    _restore_rotation(fleet)
    payload = json.loads(_get(fleet["port"], "/healthz").read())
    import os

    for snap in payload["replicas"].values():
        assert snap["pid"] == os.getpid()  # in-process replicas
        assert snap["uptime_s"] > 0


def test_hard_kill_failover_zero_failures(fleet):
    """SIGKILL analog: close one replica's listener without telling anyone.
    The next requests hit a dead socket pre-first-byte and fail over; no
    client-visible failure. Runs LAST in the module: the killed replica's
    HTTP server is gone for good (its engine is closed by the fixture)."""
    _restore_rotation(fleet)
    system = "Hard kill shared system prompt for failover."
    assert _post(fleet["port"], _body(system, "warm")).status == 200
    key = fleet["state"].affinity_key(_body(system, "x"))
    owner_id, _ = fleet["state"].affinity.lookup(
        key, {r.id for r in fleet["replicas"]})
    owner = next(r for r in fleet["replicas"] if r.id == owner_id)
    survivor = next(r for r in fleet["replicas"] if r.id != owner_id)
    owner.kill()  # affinity still points at the corpse; membership is stale
    failures = []
    for i in range(4):
        resp = _post(fleet["port"], _body(system, f"post-kill {i}",
                                          stream=(i % 2 == 0)))
        if resp.status != 200:
            failures.append((i, resp.status, resp.read()))
        else:
            _read_sse_text(resp) if i % 2 == 0 else resp.read()
    assert failures == []
    # the proxy-path mark_failed ejected the corpse synchronously
    assert [r.id for r in fleet["state"].membership.in_rotation()] == \
        [survivor.id]
    # membership holds it unreachable on subsequent polls too
    fleet["state"].membership.poll_once()
    assert fleet["state"].membership.by_id(owner.id).status == "unreachable"


def test_router_metrics_merged_with_replica_labels(fleet):
    """Fleet /metrics: router-own families plus replica-labeled scrapes.
    (Both replicas share this process's registry, so per-replica VALUES are
    not meaningful here — bench.py --replicas covers that; the merge
    structure and labels are what this pins.)"""
    text = _get(fleet["port"], "/metrics").read().decode()
    assert "# TYPE router_routes_total counter" in text
    assert text.count("# TYPE router_routes_total counter") == 1
    alive = [r for r in fleet["replicas"] if not r.closed]
    for r in alive:
        assert f'replica="{r.id}"' in text
    # replica-side families arrive labeled
    assert 'api_http_requests_total{replica="' in text
    stats = json.loads(_get(fleet["port"], "/v1/stats").read())
    assert stats["router"]["policy"] == "affinity"
    for r in alive:
        assert stats["replicas"][r.id]["replica"]["model_hash"]


def test_unknown_routes_and_bad_json(fleet):
    assert _get(fleet["port"], "/nope").status == 404
    conn = http.client.HTTPConnection("127.0.0.1", fleet["port"], timeout=30)
    conn.request("POST", "/v1/chat/completions", b"{not json",
                 {"Content-Type": "application/json"})
    assert conn.getresponse().status == 400



# ----------------------------------------------------------------------
# Replica status atomicity (ISSUE 10 satellite: lock-guard pass finding)
# ----------------------------------------------------------------------

def test_replica_status_mutation_is_atomic():
    """Regression for a lock-guard finding (docs/ANALYSIS.md): Replica
    health/status used to be mutated bare from BOTH the membership poller
    thread and every proxy handler thread (`mark_failed`), so concurrent
    ejections could lose `consecutive_failures` increments (the backoff
    exponent input) and readers could observe torn states. All mutation now
    goes through `_lock`-holding Replica methods; this hammers them from 8
    threads and asserts exact counting plus never-torn snapshots."""
    from distributed_llama_tpu.fleet.membership import Replica

    rep = Replica("host", 1234)
    n_threads, n_iter = 8, 300
    torn: list[dict] = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            snap = rep.snapshot()
            # atomic invariant: healthy=True only ever coexists with "ok"
            # (apply_poll sets both in one critical section)
            if snap["healthy"] and snap["status"] != "ok":
                torn.append(snap)

    def hammer(k: int):
        barrier.wait()
        for i in range(n_iter):
            if (i + k) % 3 == 0:
                rep.apply_poll("ok", True, {"slots": 2, "free_slots": 1,
                                            "queue_depth": i})
            else:
                rep.mark_unreachable()

    barrier = threading.Barrier(n_threads)
    rt = threading.Thread(target=reader, daemon=True)
    rt.start()
    threads = [threading.Thread(target=hammer, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    rt.join(timeout=5)
    assert not torn, f"torn replica snapshots observed: {torn[:3]}"

    # exact increment accounting: with bare `+= 1` from N threads, CPython's
    # read-modify-write interleaving can lose updates; under the lock the
    # count is exact
    rep2 = Replica("host", 4321)
    barrier = threading.Barrier(n_threads)

    def eject():
        barrier.wait()
        for _ in range(n_iter):
            rep2.mark_unreachable()

    threads = [threading.Thread(target=eject) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rep2.consecutive_failures == n_threads * n_iter
    assert rep2.status == "unreachable" and not rep2.healthy
