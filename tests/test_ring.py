"""Ring attention / sequence parallelism tests (8-device CPU mesh).

The reference has NO sequence parallelism (SURVEY.md §5); these tests hold the new
capability to the same standard as its TP tests: sharded execution must equal unsharded
execution (the commands-test pattern, src/commands-test.cpp:6-79)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.attention import gqa_attention, update_kv_cache
from distributed_llama_tpu.ops.ring_attention import (
    ring_attention,
    update_kv_cache_sharded,
)
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.parallel.mesh import make_mesh
from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache, make_sharded_forward,
                                                shard_params)
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.sampler import Sampler
from distributed_llama_tpu.compat import shard_map


@pytest.mark.parametrize("sp", [2, 4])
@pytest.mark.parametrize("t", [1, 5])
def test_ring_attention_equals_full(sp, t):
    """Ring attention over sp sequence shards == plain attention over the full cache."""
    rng = np.random.RandomState(0)
    b, hq, hk, s, hs = 1, 8, 4, 32, 16
    pos0 = 11  # queries at positions 11..11+t
    q = jnp.asarray(rng.randn(b, t, hq, hs).astype(np.float32))
    kc = jnp.asarray(rng.randn(b, hk, s, hs).astype(np.float32))
    vc = jnp.asarray(rng.randn(b, hk, s, hs).astype(np.float32))
    positions = pos0 + jnp.arange(t, dtype=jnp.int32)

    want = np.asarray(gqa_attention(q, kc, vc, positions))

    mesh = make_mesh(sp=sp, tp=1)

    def f(q, kc, vc):
        return ring_attention(q, kc, vc, positions, axis_name="sp", axis_size=sp)

    sharded = jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(P(), P(None, None, "sp", None), P(None, None, "sp", None)),
        out_specs=P(), check_vma=False))
    got = np.asarray(sharded(q, kc, vc))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("t,start", [(1, 0), (1, 17), (8, 12), (8, 16)])
def test_update_kv_cache_sharded_matches_full(t, start):
    """Sharded cache writes (incl. chunks straddling a shard boundary) == full-cache
    update then manual sharding."""
    rng = np.random.RandomState(1)
    b, hk, s, hs, sp = 1, 2, 32, 8, 4
    kc = jnp.asarray(rng.randn(b, hk, s, hs).astype(np.float32))
    vc = jnp.asarray(rng.randn(b, hk, s, hs).astype(np.float32))
    k_new = jnp.asarray(rng.randn(b, t, hk, hs).astype(np.float32))
    v_new = jnp.asarray(rng.randn(b, t, hk, hs).astype(np.float32))

    kw, vw = update_kv_cache(kc, vc, k_new, v_new, jnp.int32(start))

    mesh = make_mesh(sp=sp, tp=1)
    kvp = P(None, None, "sp", None)

    def f(kc, vc, k_new, v_new):
        return update_kv_cache_sharded(kc, vc, k_new, v_new, jnp.int32(start),
                                       axis_name="sp")

    sharded = jax.jit(shard_map(f, mesh=mesh, in_specs=(kvp, kvp, P(), P()),
                                out_specs=(kvp, kvp), check_vma=False))
    kg, vg = sharded(kc, vc, k_new, v_new)
    np.testing.assert_allclose(np.asarray(kg), np.asarray(kw), atol=1e-6)
    np.testing.assert_allclose(np.asarray(vg), np.asarray(vw), atol=1e-6)


def _tiny_spec():
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=256, seq_len=32,
                     rope_type=RopeType.LLAMA).resolved()


@pytest.mark.parametrize("cache_write", ["inscan", "deferred"])
def test_forward_sp_tp_equals_unsharded(cache_write):
    """Full model on a 2x2 (sp x tp) mesh == single-device forward: prefill then a
    decode step continuing from the sharded cache. Both cache disciplines — the
    deferred form keeps the sequence-sharded caches loop-invariant and commits via
    the masked window write (commit_kv_rows_sharded)."""
    spec = _tiny_spec()
    params = init_random_params(spec, FloatType.F32, seed=3)
    rope = RopeTables.create(spec)
    tokens = jnp.asarray([[1, 7, 23, 5, 2, 9, 11, 4]])

    kc, vc = init_kv_cache(spec)
    want, wkc, wvc = forward(params, spec, rope, tokens, kc, vc, jnp.int32(0))
    want2, _, _ = forward(params, spec, rope, jnp.asarray([[3]]), wkc, wvc,
                          jnp.int32(8))

    mesh = make_mesh(sp=2, tp=2)
    sparams = shard_params(params, mesh, spec)
    step = make_sharded_forward(spec, mesh, sparams, donate_cache=False,
                                cache_write=cache_write)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, gkc, gvc = step(sparams, rope, tokens, kc, vc, jnp.int32(0))
    got2, _, _ = step(sparams, rope, jnp.asarray([[3]]), gkc, gvc, jnp.int32(8))

    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2), atol=2e-4,
                               rtol=1e-3)


def _destripe(cache: np.ndarray, sp: int) -> np.ndarray:
    """Undo the striped sp layout: member m's local slot j holds position
    j*sp + m (ops/ring_attention.py); the GLOBAL array concatenates members'
    shards, so array index m*Sb + j -> position j*sp + m."""
    L, B, hk, S, hs = cache.shape
    sb = S // sp
    out = np.zeros_like(cache)
    for m in range(sp):
        for j in range(sb):
            out[:, :, :, j * sp + m] = cache[:, :, :, m * sb + j]
    return out


def test_sp_deferred_cache_state_matches_inscan():
    """After prefill + a boundary-straddling chunk + a decode step, the deferred
    (striped) cache must hold the same committed rows as inscan once the stripe
    permutation is undone."""
    spec = _tiny_spec()  # seq_len=32, sp=2 -> shard size 16
    params = init_random_params(spec, FloatType.F32, seed=9)
    rope = RopeTables.create(spec)
    mesh = make_mesh(sp=2, tp=2)
    sparams = shard_params(params, mesh, spec)

    caches = {}
    for cw in ("inscan", "deferred"):
        step = make_sharded_forward(spec, mesh, sparams, donate_cache=False,
                                    cache_write=cw)
        kc, vc = init_sharded_kv_cache(spec, mesh)
        # prefill 12, then a 8-token chunk at 12..20 (straddles the shard
        # boundary at 16), then a decode step at 20
        _, kc, vc = step(sparams, rope, jnp.asarray([list(range(1, 13))]), kc, vc,
                         jnp.int32(0))
        _, kc, vc = step(sparams, rope, jnp.asarray([list(range(20, 28))]), kc, vc,
                         jnp.int32(12))
        _, kc, vc = step(sparams, rope, jnp.asarray([[3]]), kc, vc, jnp.int32(20))
        caches[cw] = (np.asarray(kc), np.asarray(vc))

    kd = _destripe(caches["deferred"][0], sp=2)
    vd = _destripe(caches["deferred"][1], sp=2)
    # committed region [0, 21) must agree exactly; beyond it is unwritten scratch
    np.testing.assert_allclose(kd[:, :, :, :21],
                               caches["inscan"][0][:, :, :, :21], atol=1e-6)
    np.testing.assert_allclose(vd[:, :, :, :21],
                               caches["inscan"][1][:, :, :, :21], atol=1e-6)


def test_sp_deferred_chunk_wider_than_shard():
    """sp=4 on seq_len=32 gives 8-slot shards; a 16-token prefill chunk is wider
    than a shard — the deferred commit must scatter it across multiple shards
    (regression: the windowed write only handles t <= shard size)."""
    spec = _tiny_spec()  # seq_len=32 -> sb=8 at sp=4
    params = init_random_params(spec, FloatType.F32, seed=4)
    rope = RopeTables.create(spec)
    tokens = jnp.asarray([[(i % 200) + 1 for i in range(16)]])

    kc, vc = init_kv_cache(spec)
    want, wkc, wvc = forward(params, spec, rope, tokens, kc, vc, jnp.int32(0))
    want2, _, _ = forward(params, spec, rope, jnp.asarray([[3]]), wkc, wvc,
                          jnp.int32(16))

    mesh = make_mesh(sp=4, tp=2)
    sparams = shard_params(params, mesh, spec)
    step = make_sharded_forward(spec, mesh, sparams, donate_cache=False,
                                cache_write="deferred")
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, gkc, gvc = step(sparams, rope, tokens, kc, vc, jnp.int32(0))
    got2, _, _ = step(sparams, rope, jnp.asarray([[3]]), gkc, gvc, jnp.int32(16))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4,
                               rtol=1e-3)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2), atol=2e-4,
                               rtol=1e-3)


def test_sp_deferred_windowed_ring_matches_full():
    """Striped windowed ring: with attn_window=32 on a seq_len=64 cache, only
    ceil(32/sp)=16 slots per member rotate, and results must equal the
    unsharded forward while every live position is inside the window."""
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=256, seq_len=64,
                     rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.F32, seed=6)
    rope = RopeTables.create(spec)
    tokens = jnp.asarray([[1, 7, 23, 5, 2, 9, 11, 4]])

    kc, vc = init_kv_cache(spec)
    want, wkc, wvc = forward(params, spec, rope, tokens, kc, vc, jnp.int32(0))
    want2, _, _ = forward(params, spec, rope, jnp.asarray([[3]]), wkc, wvc,
                          jnp.int32(8))

    mesh = make_mesh(sp=2, tp=2)
    sparams = shard_params(params, mesh, spec)
    step = make_sharded_forward(spec, mesh, sparams, donate_cache=False,
                                cache_write="deferred", attn_window=32)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, gkc, gvc = step(sparams, rope, tokens, kc, vc, jnp.int32(0))
    got2, _, _ = step(sparams, rope, jnp.asarray([[3]]), gkc, gvc, jnp.int32(8))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4,
                               rtol=1e-3)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2), atol=2e-4,
                               rtol=1e-3)


def test_engine_generate_with_sp():
    """End-to-end greedy generation with sequence parallelism == tp-only engine."""
    spec = _tiny_spec()
    params = init_random_params(spec, FloatType.Q40, seed=5)
    sampler = Sampler(spec.vocab_size, temperature=0.0)
    prompt = [1, 9, 4]

    ref = Engine(spec, params, tp=1)
    want, _ = ref.generate(list(prompt), 10, sampler)

    eng = Engine(spec, params, tp=2, sp=2)
    got, _ = eng.generate(list(prompt), 10, sampler)
    assert got == want

    eng.reset()
    got2, _ = eng.generate_chunked(list(prompt), 10, sampler, chunk=4)
    assert got2 == want
