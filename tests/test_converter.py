"""Converter tests: synthetic HF checkpoints -> .m -> load -> numerically verified."""

import json
import struct

import numpy as np
import pytest

from distributed_llama_tpu.converter.convert_hf import convert as convert_hf
from distributed_llama_tpu.converter.convert_hf import permute_rotary
from distributed_llama_tpu.converter.convert_tokenizer import (
    convert_llama3,
    parse_sentencepiece_model,
)
from distributed_llama_tpu.formats.mfile import load_model
from distributed_llama_tpu.formats.tfile import load_tokenizer
from distributed_llama_tpu.quants import FloatType

torch = pytest.importorskip("torch")


def make_hf_llama_dir(tmp_path, dim=64, hidden=96, layers=2, heads=4, kv_heads=2,
                      vocab=128, moe=False, tied=False):
    from safetensors.torch import save_file

    cfg = {
        "model_type": "mixtral" if moe else "llama",
        "hidden_size": dim, "intermediate_size": hidden, "num_hidden_layers": layers,
        "num_attention_heads": heads, "num_key_value_heads": kv_heads,
        "vocab_size": vocab, "max_position_embeddings": 512,
        "hidden_act": "silu", "rope_theta": 10000.0,
    }
    if moe:
        cfg.update(num_local_experts=4, num_experts_per_tok=2)
    (tmp_path / "config.json").write_text(json.dumps(cfg))

    rng = np.random.RandomState(5)

    def t(*shape):
        return torch.from_numpy(rng.randn(*shape).astype(np.float32) * 0.05)

    kv_dim = dim * kv_heads // heads
    tensors = {"model.embed_tokens.weight": t(vocab, dim),
               "model.norm.weight": t(dim)}
    if not tied:
        tensors["lm_head.weight"] = t(vocab, dim)
    for l in range(layers):
        p = f"model.layers.{l}"
        tensors[f"{p}.self_attn.q_proj.weight"] = t(dim, dim)
        tensors[f"{p}.self_attn.k_proj.weight"] = t(kv_dim, dim)
        tensors[f"{p}.self_attn.v_proj.weight"] = t(kv_dim, dim)
        tensors[f"{p}.self_attn.o_proj.weight"] = t(dim, dim)
        tensors[f"{p}.input_layernorm.weight"] = t(dim)
        tensors[f"{p}.post_attention_layernorm.weight"] = t(dim)
        if moe:
            tensors[f"{p}.block_sparse_moe.gate.weight"] = t(4, dim)
            for e in range(4):
                ep = f"{p}.block_sparse_moe.experts.{e}"
                tensors[f"{ep}.w1.weight"] = t(hidden, dim)
                tensors[f"{ep}.w2.weight"] = t(dim, hidden)
                tensors[f"{ep}.w3.weight"] = t(hidden, dim)
        else:
            tensors[f"{p}.mlp.gate_proj.weight"] = t(hidden, dim)
            tensors[f"{p}.mlp.down_proj.weight"] = t(dim, hidden)
            tensors[f"{p}.mlp.up_proj.weight"] = t(hidden, dim)
    save_file(tensors, str(tmp_path / "model.safetensors"))
    return tensors, cfg


def test_convert_hf_dense(tmp_path):
    tensors, cfg = make_hf_llama_dir(tmp_path)
    out = str(tmp_path / "out.m")
    spec = convert_hf(str(tmp_path), FloatType.F32, out)
    assert spec.dim == 64 and spec.n_layers == 2

    spec2, params = load_model(out)
    # permutation applied to q/k only
    wq_hf = tensors["model.layers.0.self_attn.q_proj.weight"].numpy()
    np.testing.assert_allclose(params["blocks"]["wq"].to_numpy()[0],
                               permute_rotary(wq_hf, 4), atol=1e-6)
    wv_hf = tensors["model.layers.0.self_attn.v_proj.weight"].numpy()
    np.testing.assert_allclose(params["blocks"]["wv"].to_numpy()[0], wv_hf, atol=1e-6)
    # w1 = gate, w2 = down, w3 = up
    np.testing.assert_allclose(
        params["blocks"]["w1"].to_numpy()[0],
        tensors["model.layers.0.mlp.gate_proj.weight"].numpy(), atol=1e-6)
    np.testing.assert_allclose(
        params["blocks"]["w2"].to_numpy()[0],
        tensors["model.layers.0.mlp.down_proj.weight"].numpy(), atol=1e-6)


def test_convert_hf_moe_includes_router(tmp_path):
    tensors, cfg = make_hf_llama_dir(tmp_path, moe=True)
    out = str(tmp_path / "out.m")
    spec = convert_hf(str(tmp_path), FloatType.F32, out)
    assert spec.n_experts == 4 and spec.n_active_experts == 2
    _, params = load_model(out)
    np.testing.assert_allclose(
        params["blocks"]["router"].to_numpy()[0],
        tensors["model.layers.0.block_sparse_moe.gate.weight"].numpy(), atol=1e-6)
    # expert order: up(w3), gate(w1), down(w2)
    np.testing.assert_allclose(
        params["blocks"]["moe_up"].to_numpy()[0, 1],
        tensors["model.layers.0.block_sparse_moe.experts.1.w3.weight"].numpy(), atol=1e-6)


def test_convert_hf_tied_embeddings(tmp_path):
    tensors, _ = make_hf_llama_dir(tmp_path, tied=True)
    out = str(tmp_path / "out.m")
    convert_hf(str(tmp_path), FloatType.F32, out)
    _, params = load_model(out)
    np.testing.assert_allclose(params["wcls"].to_numpy(),
                               tensors["model.embed_tokens.weight"].numpy(), atol=1e-6)


def test_convert_hf_rope_scaling(tmp_path):
    _, cfg = make_hf_llama_dir(tmp_path)
    cfg["rope_scaling"] = {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
                           "high_freq_factor": 4.0,
                           "original_max_position_embeddings": 8192}
    (tmp_path / "config.json").write_text(json.dumps(cfg))
    out = str(tmp_path / "out.m")
    spec = convert_hf(str(tmp_path), FloatType.F32, out)
    spec2, _ = load_model(out)
    from distributed_llama_tpu.models.spec import RopeType

    assert spec2.rope_type == RopeType.LLAMA3_1
    assert spec2.rope_scaling_factor == 8.0
    assert spec2.rope_scaling_orig_max_seq_len == 8192


def test_converted_model_runs(tmp_path):
    """Converted checkpoint actually decodes (forward produces finite logits)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.forward import forward, init_kv_cache
    from distributed_llama_tpu.ops.rope import RopeTables

    make_hf_llama_dir(tmp_path)
    out = str(tmp_path / "out.m")
    convert_hf(str(tmp_path), FloatType.Q40, out)
    spec, params = load_model(out)
    rope = RopeTables.create(spec)
    kc, vc = init_kv_cache(spec)
    logits, _, _ = forward(params, spec, rope, jnp.asarray([[1, 2]]), kc, vc, jnp.int32(0))
    assert np.isfinite(np.asarray(logits)).all()


# ---------------------------------------------------------------------------
# tokenizer converters
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _sp_piece(piece: bytes, score: float) -> bytes:
    inner = (bytes([0x0A]) + _varint(len(piece)) + piece  # field 1, wire 2
             + bytes([0x15]) + struct.pack("<f", score))  # field 2, wire 5
    return bytes([0x0A]) + _varint(len(inner)) + inner  # outer field 1


def test_parse_sentencepiece_model(tmp_path):
    data = _sp_piece(b"<unk>", 0.0) + _sp_piece("▁he".encode(), -1.5) + \
        _sp_piece(b"llo", -2.0)
    path = tmp_path / "tokenizer.model"
    path.write_bytes(data)
    pieces, scores = parse_sentencepiece_model(str(path))
    assert pieces == [b"<unk>", "▁he".encode(), b"llo"]
    assert scores == [0.0, -1.5, -2.0]


def test_convert_llama3_tiktoken(tmp_path):
    import base64

    lines = []
    for i, tok in enumerate([b"a", b"b", b"ab", b" hello"]):
        lines.append(base64.b64encode(tok) + b" " + str(i).encode())
    (tmp_path / "tokenizer.model").write_bytes(b"\n".join(lines))
    out = str(tmp_path / "out.t")
    convert_llama3(str(tmp_path), out)
    td = load_tokenizer(out)
    assert td.vocab[:4] == [b"a", b"b", b"ab", b" hello"]
    assert td.vocab[td.bos_id] == b"<|begin_of_text|>"
    assert td.vocab[td.chat_eos_id] == b"<|eot_id|>"
    assert len(td.vocab) == 4 + 256
    assert "<|start_header_id|>" in td.chat_template
