"""Native (C++) host-runtime library tests: must agree exactly with the numpy/Python
fallbacks, which the format/tokenizer golden tests tie to the reference encoding."""

import numpy as np
import pytest

from distributed_llama_tpu import native
from distributed_llama_tpu.formats.tfile import TokenizerData
from distributed_llama_tpu.quants import (
    _Q40_STRUCT,
    _Q80_STRUCT,
    FloatType,
    QTensor,
    quantize_q40,
    quantize_q80,
)
from distributed_llama_tpu.tokenizer.bpe import Tokenizer

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_q40_deinterleave_matches_numpy():
    rng = np.random.RandomState(0)
    packed, scales = quantize_q40(rng.randn(8, 256).astype(np.float32))
    nb = packed.shape[0] * packed.shape[1]
    out = np.empty(nb, dtype=_Q40_STRUCT)
    out["d"] = scales.reshape(nb)
    out["qs"] = packed.reshape(nb, 16)
    buf = out.tobytes()

    qs, d = native.q40_deinterleave(buf, nb)
    np.testing.assert_array_equal(qs, packed.reshape(nb, 16))
    np.testing.assert_array_equal(d, scales.reshape(nb))


def test_q80_deinterleave_matches_numpy():
    rng = np.random.RandomState(1)
    vals, scales = quantize_q80(rng.randn(4, 320).astype(np.float32))
    nb = vals.shape[0] * vals.shape[1]
    out = np.empty(nb, dtype=_Q80_STRUCT)
    out["d"] = scales.reshape(nb)
    out["qs"] = vals.reshape(nb, 32)
    buf = out.tobytes()

    qs, d = native.q80_deinterleave(buf, nb)
    np.testing.assert_array_equal(qs, vals.reshape(nb, 32))
    np.testing.assert_array_equal(d, scales.reshape(nb))


def test_q40_to_i8_matches_python():
    rng = np.random.RandomState(2)
    w = QTensor.from_float(rng.randn(16, 512).astype(np.float32), FloatType.Q40)
    got = native.q40_to_i8(np.asarray(w.data), np.asarray(w.scales))
    assert got is not None
    vals, scales = got

    # force the numpy fallback by computing it inline
    packed = np.asarray(w.data)
    lo = (packed & 0x0F).astype(np.int8) - 8
    hi = (packed >> 4).astype(np.int8) - 8
    want_vals = np.concatenate([lo, hi], axis=-1).reshape(16, 512)
    np.testing.assert_array_equal(vals, want_vals)
    np.testing.assert_allclose(scales, np.asarray(w.scales, np.float32), rtol=0,
                               atol=0)


def test_f16_scale_conversion_exact():
    """f16->f32 in C++ must match numpy bit-for-bit, incl. subnormals and zeros."""
    specials = np.asarray([0.0, -0.0, 1.0, -1.5, 6.1e-5, 5.9e-8, 65504.0, -65504.0],
                          np.float16)
    rng = np.random.RandomState(3)
    vals = np.concatenate([specials, rng.randn(1000).astype(np.float16)])
    packed = np.zeros((len(vals), 16), np.uint8)  # zero nibbles -> vals*(-8) pattern
    got = native.q40_to_i8(packed.reshape(len(vals), 1, 16),
                           vals.reshape(len(vals), 1))
    np.testing.assert_array_equal(got[1].reshape(-1), vals.astype(np.float32))


def _toy_tokenizer() -> Tokenizer:
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)]
    vocab += [b" ", b"he", b"ll", b"o", b"hell", b"hello", b" hello", b"\xc3\xa9"]
    scores = [0.0] * 259 + [-1.0, -2.0, -2.5, -1.5, -3.0, -4.0, -5.0, -1.0]
    return Tokenizer(TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2,
                                   max_token_length=8))


@pytest.mark.parametrize("text", ["hello", " hello world", "", "héllo",
                                  "hello hello hello", "\x00\x01"])
def test_native_bpe_matches_python(text):
    t_native = _toy_tokenizer()
    assert t_native._native_bpe() is not None

    t_py = _toy_tokenizer()
    t_py._native_tried = True  # force the pure-Python path

    for bos, eos in ((True, False), (False, True), (True, True)):
        assert t_native.encode(text, bos, eos) == t_py.encode(text, bos, eos), text


def test_native_q40_to_i4p_matches_numpy():
    """The C++ i4p repack must produce bytes identical to the numpy path, including
    per-column-group packing."""
    from distributed_llama_tpu import native
    from distributed_llama_tpu.quants import FloatType, QTensor

    if native.q40_to_i4p(np.zeros((1, 2, 16), np.uint8)) is None:
        pytest.skip("native library unavailable")
    rng = np.random.RandomState(5)
    w = QTensor.from_float(rng.randn(8, 256).astype(np.float32), FloatType.Q40)
    for g in (1, 2, 4):
        nat = native.q40_to_i4p(np.asarray(w.data), g)
        # compare against the SHIPPED numpy fallback (not a frozen re-implementation):
        # disable the native fast path inside to_i4p_layout for the expected value
        real = native.q40_to_i4p
        try:
            native.q40_to_i4p = lambda *a, **k: None
            want = w.to_i4p_layout(col_groups=g)
        finally:
            native.q40_to_i4p = real
        np.testing.assert_array_equal(nat, np.asarray(want.data))
        # and the layout must round-trip to the same values either way
        np.testing.assert_array_equal(w.to_i4p_layout(col_groups=g).to_numpy(),
                                      want.to_numpy())
