"""Pallas q8 kernel tests (interpret mode on the CPU mesh).

The fused int8-plane matvec must agree with the planar jnp path (which the golden tests
tie to the numpy oracle): i8 layout round-trip, TP slicing of the layout along both axes,
the matvec against the dequant oracle, and the full forward pass with prepared params.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import init_random_params, prepare_for_pallas
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.pallas_q8 import q8_matvec
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import QK, FloatType, QTensor


def _to_jnp(t: QTensor) -> QTensor:
    return jax.tree_util.tree_map(jnp.asarray, t)


@pytest.mark.parametrize("ftype", [FloatType.Q40, FloatType.Q80])
def test_i8_layout_roundtrip(ftype):
    rng = np.random.RandomState(3)
    w = QTensor.from_float(rng.randn(64, 256).astype(np.float32), ftype)
    wi = w.to_i8_layout()
    np.testing.assert_allclose(wi.to_numpy(), w.to_numpy(), atol=1e-7)
    np.testing.assert_allclose(np.asarray(wi.dequantize(jnp.float32)), w.to_numpy(),
                               atol=1e-6)


def test_i8_layout_slices_both_axes():
    """Row (out) and col (in) slices of the i8 layout dequantize to the matching slices
    of the full tensor — the property TP sharding relies on (no per-shard segmenting)."""
    rng = np.random.RandomState(4)
    n, k, shards = 16, 512, 4
    w = QTensor.from_float(rng.randn(n, k).astype(np.float32), FloatType.Q40)
    wi = w.to_i8_layout()
    full = w.to_numpy()
    for s in range(shards):
        row = QTensor(wi.ftype, wi.data[s * (n // shards):(s + 1) * (n // shards)],
                      wi.scales[s * (n // shards):(s + 1) * (n // shards)], layout="i8")
        np.testing.assert_allclose(row.to_numpy(),
                                   full[s * (n // shards):(s + 1) * (n // shards)],
                                   atol=1e-7)
        kl, nbl = k // shards, (k // QK) // shards
        col = QTensor(wi.ftype, wi.data[:, s * kl:(s + 1) * kl],
                      wi.scales[:, s * nbl:(s + 1) * nbl], layout="i8")
        np.testing.assert_allclose(col.to_numpy(), full[:, s * kl:(s + 1) * kl],
                                   atol=1e-7)


def test_q8_matvec_precise_interpret():
    """f32 activations take the precise path: must match the dequant-matmul oracle."""
    rng = np.random.RandomState(6)
    n, k = 128, 512
    w = QTensor.from_float((rng.randn(n, k) * 0.05).astype(np.float32), FloatType.Q40)
    wi = _to_jnp(w.to_i8_layout())
    x = jnp.asarray(rng.randn(1, k).astype(np.float32))
    want = np.asarray(x) @ w.to_numpy().T
    got = np.asarray(q8_matvec(x, wi, interpret=True, precise=True))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_q8_matvec_int8_interpret():
    """bf16 activations take the Q80-quantized int8 MXU path: same numerics as the
    reference's Q40xQ80 kernel (activations rounded per-32-block to int8)."""
    rng = np.random.RandomState(7)
    n, k = 128, 512
    w = QTensor.from_float((rng.randn(n, k) * 0.05).astype(np.float32), FloatType.Q40)
    wi = _to_jnp(w.to_i8_layout())
    x = jnp.asarray(rng.randn(1, k).astype(np.float32)).astype(jnp.bfloat16)
    want = np.asarray(x, np.float32) @ w.to_numpy().T
    got = np.asarray(q8_matvec(x, wi, interpret=True), np.float32)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.02, rel  # Q80 activation quantization error


def test_q8_matvec_requires_i8_layout():
    w = QTensor.from_float(np.ones((8, 64), np.float32), FloatType.Q40)
    with pytest.raises(ValueError, match="i8-layout"):
        q8_matvec(jnp.ones((1, 64)), w, interpret=True)


def test_forward_with_pallas_params():
    """Full dense forward with prepare_for_pallas'd weights (interpret mode). T=1 decode
    exercises the kernel (int8 Q80-quantized activations, so compare at Q80 error
    scale); the T=3 prefill goes through the XLA dequant path and matches tightly."""
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=16,
                     rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=7)
    rope = RopeTables.create(spec)
    pparams = prepare_for_pallas(params)

    for tokens, rel_tol in ((jnp.asarray([[1, 2, 3]]), 1e-5), (jnp.asarray([[5]]), 0.03)):
        kc, vc = init_kv_cache(spec)
        want, _, _ = forward(params, spec, rope, tokens, kc, vc, jnp.int32(0))
        kc, vc = init_kv_cache(spec)
        got, _, _ = forward(pparams, spec, rope, tokens, kc, vc, jnp.int32(0),
                            use_pallas=True)
        got, want = np.asarray(got), np.asarray(want)
        rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
        assert rel < rel_tol, rel
