"""Pallas Q40 kernel tests (interpret mode on the CPU mesh).

The fused dequant-matmul must agree with the planar jnp path (which the golden tests tie
to the numpy oracle), including: the block-strided tpu layout round-trip, shard-aware
repacking for col-parallel slices, and the full forward pass with prepared params.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import init_random_params, prepare_for_pallas
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.pallas_q40 import q40_matmul
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import (
    FloatType,
    QTensor,
    dequantize_q40_tpu,
    permute_activations_tpu,
    q40_repack_tpu,
)


def test_tpu_layout_roundtrip():
    rng = np.random.RandomState(3)
    w = QTensor.from_float(rng.randn(64, 256).astype(np.float32), FloatType.Q40)
    wt = w.to_tpu_layout()
    np.testing.assert_allclose(wt.to_numpy(), w.to_numpy(), atol=1e-7)
    # jnp dequant of tpu layout matches too
    np.testing.assert_allclose(np.asarray(wt.dequantize(jnp.float32)), w.to_numpy(),
                               atol=1e-6)


def test_tpu_layout_sharded_roundtrip():
    """Repack with n_shards, slice along the packed axis, dequantize each shard
    standalone — must equal the matching natural-order columns (the property col-parallel
    TP relies on)."""
    rng = np.random.RandomState(4)
    n, k, shards = 16, 512, 4
    w = QTensor.from_float(rng.randn(n, k).astype(np.float32), FloatType.Q40)
    full = w.to_numpy()
    packed2 = q40_repack_tpu(np.asarray(w.data), np.asarray(w.scales), n_shards=shards)
    for s in range(shards):
        pk = packed2[:, s * (k // 2 // shards):(s + 1) * (k // 2 // shards)]
        sc = np.asarray(w.scales)[:, s * (k // 32 // shards):(s + 1) * (k // 32 // shards)]
        got = dequantize_q40_tpu(pk, sc.astype(np.float32))
        want = full[:, s * (k // shards):(s + 1) * (k // shards)]
        np.testing.assert_allclose(got, want, atol=1e-7)


def test_activation_permutation_inverse():
    """x_perm contracted against the *permuted-order* weights == natural x · W."""
    rng = np.random.RandomState(5)
    nb = 256 // 32
    x = rng.randn(3, 256).astype(np.float32)
    w = QTensor.from_float(rng.randn(8, 256).astype(np.float32), FloatType.Q40)
    wt = w.to_tpu_layout()
    xp = np.asarray(permute_activations_tpu(x, nb))
    # permuted-order dequant, as the kernel sees it: natural cols permuted by c=i*nb+b
    w_nat = w.to_numpy()
    w_perm = np.asarray(permute_activations_tpu(w_nat, nb))
    np.testing.assert_allclose(xp @ w_perm.T, x @ w_nat.T, atol=1e-5)


@pytest.mark.parametrize("m", [1, 3, 8])
def test_q40_matmul_interpret(m):
    rng = np.random.RandomState(6)
    n, k = 128, 512
    w = QTensor.from_float((rng.randn(n, k) * 0.05).astype(np.float32), FloatType.Q40)
    wt = jax.tree_util.tree_map(jnp.asarray, w.to_tpu_layout())
    x = jnp.asarray(rng.randn(m, k).astype(np.float32))
    want = np.asarray(x) @ w.to_numpy().T
    got = np.asarray(q40_matmul(x, wt, interpret=True, precise=True))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_q40_matmul_requires_tpu_layout():
    w = QTensor.from_float(np.ones((8, 64), np.float32), FloatType.Q40)
    with pytest.raises(ValueError, match="tpu-layout"):
        q40_matmul(jnp.ones((1, 64)), w, interpret=True)


def test_forward_with_pallas_params():
    """Full dense forward with prepare_for_pallas'd weights (interpret mode)."""
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=16,
                     rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=7)
    rope = RopeTables.create(spec)
    tokens = jnp.asarray([[1, 2, 3]])

    kc, vc = init_kv_cache(spec)
    want, _, _ = forward(params, spec, rope, tokens, kc, vc, jnp.int32(0))

    pparams = prepare_for_pallas(params)
    kc, vc = init_kv_cache(spec)
    got, _, _ = forward(pparams, spec, rope, tokens, kc, vc, jnp.int32(0),
                        use_pallas=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-3)
