"""Pipelined super-step scheduler tests (8-device CPU mesh via conftest).

The pipelined scheduler (runtime/batch_engine.py, docs/SERVING.md "Pipelined
decode") eagerly issues super-step N+1 chained from N's device-resident carry
(last token, positions, xorshift* RNG) while N's block is delivered host-side.
Load-bearing properties:

- TOKEN IDENTITY with the unpipelined scheduler — greedy and seeded
  stochastic, mixed budgets, concurrent rows — including through every flush
  path (mid-block EOS, cancellation, admission);
- the device-carried RNG round-trips bit-exactly through flushes: a sampler
  reused across requests sees one unbroken xorshift* stream either way;
- a flush discards exactly the speculated tokens (free frontier rewind) and
  the engine keeps serving;
- the argpartition top-p host sampler is bit-identical to the full-sort path
  it replaced (it sits on the overlapped delivery loop).
"""

import threading

import numpy as np
import pytest

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.obs import metrics
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.sampler import Sampler


def _spec(seq_len=128):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=256, seq_len=seq_len,
                     rope_type=RopeType.LLAMA).resolved()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


def _flushes() -> dict:
    return dict(metrics.snapshot().get("batch_pipeline_flushes_total") or {})


def _flush_delta(before: dict, reason: str | None = None) -> float:
    after = _flushes()
    keys = [k for k in after if reason is None or reason in k]
    return sum(after[k] - before.get(k, 0.0) for k in keys)


@pytest.fixture(scope="module")
def params():
    return init_random_params(_spec(), FloatType.Q40, seed=11)


def _engines(params, **kw):
    """A (pipelined, unpipelined) engine pair over the same weights."""
    spec = _spec()
    on = BatchEngine(spec, params, slots=2, tp=2, superstep=4,
                     pipeline=True, **kw)
    off = BatchEngine(spec, params, slots=2, tp=2, superstep=4,
                      pipeline=False, **kw)
    return spec, on, off


# ------------------------------------------------------------- token identity


def test_pipeline_engages_and_greedy_identity(params):
    """Steady-state greedy decode must chain dispatches (pipeline actually ON:
    depth-2 issues observed as zero-gap) and emit exactly the unpipelined
    scheduler's tokens, with max_tokens NOT a multiple of K."""
    spec, on, off = _engines(params)
    try:
        assert on.pipeline and not off.pipeline
        prompt = [1, 7, 23, 5]
        want = off.submit(list(prompt), 11, _greedy(spec)).wait(timeout=120)
        hist0 = metrics.snapshot().get("batch_dispatch_gap_seconds") or {}
        got = on.submit(list(prompt), 11, _greedy(spec)).wait(timeout=120)
        hist1 = metrics.snapshot().get("batch_dispatch_gap_seconds") or {}
        assert got == want
        # chained issues record a literal 0.0 gap in the first bucket
        b0 = (hist0.get("buckets") or {}).get("0.0001", 0)
        b1 = (hist1.get("buckets") or {}).get("0.0001", 0)
        assert b1 > b0, "no chained (zero-gap) dispatch was issued"
    finally:
        on.close()
        off.close()


def test_pipeline_mixed_budgets_concurrent_rows(params):
    """Two concurrent requests with different max_tokens (mixed per-row
    budgets: one row parks mid-scan while the other keeps decoding) must both
    match the unpipelined engine."""
    spec, on, off = _engines(params)
    try:
        outs = {}
        for label, be in (("off", off), ("on", on)):
            r1 = be.submit([1, 7, 23, 5], 13, _greedy(spec))
            r2 = be.submit([1, 9, 2], 6, _greedy(spec))
            outs[label] = (r1.wait(timeout=120), r2.wait(timeout=120))
        assert outs["on"] == outs["off"]
    finally:
        on.close()
        off.close()


def test_pipeline_stochastic_identity_and_rng_state(params):
    """Seeded stochastic decode: tokens AND the final sampler state must be
    identical pipelined vs unpipelined — the device-carried RNG chain must be
    indistinguishable from the per-dispatch upload/writeback."""
    spec, on, off = _engines(params)
    try:
        for temp, topp in ((0.8, 0.9), (1.3, 0.5)):
            outs, states = {}, {}
            for label, be in (("off", off), ("on", on)):
                s = Sampler(spec.vocab_size, temperature=temp, topp=topp,
                            seed=777)
                outs[label] = be.submit([1, 7, 23], 12, s).wait(timeout=120)
                states[label] = int(s.state)
            assert outs["on"] == outs["off"], (temp, topp, outs)
            assert states["on"] == states["off"], (temp, topp, states)
    finally:
        on.close()
        off.close()


# ------------------------------------------------------------------- flushes


def test_mid_block_eos_flushes_and_stays_identical(params):
    """A stop firing mid-block invalidates the chained dispatch: it must be
    flushed (counted by reason), the output must equal the unpipelined run,
    and a sampler reused for a follow-up request must see ONE unbroken
    xorshift* stream (the flush must not consume or skip coins)."""
    spec, on, off = _engines(params)
    try:
        results = {}
        for label, be in (("off", off), ("on", on)):
            smp = Sampler(spec.vocab_size, temperature=0.9, topp=0.9, seed=99)
            first = be.submit([1, 7, 23], 16, smp,
                              stop_check=lambda t, seen=[]: (
                                  seen.append(t) or len(seen) >= 6)
                              ).wait(timeout=120)
            second = be.submit([1, 5, 2], 8, smp).wait(timeout=120)
            results[label] = (first, second, int(smp.state))
        assert results["on"] == results["off"], results

        # greedy mid-block stop: deep enough to land mid-super-step, with the
        # successor already in flight -> a "stop" flush must be counted
        full = off.submit([1, 2, 3], 12, _greedy(spec)).wait(timeout=120)
        stop_at = full[5]
        before = _flushes()
        got = on.submit([1, 2, 3], 12, _greedy(spec),
                        stop_check=lambda t: t == stop_at).wait(timeout=120)
        assert got == full[:6]
        assert _flush_delta(before, "stop") >= 1, _flushes()
        # the engine keeps serving, and the slot state survived the flush:
        # the same prompt reuses the prefix and reproduces the full output
        again = on.submit([1, 2, 3], 12, _greedy(spec)).wait(timeout=120)
        assert again == full
    finally:
        on.close()
        off.close()


def test_admission_breaks_the_chain(params):
    """A request arriving while the pipeline is full must break the chain
    (reason "admission"), admit promptly, and both requests must still match
    the unpipelined engine token-for-token."""
    spec, on, off = _engines(params)
    try:
        outs = {}
        flush_delta = None
        for label, be in (("off", off), ("on", on)):
            before = _flushes()
            started = threading.Event()
            r1 = be.submit([1, 7, 23, 5], 40, _greedy(spec),
                           on_token=lambda _t: started.set())
            assert started.wait(timeout=120)
            r2 = be.submit([1, 9, 2, 40, 41, 42, 43, 44], 12, _greedy(spec))
            outs[label] = (r1.wait(timeout=120), r2.wait(timeout=120))
            if label == "on":
                flush_delta = _flush_delta(before, "admission")
        assert outs["on"] == outs["off"]
        assert flush_delta and flush_delta >= 1
    finally:
        on.close()
        off.close()


def test_cancel_during_inflight_dispatch(params):
    """cancel() while a chained dispatch is in flight: delivery stops at the
    token boundary, the in-flight speculation is discarded, the slot frees,
    and the engine keeps serving."""
    spec, on, _off = _engines(params)
    _off.close()
    try:
        rollback0 = (metrics.snapshot().get("batch_rollback_tokens_total")
                     or 0.0)
        req_box = []

        def on_token(_t):
            if len(req_box[0].out) == 2:
                req_box[0].cancel()

        req = on.submit([1, 8, 2], 40, _greedy(spec), on_token=on_token)
        req_box.append(req)
        out = req.wait(timeout=120)
        assert req.finish == "cancelled"
        assert len(out) == 2
        rollback1 = (metrics.snapshot().get("batch_rollback_tokens_total")
                     or 0.0)
        assert rollback1 > rollback0  # the speculated tail was discarded
        ok = on.submit([1, 8, 2], 4, _greedy(spec)).wait(timeout=120)
        assert len(ok) == 4
    finally:
        on.close()


# ------------------------------------------------------------- context end


def test_pipeline_context_end_clamp(params):
    """Rows running out of context mid-chain park clamped at seq_len-1; the
    pipelined run must match the unpipelined one and leave slot bounds
    intact (the clamp_pos machinery under speculation)."""
    spec = _spec(seq_len=16)
    params16 = init_random_params(spec, FloatType.Q40, seed=3)
    outs = {}
    for pipeline in (False, True):
        be = BatchEngine(spec, params16, slots=2, tp=1, superstep=8,
                         pipeline=pipeline)
        try:
            req = be.submit([1, 2, 3, 4], 100, _greedy(spec))
            outs[pipeline] = req.wait(timeout=120)
            assert req.finish == "length"
            for slot in be._slots:
                assert slot.pos <= spec.seq_len
                assert len(slot.history) <= spec.seq_len
        finally:
            be.close()
    assert outs[True] == outs[False]


# ------------------------------------------------------- host top-p sampler


def _tie_heavy_probs(rs, n):
    """Distributions with many exactly-equal probabilities — the adversarial
    case for the argpartition boundary (ties straddling the pivot)."""
    logits = np.round(rs.standard_normal(n).astype(np.float32) * 2) / 2
    e = np.exp(logits - logits.max())
    return (e / e.sum()).astype(np.float32)


def test_topp_argpartition_bit_identity():
    """_sample_topp (argpartition selection) must pick the SAME token as the
    full-survivor-sort oracle for every coin, topp, and tie pattern —
    including selections that must widen past the first M."""
    rs = np.random.RandomState(5)
    for n in (300, 4096):
        for topp in (0.05, 0.5, 0.9, 0.97):
            s = Sampler(n, temperature=1.0, topp=topp)
            for trial in range(8):
                probs = (_tie_heavy_probs(rs, n) if trial % 2
                         else rs.dirichlet(np.full(n, 0.05)).astype(np.float32))
                for coin in (0.0, 0.1, 0.5, 0.9, 0.999):
                    a = s._sample_topp(probs, coin)
                    b = s._sample_topp_full(probs, coin)
                    assert a == b, (n, topp, trial, coin, a, b)


def test_topp_widening_path_bit_identity():
    """A near-uniform distribution forces the selection to double past
    _TOPP_SELECT (the first M can't cover topp mass) — the widening loop must
    still be bit-identical with the oracle."""
    n = 2048
    probs = np.full(n, 1.0 / n, np.float32)
    probs[:10] += 1e-5  # tiny tilt so the prefilter keeps everything
    probs /= probs.sum()
    s = Sampler(n, temperature=1.0, topp=0.95)
    assert s._TOPP_SELECT < n
    for coin in (0.01, 0.4, 0.8, 0.99):
        assert s._sample_topp(probs, coin) == s._sample_topp_full(probs, coin)


def test_sampler_end_to_end_identity_old_vs_new():
    """Sampler.sample with the argpartition path must reproduce the exact
    token stream of the full-sort path from the same seed (state evolution
    included — one coin per sample either way)."""
    n = 1024
    rs = np.random.RandomState(9)
    a = Sampler(n, temperature=0.9, topp=0.9, seed=42)
    b = Sampler(n, temperature=0.9, topp=0.9, seed=42)
    b._sample_topp = b._sample_topp_full  # pin the oracle path
    for _ in range(64):
        logits = rs.standard_normal(n).astype(np.float32)
        ta = a.sample(logits)
        tb = b.sample(logits)
        assert ta == tb
    assert int(a.state) == int(b.state)


# ------------------------------------------------------------ stats honesty


def test_overlap_ms_recorded_only_when_pipelined(params):
    """dispatch_ms stays one-entry-per-dispatch; overlap_ms entries appear
    for pipelined super-steps (hidden host time > 0 somewhere) and stay
    all-zero when pipelining is off."""
    spec, on, off = _engines(params)
    try:
        r_off = off.submit([1, 7, 23, 5], 12, _greedy(spec))
        r_off.wait(timeout=120)
        assert all(o == 0.0 for o in r_off.stats.overlap_ms)
        r_on = on.submit([1, 7, 23, 5], 12, _greedy(spec))
        r_on.wait(timeout=120)
        assert len(r_on.stats.overlap_ms) > 0
        assert any(o > 0.0 for o in r_on.stats.overlap_ms), \
            r_on.stats.overlap_ms
        assert len(r_on.stats.dispatch_ms) >= len(r_on.stats.overlap_ms)
    finally:
        on.close()
        off.close()
