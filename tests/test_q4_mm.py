"""Fused 4-bit dequant-matmul (ops/pallas_q4_mm.py), interpret mode.

The prefill / batched-decode kernel dequantizes i4p tiles in VMEM and feeds the
MXU in bf16 — it must match dequantize-to-bf16-then-dot to float tolerance, and
the split-plane dual-view addressing (one packed tile covers two disjoint
K-ranges) must survive multi-tile K grids and TP sharding."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import (init_random_params,
                                                 prepare_for_pallas)
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.pallas_q4_mm import q4_matmul, q4_mm_supported
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import FloatType, QTensor


@pytest.mark.parametrize("m,n,k", [(8, 96, 1024), (3, 300, 2048), (1, 64, 1024)])
def test_q4_matmul_matches_dequant_dot(m, n, k):
    rng = np.random.RandomState(0)
    w = QTensor.from_float(rng.randn(n, k).astype(np.float32) * 0.02,
                           FloatType.Q40).to_i4p_layout()
    assert q4_mm_supported(w, m)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32))

    wd = w.dequantize(dtype=jnp.bfloat16)
    want = (x.astype(jnp.bfloat16) @ wd.T).astype(np.float32)
    got = q4_matmul(x, w, out_dtype=jnp.float32, interpret=True)
    # per-tile f32 accumulation vs one full-K bf16 dot: order differences at
    # bf16 product granularity
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-2, rtol=3e-2)


def test_q4_mm_supported_gates():
    rng = np.random.RandomState(1)
    w = QTensor.from_float(rng.randn(64, 1024).astype(np.float32),
                           FloatType.Q40).to_i4p_layout()
    assert q4_mm_supported(w, 64)
    assert not q4_mm_supported(w, 1024)  # M cap
    w_odd = QTensor.from_float(rng.randn(64, 576).astype(np.float32),
                               FloatType.Q40).to_i4p_layout()
    assert not q4_mm_supported(w_odd, 8)  # K/2=288 not tileable by 512
    w8 = QTensor.from_float(rng.randn(64, 1024).astype(np.float32),
                            FloatType.Q80).to_i8_layout()
    assert not q4_mm_supported(w8, 8)  # i8 layout unsupported


def _spec():
    # dim 1024 so K/2=512 tiles exactly (q4_mm_supported needs kh % 512 == 0)
    return ModelSpec(arch_type=ArchType.LLAMA, dim=1024, hidden_dim=1024,
                     n_layers=2, n_heads=8, n_kv_heads=8, vocab_size=256,
                     seq_len=32, rope_type=RopeType.LLAMA).resolved()


def test_prefill_forward_kernel_matches_xla_path():
    """T=8 prefill through use_pallas='all' (the dequant-matmul kernel) == the
    XLA dequant path at bf16-accumulation tolerance."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=7)
    rope = RopeTables.create(spec)
    pp = prepare_for_pallas(params, spec=spec)

    tokens = jnp.asarray([[1, 5, 9, 2, 7, 4, 3, 8]])
    kc, vc = init_kv_cache(spec)
    want, _, _ = forward(pp, spec, rope, tokens, kc, vc, jnp.int32(0),
                         use_pallas=True)
    kc, vc = init_kv_cache(spec)
    got, _, _ = forward(pp, spec, rope, tokens, kc, vc, jnp.int32(0),
                        use_pallas="all")
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.02, rel


def test_prefill_kernel_sharded_matches():
    """tp=2 shard_map prefill with the kernel (col-sharded wo/w2 localize to
    groups=1 self-contained packs) == the planar sharded step. The localized
    shard widths must actually take the kernel (adaptive tile width), or this
    test would pass vacuously through the XLA fallback."""
    from distributed_llama_tpu.ops.pallas_q4_mm import _pick_bkp
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                                   make_sharded_forward,
                                                   shard_params)

    spec = _spec()
    # col-sharded wo/w2 local half-plane width: (K/tp)/2 — must be tileable
    assert _pick_bkp(spec.dim // 2 // 2) is not None
    assert _pick_bkp(spec.hidden_dim // 2 // 2) is not None
    params = init_random_params(spec, FloatType.Q40, seed=3)
    mesh = make_mesh(tp=2)
    tokens = jnp.asarray([[1, 5, 9, 2]])
    rope = RopeTables.create(spec)

    base = shard_params(params, mesh, spec)
    step = make_sharded_forward(spec, mesh, base, donate_cache=False)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    want, _, _ = step(base, rope, tokens, kc, vc, jnp.int32(0))

    pp = shard_params(prepare_for_pallas(params, tp=2, spec=spec), mesh, spec)
    stepp = make_sharded_forward(spec, mesh, pp, use_pallas="all",
                                 donate_cache=False)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, _, _ = stepp(pp, rope, tokens, kc, vc, jnp.int32(0))
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.02, rel


def test_engine_prefill_kernel_generation_matches():
    """End-to-end: Engine(prefill_kernel=True) greedy tokens == baseline (the
    kernel only changes where dequant happens; decode path identical)."""
    from distributed_llama_tpu.runtime.engine import Engine
    from distributed_llama_tpu.runtime.sampler import Sampler

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=13)
    base = Engine(spec, params, tp=1, use_pallas=True)
    want, _ = base.generate([1, 7, 3, 9, 2], 6,
                            Sampler(spec.vocab_size, temperature=0.0))

    eng = Engine(spec, params, tp=1, use_pallas=True, prefill_kernel=True)
    assert eng.use_pallas == "all"
    got, _ = eng.generate([1, 7, 3, 9, 2], 6,
                          Sampler(spec.vocab_size, temperature=0.0))
    assert got == want


def test_batch_engine_with_prefill_kernel_matches():
    """Batched decode (B=2 slots) engages the dequant-matmul at m=B>1; tokens
    must match the non-kernel batched engine exactly."""
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=5)
    prompts = [[1, 7, 23, 5], [1, 9, 2]]

    def run(**kw):
        be = BatchEngine(spec, params, slots=2, tp=2, use_pallas=True, **kw)
        try:
            reqs = [be.submit(list(p), 6, Sampler(spec.vocab_size, temperature=0.0))
                    for p in prompts]
            return [r.wait(timeout=180) for r in reqs]
        finally:
            be.close()

    want = run()
    got = run(prefill_kernel=True)
    assert got == want


def test_pick_bkp_baseline_arch_coverage():
    """Pin exactly which BASELINE widths take the kernel and which fall back:
    all single-chip (tp=1) in-widths are tileable — the adaptive width exists
    because 7B's w2 half-plane (5504) is not a multiple of 512 — while the odd
    TP-local slices of 11008-class hidden dims (2752 at tp=4, 1376 at tp=8)
    are KNOWN fallbacks (half-plane not a multiple of 128). A new arch whose
    hot width lands in the fallback set should move it to the tileable list or
    widen the ladder."""
    from distributed_llama_tpu.ops.pallas_q4_mm import _pick_bkp

    # tp=1 in-widths of every BASELINE arch (dim and hidden): all tileable
    for k in (4096, 11008, 2048, 5632, 14336, 6144, 32768):
        assert _pick_bkp(k // 2) is not None, k
    assert _pick_bkp(5504) == 128  # 7B w2, the reason the ladder exists
    assert _pick_bkp(2048) == 512
    # known XLA fallbacks: odd TP-local slices of 11008/5632-class hidden dims
    for k in (2752, 1376, 704, 1408):
        assert _pick_bkp(k // 2) is None, k
    assert _pick_bkp(288) is None  # K=576: untileable, gated out
