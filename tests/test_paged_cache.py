"""Paged (out-of-core) KV cache: host/disc store + device hot ring + merged
cold attention (runtime/paged_cache.py) — the TPU-native rebuild of the
reference's `--kv-cache-storage disc` (transformer.cpp:312-318, utils.cpp:50-67).

The load-bearing property is EXACTNESS: paged attention is the flash-attention
segment decomposition, not an approximation, so a paged engine must produce the
same logits as a plain full-HBM engine at every step — including after the ring
has wrapped several times and most of the history is cold."""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.sampler import Sampler

SPEC = dict(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=3,
            n_heads=4, n_kv_heads=2, vocab_size=96, seq_len=256)
RESIDENT = 64  # already a multiple of 64; seq_len >> resident so cold is real


@pytest.fixture(scope="module")
def spec_params():
    spec = ModelSpec(**SPEC).resolved()
    return spec, init_random_params(spec, FloatType.Q40, seed=11)


def _engines(spec, params, storage, tmp=None):
    ref = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    paged = Engine(spec, dict(params), tp=1, dtype=jnp.float32,
                   kv_cache_storage=storage, kv_cache_resident=RESIDENT,
                   kv_cache_dir=str(tmp) if tmp else None)
    assert paged.paged and paged.kv_resident == RESIDENT
    assert paged.k_cache.shape[3] == RESIDENT  # hot ring, not seq_len
    return ref, paged


def _drive(ref, paged, rng, n_steps=150, chunk_mix=(64, 8, 1, 1, 7, 1)):
    """Feed identical random chunks through both engines; compare every
    logits vector. The mix crosses the cold boundary (pos 64) and wraps the
    ring twice (pos 128, 192)."""
    pos = 0
    i = 0
    while pos < n_steps:
        t = chunk_mix[i % len(chunk_mix)]
        t = min(t, n_steps - pos)
        toks = rng.integers(0, ref.spec.vocab_size, size=t).tolist()
        lr = ref.infer_chunk(toks)
        lp = paged.infer_chunk(toks)
        np.testing.assert_allclose(
            lp, lr, rtol=2e-4, atol=2e-4,
            err_msg=f"paged logits diverged at pos {pos}..{pos + t}")
        pos += t
        i += 1
    assert ref.pos == paged.pos == n_steps


def test_host_paged_matches_full_cache(spec_params):
    spec, params = spec_params
    ref, paged = _engines(spec, params, "host")
    _drive(ref, paged, np.random.default_rng(0))


def test_disc_paged_matches_full_cache_and_creates_mmap(spec_params, tmp_path):
    spec, params = spec_params
    ref, paged = _engines(spec, params, "disc", tmp=tmp_path)
    assert paged.store.paths is not None
    _drive(ref, paged, np.random.default_rng(1), n_steps=100)
    # the mmap file pair exists and is sized for the FULL context
    import os

    expected = (spec.n_layers * spec.n_kv_heads * spec.seq_len
                * spec.head_size * 4)
    for p in paged.store.paths:
        assert os.path.exists(p)
        assert os.path.getsize(p) == expected


def test_paged_generate_greedy_matches(spec_params):
    """End-to-end generate(): greedy decode far past the resident window must
    emit the same tokens as the full-cache engine."""
    spec, params = spec_params
    ref, paged = _engines(spec, params, "host")
    prompt = list(range(10, 80))  # prefill 70 > resident 64
    out_r, _ = ref.generate(prompt, 60, Sampler(spec.vocab_size, temperature=0.0))
    out_p, _ = paged.generate(prompt, 60,
                              Sampler(spec.vocab_size, temperature=0.0))
    assert out_r == out_p


def test_paged_reset_discards_stale_history(spec_params):
    """reset() + re-run must equal a fresh engine: stale ring slots and stale
    host-store rows beyond the new pos are never read."""
    spec, params = spec_params
    _, paged = _engines(spec, params, "host")
    rng = np.random.default_rng(2)
    toks = rng.integers(0, spec.vocab_size, size=90).tolist()
    for t in (64, 8, 8, 8, 1, 1):  # fill past the cold boundary
        paged.infer_chunk(toks[:t])
        toks = toks[t:]
    paged.reset()
    fresh = Engine(spec, dict(params), tp=1, dtype=jnp.float32,
                   kv_cache_storage="host", kv_cache_resident=RESIDENT)
    probe = list(range(5, 75))
    np.testing.assert_allclose(paged.infer_chunk(probe[:64]),
                               fresh.infer_chunk(probe[:64]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(paged.infer_chunk(probe[64:]),
                               fresh.infer_chunk(probe[64:]),
                               rtol=2e-4, atol=2e-4)


def test_warm_phase_skips_cold_callbacks(spec_params):
    """While pos + T <= resident the cold segment is provably empty: the
    engine must drive the callback-free plain step (no host round-trips), and
    the host store must still receive every committed row so the first paged
    step after the wrap sees the full history."""
    spec, params = spec_params
    ref, paged = _engines(spec, params, "host")
    calls = []
    orig = paged.store.cold_attend
    paged.store.cold_attend = lambda *a: (calls.append(a[0]), orig(*a))[1]
    rng = np.random.default_rng(4)
    toks = rng.integers(0, spec.vocab_size, size=80).tolist()
    for t in (40, 20):  # stays within the 64-slot ring (40+20 <= 64)
        lr = ref.infer_chunk(toks[:t])
        lp = paged.infer_chunk(toks[:t])
        np.testing.assert_allclose(lp, lr, rtol=2e-4, atol=2e-4)
        toks = toks[t:]
    assert not calls, "cold callbacks fired during the warm phase"
    # host store already holds the warm rows (appended from the device ring)
    assert np.abs(paged.store.k[:, :, :, :60]).sum() > 0
    assert np.abs(paged.store.k[:, :, :, 60:]).sum() == 0
    # crossing the boundary engages the paged step; logits still match
    lr = ref.infer_chunk(toks[:20])
    lp = paged.infer_chunk(toks[:20])
    np.testing.assert_allclose(lp, lr, rtol=2e-4, atol=2e-4)
    assert calls, "paged step crossed the ring boundary without cold callbacks"


def test_paged_disabled_when_context_fits(spec_params):
    spec, params = spec_params
    eng = Engine(spec, dict(params), tp=1, dtype=jnp.float32,
                 kv_cache_storage="host", kv_cache_resident=4096)
    assert not eng.paged  # nothing to page: full seq_len fits the budget
    assert eng.k_cache.shape[3] == spec.seq_len


def test_paged_seek_restores_ring_after_wrap(spec_params):
    """Prefix-reuse rewind (api_server NaiveCache): after the ring has
    wrapped, seek(pos) must restore the hot ring from the host store —
    wrapped slots hold the abandoned continuation's rows, which the
    slot-position formula would otherwise mislabel as earlier positions."""
    spec, params = spec_params
    rng = np.random.default_rng(5)
    shared = rng.integers(0, spec.vocab_size, size=90).tolist()  # wraps (>64)
    branch_a = rng.integers(0, spec.vocab_size, size=30).tolist()
    branch_b = rng.integers(0, spec.vocab_size, size=30).tolist()
    ref, paged = _engines(spec, params, "host")
    for eng in (ref, paged):
        pos = 0
        for t in (64, 8, 8, 8, 1, 1):
            eng.infer_chunk(shared[pos:pos + t])
            pos += t
        for i in range(0, 30, 10):
            eng.infer_chunk(branch_a[i:i + 10])
        eng.seek(90)  # rewind: drop branch A, keep the shared prefix
    for i in range(0, 30, 10):
        lr = ref.infer_chunk(branch_b[i:i + 10])
        lp = paged.infer_chunk(branch_b[i:i + 10])
        np.testing.assert_allclose(lp, lr, rtol=2e-4, atol=2e-4,
                                   err_msg=f"post-seek divergence at +{i}")


def test_moe_paged_matches_full_cache():
    """The paged branch is arch-independent (_attention only); pin that with a
    Mixtral-shaped MoE spec across the cold boundary."""
    spec = ModelSpec(arch_type=ArchType.MIXTRAL, dim=64, hidden_dim=96,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=96,
                     seq_len=256, n_experts=4, n_active_experts=2).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=13)
    ref, paged = _engines(spec, params, "host")
    _drive(ref, paged, np.random.default_rng(6), n_steps=100)


def test_disc_store_cleanup_owned_tempdir(spec_params):
    """A store that mkdtemp'd its own directory deletes it on cleanup();
    a caller-supplied directory is owner-kept."""
    import os

    from distributed_llama_tpu.runtime.paged_cache import HostKVStore

    spec, _ = spec_params
    st = HostKVStore(spec, 64, storage="disc")
    d = os.path.dirname(st.paths[0])
    assert os.path.exists(d) and st._owned_dir == d
    st.cleanup()
    assert not os.path.exists(d)
    st.cleanup()  # idempotent


def test_lse_merge_equals_monolithic_attention():
    """Property: splitting the key axis into segments and merging
    (out, lse) partials reproduces gqa_attention over the whole axis."""
    from distributed_llama_tpu.ops.attention import (
        gqa_attention, gqa_attention_lse, merge_attention_partials)

    rng = np.random.default_rng(3)
    b, t, hq, hk, hs, s = 2, 3, 4, 2, 8, 24
    q = jnp.asarray(rng.normal(size=(b, t, hq, hs)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, hk, s, hs)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, hk, s, hs)), jnp.float32)
    positions = jnp.asarray([20, 21, 22])  # all keys visible
    full = gqa_attention(q, k, v, positions)
    cut = 10
    out_a, lse_a = gqa_attention_lse(q, k[:, :, :cut], v[:, :, :cut], positions,
                                     key_positions=jnp.arange(cut))
    out_b, lse_b = gqa_attention_lse(q, k[:, :, cut:], v[:, :, cut:], positions,
                                     key_positions=jnp.arange(cut, s))
    merged = merge_attention_partials(out_a, lse_a, out_b, lse_b)
    np.testing.assert_allclose(np.asarray(merged).reshape(b, t, hq * hs),
                               np.asarray(full), rtol=1e-5, atol=1e-5)
    # empty segment: zero weight, merge degenerates to the other segment
    empty_out = jnp.zeros_like(out_a)
    empty_lse = jnp.full(lse_a.shape, -jnp.inf)
    out_f, lse_f = gqa_attention_lse(q, k, v, positions)
    alone = merge_attention_partials(out_f, lse_f, empty_out, empty_lse)
    np.testing.assert_allclose(np.asarray(alone).reshape(b, t, hq * hs),
                               np.asarray(full), rtol=1e-5, atol=1e-5)
