"""Gray-failure resilience (ISSUE 14): latency-aware routing, bounded
hedging, adaptive timeouts, and retry budgets.

- latency units: LatencyStat windowed quantiles/EWMA, TokenBudget
  accrual/spend accounting (incl. an 8-thread hammer — the budget is the
  hedge race's global spend ledger, so its arithmetic must survive
  contention exactly);
- detector units: an outlier enters probation and rejoins after
  consecutive in-band canaries; a UNIFORMLY slow fleet never ejects
  (peer-median baseline); the quorum floor stops ejection from dropping
  rotation below ceil(frac × healthy) — the acceptance-criteria
  regressions;
- Retry-After: a replica 503's hint becomes a pick() cooldown (unit), a
  clean idle poll ends it early, and live: the failover loop stops
  re-hammering the saturated replica while a different replica serves;
- adaptive timeouts: derived pre-first-byte timeout clamps to
  [floor, cap] and holds the cap until enough samples exist;
- sustained-degradation fault window: the 6-field DLLAMA_FAULTS grammar
  and the duration_s expiry (the gray chaos shape);
- live fleet: healthz round-trip surfaced in snapshot()/router /healthz;
  hedge/cancel races settle clean under an 8-thread hammer with
  seeded-stochastic byte-identity (journal reclaimed, inflight balanced,
  affinity stamps a real winner); a stream pacing just under the idle-gap
  timeout completes while a mid-stream stall fails over via the durable
  path byte-identically — the split the fixed 120 s try_timeout could
  not express.
"""

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from distributed_llama_tpu.apps.api_server import serve
from distributed_llama_tpu.fleet.latency import (GrayConfig,
                                                 GrayFailureDetector,
                                                 LatencyStat, TokenBudget)
from distributed_llama_tpu.fleet.membership import Membership, Replica
from distributed_llama_tpu.fleet.router import close_router, serve_router
from distributed_llama_tpu.formats.mfile import (load_model,
                                                 params_file_order,
                                                 write_model)
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.obs import metrics as obs_metrics
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.resilience import faults
from distributed_llama_tpu.resilience.faults import FaultSpec, parse_faults
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.tokenizer import TemplateType
from distributed_llama_tpu.tokenizer.bpe import Tokenizer

# ----------------------------------------------------------------------
# latency units
# ----------------------------------------------------------------------


def test_latency_stat_window_recency():
    s = LatencyStat(window=8)
    assert s.quantile(0.5) is None and s.count() == 0
    for _ in range(8):
        s.note(10.0)
    assert s.quantile(0.5) == 10.0
    # the window bounds judgment to RECENT behavior: after 8 fast samples
    # the slow era has fully aged out of every quantile
    for _ in range(8):
        s.note(1.0)
    assert s.quantile(0.99) == 1.0 and s.count() == 16
    assert 1.0 <= s.ewma() < 10.0
    s.reset()
    assert s.count() == 0 and s.quantile(0.5) is None


def test_latency_stat_quantile_ordering():
    s = LatencyStat(window=128)
    for i in range(100):
        s.note(float(i))
    assert s.quantile(0.0) == 0.0
    assert s.quantile(0.5) == 50.0
    assert s.quantile(0.95) == 95.0
    assert s.quantile(1.0) == 99.0


def test_token_budget_starts_full_and_bounds_spend():
    b = TokenBudget(rate=0.5, cap=2.0)
    # starts full: a cold router can still fail over the first incident
    assert b.spend() and b.spend()
    assert not b.spend()  # drained: deny instead of storming
    for _ in range(2):
        b.note()
    assert b.level() == 1.0
    assert b.spend() and not b.spend()
    for _ in range(100):
        b.note()
    assert b.level() == b.cap  # accrual is capped


def test_token_budget_hammer_exact_accounting():
    """8 threads race note()/spend(): granted spends may never exceed the
    initial cap plus everything accrued — the invariant that makes 'hedge
    spend stays within budget' assertable at all."""
    b = TokenBudget(rate=0.25, cap=4.0)
    granted = []

    def worker():
        g = 0
        for _ in range(500):
            b.note()
            if b.spend():
                g += 1
        granted.append(g)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    st = b.stats()
    assert st["noted"] == 4000
    assert sum(granted) == st["spent"]
    assert st["spent"] <= 4.0 + 0.25 * 4000
    assert 0.0 <= st["tokens"] <= b.cap


# ----------------------------------------------------------------------
# detector units (probation, uniform slowness, quorum floor)
# ----------------------------------------------------------------------


def _fake_fleet(n, p50s, min_samples=4):
    """n in-memory replicas with seeded TTFB windows (no sockets)."""
    reps = [Replica("10.0.0.1", 9000 + i) for i in range(n)]
    for rep, p50 in zip(reps, p50s):
        rep.healthy = True
        rep.status = "ok"
        for _ in range(max(min_samples, 4)):
            rep.lat.ttfb.note(p50)
    return reps


def test_detector_ejects_outlier_and_probation_exit():
    cfg = GrayConfig(eject_multiple=4.0, min_samples=4, probation_exits=2,
                     quorum_frac=0.5)
    det = GrayFailureDetector(cfg)
    reps = _fake_fleet(3, [0.05, 0.05, 1.0])
    det.evaluate(reps)
    assert [r.degraded for r in reps] == [False, False, True]
    # degraded replicas leave the peer baseline: re-evaluating must not
    # cascade (the two healthy peers are in-band vs each other)
    det.evaluate(reps)
    assert sum(r.degraded for r in reps) == 1
    # an out-of-band canary resets the streak; consecutive in-band ones
    # rejoin and reset the latency window (no re-eject on stale tail)
    det.note_outcome(reps[2], 0.06, reps)
    det.note_outcome(reps[2], 2.0, reps)   # still slow: streak back to 0
    det.note_outcome(reps[2], 0.06, reps)
    assert reps[2].degraded
    det.note_outcome(reps[2], 0.05, reps)
    assert not reps[2].degraded
    assert reps[2].lat.ttfb.count() == 0  # window reset on rejoin


def test_uniformly_slow_fleet_never_ejects():
    """Acceptance criterion: uniform slowness must degrade honestly — the
    peer-median baseline moves with the fleet, so no replica is an
    outlier vs its peers and nothing is ejected."""
    cfg = GrayConfig(eject_multiple=4.0, min_samples=4)
    det = GrayFailureDetector(cfg)
    reps = _fake_fleet(4, [2.0, 2.0, 2.0, 2.0])
    for _ in range(5):
        det.evaluate(reps)
    assert not any(r.degraded for r in reps)


def test_quorum_floor_holds_rotation():
    """Acceptance criterion: with 2 of 4 replicas genuinely slow and
    quorum_frac=0.75 (floor=3), only ONE may be ejected — the second
    ejection would drop rotation below the floor and is skipped."""
    cfg = GrayConfig(eject_multiple=4.0, min_samples=4, quorum_frac=0.75)
    det = GrayFailureDetector(cfg)
    reps = _fake_fleet(4, [0.05, 0.05, 1.0, 1.0])
    held0 = obs_metrics.snapshot().get(
        "router_probation_quorum_held_total") or 0
    for _ in range(3):
        det.evaluate(reps)
    assert sum(r.degraded for r in reps) == 1
    in_rotation = [r for r in reps if not r.degraded]
    assert len(in_rotation) == 3  # never below the floor
    held1 = obs_metrics.snapshot().get(
        "router_probation_quorum_held_total") or 0
    assert held1 > held0  # the skipped ejection is observable


def test_detector_needs_min_samples():
    cfg = GrayConfig(eject_multiple=4.0, min_samples=64)
    det = GrayFailureDetector(cfg)
    reps = _fake_fleet(2, [0.05, 5.0], min_samples=4)  # only 4 samples each
    det.evaluate(reps)
    assert not any(r.degraded for r in reps)


# ----------------------------------------------------------------------
# Retry-After cooldown + health RTT units
# ----------------------------------------------------------------------


def test_retry_after_cooldown_gates_rotation():
    m = Membership(["127.0.0.1:1", "127.0.0.1:2"])
    a, b = m.replicas
    for r in (a, b):
        r.healthy = True
        r.status = "ok"
    assert len(m.in_rotation()) == 2
    a.note_retry_after(5.0)
    assert a.in_cooldown()
    assert [r.id for r in m.in_rotation()] == [b.id]
    # the cap bounds a pathological header
    a.note_retry_after(9999.0, cap=30.0)
    assert a.retry_after_until - time.monotonic() <= 30.5
    # a clean idle poll (queue drained, slots free) ends the cooldown
    # early: the saturation the 503 reported is gone
    a.apply_poll("ok", True, {"slots": 2, "free_slots": 2,
                              "queue_depth": 0})
    assert not a.in_cooldown()
    # ... but a busy poll does NOT (the advisory window stands)
    a.note_retry_after(5.0)
    a.apply_poll("ok", True, {"slots": 2, "free_slots": 0,
                              "queue_depth": 3})
    assert a.in_cooldown()


def test_health_rtt_tie_break_in_load_score():
    a, b = Replica("10.0.0.1", 1), Replica("10.0.0.1", 2)
    for r in (a, b):
        r.slots = r.free_slots = 2
    b.lat.health_rtt.note(0.5)   # 50 buckets of 10 ms
    a.lat.health_rtt.note(0.01)  # 1 bucket
    assert a.load_score() < b.load_score()
    # equal-load, equal-RTT replicas still order deterministically by id
    a2, b2 = Replica("10.0.0.1", 3), Replica("10.0.0.1", 4)
    assert a2.load_score() < b2.load_score()
    # the snapshot surfaces the signal (None before any sample)
    assert a.snapshot()["health_rtt_ms"] == pytest.approx(10.0)
    assert a2.snapshot()["health_rtt_ms"] is None


def test_adaptive_ttfb_timeout_clamps():
    """Derived pre-first-byte timeout: the --proxy-timeout cap until
    enough samples, then mult × fleet p95 clamped to [floor, cap]."""
    from distributed_llama_tpu.fleet.router import RouterState

    m = Membership(["127.0.0.1:1"])
    st = RouterState(m, try_timeout=60.0,
                     gray=GrayConfig(min_lat_samples=8, ttfb_floor=2.0,
                                     ttfb_mult=6.0))
    assert st.ttfb_timeout() == 60.0  # no evidence: the old fixed behavior
    for _ in range(8):
        st.fleet_ttfb.note(0.05)
    assert st.ttfb_timeout() == 2.0  # 6 × 0.05 = 0.3 → floor
    for _ in range(32):
        st.fleet_ttfb.note(100.0)
    assert st.ttfb_timeout() == 60.0  # 6 × 100 → cap
    # idle-gap: fixed when configured, adaptive (mult × pace p99) else
    st.gray.idle_timeout = 7.5
    assert st.idle_timeout() == 7.5
    st.gray.idle_timeout = 0.0
    assert st.idle_timeout() == 60.0  # no pace evidence yet
    for _ in range(32):
        st.fleet_pace.note(0.02)
    assert st.idle_timeout() == pytest.approx(10.0)  # 50×0.02=1 → floor 10
    # hedge delay: None without evidence (adaptive), then ~p95
    st.gray.hedge_delay = 0.0
    st2 = RouterState(m, gray=GrayConfig(min_lat_samples=8))
    assert st2.hedge_delay() is None
    for _ in range(8):
        st2.fleet_ttfb.note(0.4)
    assert st2.hedge_delay() == pytest.approx(0.4)


# ----------------------------------------------------------------------
# sustained-degradation fault window
# ----------------------------------------------------------------------


def test_fault_spec_duration_grammar():
    (spec,) = parse_faults("api.request:latency:1::800:45")
    assert spec.kind == "latency" and spec.delay_ms == 800.0
    assert spec.duration_s == 45.0
    (spec,) = parse_faults("api.request:latency:1::800:")  # empty = none
    assert spec.duration_s is None
    with pytest.raises(ValueError):
        parse_faults("p:latency:1::800:45:extra")
    with pytest.raises(ValueError):
        parse_faults("p:latency:1::800:xyz")


def test_fault_duration_window_expires():
    """A sustained-degradation spec fires for duration_s after its FIRST
    fire, then stops — the replica 'recovers', which is what probation
    exit detection needs to observe."""
    spec = FaultSpec("gray.t", kind="latency", delay_ms=1.0,
                     duration_s=0.15)
    with faults.active(spec):
        faults.fire("gray.t")
        assert spec.fired == 1
        faults.fire("gray.t")
        assert spec.fired == 2
        time.sleep(0.2)
        faults.fire("gray.t")
        assert spec.fired == 2  # window expired: injection over
    faults.uninstall()


# ----------------------------------------------------------------------
# live: Retry-After honored across a failover
# ----------------------------------------------------------------------


class _SaturatedStub(ThreadingHTTPServer):
    """A replica that answers healthz ok (idle-looking, so least-loaded
    routing prefers it) but 503s every completion with a Retry-After —
    the saturated-replica shape the cooldown exists for."""

    def __init__(self):
        self.post_hits = 0
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps({
                    "status": "ok",
                    "replica": {"slots": 8, "free_slots": 8,
                                "queue_depth": 0},
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                stub.post_hits += 1
                body = json.dumps({"error": {
                    "message": "saturated", "type": "overloaded_error"
                }}).encode()
                self.send_response(503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Retry-After", "7")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        super().__init__(("127.0.0.1", 0), H)
        threading.Thread(target=self.serve_forever, daemon=True).start()


def test_retry_after_honored_live(fleet):
    """A replica 503ing with Retry-After serves exactly ONE try: the hint
    becomes a pick() cooldown, the request fails over, and later requests
    never re-hammer the stub until a clean idle poll clears it."""
    stub = _SaturatedStub()
    real_port = fleet["reps"][0][2]
    # warm the real replica through ITS router first: the test's first
    # completion must not pay a cold XLA compile, or the background poll
    # below fires mid-test and early-clears the cooldown under assertion
    warm = _stream(fleet["port"], _body(seed=4, max_tokens=4, user="warm"))
    assert warm["status"] == 200, warm
    honored0 = obs_metrics.snapshot().get(
        "router_retry_after_honored_total") or 0
    # poll_interval far past the test: no background poll can early-clear
    # the cooldown mid-assertion (the idle-shaped stub healthz would)
    router = serve_router(
        [f"127.0.0.1:{stub.server_address[1]}", f"127.0.0.1:{real_port}"],
        host="127.0.0.1", port=0, poll_interval=3600.0, retries=2,
        try_timeout=30.0,
        gray=GrayConfig(min_lat_samples=10 ** 9, hedge=False))
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        state = router.router_state
        victim = state.membership.by_id(
            f"127.0.0.1:{stub.server_address[1]}")
        r1 = _stream(router.server_address[1], _body(seed=5, max_tokens=6,
                                                     user="retry one"))
        assert r1["status"] == 200 and r1["error"] is None, r1
        assert stub.post_hits == 1  # idle-looking stub was tried first
        assert victim.in_cooldown()
        assert victim.snapshot()["cooldown_s"] > 0
        assert [r.id for r in state.membership.in_rotation()] \
            == [f"127.0.0.1:{real_port}"]
        honored1 = obs_metrics.snapshot().get(
            "router_retry_after_honored_total") or 0
        assert honored1 > honored0
        # a second request respects the cooldown
        r2 = _stream(router.server_address[1], _body(seed=6, max_tokens=6,
                                                     user="retry two"))
        assert r2["status"] == 200 and stub.post_hits == 1
        # a clean idle poll ends the cooldown early: back in rotation
        state.membership.poll_once()
        assert not victim.in_cooldown()
        assert len(state.membership.in_rotation()) == 2
    finally:
        close_router(router)
        stub.shutdown()
        stub.server_close()


def test_censored_timeout_canary_resets_rejoin_streak():
    """A canary try that TIMED OUT records a censored sample ("at least
    this slow") — when the effective TTFB timeout sits below the ejection
    threshold that value would read as in-band, so it must reset the
    rejoin streak, never extend it: a replica whose canaries produce no
    headers stays in probation."""
    from distributed_llama_tpu.fleet.router import RouterState

    m = Membership(["127.0.0.1:1", "127.0.0.1:2"])
    a, b = m.replicas
    for r in (a, b):
        r.healthy = True
        r.status = "ok"
    state = RouterState(m, gray=GrayConfig(min_samples=4,
                                           eject_multiple=4.0,
                                           probation_exits=3))
    for _ in range(8):
        b.lat.ttfb.note(0.1)  # peer baseline: median 100 ms
    a.set_degraded(True)
    state.note_ttfb(a, 0.15)  # in-band canaries build a streak...
    state.note_ttfb(a, 0.15)
    assert a.canary_ok == 2 and a.degraded
    # ...a censored timeout sample UNDER the 4x threshold resets it
    state.note_ttfb(a, 0.2, ok=False)
    assert a.canary_ok == 0 and a.degraded
    # and censored samples alone can never drive a rejoin
    for _ in range(6):
        state.note_ttfb(a, 0.2, ok=False)
    assert a.degraded


class _SlowOkStub(ThreadingHTTPServer):
    """A replica that answers healthz ok (idle-looking) and serves every
    completion successfully but SLOWLY — the viable-primary shape a
    saturated hedge target must not cancel."""

    def __init__(self, delay_s: float):
        self.post_hits = 0
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps({
                    "status": "ok",
                    "replica": {"slots": 8, "free_slots": 8,
                                "queue_depth": 0},
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                stub.post_hits += 1
                time.sleep(delay_s)
                body = json.dumps({"id": "slow-ok", "choices": [
                    {"message": {"role": "assistant", "content": "done"},
                     "finish_reason": "stop"}]}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        super().__init__(("127.0.0.1", 0), H)
        threading.Thread(target=self.serve_forever, daemon=True).start()


def test_hedge_503_does_not_cancel_viable_primary():
    """A hedge target answering 503 must not win the race: the refusal is
    stashed while the slow-but-viable primary finishes, the client gets
    the primary's 200, and the primary is served exactly ONCE (crowning
    the 503 used to cancel the in-flight primary and redo its work)."""
    slow = _SlowOkStub(delay_s=0.9)
    sat = _SaturatedStub()
    slow_id = f"127.0.0.1:{slow.server_address[1]}"
    # durable (default) path: its upstream leg always streams, so the
    # hedge arms even for this non-stream client; the stub's plain-JSON
    # 200 rides the pre-stream relay verbatim
    router = serve_router(
        [slow_id, f"127.0.0.1:{sat.server_address[1]}"],
        host="127.0.0.1", port=0, poll_interval=3600.0, retries=2,
        try_timeout=30.0,
        gray=GrayConfig(min_lat_samples=10 ** 9, min_samples=10 ** 9,
                        hedge=True, hedge_delay=0.25))
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        # the saturated stub must be the HEDGE, not the primary: give it
        # a worse polled load block than the idle-looking slow stub
        sat_rep = router.router_state.membership.by_id(
            f"127.0.0.1:{sat.server_address[1]}")
        sat_rep.apply_poll("ok", True, {"slots": 8, "free_slots": 1,
                                        "queue_depth": 5})
        conn = http.client.HTTPConnection("127.0.0.1",
                                          router.server_address[1],
                                          timeout=15.0)
        conn.request("POST", "/v1/chat/completions",
                     json.dumps({"messages": [{"role": "user",
                                               "content": "hi"}],
                                 "max_tokens": 4}).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = json.loads(resp.read())
        conn.close()
        assert resp.status == 200, data
        assert data["id"] == "slow-ok"
        assert slow.post_hits == 1   # never canceled + retried
        assert sat.post_hits == 1    # the hedge really launched (and lost)
        launched = (obs_metrics.snapshot().get("router_hedges_total")
                    or {}).get('{outcome="launched"}', 0)
        assert launched >= 1
    finally:
        close_router(router)
        for s in (slow, sat):
            s.shutdown()
            s.server_close()


# ----------------------------------------------------------------------
# live gray fleet
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("gray")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=262,
                     seq_len=192).resolved()
    params = init_random_params(spec, FloatType.F32, seed=23)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    return mpath, tpath


@pytest.fixture(scope="module")
def fleet(model_files):
    """Two REAL replicas + the durable router with the gray layer armed
    but inert (adaptive thresholds parked at never-adapt; tests flip the
    shared GrayConfig per scenario and restore it)."""
    mpath, tpath = model_files
    reps = []
    for _ in range(2):
        lspec, lparams = load_model(mpath, 0)
        be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), slots=2,
                         tp=1, superstep=4)
        srv = serve(None, host="127.0.0.1", port=0,
                    template_type=TemplateType.CHATML, batch_engine=be)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        reps.append((be, srv, srv.server_address[1]))
    router = serve_router([f"127.0.0.1:{p}" for _, _, p in reps],
                          host="127.0.0.1", port=0, poll_interval=0.15,
                          block_bytes=16, retries=2, try_timeout=60.0,
                          gray=GrayConfig(min_lat_samples=10 ** 9,
                                          hedge=False))
    threading.Thread(target=router.serve_forever, daemon=True).start()
    yield {"reps": reps, "router": router,
           "port": router.server_address[1]}
    close_router(router)
    for be, srv, _p in reps:
        srv.shutdown()
        srv.server_close()
        be.close()


@pytest.fixture()
def gray_cfg(fleet):
    """Mutate the router's live GrayConfig for one test, restore after."""
    g = fleet["router"].router_state.gray
    saved = dict(vars(g))
    yield g
    for k, v in saved.items():
        setattr(g, k, v)


def _body(seed=None, temperature=0.8, stream=True, max_tokens=40,
          user="hello gray"):
    b = {"messages": [
        {"role": "system", "content": "gray shared system prompt"},
        {"role": "user", "content": user}],
        "max_tokens": max_tokens, "temperature": temperature,
        "stream": stream}
    if seed is not None:
        b["seed"] = seed
    return b


def _stream(port, body, on_delta=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        conn.request("POST", "/v1/chat/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            return {"status": resp.status,
                    "body": json.loads(resp.read() or b"{}")}
        if not body.get("stream"):
            data = json.loads(resp.read())
            return {"status": 200, "error": None,
                    "text": data["choices"][0]["message"]["content"],
                    "finish": data["choices"][0].get("finish_reason")}
        text, err, finish, n = [], None, None, 0
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            payload = json.loads(line[6:])
            if "error" in payload:
                err = payload["error"]
                break
            d = payload["choices"][0]["delta"].get("content")
            f = payload["choices"][0].get("finish_reason")
            if f:
                finish = f
            if d:
                text.append(d)
                n += 1
                if on_delta:
                    on_delta(n)
        return {"status": 200, "text": "".join(text), "error": err,
                "finish": finish}
    finally:
        conn.close()


def test_health_rtt_surfaced_live(fleet):
    """The poller's healthz round-trip reaches snapshot() and the router's
    own /healthz — the latency signal exists before any traffic flows."""
    state = fleet["router"].router_state
    state.membership.poll_once()
    for rep in state.membership.replicas:
        assert rep.snapshot()["health_rtt_ms"] is not None
    conn = http.client.HTTPConnection("127.0.0.1", fleet["port"],
                                      timeout=10)
    try:
        conn.request("GET", "/healthz")
        body = json.loads(conn.getresponse().read())
    finally:
        conn.close()
    assert body["degraded"] == []
    for blk in body["replicas"].values():
        assert blk["health_rtt_ms"] is not None
        assert "cooldown_s" in blk


def test_hedge_hammer_settles_clean(fleet, gray_cfg):
    """8 threads hammer seeded-stochastic completions through a fleet
    whose victim replica serves 400 ms slow, with an aggressive fixed
    hedge delay. Every response must be byte-identical to the fault-free
    reference (the pre-first-byte phase is idempotent, first byte wins),
    and the winner/loser settlement must leak NOTHING: journal entries
    reclaimed, per-replica inflight back to zero, hedge spend inside the
    budget, affinity stamped with a real winner."""
    from distributed_llama_tpu.fleet.latency import TokenBudget

    state = fleet["router"].router_state
    gray_cfg.hedge = True
    gray_cfg.hedge_delay = 0.1
    gray_cfg.hedge_pct = 1.0  # the hammer tests settlement, not the cap
    gray_cfg.hedge_burst = 8.0
    saved_budget = state.hedge_budget
    state.hedge_budget = TokenBudget(gray_cfg.hedge_pct,
                                     gray_cfg.hedge_burst)
    # unique LEADING system prompts: the affinity key is block-granular,
    # so a shared prefix would pin every request to one replica — cold
    # keys spread primaries across BOTH replicas, and the victim-primary
    # half is what exercises hedge launch + cancel
    bodies = []
    for k in range(8):
        for i in range(4):
            b = _body(seed=424242, temperature=0.9, max_tokens=10,
                      stream=(k + i) % 2 == 0)
            b["messages"][0]["content"] = f"h{k}.{i} gray hammer system"
            bodies.append(b)
    refs = [_stream(fleet["port"], dict(b)) for b in bodies]
    for r in refs:
        assert r["status"] == 200 and r["error"] is None, r
    victim_id = f"127.0.0.1:{fleet['reps'][0][2]}"
    results: dict[int, dict] = {}

    def worker(k):
        for i in range(4):
            results[k * 4 + i] = _stream(fleet["port"],
                                         dict(bodies[k * 4 + i]))

    try:
        with faults.active(FaultSpec("api.request", kind="latency",
                                     delay_ms=400.0,
                                     match={"replica": victim_id})):
            threads = [threading.Thread(target=worker, args=(k,))
                       for k in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    finally:
        faults.uninstall()
        state.hedge_budget, hammered = saved_budget, state.hedge_budget
    assert len(results) == 32
    for idx, r in results.items():
        assert r["status"] == 200 and r["error"] is None, (idx, r)
        # a double-delivery or a loser's bytes folding in would diverge
        assert r["text"] == refs[idx]["text"], idx
    st = hammered.stats()
    assert st["spent"] >= 1, "vacuous: no hedge ever launched"
    assert st["spent"] <= st["cap"] + gray_cfg.hedge_pct * st["noted"]
    # settlement leaks nothing: journal reclaimed, inflight balanced
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        leaked = [r.id for r in state.membership.replicas if r.inflight]
        if not leaked and state.journal.inflight() == 0:
            break
        time.sleep(0.02)
    assert state.journal.inflight() == 0
    assert not leaked, f"hedge losers leaked inflight on {leaked}"
    # affinity stamped only real winners: every node the hammer recorded
    # resolves to a live replica (a canceled loser stamping would poison
    # future routing toward a replica that never delivered)
    assert state.affinity.nodes() >= 1


def _warm_replicas(fleet, body):
    """Drive `body` (non-stream) DIRECTLY against each replica so its XLA
    programs are compiled before a test arms a tight idle-gap timeout —
    a cold compile stalls the stream far past any reasonable gap and
    would read as a wedge."""
    for _be, _srv, p in fleet["reps"]:
        b = dict(body)
        b["stream"] = False
        conn = http.client.HTTPConnection("127.0.0.1", p, timeout=120)
        try:
            conn.request("POST", "/v1/chat/completions", json.dumps(b),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200, resp.read()
            resp.read()
        finally:
            conn.close()


def test_slow_paced_stream_survives_idle_timeout(fleet, gray_cfg):
    """Acceptance regression: a healthy-but-slow long stream whose
    per-token gaps sit just UNDER the idle-gap timeout must complete —
    the timeout judges the gap between events, never total duration
    (which here far exceeds the 1.5 s idle timeout)."""
    body = _body(seed=None, temperature=0.0, max_tokens=24,
                 user="slow but healthy")
    # reference + program warm BEFORE arming the tight timeout: the
    # greedy decode program may not be compiled yet, and a cold compile
    # is a legitimate >1.5 s stall, not the wedge under test
    ref = _stream(fleet["port"], dict(body))
    assert ref["status"] == 200 and ref["error"] is None, ref
    _warm_replicas(fleet, body)
    gray_cfg.idle_timeout = 1.5
    resumed0 = obs_metrics.snapshot().get(
        "router_resumed_requests_total") or 0
    t0 = time.monotonic()
    with faults.active(FaultSpec("batch.dispatch", kind="latency",
                                 delay_ms=300.0)):
        try:
            got = _stream(fleet["port"], dict(body))
        finally:
            faults.uninstall()
    assert got["status"] == 200 and got["error"] is None, got
    assert got["text"] == ref["text"] and got["finish"] == ref["finish"]
    assert time.monotonic() - t0 > 1.5  # the stream really outlived the gap
    resumed1 = obs_metrics.snapshot().get(
        "router_resumed_requests_total") or 0
    assert resumed1 == resumed0  # completed in place, no spurious failover


def test_stalled_stream_fails_over_within_idle_gap(fleet, gray_cfg):
    """The other half of the split: a mid-stream STALL (engine wedged in a
    600 s dispatch, socket open, nothing arriving) trips the idle-gap
    timeout in ~1.5 s instead of the old fixed 120 s, and the durable path
    resumes on the surviving replica byte-identically."""
    body = _body(seed=31337, temperature=0.8, max_tokens=40,
                 user="stall mid stream")
    ref = _stream(fleet["port"], dict(body))
    assert ref["status"] == 200 and ref["error"] is None, ref
    _warm_replicas(fleet, body)
    gray_cfg.idle_timeout = 1.5
    resumed0 = obs_metrics.snapshot().get(
        "router_resumed_requests_total") or 0
    stalled = []

    def stall(n):
        if n == 4 and not stalled:
            stalled.append(time.monotonic())
            faults.install([FaultSpec("batch.dispatch", kind="latency",
                                      delay_ms=600_000.0, count=1)])

    try:
        got = _stream(fleet["port"], dict(body), on_delta=stall)
    finally:
        faults.uninstall()
    assert stalled, "stall never engaged"
    assert got["status"] == 200 and got["error"] is None, got
    assert got["text"] == ref["text"] and got["finish"] == ref["finish"]
    assert time.monotonic() - stalled[0] < 45.0  # not the old 120 s shape
    resumed1 = obs_metrics.snapshot().get(
        "router_resumed_requests_total") or 0
    assert resumed1 > resumed0  # the durable path did the save
    # unstick the wedged engine (its scheduler sleeps in the injected
    # dispatch) so later tests inherit a working fleet
    for be, _srv, _p in fleet["reps"]:
        if be.dispatch_age() > 5.0:
            be.recover_wedged()
    state = fleet["router"].router_state
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        state.membership.poll_once()
        if len(state.membership.in_rotation()) == 2:
            break
        time.sleep(0.1)
    assert len(state.membership.in_rotation()) == 2
