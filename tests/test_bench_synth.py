"""bench.py param synthesis: the chunked randint path must be shape/range/dtype
equivalent to the direct path regardless of where the transient budget splits
the tensor (the r5 --layout i8 OOM was a 4x uint32 synthesis transient on the
merged stacked groups; see bench._randint_chunked)."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import bench  # noqa: E402


def test_chunked_matches_direct_semantics(monkeypatch):
    monkeypatch.setattr(bench, "_RAND_TRANSIENT_BUDGET", 1 << 12)
    for shape in [(4, 8, 16), (300, 16), (3, 3, 64, 64), (2, 2, 2, 8, 8)]:
        a = bench._randint_chunked(jax.random.PRNGKey(7), shape, -8, 8,
                                   jnp.int8)
        assert a.shape == shape
        assert a.dtype == jnp.int8
        v = np.asarray(a)
        assert v.min() >= -8 and v.max() < 8
        # every slab/slice must actually be filled with random draws, not
        # the zeros the buffer is initialized with (P(all-zero slice) ~ 0)
        flat = v.reshape(shape[0], -1)
        assert (np.abs(flat).sum(axis=1) > 0).all()


def test_small_tensor_uses_direct_path():
    # under the budget the output must be bitwise identical to plain randint
    # (same key): the chunked wrapper must not perturb existing configs
    key = jax.random.PRNGKey(3)
    direct = jax.random.randint(key, (16, 32), -8, 8, jnp.int8)
    got = bench._randint_chunked(key, (16, 32), -8, 8, jnp.int8)
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(got))


def test_synth_q40_layouts_under_tight_budget(monkeypatch):
    monkeypatch.setattr(bench, "_RAND_TRANSIENT_BUDGET", 1 << 12)
    for layout in ("i4p", "i8", "planar"):
        q = bench.synth_q40(jax.random.PRNGKey(0), (2, 64, 64), layout)
        assert q.data.shape[0] == 2
