"""Tier-1 wiring for perf/smoke_lint.py: every .py in the repo must
byte-compile and carry no dead imports — a syntax error or stale import in a
rarely-exercised app path fails HERE instead of in production (ISSUE 2
satellite; pyflakes when installed, conservative AST fallback otherwise)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "perf"))

import smoke_lint  # noqa: E402


def test_repo_compiles_and_no_dead_imports():
    files = smoke_lint.repo_py_files()
    assert len(files) > 50, "scan did not find the repo"
    errors = smoke_lint.check_compile(files)
    assert not errors, "\n".join(errors)
    dead = smoke_lint.check_dead_imports(files)
    assert not dead, "\n".join(dead)


def test_scan_covers_cache_package():
    """The prefix-cache subsystem (ISSUE 3) must ride the repo-wide compile +
    dead-import gate like every other first-party package — a scan-root
    regression would silently drop it from tier-1."""
    files = smoke_lint.repo_py_files()
    rel = {os.path.relpath(f, smoke_lint.REPO) for f in files}
    for mod in ("radix", "block_pool", "prefix_cache", "single_slot",
                "__init__"):
        assert os.path.join("distributed_llama_tpu", "cache",
                            f"{mod}.py") in rel, (mod, sorted(rel)[:5])
    assert os.path.join("perf", "prefix_seed_bench.py") in rel


def test_scan_covers_fleet_package():
    """The fleet tier (ISSUE 6) rides the same repo-wide gate: router,
    membership, affinity and the apps/router.py entrypoint must all be in
    the compile + dead-import scan."""
    files = smoke_lint.repo_py_files()
    rel = {os.path.relpath(f, smoke_lint.REPO) for f in files}
    for mod in ("router", "membership", "affinity", "__init__"):
        assert os.path.join("distributed_llama_tpu", "fleet",
                            f"{mod}.py") in rel, mod
    assert os.path.join("distributed_llama_tpu", "apps", "router.py") in rel


def test_fallback_checker_flags_planted_dead_import(tmp_path):
    """The AST fallback actually detects the defect class it exists for,
    and respects the noqa escape hatch."""
    bad = tmp_path / "mod.py"
    bad.write_text("import os\nimport json\nprint(json.dumps({}))\n")
    findings = smoke_lint._fallback_dead_imports(str(bad), bad.read_text())
    assert len(findings) == 1 and "'os' imported but unused" in findings[0]
    ok = tmp_path / "ok.py"
    ok.write_text("import os  # noqa: side-effect import\n")
    assert smoke_lint._fallback_dead_imports(str(ok), ok.read_text()) == []
