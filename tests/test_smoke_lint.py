"""Tier-1 wiring for perf/smoke_lint.py: every .py in the repo must
byte-compile and carry no dead imports — a syntax error or stale import in a
rarely-exercised app path fails HERE instead of in production (ISSUE 2
satellite; pyflakes when installed, conservative AST fallback otherwise)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "perf"))

import smoke_lint  # noqa: E402


def test_repo_compiles_and_no_dead_imports():
    files = smoke_lint.repo_py_files()
    assert len(files) > 50, "scan did not find the repo"
    errors = smoke_lint.check_compile(files)
    assert not errors, "\n".join(errors)
    dead = smoke_lint.check_dead_imports(files)
    assert not dead, "\n".join(dead)


def test_scan_covers_cache_package():
    """The prefix-cache subsystem (ISSUE 3) must ride the repo-wide compile +
    dead-import gate like every other first-party package — a scan-root
    regression would silently drop it from tier-1."""
    files = smoke_lint.repo_py_files()
    rel = {os.path.relpath(f, smoke_lint.REPO) for f in files}
    for mod in ("radix", "block_pool", "prefix_cache", "single_slot",
                "device_pool", "wire", "__init__"):
        assert os.path.join("distributed_llama_tpu", "cache",
                            f"{mod}.py") in rel, (mod, sorted(rel)[:5])
    assert os.path.join("perf", "prefix_seed_bench.py") in rel
    assert os.path.join("perf", "paged_attn_bench.py") in rel


def test_scan_covers_fleet_package():
    """The fleet tier (ISSUE 6) rides the same repo-wide gate: router,
    membership, affinity and the apps/router.py entrypoint must all be in
    the compile + dead-import scan."""
    files = smoke_lint.repo_py_files()
    rel = {os.path.relpath(f, smoke_lint.REPO) for f in files}
    for mod in ("router", "membership", "affinity", "disagg", "latency",
                "__init__"):
        assert os.path.join("distributed_llama_tpu", "fleet",
                            f"{mod}.py") in rel, mod
    assert os.path.join("distributed_llama_tpu", "apps", "router.py") in rel


def test_scan_covers_resilience_package():
    """The resilience layer (ISSUE 9 satellite, mirroring the fleet/ and
    cache/ coverage tests): faults, errors, the hung-engine supervisor and
    the durable-fleet journal must all ride the compile + dead-import
    gate."""
    files = smoke_lint.repo_py_files()
    rel = {os.path.relpath(f, smoke_lint.REPO) for f in files}
    for mod in ("faults", "errors", "supervisor", "__init__"):
        assert os.path.join("distributed_llama_tpu", "resilience",
                            f"{mod}.py") in rel, mod
    assert os.path.join("distributed_llama_tpu", "fleet",
                        "journal.py") in rel
    assert os.path.join("perf", "fault_matrix.py") in rel


def test_scan_covers_draft_package():
    """The model-drafting subsystem (ISSUE 15, mirroring the cache/ and
    fleet/ coverage tests): the drafter, its device loop, and the shared
    fleet completion client must ride the repo-wide compile + dead-import
    gate."""
    files = smoke_lint.repo_py_files()
    rel = {os.path.relpath(f, smoke_lint.REPO) for f in files}
    for mod in ("drafter", "loop", "__init__"):
        assert os.path.join("distributed_llama_tpu", "draft",
                            f"{mod}.py") in rel, mod
    assert os.path.join("distributed_llama_tpu", "fleet",
                        "client.py") in rel


def test_metric_names_documented():
    """ISSUE 7 satellite: every metrics.counter/gauge/histogram name
    registered anywhere in the package must appear in
    docs/OBSERVABILITY.md — the metric inventory can no longer rot."""
    undocumented = smoke_lint.check_metric_docs()
    assert not undocumented, "\n".join(undocumented)


def test_metric_collector_sees_known_registrations():
    """The static collector actually finds the registrations the lint
    guards: spot-check names from three different layers + the obs scan
    covers the new modules."""
    names = {n for n, _f in smoke_lint.collect_metric_names()}
    for expected in ("batch_queue_wait_seconds", "api_request_ttft_seconds",
                     "router_routes_total", "faults_injected_total",
                     "dllama_uptime_seconds", "dllama_build_info"):
        assert expected in names, (expected, sorted(names)[:10])
    assert len(names) >= 60  # the real inventory, not a partial scan


def test_metric_collector_flags_planted_metric(tmp_path):
    """A metric registered in a scanned file but absent from the doc is
    exactly what the lint exists to catch."""
    mod = tmp_path / "planted.py"
    mod.write_text(
        "from distributed_llama_tpu.obs import metrics\n"
        'M = metrics.counter("totally_undocumented_total", "x")\n'
        'G = metrics.gauge(dynamic_name, "skipped: non-literal name")\n')
    found = smoke_lint.collect_metric_names([str(mod)])
    assert [n for n, _f in found] == ["totally_undocumented_total"]


def test_metric_doc_match_is_token_delimited():
    """A name that is merely a substring/prefix of documented text must NOT
    pass — the lint matches delimited tokens, so `prefix_cache_hit` cannot
    ride on `prefix_cache_hit_tokens_total`."""
    import re

    doc = open(smoke_lint._OBS_DOC, encoding="utf-8").read()
    planted = "prefix_cache_hit"  # substring of a documented name
    assert planted in doc  # the naive check would pass...
    assert not re.search(r"(?<![A-Za-z0-9_])" + re.escape(planted)
                         + r"(?![A-Za-z0-9_])", doc)  # ...the real one won't


def test_fallback_checker_flags_planted_dead_import(tmp_path):
    """The AST fallback actually detects the defect class it exists for,
    and respects the noqa escape hatch."""
    bad = tmp_path / "mod.py"
    bad.write_text("import os\nimport json\nprint(json.dumps({}))\n")
    findings = smoke_lint._fallback_dead_imports(str(bad), bad.read_text())
    assert len(findings) == 1 and "'os' imported but unused" in findings[0]
    ok = tmp_path / "ok.py"
    ok.write_text("import os  # noqa: side-effect import\n")
    assert smoke_lint._fallback_dead_imports(str(ok), ok.read_text()) == []
