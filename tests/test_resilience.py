"""Chaos test suite for the resilience layer (docs/ROBUSTNESS.md).

Drives the fault-injection framework (distributed_llama_tpu/resilience/)
against the continuous-batching scheduler and the HTTP server on the CPU
mesh and asserts the acceptance criteria of ISSUE 4:

- killing one co-batched request (mid-prefill AND mid-super-step) leaves
  every survivor's output token-identical to a fault-free run — greedy and
  seeded-stochastic — and the scheduler thread never dies;
- transient dispatch failures are retried and invisible to clients;
- queue-TTL and wall-clock deadlines expire with finish reason "deadline"
  (DeadlineExceeded before the first token, partial output after);
- overload sheds with EngineSaturated / HTTP 503 + Retry-After;
- close() speaks typed errors (EngineClosed/EngineDraining) and drain mode
  lets in-flight requests finish;
- a SIGTERM round trip against a live server drains: /healthz flips to 503
  "draining", new requests 503, in-flight completes, server stops;
- BatchRequest.wait(timeout) auto-cancels instead of leaking the slot.
"""

import http.client
import json
import threading
import time

import pytest

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.obs import metrics
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.resilience import faults
from distributed_llama_tpu.resilience.errors import (DeadlineExceeded,
                                                     EngineClosed,
                                                     EngineDraining,
                                                     EngineSaturated,
                                                     FaultInjected,
                                                     TransientDispatchError,
                                                     classify)
from distributed_llama_tpu.resilience.faults import FaultSpec
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.sampler import Sampler


def _spec(seq_len=128):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=256, seq_len=seq_len,
                     rope_type=RopeType.LLAMA).resolved()


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    """Every test leaves the process fault-free (a leaked plan would poison
    the rest of the suite)."""
    yield
    faults.uninstall()


@pytest.fixture(scope="module")
def setup():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4)
    yield spec, params, be
    be.close()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


def _seeded(spec):
    return Sampler(spec.vocab_size, 0.8, 0.9, 123)


def _wait_until(cond, timeout=60.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {msg}")


def _counter_value(name: str, labels: str = "") -> float:
    snap = metrics.snapshot().get(name, 0.0)
    if isinstance(snap, dict):
        return snap.get(labels, 0.0)
    return snap


# ------------------------------------------------------------------
# fault framework unit tests (no engine)
# ------------------------------------------------------------------

def test_parse_faults_grammar():
    specs = faults.parse_faults(
        "batch.dispatch:transient:0.01,batch.prefill:error,"
        "paged.*:latency:1.0:3:50")
    assert [s.point for s in specs] == ["batch.dispatch", "batch.prefill",
                                       "paged.*"]
    assert specs[0].kind == "transient" and specs[0].prob == 0.01
    assert specs[1].prob == 1.0 and specs[1].count is None
    assert specs[2].count == 3 and specs[2].delay_ms == 50.0
    # sixth field = duration_s (the sustained-degradation window)
    sustained = faults.parse_faults("p:latency:1::800:45")[0]
    assert sustained.delay_ms == 800.0 and sustained.duration_s == 45.0
    assert faults.parse_faults("p:error:1:2:3:4")[0].duration_s == 4.0
    assert faults.parse_faults("p:error:1:2:3")[0].duration_s is None
    for bad in ("point-only", "p:unknownkind", "p:error:notaprob",
                "p:error:1:2:3:notasecs", "p:error:1:2:3:4:5"):
        with pytest.raises(ValueError):
            faults.parse_faults(bad)


def test_fault_spec_count_after_and_match():
    with faults.active(FaultSpec("pt", kind="error", after=2, count=1)) as plan:
        faults.fire("pt")  # skipped (after)
        faults.fire("pt")  # skipped (after)
        with pytest.raises(FaultInjected):
            faults.fire("pt")
        faults.fire("pt")  # count exhausted
        assert plan.fired() == 1
    with faults.active(FaultSpec("pt", match={"slot": 1})):
        faults.fire("pt", slot=0)  # filtered
        with pytest.raises(FaultInjected):
            faults.fire("pt", slot=1)
    assert faults.current() is None  # active() uninstalled


def test_fault_prob_seed_deterministic():
    def run(seed):
        fired = []
        plan = faults.FaultPlan([FaultSpec("p", kind="transient", prob=0.5)],
                                seed=seed)
        for i in range(64):
            try:
                plan.fire("p")
                fired.append(0)
            except TransientDispatchError:
                fired.append(1)
        return fired

    a, b = run(7), run(7)
    assert a == b and 0 < sum(a) < 64  # deterministic, actually probabilistic
    assert run(8) != a  # seed matters


def test_latency_fault_sleeps_not_raises():
    with faults.active(FaultSpec("slow", kind="latency", delay_ms=30)):
        t0 = time.perf_counter()
        faults.fire("slow")
        assert time.perf_counter() - t0 >= 0.025


def test_install_from_env():
    plan = faults.install_from_env({"DLLAMA_FAULTS": "x:error:0.5",
                                    "DLLAMA_FAULT_SEED": "9"})
    assert plan is not None and plan.seed == 9
    # explicit install wins over a second env install
    assert faults.install_from_env({"DLLAMA_FAULTS": "y:error"}) is plan
    faults.uninstall()
    assert faults.install_from_env({}) is None


def test_classify():
    assert classify(TransientDispatchError("x")) == "transient"
    assert classify(FaultInjected("x", scope="request")) == "request"
    assert classify(FaultInjected("x", scope="engine")) == "engine"
    assert classify(RuntimeError("x")) == "engine"  # conservative default


# ------------------------------------------------------------------
# satellite: wait(timeout) auto-cancel (slot-leak regression)
# ------------------------------------------------------------------

def test_wait_timeout_autocancels_and_frees_slot(setup):
    spec, params, be = setup
    req = be.submit([1, 2, 3], 64, _greedy(spec))
    with pytest.raises(TimeoutError):
        req.wait(timeout=0.01)
    assert req.cancelled
    # the scheduler reaps the cancelled request and frees the slot (+ any
    # prefix-cache lease) via the existing _finish path
    _wait_until(lambda: req.done.is_set(), msg="cancelled request reaped")
    assert req.finish == "cancelled"
    _wait_until(lambda: all(s.req is None for s in be._slots),
                msg="slot freed")
    assert all(s.lease is None for s in be._slots)
    # the engine is fully usable afterwards (no leak): a fresh request runs
    out = be.submit([1, 2, 3], 4, _greedy(spec)).wait(timeout=120)
    assert len(out) == 4


# ------------------------------------------------------------------
# blast-radius isolation: kill one co-batched request, survivors exact
# ------------------------------------------------------------------

@pytest.mark.parametrize("make_sampler", [_greedy, _seeded],
                         ids=["greedy", "seeded-stochastic"])
def test_victim_killed_mid_prefill_survivor_identical(setup, make_sampler):
    spec, params, be = setup
    survivor_prompt = [1, 7, 23, 5]
    n = 24
    base = be.submit(list(survivor_prompt), n,
                     make_sampler(spec)).wait(timeout=120)

    surv = be.submit(list(survivor_prompt), n, make_sampler(spec))
    _wait_until(lambda: len(surv.out) >= 1, msg="survivor decoding")
    faults.install([FaultSpec("batch.prefill", kind="error", count=1)])
    victim = be.submit([1] + list(range(2, 42)), 8, make_sampler(spec))
    with pytest.raises(FaultInjected):
        victim.wait(timeout=120)
    assert victim.finish == "error"
    out = surv.wait(timeout=120)
    faults.uninstall()
    assert out == base, "survivor diverged after co-batched victim died"
    assert surv.finish == "length"
    assert be.scheduler_alive()


@pytest.mark.parametrize("make_sampler", [_greedy, _seeded],
                         ids=["greedy", "seeded-stochastic"])
def test_victim_killed_mid_superstep_survivor_identical(setup, make_sampler):
    spec, params, be = setup
    survivor_prompt = [1, 9, 2]
    n = 24
    base = be.submit(list(survivor_prompt), n,
                     make_sampler(spec)).wait(timeout=120)

    surv = be.submit(list(survivor_prompt), n, make_sampler(spec))
    victim = be.submit([1, 30, 31, 32], 64, make_sampler(spec))
    _wait_until(lambda: len(victim.out) >= 1 and len(surv.out) >= 1,
                msg="both requests decoding")
    vslot = next(s for s in be._slots if s.req is victim)
    # injected at the delivery path of the victim's slot only: fires inside
    # the super-step block-delivery loop (or a single-step advance) — the
    # "sampler/callback" blast radius
    faults.install([FaultSpec("batch.emit", kind="error", count=1,
                              match={"slot": vslot.index})])
    with pytest.raises(FaultInjected):
        victim.wait(timeout=120)
    assert victim.finish == "error"
    out = surv.wait(timeout=120)
    faults.uninstall()
    assert out == base, "survivor diverged after mid-super-step victim kill"
    assert be.scheduler_alive()


def test_radix_lookup_failure_degrades_not_kills(setup, monkeypatch):
    """A raising prefix-cache LOOKUP (a real radix/pool bug, not just an
    injected seed fault) must cost only the cache win: the admitted request
    prefills from scratch and completes identically, and co-batched
    in-flight requests are untouched — the cache is never a correctness
    gate, even when it throws at admission."""
    spec, params, be = setup
    poisoned_prompt = [1, 17, 18, 19]
    inflight_prompt = [1, 7, 23, 5]
    base_poisoned = be.submit(list(poisoned_prompt), 8,
                              _greedy(spec)).wait(timeout=120)
    base_inflight = be.submit(list(inflight_prompt), 24,
                              _greedy(spec)).wait(timeout=120)

    inflight = be.submit(list(inflight_prompt), 24, _greedy(spec))
    _wait_until(lambda: len(inflight.out) >= 1, msg="in-flight decoding")

    def boom(*a, **k):
        raise RuntimeError("radix lookup boom")

    monkeypatch.setattr(be.prefix_cache, "lookup", boom)
    poisoned = be.submit(list(poisoned_prompt), 8, _greedy(spec))
    out = poisoned.wait(timeout=120)  # degraded to plain prefill, completed
    assert out == base_poisoned and poisoned.error is None
    assert inflight.wait(timeout=120) == base_inflight
    assert inflight.finish == "length"
    assert be.scheduler_alive()


def test_cache_seed_fault_degrades_to_prefill(setup):
    """An injected prefix-cache seeding fault must cost only the cache win:
    the request prefills from scratch and completes identically."""
    spec, params, be = setup
    prompt = [1, 5, 6, 7, 8, 9, 10, 11]
    base = be.submit(list(prompt), 4, _greedy(spec)).wait(timeout=120)
    before = be.prefilled_tokens
    with faults.active(FaultSpec("batch.cache_seed", kind="error")):
        out = be.submit(list(prompt), 4, _greedy(spec)).wait(timeout=120)
    assert out == base
    # seeding was refused, so the scheduler had to prefill at least the
    # portion the same-slot rewind could not cover — and nothing crashed
    assert be.prefilled_tokens >= before


# ------------------------------------------------------------------
# transient dispatch failures: retried, invisible to clients
# ------------------------------------------------------------------

def test_transient_dispatch_retried(setup):
    spec, params, be = setup
    prompt = [1, 7, 23, 5]
    base = be.submit(list(prompt), 10, _greedy(spec)).wait(timeout=120)
    retries0 = _counter_value("engine_retries_total")
    with faults.active(FaultSpec("batch.dispatch", kind="transient",
                                 count=2)) as plan:
        req = be.submit(list(prompt), 10, _greedy(spec))
        out = req.wait(timeout=120)
        assert plan.fired() == 2
    assert out == base
    assert req.error is None and req.finish == "length"
    assert _counter_value("engine_retries_total") >= retries0 + 2


def test_transient_exhausted_fails_requests_but_scheduler_survives(setup):
    spec, params, be = setup
    with faults.active(FaultSpec("batch.dispatch", kind="transient")):
        req = be.submit([1, 2, 3], 8, _greedy(spec))
        with pytest.raises(TransientDispatchError):
            req.wait(timeout=120)
        assert req.finish == "error"
    # plan uninstalled: the SAME scheduler thread serves the next request
    assert be.scheduler_alive()
    out = be.submit([1, 2, 3], 4, _greedy(spec)).wait(timeout=120)
    assert len(out) == 4
    assert all(s.req is None for s in be._slots)


# ------------------------------------------------------------------
# admission control: TTL, deadline, shedding
# ------------------------------------------------------------------

def test_queue_ttl_expiry(setup):
    spec, params, be = setup
    blockers = [be.submit([1, 2, 3 + i], 64, _greedy(spec)) for i in range(2)]
    try:
        _wait_until(lambda: sum(1 for s in be._slots if s.req) == 2,
                    msg="slots occupied")
        victim = be.submit([1, 4, 5], 8, _greedy(spec), ttl=0.15)
        with pytest.raises(DeadlineExceeded):
            victim.wait(timeout=60)
        assert victim.finish == "deadline"
        assert victim.out == []  # never admitted, nothing generated
    finally:
        for b in blockers:
            b.cancel()
        for b in blockers:
            b.done.wait(60)


def test_generation_deadline_partial_output(setup):
    spec, params, be = setup
    # a latency fault paces the decode (~40 ms/dispatch) so the deadline
    # reliably lands mid-generation: after the first token, before the
    # context fills — also exercising the latency injection kind in anger
    with faults.active(FaultSpec("batch.dispatch", kind="latency",
                                 delay_ms=40)):
        req = be.submit([1, 2, 3], 1000, _greedy(spec), deadline=0.5)
        out = req.wait(timeout=120)  # no error: partial output was generated
    assert req.finish == "deadline"
    assert 0 < len(out) < 1000


def test_deadline_before_first_token_errors(setup):
    spec, params, be = setup
    blockers = [be.submit([1, 2, 3 + i], 64, _greedy(spec)) for i in range(2)]
    try:
        _wait_until(lambda: sum(1 for s in be._slots if s.req) == 2,
                    msg="slots occupied")
        victim = be.submit([1, 6, 7], 8, _greedy(spec), deadline=0.1)
        with pytest.raises(DeadlineExceeded):
            victim.wait(timeout=60)
        assert victim.finish == "deadline" and victim.out == []
    finally:
        for b in blockers:
            b.cancel()
        for b in blockers:
            b.done.wait(60)


def test_admission_shedding(setup):
    spec, params, be = setup
    shed0 = _counter_value("engine_shed_requests_total")
    blockers = [be.submit([1, 2, 3 + i], 64, _greedy(spec)) for i in range(2)]
    try:
        _wait_until(lambda: sum(1 for s in be._slots if s.req) == 2,
                    msg="slots occupied")
        be.max_queue = 1  # AFTER the blockers left the queue for their slots
        queued = be.submit([1, 8, 9], 8, _greedy(spec))  # fills the queue
        with pytest.raises(EngineSaturated) as ei:
            be.submit([1, 10, 11], 8, _greedy(spec))
        assert ei.value.retry_after > 0
        assert _counter_value("engine_shed_requests_total") >= shed0 + 1
        queued.cancel()
        queued.done.wait(60)
    finally:
        be.max_queue = 0
        for b in blockers:
            b.cancel()
        for b in blockers:
            b.done.wait(60)


# ------------------------------------------------------------------
# typed close errors + drain
# ------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_engine_factory():
    spec = _spec(seq_len=64)
    params = init_random_params(spec, FloatType.Q40, seed=3)

    def make():
        return spec, BatchEngine(spec, params, slots=1, tp=1, superstep=2,
                                 prefix_cache=False)

    return make


def test_close_aborts_with_typed_errors(small_engine_factory):
    spec, be = small_engine_factory()
    inflight = be.submit([1, 2, 3], 500, _greedy(spec))
    _wait_until(lambda: any(s.req is not None for s in be._slots),
                msg="in-flight")
    queued = be.submit([1, 4, 5], 8, _greedy(spec))
    be.close()
    with pytest.raises(EngineClosed):
        inflight.wait(timeout=60)
    with pytest.raises(EngineClosed):
        queued.wait(timeout=60)
    with pytest.raises(EngineClosed):
        be.submit([1], 1, _greedy(spec))


def test_drain_lets_inflight_finish(small_engine_factory):
    spec, be = small_engine_factory()
    req = be.submit([1, 2, 3], 8, _greedy(spec))
    done = threading.Event()
    t = threading.Thread(target=lambda: (be.close(drain=True, timeout=120),
                                         done.set()))
    t.start()
    try:
        _wait_until(lambda: be._draining, msg="drain engaged")
        with pytest.raises(EngineDraining):
            be.submit([1], 1, _greedy(spec))
        out = req.wait(timeout=120)  # in-flight request FINISHED, not aborted
        assert req.error is None and req.finish == "length"
        assert len(out) == 8
        _wait_until(done.is_set, msg="drain close completed")
    finally:
        t.join(timeout=120)
    with pytest.raises(EngineClosed):
        be.submit([1], 1, _greedy(spec))


# ------------------------------------------------------------------
# HTTP server: validation, shedding, TTL, drain round trip
# ------------------------------------------------------------------

@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    from distributed_llama_tpu.formats.mfile import (params_file_order,
                                                     write_model)
    from distributed_llama_tpu.formats.tfile import (TokenizerData,
                                                     write_tokenizer)
    from distributed_llama_tpu.models.spec import ArchType as AT

    tmp = tmp_path_factory.mktemp("resil_api")
    spec = ModelSpec(arch_type=AT.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=262,
                     seq_len=128).resolved()
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    return mpath, tpath


def _make_server(model_files, **be_kw):
    from distributed_llama_tpu.apps.api_server import serve
    from distributed_llama_tpu.formats.mfile import load_model
    from distributed_llama_tpu.tokenizer import TemplateType
    from distributed_llama_tpu.tokenizer.bpe import Tokenizer

    mpath, tpath = model_files
    lspec, lparams = load_model(mpath, 0)
    be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), tp=1,
                     **be_kw)
    srv = serve(None, host="127.0.0.1", port=0,
                template_type=TemplateType.CHATML, batch_engine=be)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, be, srv.server_address[1]


@pytest.fixture(scope="module")
def resil_server(model_files):
    srv, be, port = _make_server(model_files, slots=1, superstep=4)
    yield srv, be, port
    srv.shutdown()
    be.close()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    return conn.getresponse()


def _post(port, body, path="/v1/chat/completions"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=180)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    return conn.getresponse()


def test_server_validation_400(resil_server):
    srv, be, port = resil_server
    # prompt beyond seq_len: 400, not a 500 or a stall
    r = _post(port, {"messages": [{"role": "user", "content": "ab" * 400}],
                     "max_tokens": 4})
    assert r.status == 400
    err = json.loads(r.read())["error"]
    assert err["type"] == "invalid_request_error"
    assert "context" in err["message"]
    # invalid max_tokens values: negative, non-integer, boolean
    for bad in (-1, "lots", 2.5, True):
        r = _post(port, {"messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": bad})
        assert r.status == 400, bad
        assert json.loads(r.read())["error"]["type"] == "invalid_request_error"
    # a STREAMING invalid request gets a real 400 (headers are deferred to
    # the first delta), not a 200 SSE stream carrying an error event
    r = _post(port, {"messages": [{"role": "user", "content": "ab" * 400}],
                     "stream": True, "max_tokens": 4})
    assert r.status == 400
    assert json.loads(r.read())["error"]["type"] == "invalid_request_error"


def test_server_sheds_503_with_retry_after(resil_server):
    srv, be, port = resil_server
    spec = be.spec
    be.max_queue = 1
    blocker = be.submit([1, 2, 3], 200, _greedy(spec))
    try:
        _wait_until(lambda: any(s.req is not None for s in be._slots),
                    msg="slot occupied")
        queued = be.submit([1, 4, 5], 4, _greedy(spec))  # fills the queue
        r = _post(port, {"messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": 4})
        assert r.status == 503
        assert r.getheader("Retry-After") is not None
        assert json.loads(r.read())["error"]["type"] == "overloaded_error"
        queued.cancel()
        queued.done.wait(60)
    finally:
        be.max_queue = 0
        blocker.cancel()
        blocker.done.wait(60)


def test_server_queue_ttl_408(resil_server):
    srv, be, port = resil_server
    spec = be.spec
    be.queue_ttl = 0.2
    blocker = be.submit([1, 2, 3], 200, _greedy(spec))
    try:
        _wait_until(lambda: any(s.req is not None for s in be._slots),
                    msg="slot occupied")
        r = _post(port, {"messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": 4})
        assert r.status == 408
        assert json.loads(r.read())["error"]["type"] == "timeout_error"
    finally:
        be.queue_ttl = 0.0
        blocker.cancel()
        blocker.done.wait(60)


def test_server_resilience_metrics_exposed(resil_server):
    srv, be, port = resil_server
    r = _get(port, "/metrics")
    text = r.read().decode()
    for name in ("batch_scheduler_alive", "batch_dispatch_age_seconds",
                 "engine_retries_total", "engine_shed_requests_total",
                 "engine_errors_total", "engine_deadline_expired_total"):
        assert name in text, name
    assert "batch_scheduler_alive 1" in text
    r = _get(port, "/v1/stats")
    stats = json.loads(r.read())["batch_engine"]
    assert stats["scheduler_alive"] is True and stats["draining"] is False


def test_single_engine_request_deadline(model_files):
    """--batch 1 servers enforce --request-deadline too (per decoded token
    via stop_check): a deadline expiring mid-generation returns 200 with
    finish_reason 'deadline' and the partial output."""
    from distributed_llama_tpu.apps.api_server import serve
    from distributed_llama_tpu.runtime.engine import Engine
    from distributed_llama_tpu.tokenizer import TemplateType

    mpath, tpath = model_files
    engine = Engine.load(mpath, tpath, tp=1)
    srv = serve(engine, host="127.0.0.1", port=0,
                template_type=TemplateType.CHATML, request_deadline=0.5)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        # first-request compile alone exceeds the 0.5 s deadline, so the
        # stop fires within the first few tokens — long before max_tokens
        r = _post(srv.server_address[1],
                  {"messages": [{"role": "user", "content": "hi"}],
                   "max_tokens": 100, "temperature": 0})
        assert r.status == 200
        body = json.loads(r.read())
        assert body["choices"][0]["finish_reason"] == "deadline"
    finally:
        srv.shutdown()


def test_server_sigterm_drain_round_trip(model_files):
    """The acceptance round trip: SIGTERM against a live server -> /healthz
    reports draining (503), new requests shed 503, the in-flight request
    completes 200, the server stops — all within --drain-timeout."""
    import signal

    from distributed_llama_tpu.apps.api_server import install_sigterm_drain

    srv, be, port = _make_server(model_files, slots=1, superstep=4)
    old_handler = signal.getsignal(signal.SIGTERM)
    try:
        installed = install_sigterm_drain(srv, srv.api_state,
                                          drain_timeout=120.0)
        if not installed:
            pytest.skip("not the main thread: cannot install SIGTERM handler")

        results = {}

        def inflight():
            r = _post(port, {"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 48, "temperature": 0})
            results["status"] = r.status
            results["body"] = json.loads(r.read())

        t = threading.Thread(target=inflight)
        t.start()
        _wait_until(lambda: any(s.req is not None for s in be._slots),
                    timeout=120, msg="in-flight request admitted")

        signal.raise_signal(signal.SIGTERM)  # the real signal path
        _wait_until(lambda: srv.api_state.draining, msg="draining flag")
        r = _get(port, "/healthz")
        assert r.status == 503
        assert json.loads(r.read())["status"] == "draining"
        # new admissions are refused while draining
        r = _post(port, {"messages": [{"role": "user", "content": "late"}],
                         "max_tokens": 4})
        assert r.status == 503

        t.join(timeout=180)
        assert not t.is_alive(), "in-flight request did not finish in drain"
        assert results["status"] == 200, results
        assert results["body"]["choices"][0]["finish_reason"] in (
            "length", "stop")
        # the drain closed the engine: everything ended cleanly
        _wait_until(lambda: be._shutdown, msg="engine closed by drain")
        assert all(s.req is None for s in be._slots)
    finally:
        signal.signal(signal.SIGTERM, old_handler)
        srv.shutdown()
        be.close()
