"""Unit tests for the static-analysis passes (distributed_llama_tpu/analysis/,
ISSUE 10): fixture modules with KNOWN violations assert each rule fires at
exactly the expected line, stays quiet on the compliant twin, and that the
suppression convention is honored, counted, and rejects reasonless markers."""

import textwrap

from distributed_llama_tpu.analysis import core, drift, hotpath, locks


def make_source(text: str, relpath: str = "distributed_llama_tpu/fx.py"):
    text = textwrap.dedent(text)
    lines = text.splitlines()
    import ast

    try:
        tree = ast.parse(text)
    except SyntaxError:
        tree = None
    sups, bad = core.parse_suppressions("/fx/" + relpath, relpath, lines, text)
    src = core.Source("/fx/" + relpath, relpath, text, lines, tree, sups)
    src.bad_suppressions = bad
    return src


# ----------------------------------------------------------------------
# lock-guard
# ----------------------------------------------------------------------

def test_lock_guard_fires_on_unguarded_access():
    src = make_source("""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()  # guards: _queue, _thread
                self._queue = []
                self._thread = None

            def good(self):
                with self._lock:
                    self._queue.append(1)

            def bad_read(self):
                return len(self._queue)

            def bad_write(self):
                self._thread = None
    """)
    fs = locks.check_locks([src])
    assert [(f.rule, f.line) for f in fs] == [("lock-guard", 15),
                                             ("lock-guard", 18)]
    assert "_queue read outside" in fs[0].message
    assert "_thread written outside" in fs[1].message


def test_lock_guard_holds_annotation_and_init_exempt():
    src = make_source("""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()  # guards: _queue
                self._queue = []  # construction: exempt

            def _drain(self):  # holds: self._lock
                self._queue.clear()

            def outer(self):
                with self._lock:
                    self._drain()
    """)
    assert locks.check_locks([src]) == []


def test_lock_guard_dataclass_field_lock():
    src = make_source("""
        import threading
        from dataclasses import dataclass, field

        @dataclass
        class Replica:
            healthy: bool = False
            _lock: threading.Lock = field(default_factory=threading.Lock)  # guards: healthy

            def eject(self):
                self.healthy = False

            def eject_locked(self):
                with self._lock:
                    self.healthy = False
    """)
    fs = locks.check_locks([src])
    assert [(f.rule, f.line) for f in fs] == [("lock-guard", 11)]


def test_lock_guard_closure_does_not_inherit_lock():
    """A nested def runs later (dispatch closure): its body must be checked
    as NOT holding the lexically enclosing lock."""
    src = make_source("""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()  # guards: _queue
                self._queue = []

            def plan(self):
                with self._lock:
                    def later():
                        return self._queue.pop()
                    return later
    """)
    fs = locks.check_locks([src])
    assert [(f.rule, f.line) for f in fs] == [("lock-guard", 12)]


# ----------------------------------------------------------------------
# lock-blocking
# ----------------------------------------------------------------------

def test_lock_blocking_fires_under_held_lock():
    src = make_source("""
        import threading
        import time

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()

            def bad_sleep(self):
                with self._lock:
                    time.sleep(1.0)

            def bad_join(self, t):
                with self._lock:
                    t.join()

            def bad_http(self, conn):
                with self._lock:
                    return conn.getresponse()

            def fine_outside(self, t):
                time.sleep(0.1)
                t.join()
    """)
    fs = locks.check_locks([src])
    assert [(f.rule, f.line) for f in fs] == [
        ("lock-blocking", 11), ("lock-blocking", 15), ("lock-blocking", 19)]
    assert "time.sleep()" in fs[0].message


def test_lock_blocking_condition_wait_and_str_join_exempt():
    src = make_source("""
        import threading

        class Sched:
            def __init__(self):
                self._cond = threading.Condition()

            def idle(self, parts):
                with self._cond:
                    self._cond.wait(timeout=0.1)  # releases the lock: fine
                    return ",".join(parts)  # str.join: takes a positional arg
    """)
    assert locks.check_locks([src]) == []


# ----------------------------------------------------------------------
# hot-path
# ----------------------------------------------------------------------

def test_hot_sync_rules_fire_only_in_marked_functions():
    src = make_source("""
        import numpy as np

        def unmarked(x):
            return np.asarray(x)  # not hot: no finding

        def deliver(x, acc, i):  # hot-path
            a = x.tolist()
            b = np.asarray(x)
            c = int(acc[i])
            print("token")
            return a, b, c
    """)
    fs = hotpath.check_hot_paths([src])
    assert [(f.rule, f.line) for f in fs] == [
        ("hot-sync", 8), ("hot-sync", 9), ("hot-sync", 10), ("hot-sync", 11)]
    assert all("deliver" in f.message for f in fs)


def test_hot_sync_host_name_tracking_exempts_fetched_arrays():
    """The one designed sync (np.asarray at the delivery fence) is flagged;
    downstream .tolist()/int(x[i]) on the SAME name are host ops, not new
    syncs — one triage point per transfer, not one per use."""
    src = make_source("""
        import numpy as np

        def deliver(fl, i):  # hot-path
            toks = np.asarray(fl.toks)
            block = toks[:4, i].tolist()
            return int(toks[0, i]), block
    """)
    fs = hotpath.check_hot_paths([src])
    assert [(f.rule, f.line) for f in fs] == [("hot-sync", 5)]


def test_hot_impure_fires_in_traced_bodies_only():
    src = make_source("""
        import time
        import random

        def host_side():  # hot-path
            return time.perf_counter()  # host timing is fine

        def step(carry, i):  # hot-path: traced
            t = time.time()
            r = random.random()
            return carry, (t, r)
    """)
    fs = hotpath.check_hot_paths([src])
    assert [(f.rule, f.line) for f in fs] == [
        ("hot-impure", 9), ("hot-impure", 10)]


# ----------------------------------------------------------------------
# suppressions
# ----------------------------------------------------------------------

def test_suppression_honored_and_counted():
    src = make_source("""
        import numpy as np

        def deliver(x):  # hot-path
            return np.asarray(x)  # dlint: ignore[hot-sync] -- the delivery fence
    """)
    fs = core.apply_suppressions([src], hotpath.check_hot_paths([src]))
    assert len(fs) == 1 and fs[0].suppressed
    assert fs[0].reason == "the delivery fence"
    assert src.suppressions[5].used == 1


def test_suppression_wrong_rule_does_not_silence():
    src = make_source("""
        import numpy as np

        def deliver(x):  # hot-path
            return np.asarray(x)  # dlint: ignore[lock-guard] -- wrong rule
    """)
    fs = core.apply_suppressions([src], hotpath.check_hot_paths([src]))
    assert len(fs) == 1 and not fs[0].suppressed
    assert src.suppressions[5].used == 0  # stale: reported, silences nothing


def test_suppression_star_matches_any_rule():
    src = make_source("""
        import numpy as np

        def deliver(x):  # hot-path
            return np.asarray(x)  # dlint: ignore[*] -- fence (multiple rules)
    """)
    fs = core.apply_suppressions([src], hotpath.check_hot_paths([src]))
    assert fs[0].suppressed


def test_reasonless_suppression_is_a_finding():
    src = make_source("""
        def f():
            return 1  # dlint: ignore[hot-sync]
    """)
    bad = src.bad_suppressions
    assert len(bad) == 1 and bad[0].rule == "bad-suppression"
    assert bad[0].line == 3
    assert 3 not in src.suppressions  # and it suppresses nothing


def test_suppression_quoted_in_docstring_is_not_parsed():
    src = make_source('''
        def f():
            """Docs may quote `# dlint: ignore[x] -- like this` freely."""
            return 1
    ''')
    assert src.suppressions == {} and src.bad_suppressions == []


# ----------------------------------------------------------------------
# drift lints
# ----------------------------------------------------------------------

def test_fault_docs_flags_undocumented_point():
    src = make_source("""
        from ..resilience import faults

        def f():
            faults.fire("totally.new_point", slot=1)
            faults.fire("batch.submit")  # documented: no finding
    """)
    fs = drift.check_fault_docs([src])
    assert len(fs) == 1 and fs[0].rule == "fault-docs"
    assert "totally.new_point" in fs[0].message and fs[0].line == 5


def test_metric_docs_flags_planted_metric():
    src = make_source("""
        from .obs import metrics

        M = metrics.counter("totally_undocumented_total", "x")
        G = metrics.gauge(dynamic_name, "skipped: non-literal name")
        K = metrics.counter("batch_queue_depth", "documented: no finding")
    """)
    fs = drift.check_metric_docs([src])
    assert len(fs) == 1
    assert "totally_undocumented_total" in fs[0].message


def test_doc_match_is_token_delimited():
    """`prefix_cache_hit` is a substring of a documented metric name but is
    NOT itself documented — the delimited matcher must say so."""
    doc = open(drift.OBS_DOC, encoding="utf-8").read()
    assert "prefix_cache_hit" in doc            # the naive check passes...
    assert not drift._delimited("prefix_cache_hit", doc)  # ...the real one won't
    assert drift._delimited("prefix_cache_hit_tokens_total", doc)


def test_hot_impure_propagates_into_nested_traced_defs():
    """A scan `step` defined inside a jitted `loop` body executes at trace
    time — impurity inside the nested def is the loop's impurity (the real
    device_loop bodies have exactly this shape)."""
    src = make_source("""
        import time

        def loop(tokens):  # hot-path: traced
            def step(carry, i):
                return carry, time.time()
            return step
    """)
    fs = hotpath.check_hot_paths([src])
    assert [(f.rule, f.line) for f in fs] == [("hot-impure", 6)]
    assert "loop.step" in fs[0].message


def test_lock_blocking_queue_get_forms():
    """Blocking queue gets flag in every spelling — bare get(), get(True),
    get(block=True), get(timeout=...) — while dict.get(key) and an explicit
    block=False stay exempt."""
    src = make_source("""
        import threading

        class Sched:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self, q):
                with self._lock:
                    a = q.get()
                    b = q.get(True)
                    c = q.get(block=True)
                    d = q.get(timeout=1.0)
                    return a, b, c, d

            def fine(self, q, d):
                with self._lock:
                    return q.get(block=False), d.get("key"), q.get_nowait()
    """)
    fs = locks.check_locks([src])
    assert [(f.rule, f.line) for f in fs] == [
        ("lock-blocking", 10), ("lock-blocking", 11),
        ("lock-blocking", 12), ("lock-blocking", 13)]
