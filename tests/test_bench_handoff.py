"""Warm-runner -> driver bench handoff (bench.py + perf/persistent_bench.py).

The round-4 failure mode: the driver's fresh `python bench.py` died on a dead
tunnel (value 0.0) while a warm runner held the only good measurement of the
day. The handoff publishes the runner's headline to BENCH_latest.json and
bench.py reports it, with provenance, when its own probe fails. These tests run
bench.py as a real subprocess with an unreachable backend (JAX_PLATFORMS=tpu in
an env with no TPU plugin) and pin the protocol:

- fresh handoff file  -> rc 0, value passed through, provenance fields present
- stale handoff file  -> rc 2, value 0.0, explicit staleness in the error
- non-headline config -> rc 2 (never silently reports the headline's number)
- drill env           -> rc 2 (the fallback drill must not "pass" via handoff)
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# every bench subprocess gets DLT_HANDOFF_PATH pointing here: the protocol is
# exercised against a scratch file, never the repo-root BENCH_latest.json (a
# real runner-published hardware result lives there mid-round; an earlier
# version of this suite deleted it in teardown)
import tempfile

_SCRATCH = tempfile.mkdtemp(prefix="dlt_handoff_test_")
LATEST = os.path.join(_SCRATCH, "BENCH_latest.json")

RESULT = {"metric": "llama2_7b_q40_decode_tok_s", "value": 32.35,
          "unit": "tok/s", "vs_baseline": 3.293, "layout": "i4p",
          "cache_write": "deferred"}


def _run_bench(extra_args=(), extra_env=None):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS")}
    # no axon sitecustomize, no TPU plugin: backend init fails fast and the
    # probe path (not a wedge-hang) is exercised
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "tpu"
    env["DLT_PROBE_TIMEOUT"] = "30"
    env["DLT_HANDOFF_PATH"] = LATEST
    env.update(extra_env or {})
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--steps", "4",
         *extra_args],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    line = p.stdout.strip().splitlines()[-1] if p.stdout.strip() else "{}"
    return p.returncode, json.loads(line)


@pytest.fixture
def handoff_file():
    def write(age_s):
        payload = {"result": dict(RESULT), "captured_unix": time.time() - age_s,
                   "captured_at": "test", "argv": "bench.py --steps 32"}
        with open(LATEST, "w") as f:
            json.dump(payload, f)
    yield write
    if os.path.exists(LATEST):
        os.remove(LATEST)


def test_fresh_handoff_reported_with_provenance(handoff_file):
    handoff_file(age_s=600)
    rc, out = _run_bench()
    assert rc == 0
    assert out["value"] == RESULT["value"]
    assert out["provenance"] == "warm-runner"
    assert 590 < out["age_s"] < 700
    assert out["warm_runner_argv"] == "bench.py --steps 32"
    assert "probe_failure_at_capture" in out


def test_stale_handoff_refused(handoff_file):
    handoff_file(age_s=30 * 3600)
    rc, out = _run_bench()
    assert rc == 2
    assert out["value"] == 0.0
    assert "stale" in out["error"]


def test_non_headline_config_never_borrows_headline(handoff_file):
    handoff_file(age_s=600)
    rc, out = _run_bench(extra_args=("--layout", "i8"))
    assert rc == 2
    assert out["value"] == 0.0


def test_drill_env_never_borrows_headline(handoff_file):
    handoff_file(age_s=600)
    rc, out = _run_bench(extra_env={"DLT_FORCE_I4P_FAILURE": "1"})
    assert rc == 2
    assert out["value"] == 0.0


def test_no_handoff_file_reports_unreachable():
    assert not os.path.exists(LATEST)
    rc, out = _run_bench()
    assert rc == 2
    assert out["value"] == 0.0
    assert "TPU unreachable" in out["error"]


def test_string_timestamp_handoff_still_served(handoff_file):
    """A hand-edited handoff with captured_unix as a numeric STRING must still
    be served (coerced), not crash or report 0.0. (Takes handoff_file purely
    for its teardown: the custom payload below must not leak into
    test_no_handoff_file_reports_unreachable under test reordering.)"""
    payload = {"result": dict(RESULT), "captured_unix": str(time.time() - 600),
               "argv": "bench.py --steps 32"}
    with open(LATEST, "w") as f:
        json.dump(payload, f)
    rc, out = _run_bench()
    assert rc == 0
    assert out["value"] == RESULT["value"]
    assert 590 < out["age_s"] < 700
