"""Warm-runner -> driver bench handoff (bench.py + perf/persistent_bench.py).

The round-4 failure mode: the driver's fresh `python bench.py` died on a dead
tunnel (value 0.0) while a warm runner held the only good measurement of the
day. The handoff publishes the runner's headline to BENCH_latest.json and
bench.py reports it, with provenance, when its own probe fails. These tests run
bench.py as a real subprocess with an unreachable backend (JAX_PLATFORMS=tpu in
an env with no TPU plugin) and pin the protocol:

- fresh handoff file  -> rc 0, value passed through, provenance fields present
- stale handoff file  -> rc 2, value 0.0, explicit staleness in the error
- non-headline config -> rc 2 (never silently reports the headline's number)
- drill env           -> rc 2 (the fallback drill must not "pass" via handoff)
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# every bench subprocess gets DLT_HANDOFF_PATH pointing here: the protocol is
# exercised against a scratch file, never the repo-root BENCH_latest.json (a
# real runner-published hardware result lives there mid-round; an earlier
# version of this suite deleted it in teardown)
import tempfile

_SCRATCH = tempfile.mkdtemp(prefix="dlt_handoff_test_")
LATEST = os.path.join(_SCRATCH, "BENCH_latest.json")

RESULT = {"metric": "llama2_7b_q40_decode_tok_s", "value": 32.35,
          "unit": "tok/s", "vs_baseline": 3.293, "layout": "i4p",
          "cache_write": "deferred"}


def _run_bench(extra_args=(), extra_env=None):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS")}
    # an unreachable backend that fails FAST: platform "tpu13" is not a
    # registered PJRT plugin, so default_backend() raises in ~1 s. (Platform
    # "tpu" is the wrong lever on a TPU-less host with libtpu installed: its
    # plugin init retries GCP metadata fetches for MINUTES while holding the
    # GIL, so even bench's own probe watchdog can't fire and every subprocess
    # here ran into the 300 s kill — ~25 wasted minutes per tier-1 run.)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "tpu13"
    env["DLT_PROBE_TIMEOUT"] = "30"
    env["DLT_HANDOFF_PATH"] = LATEST
    env["DLT_HANDOFF_TRACKED_PATH"] = ""  # never read the repo's real mirror
    # never wait on the REAL warm runner's busy marker (a live runner mid-config
    # in this repo would stall every subprocess here for its full busy_wait)
    env["DLT_BUSY_WAIT"] = "0"
    env.update(extra_env or {})
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--steps", "4",
         *extra_args],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    line = p.stdout.strip().splitlines()[-1] if p.stdout.strip() else "{}"
    return p.returncode, json.loads(line)


@pytest.fixture
def handoff_file():
    def write(age_s):
        payload = {"result": dict(RESULT), "captured_unix": time.time() - age_s,
                   "captured_at": "test", "argv": "bench.py --steps 32"}
        with open(LATEST, "w") as f:
            json.dump(payload, f)
    yield write
    if os.path.exists(LATEST):
        os.remove(LATEST)


def test_fresh_handoff_reported_with_provenance(handoff_file):
    handoff_file(age_s=600)
    rc, out = _run_bench()
    assert rc == 0
    assert out["value"] == RESULT["value"]
    assert out["provenance"] == "warm-runner"
    assert 590 < out["age_s"] < 700
    assert out["warm_runner_argv"] == "bench.py --steps 32"
    assert "probe_failure_at_capture" in out


def test_stale_handoff_refused(handoff_file):
    handoff_file(age_s=30 * 3600)
    rc, out = _run_bench()
    assert rc == 2
    assert out["value"] == 0.0
    assert "stale" in out["error"]


def test_non_headline_config_never_borrows_headline(handoff_file):
    handoff_file(age_s=600)
    rc, out = _run_bench(extra_args=("--layout", "i8"))
    assert rc == 2
    assert out["value"] == 0.0


def test_drill_env_never_borrows_headline(handoff_file):
    handoff_file(age_s=600)
    rc, out = _run_bench(extra_env={"DLT_FORCE_I4P_FAILURE": "1"})
    assert rc == 2
    assert out["value"] == 0.0


def test_no_handoff_file_reports_unreachable():
    assert not os.path.exists(LATEST)
    rc, out = _run_bench()
    assert rc == 2
    assert out["value"] == 0.0
    assert "TPU unreachable" in out["error"]


def test_tracked_mirror_served_when_latest_missing(handoff_file):
    """The 2026-07-31 03:15 container restart deleted the gitignored
    BENCH_latest.json; the git-tracked mirror must keep serving the result."""
    mirror = os.path.join(_SCRATCH, "BENCH_handoff.json")
    payload = {"result": dict(RESULT), "captured_unix": time.time() - 900,
               "captured_at": "test", "argv": "bench.py --steps 32"}
    with open(mirror, "w") as f:
        json.dump(payload, f)
    try:
        assert not os.path.exists(LATEST)
        rc, out = _run_bench(extra_env={"DLT_HANDOFF_TRACKED_PATH": mirror})
        assert rc == 0
        assert out["value"] == RESULT["value"]
        assert out["provenance"] == "warm-runner"
        assert 890 < out["age_s"] < 1000
    finally:
        os.remove(mirror)


def test_freshest_handoff_wins(handoff_file):
    """When both handoff files parse, the younger capture is served (the
    runner refreshes BENCH_latest between mirror commits — and after a restore
    the mirror may be the younger one)."""
    handoff_file(age_s=3000)  # LATEST: older
    mirror = os.path.join(_SCRATCH, "BENCH_handoff.json")
    fresh = dict(RESULT, value=61.5)
    payload = {"result": fresh, "captured_unix": time.time() - 300,
               "captured_at": "test", "argv": "bench.py --steps 32"}
    with open(mirror, "w") as f:
        json.dump(payload, f)
    try:
        rc, out = _run_bench(extra_env={"DLT_HANDOFF_TRACKED_PATH": mirror})
        assert rc == 0
        assert out["value"] == 61.5
        assert 290 < out["age_s"] < 400
    finally:
        os.remove(mirror)


def test_future_timestamp_handoff_refused(handoff_file):
    """A captured_unix far in the future (corrupt or hand-edited) must not be
    served: negative age would otherwise shadow every legitimate file AND make
    the staleness ceiling unreachable."""
    handoff_file(age_s=-2 * 3600)
    rc, out = _run_bench()
    assert rc == 2
    assert out["value"] == 0.0


def test_tracked_mirror_git_commit_of_untracked_file(tmp_path):
    """Pin the git sequence commit_tracked_handoff relies on: a pathspec commit
    alone REJECTS an untracked file ('did not match any file(s) known to git'),
    so the helper must add-then-commit — in a scratch repo, never the real one."""
    import subprocess

    sys.path.insert(0, os.path.join(REPO, "perf"))
    from persistent_bench import _git_commit_path

    repo = str(tmp_path)
    subprocess.run(["git", "init", "-q", repo], check=True)
    subprocess.run(["git", "-C", repo, "-c", "user.name=t",
                    "-c", "user.email=t@t", "commit", "-q", "--allow-empty",
                    "-m", "root"], check=True)
    mirror = os.path.join(repo, "BENCH_handoff.json")
    with open(mirror, "w") as f:
        json.dump({"result": dict(RESULT)}, f)
    ok, detail = _git_commit_path(repo, mirror)
    assert ok, detail
    tracked = subprocess.run(["git", "-C", repo, "ls-files", mirror],
                             capture_output=True, text=True)
    assert tracked.stdout.strip()  # the mirror is now tracked + committed
    # second call with no change: ok without a new commit
    ok, detail = _git_commit_path(repo, mirror)
    assert ok and detail == "unchanged"


def test_test_mode_subprocess_preserves_foreign_sentinel(handoff_file):
    """A scratch-mode (DLT_HANDOFF_PATH) bench subprocess neither creates the
    real driver sentinel nor deletes one a concurrent REAL driver owns — a
    test run must not un-pause the warm runner mid-driver-bench."""
    handoff_file(age_s=600)
    sentinel = os.path.join(REPO, "perf", ".driver_bench_active")
    existed = os.path.exists(sentinel)
    try:
        if not existed:
            with open(sentinel, "w") as f:
                f.write(str(time.time()))
        rc, out = _run_bench()
        assert rc == 0
        assert os.path.exists(sentinel), "test subprocess deleted a foreign sentinel"
    finally:
        if not existed and os.path.exists(sentinel):
            os.remove(sentinel)


def test_string_timestamp_handoff_still_served(handoff_file):
    """A hand-edited handoff with captured_unix as a numeric STRING must still
    be served (coerced), not crash or report 0.0. (Takes handoff_file purely
    for its teardown: the custom payload below must not leak into
    test_no_handoff_file_reports_unreachable under test reordering.)"""
    payload = {"result": dict(RESULT), "captured_unix": str(time.time() - 600),
               "argv": "bench.py --steps 32"}
    with open(LATEST, "w") as f:
        json.dump(payload, f)
    rc, out = _run_bench()
    assert rc == 0
    assert out["value"] == RESULT["value"]
    assert 590 < out["age_s"] < 700
