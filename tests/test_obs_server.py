"""Observability integration tests: /metrics + /healthz + /v1/stats on a live
batched api_server under concurrent requests, OpenAI-style error bodies, and
--trace Chrome-trace emission from the CLI and the BatchEngine scheduler."""

import http.client
import json
import re
import threading

import pytest

from distributed_llama_tpu.formats.mfile import (load_model, params_file_order,
                                                 write_model)
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.obs import trace as trace_mod
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.apps.api_server import serve
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.tokenizer import TemplateType
from distributed_llama_tpu.tokenizer.bpe import Tokenizer


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("obs_api")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=262, seq_len=128).resolved()
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    return mpath, tpath


@pytest.fixture(scope="module")
def obs_server(model_files):
    """Batched server (--batch 2): the acceptance config — BatchEngine
    scheduler metrics must show up on /metrics under concurrent requests."""
    mpath, tpath = model_files
    lspec, lparams = load_model(mpath, 0)
    be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), slots=2, tp=1)
    srv = serve(None, host="127.0.0.1", port=0, template_type=TemplateType.CHATML,
                batch_engine=be)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield port
    srv.shutdown()
    be.close()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    return conn.getresponse()


def _post(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    return conn.getresponse()


_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})? '
    r"(-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$")


def _parse_prometheus(text: str) -> dict:
    """Strict-enough exposition parse: every non-comment line must be a valid
    sample; returns {sample_name_with_labels: float}."""
    assert text.endswith("\n"), "exposition must end with a newline"
    samples = {}
    typed = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            typed.add(line.split()[2])
            continue
        if line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
        name_lbl, val = line.rsplit(" ", 1)
        samples[name_lbl] = float(val.replace("+Inf", "inf"))
        base = name_lbl.split("{")[0]
        root = re.sub(r"_(bucket|sum|count)$", "", base)
        assert base in typed or root in typed, f"sample {base} missing # TYPE"
    return samples


def test_healthz(obs_server):
    r = _get(obs_server, "/healthz")
    assert r.status == 200
    assert json.loads(r.read())["status"] == "ok"
    assert _get(obs_server, "/health").status == 200


def test_metrics_under_concurrent_requests(obs_server):
    """The acceptance criterion: concurrent completions against a --batch
    server, then /metrics serves valid Prometheus text including the
    TTFT/TPOT/E2E histograms and the BatchEngine queue/occupancy gauges."""
    body = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 12, "temperature": 0, "seed": 5}
    results = []

    def client(i):
        r = _post(obs_server, "/v1/chat/completions",
                  dict(body, messages=[{"role": "user",
                                        "content": f"hi {i}"}]))
        results.append(r.status)
        r.read()

    threads = [threading.Thread(target=client, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert results == [200, 200, 200]

    r = _get(obs_server, "/metrics")
    assert r.status == 200
    assert r.getheader("Content-Type").startswith("text/plain")
    text = r.read().decode()
    samples = _parse_prometheus(text)

    # per-request latency histograms
    assert samples["api_request_ttft_seconds_count"] >= 3
    assert samples["api_request_e2e_seconds_count"] >= 3
    assert samples["api_request_tpot_seconds_count"] >= 3
    assert samples["api_request_e2e_seconds_sum"] > 0
    # histograms expose cumulative buckets ending in +Inf
    assert any(k.startswith('api_request_ttft_seconds_bucket{le="')
               for k in samples)
    assert (samples['api_request_ttft_seconds_bucket{le="+Inf"}']
            == samples["api_request_ttft_seconds_count"])

    # BatchEngine scheduler: queue + occupancy + dispatch telemetry
    assert samples["batch_slots_total"] == 2
    assert "batch_slots_occupied" in samples
    assert "batch_queue_depth" in samples
    assert samples["batch_queue_wait_seconds_count"] >= 3
    assert samples["batch_prefill_tokens_total"] > 0
    assert samples["batch_decode_tokens_total"] > 0
    dispatch = [k for k in samples
                if k.startswith('batch_dispatch_seconds_bucket')]
    assert dispatch, "per-dispatch histogram missing"
    # HTTP accounting saw the completions and this scrape's own route
    assert samples[
        'api_http_requests_total{route="/v1/chat/completions",code="200"}'] >= 3


def test_v1_stats_snapshot(obs_server):
    # self-contained: issue one completion so the snapshot has traffic even
    # when this test runs first / in isolation
    r = _post(obs_server, "/v1/chat/completions",
              {"messages": [{"role": "user", "content": "stats"}],
               "max_tokens": 4, "temperature": 0})
    assert r.status == 200
    r.read()
    r = _get(obs_server, "/v1/stats")
    assert r.status == 200
    data = json.loads(r.read())
    assert data["model"] == "distributed-llama-tpu"
    be = data["batch_engine"]
    assert be["slots"] == 2 and be["superstep"] >= 1
    assert be["prefilled_tokens"] > 0
    # the same histogram data as /metrics, JSON-shaped
    ttft = data["metrics"]["api_request_ttft_seconds"]
    assert ttft["count"] >= 1 and "buckets" in ttft


def test_openai_error_bodies(obs_server):
    # unknown route: GET and POST
    for r in (_get(obs_server, "/v1/embeddings"),
              _post(obs_server, "/v1/embeddings", {"input": "x"})):
        assert r.status == 404
        err = json.loads(r.read())["error"]
        assert err["type"] == "invalid_request_error" and err["message"]
    # malformed JSON body
    conn = http.client.HTTPConnection("127.0.0.1", obs_server, timeout=30)
    conn.request("POST", "/v1/chat/completions", "{not json",
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 400
    err = json.loads(r.read())["error"]
    assert err["type"] == "invalid_request_error"
    # missing messages[]
    r = _post(obs_server, "/v1/chat/completions", {"max_tokens": 4})
    assert r.status == 400
    assert json.loads(r.read())["error"]["type"] == "invalid_request_error"


def test_dllama_trace_flag(model_files, tmp_path, capsys):
    """`dllama --trace out.json` writes a Chrome trace that round-trips
    json.load with engine.dispatch spans nested inside engine.prefill."""
    from distributed_llama_tpu.apps import dllama

    mpath, tpath = model_files
    out = str(tmp_path / "trace.json")
    try:
        dllama.main(["inference", "--model", mpath, "--tokenizer", tpath,
                     "--tp", "1", "--steps", "4", "--prompt", "ab ab ab ab ab",
                     "--temperature", "0", "--trace", out])
    finally:
        trace_mod.uninstall()
    with open(out) as f:
        doc = json.load(f)
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    prefills = [e for e in evs if e["name"] == "engine.prefill"]
    dispatches = [e for e in evs if e["name"] == "engine.dispatch"]
    assert prefills and dispatches
    p = prefills[0]
    nested = [d for d in dispatches
              if p["ts"] <= d["ts"] and
              d["ts"] + d["dur"] <= p["ts"] + p["dur"]]
    assert nested, "prefill chunk dispatches must nest inside engine.prefill"
    # decode dispatches follow the prefill span
    assert any(d["ts"] >= p["ts"] + p["dur"] for d in dispatches)


def test_flight_recorder_endpoints(obs_server):
    """Tentpole (ISSUE 7): a completion's id resolves to its full flight
    timeline at GET /v1/requests/<id> (queue wait, prefill/super-steps,
    finish reason, TTFT/E2E), the listing supports ?slowest=K, and unknown
    ids 404 with an OpenAI-shaped error."""
    r = _post(obs_server, "/v1/chat/completions",
              {"messages": [{"role": "user", "content": "flight check"}],
               "max_tokens": 8, "temperature": 0})
    assert r.status == 200
    rid = r.getheader("X-Request-Id")
    body = json.loads(r.read())
    assert rid and body["id"] == rid  # completion id == flight key
    assert r.getheader("X-Replica")  # serving replica identity

    r = _get(obs_server, f"/v1/requests/{rid}")
    assert r.status == 200
    rec = json.loads(r.read())
    assert rec["id"] == rid and len(rec["trace_id"]) == 32
    assert rec["finish"] in ("length", "stop")
    assert rec["e2e_ms"] > 0 and rec["ttft_ms"] is not None
    assert rec["tokens"] == rec["generated_tokens"] == 8
    names = [e["event"] for e in rec["events"]]
    assert "admitted" in names, names
    assert any(n in ("prefill_chunk", "super_step") for n in names), names
    admitted = next(e for e in rec["events"] if e["event"] == "admitted")
    assert admitted["queue_wait_ms"] >= 0
    # timeline events are time-ordered offsets from request start
    ts = [e["t_ms"] for e in rec["events"]]
    assert ts == sorted(ts) and all(t >= 0 for t in ts)

    # the same record is reachable by its trace id (merged-trace workflow)
    r = _get(obs_server, f"/v1/requests/{rec['trace_id']}")
    assert r.status == 200 and json.loads(r.read())["id"] == rid

    # listing + slowest=K + bad query + unknown id
    r = _get(obs_server, "/v1/requests")
    assert r.status == 200
    listing = json.loads(r.read())
    assert any(s["id"] == rid for s in listing["completed"])
    r = _get(obs_server, "/v1/requests?slowest=1")
    assert r.status == 200 and len(json.loads(r.read())["completed"]) == 1
    assert _get(obs_server, "/v1/requests?slowest=x").status == 400
    r = _get(obs_server, "/v1/requests/chatcmpl-nonexistent")
    assert r.status == 404
    assert json.loads(r.read())["error"]["type"] == "invalid_request_error"


def test_traceparent_adoption_and_trace_endpoint(obs_server):
    """A client traceparent is adopted end-to-end: the flight record and the
    engine-side spans carry the inbound trace id, and GET /v1/trace serves
    the live Chrome trace (404 while tracing is disabled)."""
    assert _get(obs_server, "/v1/trace").status == 404
    tr = trace_mod.install(capacity=8192)
    try:
        tid = "ab" * 16
        conn = http.client.HTTPConnection("127.0.0.1", obs_server, timeout=120)
        conn.request("POST", "/v1/chat/completions",
                     json.dumps({"messages": [{"role": "user",
                                               "content": "traced request"}],
                                 "max_tokens": 6, "temperature": 0}),
                     {"Content-Type": "application/json",
                      "traceparent": f"00-{tid}-{'12' * 8}-01"})
        r = conn.getresponse()
        assert r.status == 200
        rid = r.getheader("X-Request-Id")
        r.read()

        rec = json.loads(_get(obs_server, f"/v1/requests/{rid}").read())
        assert rec["trace_id"] == tid  # adopted, not re-originated

        r = _get(obs_server, "/v1/trace")
        assert r.status == 200
        doc = json.loads(r.read())
        assert doc["otherData"]["pid"] == tr.pid
        stamped = [e for e in doc["traceEvents"]
                   if (e.get("args") or {}).get("trace_id") == tid]
        # scheduler-thread spans carry the request's trace id even though
        # the dispatch is shared (cross-thread reqctx re-entry)
        assert any(e["name"].startswith("batch.") for e in stamped), \
            [e["name"] for e in doc["traceEvents"]][:20]
    finally:
        trace_mod.uninstall()


def test_process_self_telemetry(obs_server):
    """Satellite: uptime/RSS/threads/tracer-drops gauges and the build-info
    gauge appear on /metrics with sane values."""
    text = _get(obs_server, "/metrics").read().decode()
    samples = _parse_prometheus(text)
    assert samples["dllama_uptime_seconds"] > 0
    assert samples["dllama_process_rss_bytes"] > 10 * 1024 * 1024
    assert samples["dllama_threads"] >= 2  # main + scheduler at least
    assert "dllama_tracer_dropped_events" in samples
    assert samples["dllama_process_pid"] > 0
    build = [k for k in samples if k.startswith("dllama_build_info{")]
    assert len(build) == 1 and samples[build[0]] == 1
    assert 'python="3.' in build[0] and "jax=" in build[0]


def test_batch_trace_superstep_spans(model_files):
    """Tracing a BatchEngine run records super-step spans that do not overlap
    on the scheduler thread (the nesting/ordering the acceptance names)."""
    mpath, tpath = model_files
    lspec, lparams = load_model(mpath, 0)
    be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), slots=2, tp=1,
                     superstep=4)
    tr = trace_mod.install(capacity=4096)
    try:
        from distributed_llama_tpu.runtime.sampler import Sampler

        sampler = Sampler(lspec.vocab_size, 0.0, 0.9, 0)
        out, _ = be.generate([1, 5, 9, 13], 12, sampler)
        assert len(out) == 12
        evs = [e for e in tr.events() if e["ph"] == "X"]
        supers = sorted((e for e in evs if e["name"] == "batch.super_step"),
                        key=lambda e: e["ts"])
        prefills = [e for e in evs if e["name"] in ("batch.prefill",
                                                    "batch.mixed_step")]
        assert supers and prefills
        assert supers[0]["args"]["k"] == 4
        # scheduler spans are sequential: no super-step starts before the
        # previous one (same thread) ended
        for a, b in zip(supers, supers[1:]):
            assert a["ts"] + a["dur"] <= b["ts"] + 1e-3
        # prefill precedes the first super-step
        assert min(e["ts"] for e in prefills) <= supers[0]["ts"]
    finally:
        trace_mod.uninstall()
        be.close()
