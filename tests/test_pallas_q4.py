"""Pallas q4 (split-plane packed nibble) kernel tests — interpret mode on CPU.

The i4p layout keeps the reference's exact Q40 HBM density (src/quants.hpp:17-20);
these tests pin (a) the layout round-trip, (b) the column-group packing that makes
in-axis TP slices self-contained, (c) kernel-vs-oracle numerics, and (d) the windowed
forward being exactly equivalent to the full-cache forward.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import init_random_params, prepare_for_pallas
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.pallas_q4 import q4_matvec
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import QK, FloatType, QTensor


def _to_jnp(t: QTensor) -> QTensor:
    return jax.tree_util.tree_map(jnp.asarray, t)


def test_f16_bits_decode_exhaustive():
    """The in-kernel f16-bits->f32 decode (_f16_bits_to_f32) must be bit-exact for
    EVERY finite f16 pattern — including subnormals and signed zeros — because the
    i4p layout ships the reference's Q40 deltas as raw int16 bit patterns. (The
    magic-multiply half->float trick fails this on TPU hardware: the VPU flushes
    subnormal f32 intermediates; the integer-math decode keeps every intermediate
    normal. Verified on a real v5e in round 4; this pins the math in interpret.)"""
    from distributed_llama_tpu.ops.pallas_q4 import _f16_bits_to_f32

    allbits = np.arange(65536, dtype=np.uint16)
    finite = ((allbits >> 10) & 0x1F) != 31  # exclude inf/nan (never valid deltas)
    got = np.asarray(jax.jit(_f16_bits_to_f32)(jnp.asarray(allbits.view(np.int16))))
    want = allbits.view(np.float16).astype(np.float32)
    np.testing.assert_array_equal(got[finite], want[finite])


def test_i4p_roundtrip_exact():
    rng = np.random.RandomState(3)
    w = QTensor.from_float(rng.randn(64, 256).astype(np.float32), FloatType.Q40)
    wi = w.to_i4p_layout()
    assert wi.data.shape == (64, 128) and wi.scales.dtype == np.int16
    np.testing.assert_array_equal(wi.to_numpy(), w.to_numpy())
    np.testing.assert_allclose(np.asarray(wi.dequantize(jnp.float32)), w.to_numpy(),
                               atol=1e-6)


def test_i4p_col_groups_make_shards_self_contained():
    """Slicing a col_groups=G i4p tensor along the packed axis into G parts must give
    each shard the exact i4p pack of its own natural column slice — the property that
    lets device_put shard in-axis (ColMatmulSlice) weights without repacking."""
    rng = np.random.RandomState(4)
    n, k, g = 16, 512, 4
    w = QTensor.from_float(rng.randn(n, k).astype(np.float32), FloatType.Q40)
    grouped = w.to_i4p_layout(col_groups=g)
    full = w.to_numpy()
    kl, khl, nbl = k // g, k // (2 * g), (k // QK) // g
    for s in range(g):
        shard = QTensor(grouped.ftype, grouped.data[:, s * khl:(s + 1) * khl],
                        grouped.scales[:, s * nbl:(s + 1) * nbl], layout="i4p")
        np.testing.assert_array_equal(shard.to_numpy(), full[:, s * kl:(s + 1) * kl])


def test_q4_matvec_matches_oracle():
    rng = np.random.RandomState(7)
    n, k = 128, 512
    w = QTensor.from_float((rng.randn(n, k) * 0.05).astype(np.float32), FloatType.Q40)
    wi = _to_jnp(w.to_i4p_layout())
    x = jnp.asarray(rng.randn(1, k).astype(np.float32)).astype(jnp.bfloat16)
    want = np.asarray(x, np.float32) @ w.to_numpy().T
    got = np.asarray(q4_matvec(x, wi, interpret=True), np.float32)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.02, rel  # Q80 activation quantization error scale


def test_q4_matvec_agrees_with_q8_kernel():
    """Same weights through the 4-bit packed kernel and the int8-plane kernel must be
    bit-identical modulo f16-vs-f32 scale precision (both quantize activations to the
    same Q80 blocks)."""
    from distributed_llama_tpu.ops.pallas_q8 import q8_matvec

    rng = np.random.RandomState(9)
    n, k = 64, 256
    w = QTensor.from_float((rng.randn(n, k) * 0.05).astype(np.float32), FloatType.Q40)
    x = jnp.asarray(rng.randn(1, k).astype(np.float32)).astype(jnp.bfloat16)
    y4 = np.asarray(q4_matvec(x, _to_jnp(w.to_i4p_layout()), interpret=True), np.float32)
    y8 = np.asarray(q8_matvec(x, _to_jnp(w.to_i8_layout()), interpret=True), np.float32)
    np.testing.assert_allclose(y4, y8, rtol=2e-3, atol=1e-5)


def test_q4_matvec_requires_i4p_layout():
    w = QTensor.from_float(np.ones((8, 64), np.float32), FloatType.Q40)
    with pytest.raises(ValueError, match="i4p"):
        q4_matvec(jnp.ones((1, 64)), w, interpret=True)


def test_prepare_for_pallas_picks_i4p_for_q40():
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=16,
                     rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=7)
    pp = prepare_for_pallas(params, tp=2, spec=spec)
    # QKV and gate/up merge into single row-concatenated tensors (fuse_matvec_groups)
    assert pp["blocks"]["wqkv"].layout == "i4p" and pp["blocks"]["wqkv"].groups == 1
    assert pp["blocks"]["wqkv"].shape[1] == spec.dim + 2 * spec.kv_dim
    assert pp["blocks"]["w13"].shape[1] == 2 * spec.hidden_dim
    assert "wq" not in pp["blocks"] and "w1" not in pp["blocks"]
    assert pp["blocks"]["w2"].layout == "i4p" and pp["blocks"]["w2"].groups == 2
    assert pp["wcls"].layout == "i4p"
    # Q80 weights keep the int8-plane layout (no 4-bit repack possible)
    p80 = prepare_for_pallas(init_random_params(spec, FloatType.Q80, seed=7), tp=1)
    assert p80["blocks"]["wqkv"].layout == "i8"


def test_sharded_forward_with_i4p_params():
    """tp=2 shard_map over grouped-i4p params (the col-sharded w2/wo carry groups=tp in
    their pytree aux): shard_params + the jitted step must run and match the planar
    TP step. Regression test for the groups-aux pytree mismatch."""
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                                   make_sharded_forward, shard_params)

    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=16,
                     rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=3)
    mesh = make_mesh(tp=2)
    tokens = jnp.asarray([[1, 2, 3]])

    base = shard_params(params, mesh, spec)
    step = make_sharded_forward(spec, mesh, base, donate_cache=False)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    want, _, _ = step(base, RopeTables.create(spec), tokens, kc, vc, jnp.int32(0))

    pp = shard_params(prepare_for_pallas(params, tp=2), mesh, spec)
    assert pp["blocks"]["w2"].groups == 2
    stepp = make_sharded_forward(spec, mesh, pp, donate_cache=False)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, _, _ = stepp(pp, RopeTables.create(spec), tokens, kc, vc, jnp.int32(0))
    # prefill goes through the XLA dequant path; i4p dequant must match planar exactly
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4)


def test_moe_decode_kernel_path_matches_planar():
    """Mixtral decode with i4p expert stacks (the kernel path slices each active
    expert's packed planes with dynamic_slice) must match the planar gather path at
    Q80 activation-quantization error scale."""
    spec = ModelSpec(arch_type=ArchType.MIXTRAL, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=16,
                     n_experts=4, n_active_experts=2,
                     rope_type=RopeType.FALCON).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=13)
    rope = RopeTables.create(spec)
    pp = prepare_for_pallas(params)
    # up+gate merge into the moe_gu stack (fuse_matvec_groups)
    assert pp["blocks"]["moe_gu"].layout == "i4p"
    assert pp["blocks"]["moe_gu"].shape[-2] == 2 * spec.hidden_dim

    tok = jnp.asarray([[5]])
    kc, vc = init_kv_cache(spec)
    want, _, _ = forward(params, spec, rope, tok, kc, vc, jnp.int32(0))
    kc, vc = init_kv_cache(spec)
    got, _, _ = forward(pp, spec, rope, tok, kc, vc, jnp.int32(0), use_pallas=True)
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.03, rel


def test_windowed_forward_equals_full():
    """attn_window >= pos+T must give EXACTLY the full-cache forward's logits — the
    positions mask already hides everything past pos, the window only trims dead reads."""
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=64,
                     rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.F32, seed=5)
    rope = RopeTables.create(spec)
    tokens = jnp.asarray([[9, 2, 17, 4, 31]])

    kc, vc = init_kv_cache(spec)
    want, kcf, vcf = forward(params, spec, rope, tokens, kc, vc, jnp.int32(0))
    kc, vc = init_kv_cache(spec)
    got, kcw, vcw = forward(params, spec, rope, tokens, kc, vc, jnp.int32(0),
                            attn_window=16)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # the cache itself is identical (same writes, windowing only affects reads)
    np.testing.assert_array_equal(np.asarray(kcw), np.asarray(kcf))

    # decode continuation at pos=5 with a window still matches
    tok = jnp.asarray([[7]])
    want2, _, _ = forward(params, spec, rope, tok, kcf, vcf, jnp.int32(5))
    got2, _, _ = forward(params, spec, rope, tok, kcw, vcw, jnp.int32(5),
                         attn_window=16)
    np.testing.assert_array_equal(np.asarray(got2), np.asarray(want2))


def test_q4_inline_xexp_matches_standard(monkeypatch):
    """The scratch-built Xexp variant must produce bit-identical results to the
    HBM-materialized one (same int8 quantization, same dots) — across a MULTI-step
    grid, so the build-at-step-0/reuse-later scratch mechanism is actually exercised."""
    import distributed_llama_tpu.ops.pallas_q4 as pq4

    monkeypatch.setattr(pq4, "_pick_bn", lambda n, k, budget_bytes=0: 128)
    rng = np.random.RandomState(21)
    n, k = 512, 512  # grid = 4 row blocks
    w = QTensor.from_float((rng.randn(n, k) * 0.05).astype(np.float32), FloatType.Q40)
    wi = _to_jnp(w.to_i4p_layout())
    x = jnp.asarray(rng.randn(1, k).astype(np.float32)).astype(jnp.bfloat16)
    y0 = np.asarray(q4_matvec(x, wi, interpret=True, inline_xexp=False))
    y1 = np.asarray(q4_matvec(x, wi, interpret=True, inline_xexp=True))
    np.testing.assert_array_equal(y0, y1)
