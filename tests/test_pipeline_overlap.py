"""Tier-1 wiring for perf/pipeline_overlap.py (ISSUE 5 satellite, the
test_smoke_lint.py pattern): pipelined super-steps must cut the device-idle
gap to < 50% of the unpipelined scheduler's on the CPU mesh, and a stream of
1-token requests (maximum flush pressure) must complete without deadlock or
slot/lease leak."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "perf"))

import pipeline_overlap  # noqa: E402


def test_pipeline_halves_device_idle_gap():
    spec = pipeline_overlap._spec()
    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.quants import FloatType

    params = init_random_params(spec, FloatType.Q40, seed=11)
    gap_off, n_off = pipeline_overlap.measure_gap(spec, params, pipeline=False)
    gap_on, n_on = pipeline_overlap.measure_gap(spec, params, pipeline=True)
    assert n_off > 0 and n_on > 0
    assert gap_on < 0.5 * gap_off, (gap_on, gap_off)


def test_flush_storm_no_deadlock_no_leak():
    spec = pipeline_overlap._spec()
    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.quants import FloatType

    params = init_random_params(spec, FloatType.Q40, seed=11)
    problems = pipeline_overlap.flush_storm(spec, params)
    assert not problems, "\n".join(problems)
