"""Multi-tenant SLO-aware serving (ISSUE 11, docs/SERVING.md "Multi-tenant
serving"): the policy layer (resilience/tenancy.py) and its wiring through
the BatchEngine scheduler, the api_server HTTP surface, and the fleet
router.

- weighted-fair queue vs an ideal fluid-share oracle (service within ε of
  weights over any window), class priority, least-entitled eviction;
- token-bucket quotas (429 + bucket-derived Retry-After) and the
  drain-rate estimator whose Retry-After hints track measured load (the
  hardcoded-1.0 regression, ISSUE 11 satellite);
- no tenant starves under an adversarial flooding tenant;
- a batch-class request preempted at a super-step boundary resumes
  BYTE-IDENTICAL to an uninterrupted run (greedy AND seeded-stochastic);
- tenant attribution end-to-end: X-Tenant → reqctx → flight timelines →
  /v1/requests?tenant= filtering; the router relays the header upstream.
"""

import http.client
import json
import threading
import time

import pytest

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.obs import flight
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.resilience.errors import (EngineSaturated,
                                                     QuotaExceeded)
from distributed_llama_tpu.resilience.tenancy import (DrainRate, FairGate,
                                                      TenantRegistry,
                                                      TokenBucket,
                                                      WeightedFairQueue,
                                                      sanitize_tenant)
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.sampler import Sampler

VOCAB = 256


def _spec(seq_len=160):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=VOCAB,
                     seq_len=seq_len, rope_type=RopeType.LLAMA).resolved()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


# ----------------------------------------------------------------------
# policy primitives (no engine)
# ----------------------------------------------------------------------

def test_token_bucket_rate_and_burst():
    b = TokenBucket(rate=100.0, burst=50.0)
    ok, _ = b.try_acquire(50.0)  # full burst available immediately
    assert ok
    ok, wait = b.try_acquire(50.0)  # empty: must wait ~cost/rate
    assert not ok
    assert 0.1 < wait <= 0.5 + 1e-6
    time.sleep(wait + 0.05)
    ok, _ = b.try_acquire(50.0)  # refilled at `rate`
    assert ok


def test_token_bucket_oversized_cost_clamped():
    b = TokenBucket(rate=10.0, burst=20.0)
    ok, _ = b.try_acquire(10_000.0)  # clamped to burst: passes when full
    assert ok
    ok, wait = b.try_acquire(10_000.0)
    assert not ok and wait <= 2.0 + 1e-6  # never quotes an unserviceable wait


def test_registry_parse_resolve_and_canonical():
    reg = TenantRegistry.parse(
        "gold:weight=4,rate=100,burst=200;free:weight=1;default:rate=50")
    assert reg.resolve("gold").weight == 4
    assert reg.resolve("gold").bucket is not None
    assert reg.resolve("free").bucket is None  # no rate = unlimited
    # unknown ids share the default policy — bounded cardinality
    assert reg.resolve("attacker-4711") is reg.resolve(None)
    assert reg.canonical("attacker-4711") == "default"
    assert reg.canonical("gold") == "gold"
    assert reg.resolve(None).bucket is not None  # default got a quota
    with pytest.raises(ValueError):
        TenantRegistry.parse("bad:velocity=9")
    with pytest.raises(AssertionError):
        TenantRegistry.parse("zero:weight=0")


def test_registry_quota_raises_with_retry_after():
    reg = TenantRegistry.parse("tiny:rate=10,burst=10")
    reg.acquire("tiny", 10.0)
    with pytest.raises(QuotaExceeded) as ei:
        reg.acquire("tiny", 10.0)
    assert ei.value.tenant == "tiny"
    assert 0.0 < ei.value.retry_after <= 1.0 + 1e-6
    assert reg.stats()["tiny"]["throttled"] == 1


def test_sanitize_tenant():
    assert sanitize_tenant("acme-prod.v2") == "acme-prod.v2"
    assert sanitize_tenant(None) == "default"
    assert sanitize_tenant("  ") == "default"
    assert sanitize_tenant("x" * 65) == "default"
    assert sanitize_tenant("bad tenant\n") == "default"


def test_drain_rate_retry_after_tracks_load():
    """ISSUE 11 satellite regression: the backoff hint must TRACK the
    measured drain rate and depth — not a constant. Same depth drains
    faster → smaller hint; same rate, deeper queue → larger hint; floor
    and cap are honored."""
    fast, slow = DrainRate(tau=1.0), DrainRate(tau=1.0)
    for _ in range(50):
        fast.note()
    for _ in range(2):
        slow.note()
    assert fast.rate() > slow.rate() > 0.0
    depth = 40
    assert fast.retry_after(depth) < slow.retry_after(depth)
    assert fast.retry_after(depth) <= slow.retry_after(depth)
    # deeper queue at the same rate → larger (monotone) hint
    assert slow.retry_after(depth) <= slow.retry_after(4 * depth)
    # floor: an instant drain never quotes ~0 (busy-spin protection)
    assert fast.retry_after(0) >= fast.floor
    # cap: a stalled queue never quotes an hour
    assert slow.retry_after(10_000_000) <= slow.cap
    # cold start: no completions observed — floor, and never a shed signal
    cold = DrainRate()
    assert cold.retry_after(100) == cold.floor
    assert cold.queue_wait(100) == 0.0


def test_wfq_matches_fluid_share_oracle():
    """Property test vs the ideal fluid server: with every tenant
    backlogged, service delivered over ANY window of consecutive pops is
    within ε of the weight shares — the no-starvation guarantee."""
    from distributed_llama_tpu.resilience.tenancy import TenantPolicy

    weights = {"a": 5.0, "b": 2.0, "c": 1.0}
    reg = TenantRegistry([TenantPolicy(n, weight=w)
                          for n, w in weights.items()])
    q = WeightedFairQueue(reg)
    n_items = 420
    for t in weights:  # every tenant stays backlogged through all pops
        for i in range(2 * n_items):
            q.push((t, i), t, "batch", 1.0)
    order = [q.pop_next() for _ in range(n_items)]
    total_w = sum(weights.values())
    window = 80
    for start in range(0, n_items - window, 17):
        win = [t for t, _i in order[start:start + window]]
        for t, w in weights.items():
            expected = window * w / total_w
            got = win.count(t)
            assert abs(got - expected) <= 0.1 * window + 2.0, \
                (start, t, got, expected)
    # per-tenant FIFO order is preserved
    for t in weights:
        idx = [i for tt, i in order if tt == t]
        assert idx == sorted(idx)


def test_wfq_weighted_costs_and_interactive_priority():
    reg = TenantRegistry.parse("heavy:weight=1;light:weight=1")
    q = WeightedFairQueue(reg)
    # heavy items cost 4x: light should be served ~4x as often
    for i in range(40):
        q.push(("h", i), "heavy", "batch", 4.0)
        q.push(("l", i), "light", "batch", 1.0)
    first = [q.pop_next()[0] for _ in range(20)]
    assert first.count("l") >= 3 * first.count("h")
    # interactive strictly precedes every queued batch item
    q.push(("i", 0), "heavy", "interactive", 100.0)
    assert q.pop_next()[0] == "i"


def test_wfq_evict_last_picks_least_entitled_batch():
    reg = TenantRegistry.parse("a:weight=1;b:weight=1")
    q = WeightedFairQueue(reg)
    q.push("a0", "a", "batch", 1.0)
    q.push("b0", "b", "batch", 1.0)
    q.push("b1", "b", "batch", 1.0)  # b's newest: max finish tag
    q.push("i0", "a", "interactive", 1.0)
    assert q.evict_last("batch") == "b1"
    assert q.evict_last("interactive") == "i0"
    assert len(q) == 2
    # eviction rolled b's tag back: next b push is not charged for b1
    q.push("b2", "b", "batch", 1.0)
    got = [q.pop_next() for _ in range(3)]
    assert set(got) == {"a0", "b0", "b2"}


def test_wfq_idle_tenant_not_starved_on_return():
    """Review regression: virtual time must advance as items are SERVED
    (pop_next) — a tenant returning from idle is charged from "now", not
    from zero, so a long-served tenant is never starved behind a
    newcomer's fresh tags."""
    reg = TenantRegistry.parse("old:weight=1;new:weight=1")
    q = WeightedFairQueue(reg)
    for i in range(60):  # a long 'old'-only service history
        q.push(("old", i), "old", "batch", 1.0)
    for _ in range(60):
        assert q.pop_next()[0] == "old"
    # newcomer arrives; old keeps submitting — they must interleave ~1:1
    for i in range(20):
        q.push(("new", i), "new", "batch", 1.0)
        q.push(("old", 100 + i), "old", "batch", 1.0)
    first10 = [q.pop_next()[0] for _ in range(10)]
    assert first10.count("old") >= 3, first10  # not starved behind 'new'


def test_wfq_clear_resets_virtual_time():
    """Review regression: clear() (the fail-all/recovery path) must drop
    per-tenant tags — pre-wedge service must not starve a tenant against
    one that was idle when the engine wedged."""
    reg = TenantRegistry.parse("busy:weight=1;idle:weight=1")
    q = WeightedFairQueue(reg)
    for i in range(50):
        q.push(("busy", i), "busy", "batch", 1.0)
    for _ in range(50):
        q.pop_next()
    q.clear()
    for i in range(10):
        q.push(("busy", i), "busy", "batch", 1.0)
        q.push(("idle", i), "idle", "batch", 1.0)
    first6 = [q.pop_next()[0] for _ in range(6)]
    assert first6.count("busy") >= 2, first6


def test_quota_refund_restores_bucket():
    """Review regression: a request shed AFTER the quota debit (admission
    control, router gate) received zero service — the refund restores the
    bucket so the retry is not double-punished."""
    reg = TenantRegistry.parse("t:rate=10,burst=20")
    before = reg.resolve("t").bucket.available()
    reg.acquire("t", 15.0)
    reg.refund("t", 15.0)
    assert reg.resolve("t").bucket.available() >= before - 0.5
    # refund never overflows the burst
    reg.refund("t", 1e9)
    assert reg.resolve("t").bucket.available() <= 20.0


def test_fair_gate_orders_waiters():
    gate = FairGate(1, TenantRegistry.parse("x:weight=1;y:weight=1"))
    assert gate.acquire("x", "batch")  # takes the only slot
    got = []
    ev = threading.Event()

    def waiter(tenant, klass):
        assert gate.acquire(tenant, klass, timeout=10.0)
        got.append((tenant, klass))
        ev.set()

    t1 = threading.Thread(target=waiter, args=("x", "batch"))
    t1.start()
    time.sleep(0.05)
    t2 = threading.Thread(target=waiter, args=("y", "interactive"))
    t2.start()
    time.sleep(0.05)
    gate.release()  # the LATER interactive waiter must win the slot
    ev.wait(5.0)
    assert got == [("y", "interactive")]
    gate.release()
    t1.join(5.0)
    t2.join(5.0)
    assert got == [("y", "interactive"), ("x", "batch")]
    assert gate.acquire("x", "batch", timeout=0.05) is False  # full again
    # disabled gate is a no-op
    assert FairGate(0).acquire("anyone", "batch", timeout=0.0)


# ----------------------------------------------------------------------
# engine integration
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    reg = TenantRegistry.parse("alpha:weight=4;beta:weight=2;flood:weight=1")
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4, tenants=reg)
    be.generate([1, 7, 23, 5], 4, _greedy(spec))  # warm the shapes
    yield spec, be
    be.close()


def test_no_starvation_under_flooding_tenant(engine):
    """An adversarial tenant floods the queue FIRST; later light tenants
    must still complete promptly — every victim finishes before the
    flood's tail (weighted-fair + class priority), and nobody times out."""
    spec, be = engine
    flood = [be.submit([1, 40 + i, 23, 5], 12, _greedy(spec),
                       tenant="flood", klass="batch")
             for i in range(10)]
    victims = [be.submit([1, 60 + i, 3], 6, _greedy(spec), tenant=t,
                         klass="interactive")
               for i, t in enumerate(("alpha", "beta", "alpha", "beta"))]
    for r in victims:
        r.wait(timeout=120)
    # the victims did NOT queue behind the whole flood: when the last
    # victim finished, flood work remained (or its rows were preempted)
    flood_unfinished = sum(1 for r in flood if not r.done.is_set())
    for r in flood:
        r.wait(timeout=120)
    assert all(len(r.out) == 12 for r in flood)   # flooder not starved either
    assert all(len(r.out) == 6 for r in victims)  # victims fully served
    assert (flood_unfinished >= 1
            or sum(r.preemptions for r in flood) >= 1), \
        "victims waited behind the entire flood (FIFO behavior)"


@pytest.fixture(scope="module")
def solo_engine():
    """slots=1: preemption timing is deterministic — the single slot is
    always busy with the batch victim when the interactive arrives."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=1, tp=1, superstep=4)
    be.generate([1, 7, 23, 5], 4, _greedy(spec))
    yield spec, be
    be.close()


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_preempted_batch_resumes_byte_identical(solo_engine, temperature):
    """ISSUE 11 acceptance: a batch request preempted at a super-step
    boundary (slot handed to an interactive arrival) resumes
    byte-identical to an uninterrupted run — greedy AND seeded-stochastic
    (the sampler replays only delivered coins; re-admission prefills
    prompt ⊕ delivered, mostly a radix prefix-cache hit)."""
    spec, be = solo_engine
    prompt, gen, seed = [1, 9, 9, 2], 80, 1234

    def sampler():
        return Sampler(spec.vocab_size, temperature, 0.9, seed)

    ref = be.submit(list(prompt), gen, sampler(), klass="batch").wait(
        timeout=300)
    assert len(ref) == gen
    victim = be.submit(list(prompt), gen, sampler(), klass="batch")
    while len(victim.out) < 9:  # mid-generation, several super-steps in
        time.sleep(0.003)
    inter = be.submit([1, 2, 3], 4, _greedy(spec), klass="interactive")
    assert inter.wait(timeout=300) is not None
    out = victim.wait(timeout=300)
    assert victim.preemptions >= 1, "the preemption never engaged"
    assert out == ref, (temperature, victim.preemptions)
    assert victim.stats.reused_tokens > 0  # resume was not a full re-prefill


def test_interactive_rows_never_preempted(solo_engine):
    spec, be = solo_engine
    a = be.submit([1, 9, 9, 2], 40, _greedy(spec), klass="interactive")
    while len(a.out) < 4:
        time.sleep(0.003)
    b = be.submit([1, 2, 3], 4, _greedy(spec), klass="interactive")
    a_out = a.wait(timeout=300)
    b.wait(timeout=300)
    assert a.preemptions == 0 and len(a_out) == 40


def test_engine_saturated_retry_after_is_drain_derived(engine):
    """ISSUE 11 satellite regression: EngineSaturated.retry_after comes
    from the engine's DrainRate estimator (depth / measured rate), not the
    old hardcoded max(queue_ttl, 1.0)."""
    spec, be = engine

    class StubDrain:
        floor = 1.0

        def note(self, n=1.0):
            pass

        def rate(self):
            return 0.125  # 1 completion / 8s

        def queue_wait(self, depth):
            return depth / 0.125

        def retry_after(self, depth):
            return min(max(depth / 0.125, 1.0), 60.0)

    old_drain, old_mq = be._drain, be.max_queue
    be._drain, be.max_queue = StubDrain(), 1
    blocker = []
    try:
        with pytest.raises(EngineSaturated) as ei:
            for i in range(32):  # the queue refills as rows admit
                blocker.append(be.submit([1, 77 + i % 50, 5], 30,
                                         _greedy(spec), klass="batch"))
        # depth >= 1 at 0.125/s → at least 8s, and never the 1.0 constant
        assert ei.value.retry_after >= 8.0, ei.value.retry_after
        assert ei.value.retry_after <= 60.0
    finally:
        be._drain, be.max_queue = old_drain, old_mq
        for r in blocker:
            try:
                r.wait(timeout=300)
            except Exception:
                pass


def test_slo_shed_requires_backlog(engine):
    """Regression (found driving a live server): an engine idle long enough
    for the drain EMA to decay to ~0 must still ADMIT a batch request when
    the queue is empty — the SLO projection applies only to real backlog,
    never to a decayed denominator at queue depth 0."""
    from distributed_llama_tpu.resilience.tenancy import DrainRate

    spec, be = engine
    old_drain, old_tgt = be._drain, dict(be.slo_ttft)
    decayed = DrainRate()
    decayed.note()
    with decayed._lock:  # age the one completion 10 minutes into the past
        decayed._t -= 600.0
    assert 0.0 <= decayed.rate() < 1e-3
    be._drain = decayed
    be.slo_ttft["batch"] = 0.5
    try:
        r = be.submit([1, 8, 8], 4, _greedy(spec), klass="batch")
        assert len(r.wait(timeout=120)) == 4  # admitted, not shed
    finally:
        be._drain, be.slo_ttft = old_drain, old_tgt


def test_interactive_evicts_queued_batch_when_saturated(engine):
    """Shed batch before interactive: at max_queue, an interactive arrival
    displaces the least-entitled QUEUED batch request instead of shedding
    itself."""
    spec, be = engine
    old_mq = be.max_queue
    be.max_queue = 2
    try:
        held = [be.submit([1, 30 + i, 5], 25, _greedy(spec), klass="batch")
                for i in range(2)]  # occupy both slots
        deadline = time.monotonic() + 30
        while be.load_stats()["free_slots"] and time.monotonic() < deadline:
            time.sleep(0.005)  # wait for both to be admitted onto slots
        queued = [be.submit([1, 50 + i, 5], 25, _greedy(spec), klass="batch")
                  for i in range(2)]  # fill the wait queue to max_queue
        deadline = time.monotonic() + 30
        while len(be._pending) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)  # eviction searches the drained fair queue
        inter = be.submit([1, 2, 3], 4, _greedy(spec), klass="interactive")
        out = inter.wait(timeout=300)
        assert len(out) == 4
        # exactly one queued batch request was shed with the honest 503
        shed = [r for r in queued if r.error is not None]
        assert len(shed) == 1
        assert isinstance(shed[0].error, EngineSaturated)
        assert shed[0].error.retry_after >= 1.0
        for r in held + [r for r in queued if r.error is None]:
            r.wait(timeout=300)
    finally:
        be.max_queue = old_mq


def test_interactive_evicts_batch_still_in_cross_thread_queue(engine):
    """Review regression: eviction must see batch work still sitting in
    the cross-thread submit queue (scheduler mid-dispatch), not only the
    drained fair queue — an interactive arrival is never refused while ANY
    queued batch request exists."""
    from distributed_llama_tpu.runtime.batch_engine import BatchRequest

    spec, be = engine
    old_mq = be.max_queue
    be.max_queue = 1
    ghost = BatchRequest([1, 2, 3], 4, _greedy(spec))
    ghost.klass = "batch"
    ghost.wfq_cost = 7.0
    try:
        # plant a batch request in the CROSS-THREAD queue only (white-box:
        # as if submitted while the scheduler is stuck in a long dispatch)
        be._queue.put(ghost)
        inter = be.submit([1, 2, 3], 4, _greedy(spec), klass="interactive")
        assert len(inter.wait(timeout=120)) == 4  # admitted, not refused
        assert ghost.done.is_set()  # the ghost was the evicted victim
        assert isinstance(ghost.error, EngineSaturated)
    finally:
        be.max_queue = old_mq


def test_tenant_attribution_in_flight_records(engine):
    spec, be = engine
    rec = flight.install(64)
    try:
        r = be.submit([1, 5, 6], 4, _greedy(spec), tenant="alpha",
                      klass="batch", rid="tn-attr-1")
        r.wait(timeout=120)
        full = rec.get("tn-attr-1")
        assert full["tenant"] == "alpha" and full["class"] == "batch"
        listing = rec.requests(tenant="alpha")
        assert any(s["id"] == "tn-attr-1" for s in listing["completed"])
        assert all(s["tenant"] == "alpha" for s in listing["completed"])
        empty = rec.requests(tenant="nobody")
        assert empty["completed"] == [] and empty["live"] == []
    finally:
        flight.uninstall()


def test_quota_throttle_at_engine(engine):
    spec, be = engine
    reg = be.tenants
    # graft a tight quota onto a fresh tenant entry for this test
    from distributed_llama_tpu.resilience.tenancy import TenantPolicy

    reg._policies["capped"] = TenantPolicy("capped", weight=1.0, rate=20.0,
                                           burst=40.0)
    with pytest.raises(QuotaExceeded) as ei:
        for i in range(8):
            be.submit([1, 4, 4], 30, _greedy(spec),
                      tenant="capped").wait(timeout=120)
    assert ei.value.retry_after > 0.0


# ----------------------------------------------------------------------
# router-level: relay + drain-derived hint (stub replicas, no model)
# ----------------------------------------------------------------------

def _stub_replica(seen: list):
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Stub(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *a):
            pass

        def do_GET(self):
            body = json.dumps({"status": "ok", "replica": {
                "id": "stub", "model_hash": "deadbeef0000", "slots": 2,
                "free_slots": 2, "queue_depth": 0, "draining": False,
            }}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            seen.append({"X-Tenant": self.headers.get("X-Tenant"),
                         "X-Class": self.headers.get("X-Class")})
            body = json.dumps({"choices": [{"message": {
                "role": "assistant", "content": "ok"},
                "finish_reason": "stop", "index": 0}]}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Stub)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_router_relays_tenant_and_throttles():
    from distributed_llama_tpu.fleet.router import close_router, serve_router

    seen: list = []
    stub = _stub_replica(seen)
    router = serve_router([f"127.0.0.1:{stub.server_address[1]}"],
                          host="127.0.0.1", port=0, poll_interval=0.2,
                          retries=1, try_timeout=10.0,
                          tenants="capped:weight=1,rate=5,burst=60")
    threading.Thread(target=router.serve_forever, daemon=True).start()
    rport = router.server_address[1]
    try:
        def post(tenant, klass=None, max_tokens=8):
            conn = http.client.HTTPConnection("127.0.0.1", rport, timeout=30)
            try:
                hdrs = {"Content-Type": "application/json",
                        "X-Tenant": tenant}
                if klass:
                    hdrs["X-Class"] = klass
                conn.request("POST", "/v1/chat/completions", json.dumps(
                    {"messages": [{"role": "user", "content": "hi"}],
                     "max_tokens": max_tokens}), hdrs)
                resp = conn.getresponse()
                return resp.status, dict(resp.getheaders()), resp.read()
            finally:
                conn.close()

        status, _h, _b = post("acme", klass="batch")
        assert status == 200
        assert seen[-1] == {"X-Tenant": "acme", "X-Class": "batch"}
        # unlabeled traffic relays the canonical default tenant
        status, _h, _b = post("", klass=None)
        assert status == 200
        assert seen[-1]["X-Tenant"] == "default"
        # router-level quota: burst 60 ≈ one 8-token request + change, so a
        # hammering capped tenant sees 429 + Retry-After before any proxy
        saw_429 = None
        for _ in range(8):
            status, hdrs, body = post("capped", max_tokens=30)
            if status == 429:
                saw_429 = (hdrs, body)
                break
        assert saw_429 is not None, "quota never throttled"
        hdrs, body = saw_429
        assert "Retry-After" in hdrs
        assert json.loads(body)["error"]["type"] == "rate_limit_error"
        assert seen[-1]["X-Tenant"] != "capped" or status != 429 or True
    finally:
        close_router(router)
        stub.shutdown()
        stub.server_close()


def test_router_retry_after_hint_tracks_load():
    """ISSUE 11 satellite regression: the fleet-saturation Retry-After is
    measured-drain-derived (completions/sec vs backlog), not the
    poll_interval constant."""
    from distributed_llama_tpu.fleet.membership import Membership
    from distributed_llama_tpu.fleet.router import RouterState

    mem = Membership(["127.0.0.1:1"], poll_interval=2.0, poll_timeout=0.2)
    state = RouterState(mem, retries=0)
    # cold start: floor (and finite), not the poll interval
    assert state.retry_after_hint() == state.drain.floor
    for _ in range(30):  # a briskly draining fleet
        state.note_done()
    fast = state.retry_after_hint()
    mem.replicas[0].queue_depth = 500  # now a deep backlog builds up
    deep = state.retry_after_hint()
    assert deep > fast
    assert state.drain.floor <= deep <= state.drain.cap


# ----------------------------------------------------------------------
# live api_server: X-Tenant end to end
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def tenant_server(tmp_path_factory):
    from distributed_llama_tpu.apps.api_server import serve
    from distributed_llama_tpu.formats.mfile import (load_model,
                                                     params_file_order,
                                                     write_model)
    from distributed_llama_tpu.formats.tfile import (TokenizerData,
                                                     write_tokenizer)
    from distributed_llama_tpu.tokenizer import TemplateType
    from distributed_llama_tpu.tokenizer.bpe import Tokenizer

    tmp = tmp_path_factory.mktemp("tenancy_api")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=262,
                     seq_len=128).resolved()
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    lspec, lparams = load_model(mpath, 0)
    reg = TenantRegistry.parse("gold:weight=4;capped:weight=1,rate=8,burst=40")
    be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), slots=2, tp=1,
                     tenants=reg)
    srv = serve(None, host="127.0.0.1", port=0,
                template_type=TemplateType.CHATML, batch_engine=be)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield port
    srv.shutdown()
    be.close()


def _post(port, body, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        h = {"Content-Type": "application/json"}
        if headers:
            h.update(headers)
        conn.request("POST", "/v1/chat/completions", json.dumps(body), h)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), \
            json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def test_api_tenant_attribution_end_to_end(tenant_server):
    port = tenant_server
    body = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 4}
    status, hdrs, payload = _post(port, body, {"X-Tenant": "gold"})
    assert status == 200
    rid = hdrs.get("X-Request-Id") or payload["id"]
    st, rec = _get(port, f"/v1/requests/{rid}")
    assert st == 200 and rec["tenant"] == "gold"
    assert rec["class"] == "interactive"
    st, listing = _get(port, "/v1/requests?tenant=gold")
    assert st == 200
    assert any(s["id"] == rid for s in listing["completed"])
    st, empty = _get(port, "/v1/requests?tenant=nonexistent")
    assert st == 200 and empty["completed"] == [] and empty["live"] == []
    # /v1/stats exposes the registry
    st, stats = _get(port, "/v1/stats")
    assert st == 200 and "gold" in stats["tenants"]


def test_api_class_field_and_validation(tenant_server):
    port = tenant_server
    base = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 2}
    status, _h, _p = _post(port, {**base, "class": "batch"},
                           {"X-Tenant": "gold"})
    assert status == 200
    status, _h, payload = _post(port, {**base, "class": "express"})
    assert status == 400
    assert payload["error"]["type"] == "invalid_request_error"


def test_api_quota_429_with_retry_after(tenant_server):
    port = tenant_server
    body = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 16}
    saw = None
    for _ in range(8):
        status, hdrs, payload = _post(port, body, {"X-Tenant": "capped"})
        if status == 429:
            saw = (hdrs, payload)
            break
    assert saw is not None, "quota never throttled"
    hdrs, payload = saw
    assert payload["error"]["type"] == "rate_limit_error"
    assert int(hdrs["Retry-After"]) >= 1
    # the throttle is the tenant's problem, not the server's: gold serves
    status, _h, _p = _post(port, {**body, "max_tokens": 2},
                           {"X-Tenant": "gold"})
    assert status == 200
