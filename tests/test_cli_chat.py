"""dllama chat REPL driven end-to-end with scripted stdin (Chat::chat parity,
reference dllama.cpp:132-193): KV position must persist across turns, the template
must wrap each user message, and the REPL must stop cleanly at EOF and context end."""

import io
import sys

import pytest

from distributed_llama_tpu.formats.mfile import params_file_order, write_model
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.quants import FloatType


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("chat_cli")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=262, seq_len=192).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=23)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.Q40)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    return mpath, tpath


def test_chat_repl_two_turns(model_files, monkeypatch, capsysbinary):
    from distributed_llama_tpu.apps import dllama

    mpath, tpath = model_files
    # system prompt line, then two user turns, then EOF
    monkeypatch.setattr(sys, "stdin", io.StringIO("be terse\nhello there\nand again\n"))
    args = dllama.build_parser().parse_args(
        ["chat", "--model", mpath, "--tokenizer", tpath, "--temperature", "0",
         "--seed", "3", "--chat-template", "chatml", "--tp", "2"])
    dllama.mode_chat(args)
    out = capsysbinary.readouterr().out.decode("utf-8", errors="replace")
    # at least one turn served (a random-weight model may fill the context in turn
    # one), the REPL exited cleanly (EOF or announced context end), no traceback
    assert out.count("🤖 Assistant") >= 1
    assert "💻 System prompt" in out


def test_chat_repl_turns_persist_and_prompt_overflow_guard(model_files, monkeypatch,
                                                           capsysbinary):
    """Multi-turn REPL invariants, with per-turn generation capped so turns stay
    short: (a) engine.pos persists and grows across turns (KV never reset —
    Chat::chat parity, dllama.cpp:132-193); (b) a next-turn prompt that no longer
    fits triggers the pre-prefill guard (clean context-end stop, not the
    ValueError('context overflow') Engine.infer_chunk would raise)."""
    from distributed_llama_tpu.apps import dllama

    mpath, tpath = model_files
    engines = []
    pos_after_turn = []
    real_make = dllama.make_engine

    def capped_make(args):
        eng = real_make(args)
        real_gen = eng.generate_with

        def capped(prompt, max_tokens, sampler, **kw):
            r = real_gen(prompt, min(max_tokens, 3), sampler, **kw)
            pos_after_turn.append(eng.pos)
            return r

        eng.generate_with = capped
        engines.append(eng)
        return eng

    monkeypatch.setattr(dllama, "make_engine", capped_make)
    # two short turns, then a user line far longer than the 64-token context
    monkeypatch.setattr(sys, "stdin",
                        io.StringIO("\nhi\nyo\n" + "x" * 300 + "\n"))
    args = dllama.build_parser().parse_args(
        ["chat", "--model", mpath, "--tokenizer", tpath, "--temperature", "0",
         "--seed", "3", "--chat-template", "chatml", "--max-seq-len", "128",
         "--tp", "2"])
    dllama.mode_chat(args)
    out = capsysbinary.readouterr().out.decode("utf-8", errors="replace")
    assert "(context end reached)" in out
    # two real turns ran; the third (oversized) was rejected by the guard BEFORE any
    # prefill: pos still where turn two left it, strictly growing across turns
    assert len(pos_after_turn) == 2 and pos_after_turn[1] > pos_after_turn[0] > 0
    assert engines[0].pos == pos_after_turn[1] < engines[0].spec.seq_len - 1
