"""Device-resident paged KV tests (ISSUE 12, docs/PAGED_KV.md).

Load-bearing properties:
- pool refcount/alloc/CoW metadata vs a brute-force oracle;
- directory remap/demote/promote lifecycle (zero-copy hits, cold uploads);
- token identity PAGED vs DENSE on the CPU mesh — greedy AND
  seeded-stochastic, speculative verify, pipelined chains — resting on the
  gather path's bit-exactness with the dense window computation;
- durable-resume admissions over remapped blocks;
- clamped parks copy-on-write instead of corrupting directory blocks;
- pool exhaustion fails only the starving request (scheduler survives);
- the Pallas kernel (interpret mode) serves the same tokens;
- the perf/paged_attn_bench.py parity gate (tier-1 smoke).
"""

import os
import sys
import time

import numpy as np
import pytest

from distributed_llama_tpu.cache.device_pool import (DeviceKVPool,
                                                     KVPoolExhausted,
                                                     PagedPrefixCache,
                                                     SCRATCH_BLOCK)
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.sampler import Sampler

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "perf"))


def _spec(seq_len=128):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=seq_len, rope_type=RopeType.LLAMA).resolved()


def _settle(pred, timeout=10):
    t0 = time.time()
    while not pred() and time.time() - t0 < timeout:
        time.sleep(0.01)
    assert pred()


# ------------------------------------------------------------------ pool


def test_pool_refcount_property_vs_oracle():
    """Random alloc/incref/decref interleavings against a dict oracle:
    conservation (allocated + free == capacity - scratch), refcount
    equality, no double-free, scratch never allocated."""
    rng = np.random.default_rng(7)
    pool = DeviceKVPool(24, 8)
    oracle: dict[int, int] = {}  # bid -> refs
    for _ in range(2000):
        op = rng.integers(0, 3)
        if op == 0:
            n = int(rng.integers(1, 4))
            ids = pool.alloc(n)
            if 24 - 1 - len(oracle) < n:
                assert ids is None
            else:
                assert ids is not None and len(ids) == n
                for b in ids:
                    assert b != SCRATCH_BLOCK and b not in oracle
                    oracle[b] = 1
        elif op == 1 and oracle:
            b = int(rng.choice(list(oracle)))
            pool.incref([b])
            oracle[b] += 1
        elif op == 2 and oracle:
            b = int(rng.choice(list(oracle)))
            pool.decref([b])
            oracle[b] -= 1
            if oracle[b] == 0:
                del oracle[b]
        refs = pool.refcounts()
        assert refs[SCRATCH_BLOCK] == 1
        for b, r in oracle.items():
            assert refs[b] == r, (b, refs[b], r)
        assert pool.free_blocks() == 24 - 1 - len(oracle)
        for b in range(1, 24):
            assert pool.shared(b) == (oracle.get(b, 0) > 1)
    if oracle:
        pool.decref([b for b, r in oracle.items() for _ in range(r)])
    assert pool.free_blocks() == 23


def test_directory_remap_demote_promote_roundtrip():
    """Insert-by-reference, lookup leases, demotion to the cold tier under
    reclaim, and promotion back on a later hit — block DATA round-trips
    through the host tier exactly (q80 off)."""
    pool = DeviceKVPool(16, 4)
    pc = PagedPrefixCache(pool, 4, cold_blocks=8, q80=False)
    store = {}  # bid -> (k, v) the fake device pool

    def read_block(bid):
        return store[bid]

    toks = list(range(1, 13))  # 3 full blocks of 4
    ids = pool.alloc(3)
    for i, b in enumerate(ids):
        store[b] = (np.full((2, 2, 4, 8), 10.0 + i, np.float32),
                    np.full((2, 2, 4, 8), 20.0 + i, np.float32))
    created = pc.insert_blocks(toks, ids)
    assert created == 3 and pc.radix.nodes == 3
    refs = pool.refcounts()
    assert all(refs[b] == 2 for b in ids)  # slot ref + directory ref

    # zero-copy hit: the lease resolves to the SAME device blocks
    lease = pc.lookup(toks + [99])
    assert lease is not None and lease.tokens == 12
    assert [n.handle for n in lease.nodes] == [("dev", b) for b in ids]
    pc.mark_seeded(lease, 12)
    pc.release(lease)

    # the "slot" releases its refs; reclaim demotes all three to the cold
    # tier and frees the device blocks
    pool.decref(ids)
    freed = pc.reclaim(3, read_block)
    assert freed == 3 and pool.free_blocks() == 15
    st = pc.stats()
    assert st["cold_blocks"] == 3 and st["dev_blocks"] == 0
    assert st["demoted_blocks"] == 3

    # a later hit still matches; promotion restores the exact rows
    lease = pc.lookup(toks + [99])
    assert lease is not None and lease.tokens == 12
    for i, node in enumerate(lease.nodes):
        tier, h = node.handle
        assert tier == "cold"
        k, v = pc.fetch_cold(h)
        assert np.array_equal(k, store[ids[i]][0])
        assert np.array_equal(v, store[ids[i]][1])
        nb = pool.alloc(1)[0]
        pc.promote(node, nb)
        assert node.handle == ("dev", nb)
    assert pc.stats()["dev_blocks"] == 3
    pc.release(lease)
    assert pc.total_refs() == 0


def test_cold_subtree_eviction_releases_dev_descendants():
    """Review regression: when a FULL cold tier forces _evict_cold_locked
    to drop a cold subtree, any dev-tier descendants dropped with it must
    surrender their pool refs — and the demotion loop must not double-count
    a victim that rode out with the dropped subtree."""
    pool = DeviceKVPool(8, 4)
    pc = PagedPrefixCache(pool, 4, cold_blocks=1, q80=False)
    store = {}

    def read_block(bid):
        return store[bid]

    toks = list(range(1, 9))  # 2 full blocks of 4
    ids = pool.alloc(2)
    for b in ids:
        store[b] = (np.full((1, 1, 4, 8), float(b), np.float32),
                    np.full((1, 1, 4, 8), float(b) + 0.5, np.float32))
    pc.insert_blocks(toks, ids)
    pool.decref(ids)  # directory-only refs remain
    pc.reclaim(1, read_block)   # parent demotes; cold tier now FULL
    assert pc.stats()["cold_blocks"] == 1
    pc.reclaim(1, read_block)   # child's demotion must evict the cold
    # subtree (which contains the child itself) exactly once
    assert pool.free_blocks() == 7, pool.refcounts()
    assert pc.radix.nodes == 0


def test_reclaim_spares_the_excluded_slot():
    """Review regression: the adopting slot looks idle (req bound only
    after _paged_adopt returns) — reclaim must never release the slot the
    allocation is being performed FOR."""
    spec = _spec(seq_len=64)
    params = init_random_params(spec, FloatType.Q40, seed=3)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                     prefix_cache=False, kv_block_tokens=8)
    try:
        slot = be._slots[0]
        be._paged_ensure(slot, 16)
        assert len(slot.blocks) == 2 and slot.req is None
        be._paged_reclaim(10 ** 6, exclude=slot)  # cannot be satisfied
        assert len(slot.blocks) == 2  # the excluded slot kept its table
        be._paged_reclaim(10 ** 6)    # unshielded: idle stock IS reclaimed
        assert slot.blocks == []
    finally:
        be.close()


# --------------------------------------------------- engine token identity


@pytest.fixture(scope="module")
def engines():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=23)
    dense = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                        paged_kv=False, prefix_cache=False)
    paged = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                        kv_block_tokens=8)
    yield spec, params, dense, paged
    paged.close()
    dense.close()


def _run(be, prompt, n, temperature=0.0, seed=0, vocab=256):
    return be.submit(list(prompt), n,
                     Sampler(vocab, temperature=temperature,
                             seed=seed)).wait(timeout=240)


SHARED = [1] + [10 + (i * 7) % 90 for i in range(33)]


def test_paged_vs_dense_token_identity(engines):
    """ISSUE 12 acceptance: greedy AND seeded-stochastic outputs are
    byte-identical paged-vs-dense, including cross-slot directory remaps
    mid-sequence."""
    spec, params, dense, paged = engines
    prompts = [SHARED + [200 + i] for i in range(3)] + [[1, 99, 98]]
    plans = [(0.0, 0), (0.8, 7), (0.8, 11), (0.0, 0)]
    wants = [_run(dense, p, 9, t, s) for p, (t, s) in zip(prompts, plans)]
    # concurrent co-batched mix: pipelined chains, shared radix, remaps
    # mid-run — every row must still match its dense sequential reference
    reqs = [paged.submit(list(p), 9, Sampler(spec.vocab_size, temperature=t,
                                             seed=s))
            for p, (t, s) in zip(prompts, plans)]
    outs = [r.wait(timeout=240) for r in reqs]
    assert outs == wants
    _settle(lambda: paged.prefix_cache.total_refs() == 0)


def test_paged_vs_dense_speculative_identity():
    """Speculative verify dispatches ride the paged pool byte-identically
    (repetitive prompts engage real (B, 1+k) verify blocks)."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=5)
    rep = [9, 21, 33] * 6
    outs = {}
    for paged in (False, True):
        be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                         speculative=4, paged_kv=paged, prefix_cache=paged)
        try:
            a = be.submit(list(rep), 16, Sampler(spec.vocab_size))
            b = be.submit(list(rep[2:]), 16,
                          Sampler(spec.vocab_size, temperature=0.8, seed=3))
            outs[paged] = (a.wait(240), b.wait(240))
            if paged:
                assert be.verify_steps >= 1  # the verify path really ran
        finally:
            be.close()
    assert outs[True] == outs[False]


def test_cache_on_off_identical_and_zero_seed_bytes():
    """Within the paged engine: directory on vs off is token-identical, the
    warm resubmit is a REMAP (blocks reused, zero host→device KV bytes),
    and the prefill skip is real."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=17)
    prompts = [SHARED + [210 + i] for i in range(3)]
    outs = {}
    for on in (False, True):
        be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                         prefix_cache=on, kv_block_tokens=8)
        try:
            outs[on] = [_run(be, prompts[0], 8)]
            _run(be, [1, 77, 78], 8)  # dirty both slots
            time.sleep(0.2)
            base = be.prefilled_tokens
            outs[on].append(_run(be, prompts[1], 8))
            if on:
                # 34 shared tokens -> 4 full 8-token blocks remapped (the
                # slot's own 1-token rewind overlap counts as resident)
                assert be.prefilled_tokens - base <= len(prompts[1]) - 32
                st = be.prefix_cache.stats()
                assert st["hit_tokens"] + st["resident_tokens"] >= 32
                assert st["hit_tokens"] >= 31
                assert be.seed_bytes == 0, be.seed_bytes
                _settle(lambda: be.prefix_cache.total_refs() == 0)
            outs[on].append(_run(be, prompts[2], 8))
        finally:
            be.close()
    assert outs[True] == outs[False]


def test_resume_over_remapped_blocks_byte_identical():
    """Durable-resume construction (prompt ⊕ delivered, fast-forwarded
    sampler) admitted over a DIRECTORY REMAP: the resumed stream must be
    byte-identical to the uninterrupted run, greedy and stochastic."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=29)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                     kv_block_tokens=8)
    prompt = SHARED[:17]
    try:
        for temperature, seed in ((0.0, 0), (0.8, 13)):
            smp = Sampler(spec.vocab_size, temperature=temperature, seed=seed)
            full = be.submit(list(prompt), 16, smp).wait(240)
            # dirty BOTH slots so the resume MUST come from the directory
            ra = be.submit([1, 3, 5], 6, Sampler(spec.vocab_size))
            rb = be.submit([1, 4, 6], 6, Sampler(spec.vocab_size))
            ra.wait(240), rb.wait(240)
            time.sleep(0.2)
            k = 7
            smp2 = Sampler(spec.vocab_size, temperature=temperature,
                           seed=seed)
            smp2.fast_forward(k)
            req = be.submit(prompt + full[:k], 16 - k, smp2,
                            resume_tokens=k)
            rest = req.wait(240)
            assert full[:k] + rest == full, (temperature, rest)
            assert req.stats.reused_tokens >= 8  # at least one block remap
    finally:
        be.close()


def test_cold_promotion_does_not_leak_pool_blocks():
    """Review regression (confirmed leak): _paged_adopt's cold promotion
    allocates a device block, promote() takes the directory's ref, and the
    ALLOCATION ref must be dropped — or every demote→promote cycle orphans
    one block until the pool starves. Cycle the same prefix through the
    cold tier and pin used-block conservation."""
    spec = _spec(seq_len=64)
    params = init_random_params(spec, FloatType.Q40, seed=7)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                     kv_block_tokens=8)
    prompt = SHARED[:17]
    try:
        _run(be, prompt, 4)
        time.sleep(0.2)
        used = []
        for i in range(3):
            be._paged_reclaim(be.kv_pool.n_blocks)  # demote to cold
            out = _run(be, prompt + [240 + i], 4)   # promote + remap
            assert len(out) == 4
            time.sleep(0.2)
            used.append(be.kv_pool.used_blocks())
        assert used[2] <= used[0], used  # conservation: no orphaned refs
        assert be.prefix_cache.stats()["promoted_blocks"] >= 2
    finally:
        be.close()


def test_context_end_clamp_does_not_corrupt_directory():
    """Clamped parks (rows near seq_len) overwrite their own tail rows; in
    paged mode those rows may back DIRECTORY blocks — copy-on-write must
    keep the shared copies intact, so a later remap still reproduces the
    dense outputs, and lease pins shrink back to zero."""
    spec = _spec(seq_len=32)
    params = init_random_params(spec, FloatType.Q40, seed=5)
    prompts = [[1, 2, 3, 4, 5, 6, 7, 8, 9, 10], [1, 2, 3, 4, 5, 6, 7, 8, 11]]
    outs = {}
    for paged in (False, True):
        be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                         prefix_cache=paged, paged_kv=paged,
                         kv_block_tokens=4)
        try:
            if paged:
                _run(be, prompts[0], 30)  # warm: harvest + clamp at the wall
            reqs = [be.submit(list(p), 30, Sampler(spec.vocab_size))
                    for p in prompts]
            outs[paged] = [r.wait(240) for r in reqs]
            for r in reqs:
                assert r.finish == "length"
            if paged:
                # the re-run of prompts[0] after the clamp must have REUSED
                # directory blocks and still produced the dense tokens
                assert be.prefix_cache.stats()["hit_tokens"] > 0
                _settle(lambda: be.prefix_cache.total_refs() == 0)
        finally:
            be.close()
    assert outs[True] == outs[False]


def test_pool_exhaustion_fails_only_the_starving_request():
    """A pool sized for ~one context cannot serve two concurrent long
    requests: one fails with the typed KVPoolExhausted (request scope), the
    other completes, the scheduler survives and keeps serving."""
    spec = _spec(seq_len=64)
    params = init_random_params(spec, FloatType.Q40, seed=3)
    w = 64 // 8
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                     kv_block_tokens=8, kv_pool_blocks=w + 2,
                     prefix_cache=False)
    try:
        a = be.submit([1, 2, 3], 56, Sampler(spec.vocab_size))
        b = be.submit([1, 2, 4], 56, Sampler(spec.vocab_size))
        res = []
        for r in (a, b):
            try:
                r.wait(timeout=240)
                res.append(("ok", r))
            except KVPoolExhausted:
                res.append(("exhausted", r))
        kinds = sorted(k for k, _ in res)
        assert kinds in (["exhausted", "ok"], ["ok", "ok"]), kinds
        assert be.scheduler_alive()
        # the engine still serves after the pressure event
        out = be.submit([1, 9, 9], 6, Sampler(spec.vocab_size)).wait(240)
        assert len(out) == 6
    finally:
        be.close()


def test_interpret_kernel_serves_identical_greedy_tokens():
    """The Pallas paged-attention kernel (interpret mode on CPU) plugged
    into the full engine serves the same greedy tokens as the XLA gather
    path — the deterministic end-to-end smoke for the TPU kernel route."""
    spec = _spec(seq_len=64)  # small W keeps the interpreted grid cheap
    params = init_random_params(spec, FloatType.Q40, seed=11)
    prompt = SHARED[:12]
    outs = {}
    for kernel in (False, True):
        be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                         kv_block_tokens=8, paged_kernel=kernel)
        try:
            assert be._eng.paged_kernel == kernel
            outs[kernel] = _run(be, prompt, 8)
        finally:
            be.close()
    assert outs[True] == outs[False]


def test_paged_attn_bench_parity_gate():
    """Tier-1 smoke for perf/paged_attn_bench.py: XLA-vs-dense bit
    exactness, kernel max|Δ| under tolerance, greedy-pick agreement — the
    decode (T=1) and verify (T=5) shapes."""
    import paged_attn_bench

    rows = paged_attn_bench.run(small=True)
    assert {r["shape"] for r in rows} == {"decode_t1", "verify_t5"}
    for r in rows:
        assert r["xla_vs_dense_bit_exact"]
        assert r["kernel_max_abs_err"] < 2e-5
        assert r["greedy_pick_agree"]
