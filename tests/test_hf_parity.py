"""End-to-end parity against a REAL transformers LlamaForCausalLM.

The strongest real-checkpoint evidence available in a zero-egress container: build an
actual HuggingFace Llama model (random init — the architecture, layouts, and rotary
conventions are exactly those of every published Llama checkpoint), save it with
save_pretrained (true config.json + model.safetensors), run it through THIS repo's
convert_hf -> .m -> Engine pipeline, and require the logits to match torch's forward
pass. This pins the full conversion chain the way decoding a downloaded TinyLlama
would: any error in tensor ordering, HF Q/K rotary re-permutation (convert-hf.py:12-15),
GQA head mapping, norm placement, or rope tables diverges immediately.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_llama_tpu.models.spec import ArchType  # noqa: E402
from distributed_llama_tpu.quants import FloatType  # noqa: E402


def _build_hf_llama(tmp_path, n_kv_heads=2):
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=n_kv_heads,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    torch.manual_seed(7)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


@pytest.mark.parametrize("n_kv_heads", [4, 2])
def test_logits_match_transformers(tmp_path, n_kv_heads):
    from distributed_llama_tpu.converter.convert_hf import convert
    from distributed_llama_tpu.runtime.engine import Engine

    model = _build_hf_llama(tmp_path, n_kv_heads=n_kv_heads)
    out_m = str(tmp_path / "model.m")
    convert(str(tmp_path), FloatType.F32, out_m)

    eng = Engine(*_load(out_m), tp=1)
    tokens = [1, 17, 93, 4, 200, 55]

    with torch.no_grad():
        want = model(torch.tensor([tokens])).logits[0].float().numpy()

    import jax.numpy as jnp
    logits, eng.k_cache, eng.v_cache = eng._step(
        eng.params, eng.rope, jnp.asarray([tokens], jnp.int32), eng.k_cache,
        eng.v_cache, jnp.int32(0))
    got = np.asarray(logits)[0]

    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_greedy_decode_matches_transformers(tmp_path):
    """Greedy continuation must emit the same token ids as transformers.generate."""
    from distributed_llama_tpu.converter.convert_hf import convert
    from distributed_llama_tpu.runtime.engine import Engine
    from distributed_llama_tpu.runtime.sampler import Sampler

    model = _build_hf_llama(tmp_path)
    out_m = str(tmp_path / "model.m")
    convert(str(tmp_path), FloatType.F32, out_m)

    prompt = [1, 9, 42, 7]
    with torch.no_grad():
        want = model.generate(
            torch.tensor([prompt]), max_new_tokens=8, do_sample=False,
            pad_token_id=0)[0].tolist()[len(prompt):]

    eng = Engine(*_load(out_m), tp=1)
    got, _ = eng.generate(list(prompt), 8, Sampler(eng.spec.vocab_size, temperature=0.0))
    assert got == want


def _load(path):
    from distributed_llama_tpu.formats.mfile import load_model

    spec, params = load_model(path, 0, None)
    assert spec.arch_type == ArchType.LLAMA
    return spec, params


def test_mixtral_logits_match_transformers(tmp_path):
    """Same oracle for the MoE path: a real transformers MixtralForCausalLM through
    convert_hf (incl. the router tensor the reference fork's plan omits) must match
    torch's forward logits."""
    from distributed_llama_tpu.converter.convert_hf import convert
    from distributed_llama_tpu.runtime.engine import Engine

    cfg = transformers.MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    torch.manual_seed(11)
    model = transformers.MixtralForCausalLM(cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)

    out_m = str(tmp_path / "model.m")
    convert(str(tmp_path), FloatType.F32, out_m)

    from distributed_llama_tpu.formats.mfile import load_model
    spec, params = load_model(out_m, 0, None)
    assert spec.arch_type == ArchType.MIXTRAL and spec.n_experts == 4

    tokens = [1, 17, 93, 4]
    with torch.no_grad():
        want = model(torch.tensor([tokens])).logits[0].float().numpy()

    import jax.numpy as jnp
    eng = Engine(spec, params, tp=1)
    logits, eng.k_cache, eng.v_cache = eng._step(
        eng.params, eng.rope, jnp.asarray([tokens], jnp.int32), eng.k_cache,
        eng.v_cache, jnp.int32(0))
    got = np.asarray(logits)[0]
    # MoE sums two expert outputs with renormalized weights in a different
    # accumulation order than HF's index_add loop; noise is larger than dense
    np.testing.assert_allclose(got, want, atol=5e-3, rtol=0)
    # expert ROUTING must agree exactly: compare argmax tokens, not just logits
    assert np.argmax(got, -1).tolist() == np.argmax(want, -1).tolist()
