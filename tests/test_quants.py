"""Quantization round-trip + byte-format compatibility tests.

Mirrors the reference test strategy (src/quants-test.cpp: Q80 round-trip error <= 0.0043
across several lengths) and adds byte-level golden checks against the reference writer
semantics (converter/writer.py:29-74).
"""

import struct

import numpy as np
import pytest

from distributed_llama_tpu.quants import (
    QK,
    FloatType,
    QTensor,
    batch_bytes,
    dequantize_q40,
    dequantize_q80,
    jnp_dequantize_q40,
    jnp_quantize_q80,
    q40_from_bytes,
    q40_to_bytes,
    q80_from_bytes,
    q80_to_bytes,
    quantize_q40,
    quantize_q80,
)


def _xorshift_data(n, seed=123456789):
    # deterministic pseudorandom floats in [-1, 1), same spirit as funcs-test.cpp:21
    rng = np.random.RandomState(seed)
    return (rng.rand(n).astype(np.float32) * 2.0) - 1.0


@pytest.mark.parametrize("n", [1024, 768, 2752])
def test_q80_roundtrip_error(n):
    x = _xorshift_data(n)
    vals, scales = quantize_q80(x)
    y = dequantize_q80(vals, scales)
    # reference tolerance: 0.0043 (src/quants-test.cpp:7-52)
    assert np.max(np.abs(x - y)) <= 0.0043


@pytest.mark.parametrize("n", [1024, 4096])
def test_q40_roundtrip_error(n):
    x = _xorshift_data(n)
    packed, scales = quantize_q40(x)
    y = dequantize_q40(packed, scales)
    # 4-bit with floor(+8.5 offset): max error ~ one delta = absmax/8 -> 0.125 for [-1,1]
    assert np.max(np.abs(x - y)) <= 0.13


def test_q40_bytes_reference_layout():
    """Byte stream must match the reference writer exactly (converter/writer.py:29-53)."""
    x = _xorshift_data(QK * 3)
    packed, scales = quantize_q40(x)
    buf = q40_to_bytes(packed, scales)
    assert len(buf) == batch_bytes(FloatType.Q40, QK * 3)

    # independently re-encode block 0 with the reference algorithm
    g = x[:QK]
    delta = (g.min() if -g.min() > g.max() else g.max()) / -8.0
    d16 = np.float16(delta)
    q = np.clip(g * (1.0 / delta) + 8.5, 0, 15).astype(int)
    expect = struct.pack("<e16B", d16, *((q[:16] & 0xF) | ((q[16:] & 0xF) << 4)))
    assert buf[:18] == expect

    packed2, scales2 = q40_from_bytes(buf, (QK * 3,))
    np.testing.assert_array_equal(packed2, packed)
    np.testing.assert_array_equal(scales2, scales)


def test_q80_bytes_roundtrip():
    x = _xorshift_data(QK * 5).reshape(5, QK)  # 2-D tensor (rows, n)
    vals, scales = quantize_q80(x)
    buf = q80_to_bytes(vals, scales)
    assert len(buf) == batch_bytes(FloatType.Q80, QK, 5)
    vals2, scales2 = q80_from_bytes(buf, (5, QK))
    np.testing.assert_array_equal(vals2, vals)
    np.testing.assert_array_equal(scales2, scales)


def test_batch_bytes():
    # reference getBatchBytes (src/quants.cpp:28-51)
    assert batch_bytes(FloatType.F32, 32, 2) == 256
    assert batch_bytes(FloatType.F16, 32, 2) == 128
    assert batch_bytes(FloatType.Q40, 32, 2) == 36
    assert batch_bytes(FloatType.Q80, 32, 2) == 68


def test_jnp_dequant_matches_numpy():
    import jax.numpy as jnp

    x = _xorshift_data(2 * 256).reshape(2, 256)
    packed, scales = quantize_q40(x)
    ref = dequantize_q40(packed, scales)
    dev = jnp_dequantize_q40(jnp.asarray(packed), jnp.asarray(scales), dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(dev), ref, atol=1e-6)


def test_jnp_quantize_q80_matches_numpy():
    import jax.numpy as jnp

    x = _xorshift_data(512)
    vals_np, scales_np = quantize_q80(x)
    vals_j, scales_j = jnp_quantize_q80(jnp.asarray(x))
    # scales match exactly; int8 values may differ by 1 ulp at rounding boundaries
    np.testing.assert_array_equal(np.asarray(scales_j), scales_np)
    assert np.max(np.abs(np.asarray(vals_j).astype(int) - vals_np.astype(int))) <= 1


def test_qtensor_pytree():
    import jax

    x = _xorshift_data(4 * 64).reshape(4, 64)
    qt = QTensor.from_float(x, FloatType.Q40)
    leaves, treedef = jax.tree_util.tree_flatten(qt)
    assert len(leaves) == 2
    qt2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert qt2.ftype == FloatType.Q40 and qt2.shape == (4, 64)
    np.testing.assert_allclose(qt2.to_numpy(), dequantize_q40(*quantize_q40(x)))


def test_qtensor_dense():
    x = _xorshift_data(8).reshape(2, 4)
    for ft in (FloatType.F32, FloatType.F16):
        qt = QTensor.from_float(x, ft)
        assert qt.scales is None
        np.testing.assert_allclose(qt.to_numpy(), x, atol=1e-3)
