"""API server tests: OpenAI-compatible endpoints over a real socket (tiny CPU model)."""

import http.client
import json
import threading

import pytest

from distributed_llama_tpu.formats.mfile import params_file_order, write_model
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.apps.api_server import serve
from distributed_llama_tpu.tokenizer import TemplateType


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("api")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=262, seq_len=128).resolved()
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))

    engine = Engine.load(mpath, tpath, tp=1)
    srv = serve(engine, host="127.0.0.1", port=0, template_type=TemplateType.CHATML)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield port
    srv.shutdown()


def _post(port, path, body, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", path, json.dumps(body), headers or {"Content-Type": "application/json"})
    return conn.getresponse()


def test_models_endpoint(server):
    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=30)
    conn.request("GET", "/v1/models")
    r = conn.getresponse()
    assert r.status == 200
    data = json.loads(r.read())
    assert data["object"] == "list" and len(data["data"]) == 1


def test_chat_completion_non_stream(server):
    r = _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "ab"}],
        "max_tokens": 8, "temperature": 0,
    })
    assert r.status == 200
    data = json.loads(r.read())
    assert data["object"] == "chat.completion"
    choice = data["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert choice["finish_reason"] in ("length", "stop")


def test_chat_completion_stream_sse(server):
    r = _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "cd"}],
        "max_tokens": 6, "temperature": 0, "stream": True,
    })
    assert r.status == 200
    assert r.getheader("Content-Type") == "text/event-stream"
    raw = r.read().decode()
    events = [ln[6:] for ln in raw.splitlines() if ln.startswith("data: ")]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    assert chunks[-1]["choices"][0]["finish_reason"] in ("length", "stop")


def test_deterministic_with_seed(server):
    body = {"messages": [{"role": "user", "content": "xyz"}],
            "max_tokens": 6, "temperature": 0.9, "seed": 7}
    a = json.loads(_post(server, "/v1/chat/completions", body).read())
    b = json.loads(_post(server, "/v1/chat/completions", body).read())
    assert a["choices"][0]["message"]["content"] == b["choices"][0]["message"]["content"]


def test_prefix_cache_consistency(server):
    """Extending a conversation (NaiveCache hit) must give the same output as a cold
    engine would — greedy determinism across the rewind path."""
    msgs = [{"role": "user", "content": "ab"}]
    r1 = json.loads(_post(server, "/v1/chat/completions",
                          {"messages": msgs, "max_tokens": 4, "temperature": 0}).read())
    first = r1["choices"][0]["message"]["content"]
    msgs2 = msgs + [{"role": "assistant", "content": first},
                    {"role": "user", "content": "cd"}]
    r2 = _post(server, "/v1/chat/completions",
               {"messages": msgs2, "max_tokens": 4, "temperature": 0})
    assert r2.status == 200
    # identical repeat of the extended conversation hits the cache again
    r3 = _post(server, "/v1/chat/completions",
               {"messages": msgs2, "max_tokens": 4, "temperature": 0})
    assert (json.loads(r2.read())["choices"][0]["message"]["content"] ==
            json.loads(r3.read())["choices"][0]["message"]["content"])


def test_bad_json_rejected(server):
    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=30)
    conn.request("POST", "/v1/chat/completions", "{not json",
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 400


def test_missing_messages_rejected(server):
    r = _post(server, "/v1/chat/completions", {"max_tokens": 4})
    assert r.status == 400


def test_unknown_route_404(server):
    r = _post(server, "/v1/embeddings", {"input": "x"})
    assert r.status == 404


def test_stop_sequence_override(server):
    r = _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "ab"}],
        "max_tokens": 32, "temperature": 0, "stop": ["e"],
    })
    data = json.loads(r.read())
    content = data["choices"][0]["message"]["content"]
    assert "e" not in content
