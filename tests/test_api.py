"""API server tests: OpenAI-compatible endpoints over a real socket (tiny CPU model)."""

import http.client
import json
import threading

import pytest

from distributed_llama_tpu.formats.mfile import params_file_order, write_model
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.apps.api_server import serve
from distributed_llama_tpu.tokenizer import TemplateType


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("api")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=262, seq_len=128).resolved()
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))

    engine = Engine.load(mpath, tpath, tp=1)
    global _MODEL_FILES
    _MODEL_FILES = (mpath, tpath)  # for tests that spin up a second server
    srv = serve(engine, host="127.0.0.1", port=0, template_type=TemplateType.CHATML)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield port
    srv.shutdown()


def _post(port, path, body, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", path, json.dumps(body), headers or {"Content-Type": "application/json"})
    return conn.getresponse()


def test_models_endpoint(server):
    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=30)
    conn.request("GET", "/v1/models")
    r = conn.getresponse()
    assert r.status == 200
    data = json.loads(r.read())
    assert data["object"] == "list" and len(data["data"]) == 1


def test_chat_completion_non_stream(server):
    r = _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "ab"}],
        "max_tokens": 8, "temperature": 0,
    })
    assert r.status == 200
    data = json.loads(r.read())
    assert data["object"] == "chat.completion"
    choice = data["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert choice["finish_reason"] in ("length", "stop")


def test_chat_completion_stream_sse(server):
    r = _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "cd"}],
        "max_tokens": 6, "temperature": 0, "stream": True,
    })
    assert r.status == 200
    assert r.getheader("Content-Type") == "text/event-stream"
    raw = r.read().decode()
    events = [ln[6:] for ln in raw.splitlines() if ln.startswith("data: ")]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    assert chunks[-1]["choices"][0]["finish_reason"] in ("length", "stop")


def test_deterministic_with_seed(server):
    body = {"messages": [{"role": "user", "content": "xyz"}],
            "max_tokens": 6, "temperature": 0.9, "seed": 7}
    a = json.loads(_post(server, "/v1/chat/completions", body).read())
    b = json.loads(_post(server, "/v1/chat/completions", body).read())
    assert a["choices"][0]["message"]["content"] == b["choices"][0]["message"]["content"]


def test_prefix_cache_consistency(server):
    """Extending a conversation (NaiveCache hit) must give the same output as a cold
    engine would — greedy determinism across the rewind path."""
    msgs = [{"role": "user", "content": "ab"}]
    r1 = json.loads(_post(server, "/v1/chat/completions",
                          {"messages": msgs, "max_tokens": 4, "temperature": 0}).read())
    first = r1["choices"][0]["message"]["content"]
    msgs2 = msgs + [{"role": "assistant", "content": first},
                    {"role": "user", "content": "cd"}]
    r2 = _post(server, "/v1/chat/completions",
               {"messages": msgs2, "max_tokens": 4, "temperature": 0})
    assert r2.status == 200
    # identical repeat of the extended conversation hits the cache again
    r3 = _post(server, "/v1/chat/completions",
               {"messages": msgs2, "max_tokens": 4, "temperature": 0})
    assert (json.loads(r2.read())["choices"][0]["message"]["content"] ==
            json.loads(r3.read())["choices"][0]["message"]["content"])


def test_speculative_server_matches_plain(server):
    """A --speculative server must return exactly what the plain server
    returns for greedy requests (the flag only changes dispatch count),
    and must silently fall back for temperature > 0."""
    msgs = [{"role": "user", "content": "ab ab ab ab"}]
    plain = json.loads(_post(server, "/v1/chat/completions",
                             {"messages": msgs, "max_tokens": 8,
                              "temperature": 0}).read())
    mpath, tpath = _MODEL_FILES
    eng = Engine.load(mpath, tpath, tp=1)
    srv = serve(eng, host="127.0.0.1", port=0,
                template_type=TemplateType.CHATML, speculative_k=6)
    port2 = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        spec_r = json.loads(_post(port2, "/v1/chat/completions",
                                  {"messages": msgs, "max_tokens": 8,
                                   "temperature": 0}).read())
        assert (spec_r["choices"][0]["message"]["content"]
                == plain["choices"][0]["message"]["content"])
        sampled = _post(port2, "/v1/chat/completions",
                        {"messages": msgs, "max_tokens": 4,
                         "temperature": 0.8, "seed": 5})
        assert sampled.status == 200  # graceful fallback, not an error
    finally:
        srv.shutdown()
        srv.server_close()


def test_paged_server_multi_turn_consistency(server):
    """A --kv-cache-storage host server serving alternating conversations
    exercises Engine.seek()'s ring restore (wrapped slots hold the abandoned
    branch's rows); greedy outputs must match the plain server's."""
    mpath, tpath = _MODEL_FILES
    eng = Engine.load(mpath, tpath, kv_cache_storage="host",
                      kv_cache_resident=64)
    assert eng.paged  # seq_len 128 > resident 64
    srv = serve(eng, host="127.0.0.1", port=0,
                template_type=TemplateType.CHATML)
    port2 = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        # conversation A (long enough to wrap the 64-slot ring: the byte-
        # fallback vocab costs ~3 tokens per "ab ", so ~75 prompt tokens),
        # then B, then REPEAT A — the repeat rewinds to A's prefix through the
        # wrapped ring and must reproduce the plain server's continuation
        a1 = [{"role": "user", "content": "ab " * 20}]
        b1 = [{"role": "user", "content": "cd cd cd"}]
        for p in (server, port2):
            r0 = json.loads(_post(p, "/v1/chat/completions",
                                  {"messages": a1, "max_tokens": 6,
                                   "temperature": 0}).read())
            assert "choices" in r0, r0  # prompt must FIT (no overflow 400)
        assert eng.pos > 64, "conversation A never wrapped the 64-slot ring"
        outs = {}
        for p in (server, port2):
            json.loads(_post(p, "/v1/chat/completions",
                             {"messages": b1, "max_tokens": 4,
                              "temperature": 0}).read())
            r = json.loads(_post(p, "/v1/chat/completions",
                                 {"messages": a1, "max_tokens": 6,
                                  "temperature": 0}).read())
            outs[p] = r["choices"][0]["message"]["content"]
        assert outs[server] == outs[port2]
    finally:
        srv.shutdown()
        srv.server_close()


def test_bad_json_rejected(server):
    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=30)
    conn.request("POST", "/v1/chat/completions", "{not json",
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 400


def test_missing_messages_rejected(server):
    r = _post(server, "/v1/chat/completions", {"max_tokens": 4})
    assert r.status == 400


def test_unknown_route_404(server):
    r = _post(server, "/v1/embeddings", {"input": "x"})
    assert r.status == 404


def test_stop_sequence_override(server):
    r = _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "ab"}],
        "max_tokens": 32, "temperature": 0, "stop": ["e"],
    })
    data = json.loads(r.read())
    content = data["choices"][0]["message"]["content"]
    assert "e" not in content


@pytest.fixture(scope="module")
def batched_server(tmp_path_factory):
    """Server in continuous-batching mode (--batch 2): concurrent requests share steps."""
    from distributed_llama_tpu.formats.mfile import load_model
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.tokenizer.bpe import Tokenizer

    tmp = tmp_path_factory.mktemp("api_batched")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=262, seq_len=128).resolved()
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))

    lspec, lparams = load_model(mpath, 0)
    be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), slots=2, tp=1)
    srv = serve(None, host="127.0.0.1", port=0, template_type=TemplateType.CHATML,
                batch_engine=be)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield port
    srv.shutdown()
    be.close()


def test_batched_concurrent_requests(batched_server):
    """Two concurrent clients must both get valid completions, and their generation
    must overlap in time (no serialization behind a server lock)."""
    import time

    body = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 16, "temperature": 0, "seed": 5}
    # warm all compiled shapes once
    assert _post(batched_server, "/v1/chat/completions", dict(body)).status == 200

    results = {}
    spans = {}

    def client(i):
        t0 = time.perf_counter()
        r = _post(batched_server, "/v1/chat/completions",
                  dict(body, messages=[{"role": "user", "content": f"hello {i}"}]))
        assert r.status == 200
        results[i] = json.loads(r.read())
        spans[i] = (t0, time.perf_counter())

    def run_both():
        threads = [threading.Thread(target=client, args=(i,)) for i in range(2)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        return time.perf_counter() - t0

    run_both()  # warm every compiled shape both request lengths touch
    t_both = run_both()

    for i in range(2):
        choice = results[i]["choices"][0]
        assert choice["message"]["content"] is not None
        assert choice["finish_reason"] in ("length", "stop")
    # concurrency: the two requests' service windows overlapped
    overlap = min(spans[0][1], spans[1][1]) - max(spans[0][0], spans[1][0])
    assert overlap > 0, spans

    # throughput sanity at the HTTP level: both together well under 2x a single
    # request (the tight >1.5x throughput assertion lives in the engine-level test
    # tests/test_batch_engine.py::test_two_concurrent_beat_single_throughput, where
    # timing is not subject to HTTP/thread scheduling noise)
    t0 = time.perf_counter()
    client("solo")
    t_solo = time.perf_counter() - t0
    assert t_both < 1.9 * t_solo, (t_both, t_solo)
