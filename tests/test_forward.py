"""Golden forward-pass tests: JAX model vs an independent numpy oracle.

Reference pattern: llama2-tasks-test.cpp / grok1-tasks-test.cpp run a full block with
seeded random weights through the real execution machinery and compare against golden
values. Here the golden values come from a straightforward numpy reimplementation written
against the reference's math (not against our JAX code), run over multiple tokens,
including GQA, all three archs, and both rope layouts.
"""

import numpy as np
import jax.numpy as jnp

from distributed_llama_tpu.models.forward import (
    GROK_EMBEDDING_SCALE,
    GROK_LOGITS_SCALE,
    forward,
    init_kv_cache,
)
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec, RopeType
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import FloatType


# ---------------------------------------------------------------------------
# numpy oracle
# ---------------------------------------------------------------------------

def np_rmsnorm(x, w, eps=1e-5):
    ms = np.mean(x * x, axis=-1, keepdims=True)
    return w * (x / np.sqrt(ms + eps))


def np_softmax(x):
    m = x.max(axis=-1, keepdims=True)
    e = np.exp(x - m)
    return e / e.sum(axis=-1, keepdims=True)


def np_silu(x):
    return x / (1.0 + np.exp(-x))


def np_gelu(x):
    return 0.5 * x * (1.0 + np.tanh(0.79788456080286535588 * x * (1.0 + 0.044715 * x * x)))


def np_rope(x, pos, theta, style):
    """x: (heads, hs), one position."""
    heads, hs = x.shape
    out = x.copy()
    for h in range(heads):
        for j in range(hs // 2):
            freq = 1.0 / (theta ** (2.0 * j / hs))
            val = pos * freq
            c, s = np.cos(val), np.sin(val)
            if style == "interleaved":
                a, b = x[h, 2 * j], x[h, 2 * j + 1]
                out[h, 2 * j] = a * c - b * s
                out[h, 2 * j + 1] = a * s + b * c
            else:  # half-rotation (falcon/neox)
                a, b = x[h, j], x[h, j + hs // 2]
                out[h, j] = a * c - b * s
                out[h, j + hs // 2] = a * s + b * c
    return out


def oracle_forward(params, spec, tokens):
    """Process tokens sequentially (decode-style), return logits for every position."""
    L = spec.n_layers
    hs, hq, hk = spec.head_size, spec.n_heads, spec.n_kv_heads
    g = hq // hk
    style = "interleaved" if spec.rope_type in (RopeType.LLAMA, RopeType.LLAMA3_1) else "half"
    act = np_silu if spec.hidden_act == HiddenAct.SILU else np_gelu

    def W(name, l):
        t = params["blocks"][name]
        if hasattr(t, "to_numpy"):
            return t.to_numpy()[l]
        return np.asarray(t)[l]

    k_cache = np.zeros((L, hk, len(tokens), hs), np.float32)
    v_cache = np.zeros((L, hk, len(tokens), hs), np.float32)
    logits_all = []
    for pos, tok in enumerate(tokens):
        x = params["embedding"][tok].astype(np.float32).copy()
        if spec.arch_type == ArchType.GROK1:
            x = x * GROK_EMBEDDING_SCALE
        for l in range(L):
            xb = np_rmsnorm(x, W("rms_att", l))
            q = (W("wq", l) @ xb).reshape(hq, hs)
            k = (W("wk", l) @ xb).reshape(hk, hs)
            v = (W("wv", l) @ xb).reshape(hk, hs)
            q = np_rope(q, pos, spec.rope_theta, style)
            k = np_rope(k, pos, spec.rope_theta, style)
            k_cache[l, :, pos] = k
            v_cache[l, :, pos] = v
            att = np.zeros((hq, hs), np.float32)
            for h in range(hq):
                kv_h = h // g
                scores = (k_cache[l, kv_h, : pos + 1] @ q[h]) / np.sqrt(hs)
                p = np_softmax(scores[None, :])[0]
                att[h] = p @ v_cache[l, kv_h, : pos + 1]
            attn_out = W("wo", l) @ att.reshape(-1)

            if spec.arch_type == ArchType.GROK1:
                x = x + np_rmsnorm(attn_out, W("rms_ffn", l))
                xb2 = np_rmsnorm(x, W("rms_moe", l))
                moe = oracle_moe(xb2, params, spec, l, act)
                x = x + np_rmsnorm(moe, W("rms_ffn2", l))
            elif spec.is_moe:
                x = x + attn_out
                xb2 = np_rmsnorm(x, W("rms_ffn", l))
                x = x + oracle_moe(xb2, params, spec, l, act)
            else:
                x = x + attn_out
                xb2 = np_rmsnorm(x, W("rms_ffn", l))
                hbuf = act(W("w1", l) @ xb2) * (W("w3", l) @ xb2)
                x = x + W("w2", l) @ hbuf

        x = np_rmsnorm(x, np.asarray(params["rms_final"]))
        wcls = params["wcls"].to_numpy()
        logits = wcls @ x
        if spec.arch_type == ArchType.GROK1:
            logits = logits * GROK_LOGITS_SCALE
        logits_all.append(logits)
    return np.stack(logits_all)


def oracle_moe(xb, params, spec, l, act):
    router = params["blocks"]["router"].to_numpy()[l]
    probs = np_softmax((router @ xb)[None, :])[0]
    top = np.argsort(-probs)[: spec.n_active_experts]
    w = probs[top] / probs[top].sum()
    out = np.zeros_like(xb)
    for ae, e in enumerate(top):
        up = params["blocks"]["moe_up"].to_numpy()[l, e]
        gate = params["blocks"]["moe_gate"].to_numpy()[l, e]
        down = params["blocks"]["moe_down"].to_numpy()[l, e]
        hb = (up @ xb) * act(gate @ xb)
        out = out + w[ae] * (down @ hb)
    return out


# ---------------------------------------------------------------------------
# specs
# ---------------------------------------------------------------------------

def tiny_spec(arch=ArchType.LLAMA, rope=RopeType.LLAMA, **kw):
    defaults = dict(
        arch_type=arch, dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
        vocab_size=128, seq_len=32, rope_type=rope,
    )
    if arch != ArchType.LLAMA:
        defaults.update(n_experts=4, n_active_experts=2, rope_type=RopeType.FALCON)
    if arch == ArchType.GROK1:
        defaults.update(hidden_act=HiddenAct.GELU)
    defaults.update(kw)
    return ModelSpec(**defaults).resolved()


def run_both(spec, ftype=FloatType.F32, n_tokens=5, seed=3):
    params = init_random_params(spec, ftype, seed=seed)
    rope = RopeTables.create(spec)
    tokens = np.arange(1, n_tokens + 1, dtype=np.int32)

    kc, vc = init_kv_cache(spec)
    logits, _, _ = forward(params, spec, rope, jnp.asarray(tokens)[None, :], kc, vc,
                           jnp.int32(0))
    got = np.asarray(logits)[0]
    want = oracle_forward(params, spec, tokens)
    return got, want


def test_llama_dense_golden():
    got, want = run_both(tiny_spec())
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


def test_llama_dense_decode_equals_prefill():
    """Token-by-token decode must equal chunked prefill (the reference only has the
    former; our chunked path must agree)."""
    spec = tiny_spec()
    params = init_random_params(spec, FloatType.F32, seed=5)
    rope = RopeTables.create(spec)
    tokens = np.array([7, 3, 11, 2], np.int32)

    kc, vc = init_kv_cache(spec)
    chunk_logits, _, _ = forward(params, spec, rope, jnp.asarray(tokens)[None, :], kc, vc,
                                 jnp.int32(0))
    kc, vc = init_kv_cache(spec)
    step_logits = []
    for pos, tok in enumerate(tokens):
        lg, kc, vc = forward(params, spec, rope, jnp.asarray([[tok]]), kc, vc,
                             jnp.int32(pos))
        step_logits.append(np.asarray(lg)[0, 0])
    np.testing.assert_allclose(np.asarray(chunk_logits)[0], np.stack(step_logits),
                               atol=2e-4, rtol=1e-3)


def test_llama_q40_weights_close():
    """Q40-quantized weights run the same graph; outputs differ only by quant noise."""
    spec = tiny_spec()
    got_q, want_q = run_both(spec, FloatType.Q40)
    # oracle uses the SAME dequantized weights, so tolerance stays tight
    np.testing.assert_allclose(got_q, want_q, atol=3e-4, rtol=1e-3)


def test_falcon_rope_golden():
    got, want = run_both(tiny_spec(rope=RopeType.FALCON))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


def test_mixtral_golden():
    got, want = run_both(tiny_spec(arch=ArchType.MIXTRAL))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


def test_grok1_golden():
    got, want = run_both(tiny_spec(arch=ArchType.GROK1))
    np.testing.assert_allclose(got, want, atol=3e-4, rtol=2e-3)


def test_gqa_head_counts():
    spec = tiny_spec(n_heads=8, n_kv_heads=2, dim=64)
    got, want = run_both(spec)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)
