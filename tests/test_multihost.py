"""Multi-host bootstrap: init_multihost + make_pod_mesh across REAL processes.

The reference bootstraps a cluster with `dllama worker --port ...` on each node plus
`--workers host:port ...` at the root (src/apps/dllama/dllama.cpp:205-221). The SPMD
replacement is jax.distributed: every host runs the SAME program and
init_multihost() wires them into one runtime whose jax.devices() is global.

This test launches TWO actual OS processes with JAX_PLATFORMS=cpu (2 local CPU
devices each), joins them through init_multihost on a localhost coordinator, builds
the pod mesh over the 4 global devices, and runs a shard_map psum over the
process-spanning tp axis — the same collective path a 405B tp=16 pod job exercises,
minus the ICI. Skipped quietly if the cross-process CPU collective backend is
unavailable in this jax build.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import jax
import numpy as np

coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
from distributed_llama_tpu.parallel.mesh import AXIS_TP, init_multihost, make_pod_mesh

idx = init_multihost(coordinator=coord, num_processes=nproc, process_id=pid)
assert idx == pid, (idx, pid)
assert jax.process_count() == nproc
mesh = make_pod_mesh()  # all 4 global devices -> tp axis (single ICI-equivalent domain)
assert mesh.shape[AXIS_TP] == jax.device_count() == 2 * nproc, mesh.shape

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

x = jax.device_put(
    np.arange(jax.device_count(), dtype=np.float32),
    NamedSharding(mesh, P(AXIS_TP)))
from distributed_llama_tpu.compat import shard_map
f = jax.jit(shard_map(lambda v: jax.lax.psum(v, AXIS_TP), mesh=mesh,
                      in_specs=P(AXIS_TP), out_specs=P(AXIS_TP)))
out = f(x)
total = float(np.asarray(jax.device_get(out.addressable_shards[0].data))[0])
want = sum(range(jax.device_count()))
assert total == want, (total, want)
print(f"POD_OK process={pid} devices={jax.device_count()} psum={total}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_pod_bootstrap(tmp_path):
    # watchdog lives in communicate(timeout=210) below; pytest-timeout is not
    # installed in this image, so a mark would be inert
    worker = tmp_path / "pod_worker.py"
    worker.write_text(_WORKER)
    coord = f"127.0.0.1:{_free_port()}"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    # PYTHONPATH is repo_root ONLY: launch environments (axon) preload a
    # sitecustomize that imports jax at interpreter start, and a pre-initialized
    # backend makes jax.distributed.initialize hang in the child.
    env["PYTHONPATH"] = repo_root
    env.pop("PYTHONWARNINGS", None)
    procs = [
        subprocess.Popen([sys.executable, str(worker), coord, "2", str(i)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env, cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=210)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    joined = "\n---\n".join(outs)
    lowered = joined.lower()
    if any(p.returncode != 0 for p in procs) and (
            ("multihost" in lowered or "multiprocess" in lowered)
            and ("not implemented" in lowered or "implemented" in lowered
                 and "n't" in lowered)):
        # e.g. "Multiprocess computations aren't implemented on the CPU
        # backend" (jaxlib wording varies across versions)
        pytest.skip(f"cross-process CPU collectives unavailable: {joined[-300:]}")
    assert all(p.returncode == 0 for p in procs), joined
    assert "POD_OK process=0 devices=4" in joined, joined
    assert "POD_OK process=1 devices=4" in joined, joined
