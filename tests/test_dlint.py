"""Tier-1 wiring for the unified static-analysis runner (ISSUE 10,
docs/ANALYSIS.md): the whole repo must carry ZERO unsuppressed findings
across every pass, every suppression must carry a written reason, and the
compile-manifest gate must hold on the pinned manifest AND catch an
injected recompile with the offending cache key named."""

import json
import os
import sys

from distributed_llama_tpu.analysis import core, drift, runner

REPO = core.REPO


def test_repo_zero_unsuppressed_findings():
    """The acceptance gate: every pass over every first-party file, zero
    unsuppressed findings. A new violation fails HERE with its rule, file,
    and line; the fix is to repair the code or triage it with a reasoned
    `# dlint: ignore[rule] -- why` (never to widen the lint)."""
    report = runner.run()
    assert report.files_scanned > 100, "scan did not find the repo"
    assert not report.unsuppressed, "\n".join(
        f.format() for f in report.unsuppressed)
    # the annotation conventions are live, not vestigial: the lock and
    # hot-path passes actually guard real declarations in the package
    assert report.suppressed, "expected triaged suppressions in the repo"
    for f in report.suppressed:
        assert f.reason, f"suppression without a reason: {f.format()}"
    # no stale excuses: a suppression matching nothing outlived its defect
    assert not report.unused_suppressions, report.unused_suppressions


def test_analysis_scan_covers_itself_and_the_runner():
    files = {os.path.relpath(f, REPO) for f in core.repo_py_files()}
    for mod in ("core", "locks", "hotpath", "drift", "smoke", "runner",
                "compile_audit", "__init__"):
        assert os.path.join("distributed_llama_tpu", "analysis",
                            f"{mod}.py") in files, mod
    assert os.path.join("perf", "dlint.py") in files


def test_fault_point_inventory_complete():
    """ISSUE 10 satellite: every `faults.fire("...")` in the package must be
    in docs/ROBUSTNESS.md's injection-point inventory (same drift pattern
    as the metric-docs lint)."""
    sources = core.load_sources(core.package_py_files())
    points = {p for p, _f, _l in drift.collect_fault_points(sources)}
    # the collector sees the real inventory, not a partial scan
    for expected in ("batch.submit", "batch.dispatch", "engine.reinit",
                     "router.proxy", "router.health",
                     "device_loop.verify_dispatch", "api.request"):
        assert expected in points, (expected, sorted(points))
    missing = drift.check_fault_docs(sources)
    assert not missing, "\n".join(f.format() for f in missing)


def test_dlint_cli_emits_json_artifact(tmp_path):
    """`perf/dlint.py --json` writes the findings/suppressions summary
    artifact (satellite: machine-readable output next to the BENCH files)."""
    sys.path.insert(0, os.path.join(REPO, "perf"))
    try:
        import dlint
    finally:
        sys.path.pop(0)
    out = tmp_path / "DLINT.json"
    rc = dlint.main(["--json", str(out)])
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["ok"] is True
    assert data["counts"]["unsuppressed"] == 0
    assert data["counts"]["suppressed"] >= 1
    assert all(s["reason"] for s in data["suppressions"])
    assert data["files_scanned"] > 100


def test_compile_manifest_gate_holds_and_catches_injection():
    """The runtime compile audit: (1) the fixed tiny-model scenario —
    prefill, scans, pipelined chains, draft-verify blocks, a stochastic
    row, a durable resume — compiles ONLY programs/signatures the pinned
    perf/compile_manifest.json covers; (2) a deliberately injected shape
    bucket (a k=6 scan the scheduler never uses) fails the gate with the
    offending cache key named. One scenario run serves both halves."""
    from distributed_llama_tpu.analysis import compile_audit

    pinned = compile_audit.load_manifest()
    assert pinned is not None, "perf/compile_manifest.json missing"
    audit = compile_audit.CompileAudit()
    with audit:
        eng = compile_audit.run_scenario(keep_engine=True)
        try:
            clean = compile_audit.diff_manifest(audit.manifest(), pinned)
            assert clean == [], "\n".join(f.message for f in clean)
            # inject recompile creep: a new scan bucket = a new cache key
            eng._batched_loop(6, "greedy", None)
        finally:
            eng.close()
    findings = compile_audit.diff_manifest(audit.manifest(), pinned)
    assert findings, "gate failed to detect the injected shape bucket"
    assert any("batched_scan[k=6,mode=greedy,window=None,paged=16]"
               in f.message for f in findings), [f.message for f in findings]
    assert all(f.rule == "compile-manifest" for f in findings)


def test_compile_manifest_names_rogue_fused_bucket():
    """ISSUE 16 satellite: the kernel policy is part of the program cache
    key — a verify T bucket minted under the fused policy outside the
    pinned set must fail the gate BY NAME (kernel=fused in the key), never
    alias onto the kernel-off pin. The factory call alone records the
    build (jit traces lazily), so the test costs no compile."""
    from distributed_llama_tpu.analysis import compile_audit
    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.quants import FloatType
    from distributed_llama_tpu.runtime import device_loop

    pinned = compile_audit.load_manifest()
    assert pinned is not None
    spec = compile_audit.scenario_spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    audit = compile_audit.CompileAudit()
    with audit:
        device_loop.make_batched_verify_loop(
            spec, make_mesh(tp=1), params, 9, mode="greedy",
            attn_window=None, use_pallas="fused", kv_block_tokens=16)
    findings = compile_audit.diff_manifest(audit.manifest(), pinned)
    assert findings, "gate missed the rogue fused T bucket"
    key = "verify[t=9,mode=greedy,window=None,paged=16,kernel=fused]"
    assert any(key in f.message for f in findings), \
        [f.message for f in findings]


def test_compile_manifest_catches_block_table_shape_creep():
    """ISSUE 12 satellite: block-table shapes must be padded/bucketed so
    per-request table growth never mints a fresh XLA lowering. Inject a
    dispatch whose table widened by one entry (the bug a per-request table
    shape would cause) through the SAME record path real dispatches hit —
    the gate must fail naming the offending cache key and the drifted
    signature."""
    import numpy as np

    from distributed_llama_tpu.analysis import compile_audit

    pinned = compile_audit.load_manifest()
    assert pinned is not None
    key = "batched_scan[k=4,mode=greedy,window=None,paged=16]"
    good = pinned["programs"][key]["signatures"][0]
    audit = compile_audit.CompileAudit()
    audit.record_call(key, (np.zeros((2, 5), np.int32),))  # table grew 4 -> 5
    findings = compile_audit.diff_manifest(audit.manifest(), pinned)
    assert findings and all(f.rule == "compile-manifest" for f in findings)
    msg = findings[0].message
    assert key in msg and "int32(2, 5)" in msg, msg
    # the pinned width stays clean through the same path
    clean_audit = compile_audit.CompileAudit()
    clean_audit.programs[key] = {"builds": 0, "signatures": {good}}
    assert compile_audit.diff_manifest(clean_audit.manifest(), pinned) == []
