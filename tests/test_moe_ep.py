"""Expert parallelism: moe_sharding="expert" must reproduce the replicated model.

Whole experts shard over the tp axis (parallel/sharding.py _EP_SPECS): each shard
owns E/tp complete experts, decode computes active experts only on their owners
(lax.cond), prefill scans the local stack against the globally-routed combine
weights, and the FFN-output psum merges. No reference counterpart (the reference
always hidden-slices experts); this is the capacity axis that lets Grok-1-314B-class
expert weights span chips.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import init_random_params, prepare_for_pallas
from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec, RopeType
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.parallel.mesh import make_mesh
from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                               make_sharded_forward, shard_params)
from distributed_llama_tpu.quants import FloatType


def _moe_spec(arch=ArchType.MIXTRAL, **kw):
    base = dict(arch_type=arch, dim=128, hidden_dim=128, n_layers=2, n_heads=4,
                n_kv_heads=4, vocab_size=128, seq_len=32, n_experts=4,
                n_active_experts=2, rope_type=RopeType.FALCON)
    if arch == ArchType.GROK1:
        base["hidden_act"] = HiddenAct.GELU
    base.update(kw)
    return ModelSpec(**base).resolved()


@pytest.mark.parametrize("arch", [ArchType.MIXTRAL, ArchType.GROK1])
@pytest.mark.parametrize("tokens", [[[1, 2, 3]], [[9]]])  # prefill chunk + decode
def test_expert_sharded_matches_replicated(arch, tokens):
    spec = _moe_spec(arch)
    params = init_random_params(spec, FloatType.F32, seed=7)
    rope = RopeTables.create(spec)
    toks = jnp.asarray(tokens)

    # replicated (single-device) oracle — decode continues from a seeded cache so the
    # 1-token case exercises pos > 0
    kc, vc = init_kv_cache(spec)
    seedp = jnp.asarray([[5, 6]])
    _, kc0, vc0 = forward(params, spec, rope, seedp, kc, vc, jnp.int32(0))
    want, _, _ = forward(params, spec, rope, toks, kc0, vc0, jnp.int32(2))

    mesh = make_mesh(tp=4)
    sharded = shard_params(params, mesh, spec, moe_sharding="expert")
    step = make_sharded_forward(spec, mesh, sharded, donate_cache=False,
                                moe_sharding="expert")
    kc, vc = init_sharded_kv_cache(spec, mesh)
    _, kc1, vc1 = step(sharded, rope, seedp, kc, vc, jnp.int32(0))
    got, _, _ = step(sharded, rope, toks, kc1, vc1, jnp.int32(2))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_expert_sharded_quantized_kernel_path():
    """Q40 weights + prepare_for_pallas(moe_sharding='expert') + use_pallas decode:
    the owner shards run the fused q4 kernel on whole-expert matrices (groups=1)."""
    spec = _moe_spec(ArchType.MIXTRAL)
    params = init_random_params(spec, FloatType.Q40, seed=3)
    rope = RopeTables.create(spec)

    kc, vc = init_kv_cache(spec)
    want, _, _ = forward(params, spec, rope, jnp.asarray([[7]]), kc, vc, jnp.int32(0))

    mesh = make_mesh(tp=4)
    pp = prepare_for_pallas(params, tp=4, moe_sharding="expert")
    assert pp["blocks"]["moe_down"].groups == 1  # whole experts: no column groups
    sharded = shard_params(pp, mesh, spec, moe_sharding="expert")
    step = make_sharded_forward(spec, mesh, sharded, donate_cache=False,
                                use_pallas=True, moe_sharding="expert")
    kc, vc = init_sharded_kv_cache(spec, mesh)
    got, _, _ = step(sharded, rope, jnp.asarray([[7]]), kc, vc, jnp.int32(0))
    got, want = np.asarray(got), np.asarray(want)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.03, rel  # Q80 activation-quantization error scale
    assert np.argmax(got, -1).tolist() == np.argmax(want, -1).tolist()


def test_expert_sharded_batched_decode_matches_replicated():
    """Batched decode (BatchEngine shape: b=2, t=1, per-row positions) through the
    per-(row, expert) cond path must match the replicated model per row."""
    spec = _moe_spec(ArchType.MIXTRAL)
    params = init_random_params(spec, FloatType.F32, seed=12)
    rope = RopeTables.create(spec)

    # seed two rows to different depths, replicated oracle
    kc, vc = init_kv_cache(spec, batch=2)
    seed = jnp.asarray([[1, 2, 3], [4, 5, 6]])
    _, kc0, vc0 = forward(params, spec, rope, seed, kc, vc, jnp.int32(0))
    pos = jnp.asarray([3, 2], jnp.int32)
    tok = jnp.asarray([[7], [9]])
    want, _, _ = forward(params, spec, rope, tok, kc0, vc0, pos)

    mesh = make_mesh(tp=4)
    sharded = shard_params(params, mesh, spec, moe_sharding="expert")
    step = make_sharded_forward(spec, mesh, sharded, donate_cache=False,
                                moe_sharding="expert")
    kc, vc = init_sharded_kv_cache(spec, mesh, batch=2)
    _, kc1, vc1 = step(sharded, rope, seed, kc, vc, jnp.int32(0))
    got, _, _ = step(sharded, rope, tok, kc1, vc1, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_engine_expert_sharded_generation():
    """Engine(moe_sharding='expert') greedy generation over a tp=4 mesh matches the
    replicated engine token-for-token."""
    from distributed_llama_tpu.runtime.engine import Engine
    from distributed_llama_tpu.runtime.sampler import Sampler

    spec = _moe_spec(ArchType.MIXTRAL)
    params = init_random_params(spec, FloatType.F32, seed=21)

    ref = Engine(spec, params, tp=1)
    want, _ = ref.generate([1, 5, 9], 6, Sampler(spec.vocab_size, temperature=0.0))

    eng = Engine(spec, params, tp=4, moe_sharding="expert")
    assert eng.moe_sharding == "expert"
    got, _ = eng.generate([1, 5, 9], 6, Sampler(spec.vocab_size, temperature=0.0))
    assert got == want, (got, want)


def test_expert_sharding_requires_divisibility():
    from distributed_llama_tpu.parallel.sharding import check_divisibility

    spec = _moe_spec(dim=256, n_experts=4, n_heads=8, n_kv_heads=8)
    with pytest.raises(AssertionError, match="n_experts"):
        check_divisibility(spec, tp=8, moe_sharding="expert")
