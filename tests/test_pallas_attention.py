"""Fused decode-attention kernel vs the XLA deferred-layout oracle (interpret mode).

The kernel must reproduce ops/attention.gqa_attention over the deferred-write key
layout ([window slots ++ current token], stale slots masked) for every (pos, window)
relationship decode meets: empty cache, partially filled window, full window.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_tpu.ops.attention import gqa_attention
from distributed_llama_tpu.ops.pallas_attention import fused_decode_attention


def _oracle(q_btgh, kc, vc, k_new, v_new, layer_idx, pos, window):
    """XLA composition: windowed slice + concat current token + masked attention."""
    l, b, hk, s, hs = kc.shape
    win = min(window, s)
    kw = kc[layer_idx, :, :, :win]  # (B, hk, win, hs)
    vw = vc[layer_idx, :, :, :win]
    slot = jnp.arange(win)
    slot_pos = jnp.where(slot < pos, slot, s + 1)
    key_pos = jnp.concatenate([slot_pos, jnp.asarray([pos])])
    kfull = jnp.concatenate([kw, k_new[None]], axis=2)  # (1, hk, win+1, hs)
    vfull = jnp.concatenate([vw, v_new[None]], axis=2)
    return gqa_attention(q_btgh, kfull, vfull, jnp.asarray([pos]),
                         key_positions=key_pos)


@pytest.mark.parametrize("pos,window", [(0, 16), (5, 16), (15, 16), (16, 32), (40, 64)])
@pytest.mark.parametrize("g", [1, 4])
def test_fused_decode_attention_matches_oracle(pos, window, g):
    hk, hs, s, l = 4, 32, 64, 3
    hq = hk * g
    rng = np.random.RandomState(pos * 7 + g)
    kc = jnp.asarray(rng.randn(l, 1, hk, s, hs).astype(np.float32))
    vc = jnp.asarray(rng.randn(l, 1, hk, s, hs).astype(np.float32))
    k_new = jnp.asarray(rng.randn(hk, 1, hs).astype(np.float32))
    v_new = jnp.asarray(rng.randn(hk, 1, hs).astype(np.float32))
    q = jnp.asarray(rng.randn(hk, g, hs).astype(np.float32))
    layer_idx = 1

    got = fused_decode_attention(q, kc, vc, k_new, v_new, layer_idx, pos,
                                 window=window, interpret=True)
    # oracle consumes (B, T, hq, hs) and returns (B, T, hq*hs)
    q_btgh = q.reshape(1, 1, hq, hs)
    want = _oracle(q_btgh, kc, vc, k_new, v_new, layer_idx, pos, window)
    np.testing.assert_allclose(np.asarray(got).reshape(1, 1, hq * hs),
                               np.asarray(want), atol=2e-5, rtol=2e-5)


def test_fused_decode_attention_bf16_cache():
    hk, g, hs, s, l = 2, 2, 32, 32, 2
    rng = np.random.RandomState(0)
    kc = jnp.asarray(rng.randn(l, 1, hk, s, hs).astype(np.float32)).astype(jnp.bfloat16)
    vc = jnp.asarray(rng.randn(l, 1, hk, s, hs).astype(np.float32)).astype(jnp.bfloat16)
    k_new = jnp.asarray(rng.randn(hk, 1, hs)).astype(jnp.bfloat16)
    v_new = jnp.asarray(rng.randn(hk, 1, hs)).astype(jnp.bfloat16)
    q = jnp.asarray(rng.randn(hk, g, hs).astype(np.float32))
    got = fused_decode_attention(q, kc, vc, k_new, v_new, 0, 7, window=16,
                                 interpret=True)
    want = _oracle(q.reshape(1, 1, hk * g, hs), kc, vc, k_new, v_new, 0, 7, 16)
    np.testing.assert_allclose(np.asarray(got).reshape(1, 1, -1), np.asarray(want),
                               atol=2e-2, rtol=2e-2)


def test_tiled_window_matches_one_block(monkeypatch):
    """The window-tiled (flash-carry) form must reproduce the single-block
    kernel exactly on the same inputs — forced by shrinking the one-block VMEM
    budget so a small window takes the tiled branch (with a tile size that
    yields several tiles plus a padded tail)."""
    import distributed_llama_tpu.ops.pallas_attention as pa

    rng = np.random.RandomState(7)
    L, hk, g, s, hs = 2, 2, 3, 96, 16
    q = jnp.asarray(rng.randn(hk, g, hs).astype(np.float32))
    kc = jnp.asarray(rng.randn(L, 1, hk, s, hs).astype(np.float32))
    vc = jnp.asarray(rng.randn(L, 1, hk, s, hs).astype(np.float32))
    kn = jnp.asarray(rng.randn(hk, 1, hs).astype(np.float32))
    vn = jnp.asarray(rng.randn(hk, 1, hs).astype(np.float32))

    want = pa.fused_decode_attention(q, kc, vc, kn, vn, 1, 37, window=96)
    monkeypatch.setattr(pa, "_FUSED_ONE_BLOCK_LIMIT", 1)
    monkeypatch.setattr(pa, "_WT", 40)  # 96 -> tiles of 40/40/16(padded)
    pa.fused_decode_attention._clear_cache()
    got = pa.fused_decode_attention(q, kc, vc, kn, vn, 1, 37, window=96)
    pa.fused_decode_attention._clear_cache()
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
