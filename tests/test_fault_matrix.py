"""Tier-1 wiring for perf/fault_matrix.py (ISSUE 4 satellite, the
test_smoke_lint.py pattern): the full injection-point x fault-kind matrix
runs against the CPU-mesh engines and must produce ZERO invariant
violations — no scheduler-thread death, no slot/lease leak, no unusable
engine after an injected fault."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "perf"))

import fault_matrix  # noqa: E402


def test_fault_matrix_no_scheduler_death_or_slot_leak():
    cells, problems = fault_matrix.run_matrix(include_paged=True)
    # the batch family runs twice: pipelined AND serialized super-steps —
    # every injection point's invariants must hold under overlapped
    # dispatches too (docs/SERVING.md "Pipelined decode"); the speculation
    # family likewise runs spec-enabled engines under both schedulers with
    # survivor token-identity on its victim-only cells
    expected = (2 * len(fault_matrix.BATCH_POINTS)
                + 2 * len(fault_matrix.SPEC_POINTS)
                + len(fault_matrix.ENGINE_POINTS)
                + len(fault_matrix.PAGED_POINTS)
                + len(fault_matrix.ROUTER_POINTS)) * len(fault_matrix.KINDS) \
        + fault_matrix.SUPERVISOR_CELLS + fault_matrix.DURABILITY_CELLS \
        + fault_matrix.FAIRNESS_CELLS + fault_matrix.DISAGG_CELLS \
        + fault_matrix.GRAY_CELLS + fault_matrix.DRAFT_CELLS \
        + fault_matrix.FUSED_CELLS + fault_matrix.CONSTRAIN_CELLS
    assert cells == expected, (cells, expected)
    assert not problems, "\n".join(problems)


def test_matrix_covers_documented_inventory():
    """Every runtime injection point named in docs/ROBUSTNESS.md must be in
    the matrix — adding a fire() site without matrix coverage is exactly the
    silent-cap failure mode this wrapper exists to prevent."""
    covered = set(fault_matrix.BATCH_POINTS + fault_matrix.SPEC_POINTS
                  + fault_matrix.ENGINE_POINTS
                  + fault_matrix.PAGED_POINTS + fault_matrix.ROUTER_POINTS
                  + fault_matrix.DISAGG_POINTS
                  + fault_matrix.DISAGG_PLAN_POINTS
                  + fault_matrix.DRAFT_POINTS
                  + fault_matrix.CONSTRAIN_POINTS
                  + (fault_matrix.FUSED_POINT,))
    doc = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                            "ROBUSTNESS.md")).read()
    for point in covered:
        assert f"`{point}`" in doc, f"{point} missing from docs/ROBUSTNESS.md"