"""Test configuration: force an 8-device virtual CPU platform BEFORE jax import.

The reference project tests multi-node slicing without a cluster (SURVEY.md §4); we improve
on that with a real 8-device mesh of virtual CPU devices, so TP/SP sharding tests exercise
actual collectives.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize.py (axon TPU tunnel) imports jax at interpreter startup, so jax's config
# snapshot of JAX_PLATFORMS predates this file — override it explicitly.
jax.config.update("jax_platforms", "cpu")
# golden tests compare against f32 numpy oracles; don't let matmuls drop to bf16
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]
