"""Fused-epilogue Q40 kernels in the batched serving runtime (ISSUE 16).

Three layers of assurance, all interpret-mode on CPU:

- unit: the residual-add and gated silu·mul kernel epilogues against the
  dequantize-then-compute reference (ops/pallas_q4_mm.py);
- analytic: the per-dispatch HBM byte model stays within packed-weight
  density at every serving bucket, and the kernels are consistent with the
  XLA oracle — greedy argmax identity included (perf/q4_mm_bench.py);
- end-to-end: a --fused-matmul BatchEngine (pipelined + speculative +
  model drafter) and the T-bucket verify programs emit tokens IDENTICAL to
  the kernel-off engine, greedy and seeded-stochastic, with the selection
  registry proving the kernels actually served (no vacuous pass through
  the XLA fallback).
"""

import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llama_tpu.models.params import (init_random_params,
                                                 prepare_for_pallas)
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.pallas_q4_mm import (q4_gated_matmul,
                                                    q4_gated_supported,
                                                    q4_matmul)
from distributed_llama_tpu.quants import FloatType, QTensor

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "perf"))

import q4_mm_bench  # noqa: E402


def _w(n, k, seed=0):
    import jax
    rng = np.random.RandomState(seed)
    qt = QTensor.from_float(rng.randn(n, k).astype(np.float32) * 0.02,
                            FloatType.Q40).to_i4p_layout()
    return jax.tree_util.tree_map(jnp.asarray, qt)


def test_q4_matmul_residual_epilogue_matches():
    m, n, k = 8, 256, 1024
    w = _w(n, k)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32) * 0.1)
    res = jnp.asarray(rng.randn(m, n).astype(np.float32) * 0.1)
    want = (np.asarray(res, np.float32)
            + np.asarray(x, np.float32) @ np.asarray(
                w.dequantize(dtype=jnp.float32)).T)
    got = q4_matmul(x, w, out_dtype=jnp.float32, residual=res,
                    interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-2, rtol=3e-2)


@pytest.mark.parametrize("act", ["silu", "gelu_tanh"])
def test_q4_gated_matmul_matches(act):
    m, n, k = 8, 256, 1024
    w1, w3 = _w(n, k, seed=2), _w(n, k, seed=3)
    assert q4_gated_supported(w1, w3, m)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32) * 0.1)
    h1 = np.asarray(x, np.float32) @ np.asarray(
        w1.dequantize(dtype=jnp.float32)).T
    h3 = np.asarray(x, np.float32) @ np.asarray(
        w3.dequantize(dtype=jnp.float32)).T
    if act == "silu":
        want = h1 / (1.0 + np.exp(-h1)) * h3
    else:
        c = 0.7978845608028654
        want = 0.5 * h1 * (1.0 + np.tanh(c * (h1 + 0.044715 * h1 ** 3))) * h3
    got = q4_gated_matmul(x, w1, w3, act=act, out_dtype=jnp.float32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-2, rtol=5e-2)


def test_gated_supported_gates():
    w1, w3 = _w(256, 1024, seed=5), _w(256, 1024, seed=6)
    assert q4_gated_supported(w1, w3, 8)
    w_narrow = _w(128, 1024, seed=7)
    assert not q4_gated_supported(w1, w_narrow, 8)  # mismatched pair
    with pytest.raises(ValueError):
        q4_gated_matmul(jnp.ones((8, 1024), jnp.bfloat16), w1, w3,
                        act="tanh", interpret=True)


def test_bench_byte_model_within_packed_density():
    """Satellite smoke: at EVERY serving bucket x op the analytic HBM
    traffic of a fused dispatch is <= packed-weight bytes x 2 (the
    'small constant' bar — weights dominate; the dequantized bf16 image
    alone would be 3.56x), and the weight stream is exactly Q40 packed
    density (0.5625 B/weight)."""
    for bucket, m, shapes in q4_mm_bench.BUCKETS:
        for n, k in shapes:
            for kw in ({}, {"residual": True}, {"gated": True}):
                rec = q4_mm_bench.hbm_model(m, n, k, **kw)
                assert rec["ratio"] <= 2.0, (bucket, m, n, k, kw, rec)
                assert rec["density"] == 0.5625, (bucket, rec)


def test_bench_kernels_consistent_with_xla_oracle():
    """Satellite smoke: interpret-mode kernels vs the XLA dequant+dot
    oracle — close in f32 AND identical greedy argmax per row, on every
    fused variant (mm, mm+res, gated)."""
    problems = q4_mm_bench.check_consistency()
    assert problems == [], "\n".join(problems)


def _spec():
    # dim 1024: K/2 = 512 tiles exactly (ops/pallas_q4_mm._pick_bkp), so the
    # fused kernels actually serve — a non-tileable dim would shape-gate to
    # XLA and verify nothing; the registry assertion below guards that.
    # (dim 512 tiles too, but its bkp=256 two-step accumulation order rounds
    # differently enough from the XLA dot to flip near-tie greedy argmaxes
    # at this vocab — the single-K-tile dim keeps the identity bar exact.)
    return ModelSpec(arch_type=ArchType.LLAMA, dim=1024, hidden_dim=1024,
                     n_layers=2, n_heads=8, n_kv_heads=8, vocab_size=256,
                     seq_len=32, rope_type=RopeType.LLAMA).resolved()


REP = [7, 31, 5, 102] * 4  # n-gram-dense: engages the verify path


def _run_batch(spec, params, reqs, *, draft=False, **kw):
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    V = spec.vocab_size
    if draft:
        kw["draft_model"] = (spec, params)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4, pipeline=True,
                     speculative=4, spec_min_draft=1, **kw)
    try:
        subs = [be.submit(list(p), gen, Sampler(V, temperature=temp,
                                                seed=seed))
                for p, gen, temp, seed in reqs]
        return [r.wait(timeout=300) for r in subs]
    finally:
        be.close()


def test_batch_engine_fused_token_identity():
    """The acceptance gate: a fused BatchEngine (pipelined + speculative,
    with the co-resident model drafter so its k-step scan runs the kernels
    too) emits tokens IDENTICAL to the kernel-off engine for greedy AND
    seeded-stochastic requests, and the selection registry proves all
    three kernel families served (q4_mm for wqkv/wcls, q4_mm+res for
    wo/w2, q4_gated_mm for the w1/w3 pair) — the fallback recording would
    expose a silently-degraded run."""
    from distributed_llama_tpu.ops.matmul import (kernel_selections,
                                                  reset_kernel_selections)

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=5)
    reqs = [(REP, 8, 0.0, 0),               # greedy, verify-engaging
            ([1, 9, 2, 7], 8, 0.0, 0),      # greedy, scan path
            (REP, 6, 0.8, 11)]              # seeded stochastic
    want = _run_batch(spec, params, reqs, draft=True)
    reset_kernel_selections()
    got = _run_batch(spec, params, reqs, draft=True, use_pallas=True,
                     fused_matmul=True)
    assert got == want
    sel = set(kernel_selections().values())
    assert {"q4_mm", "q4_mm+res", "q4_gated_mm"} <= sel, sel


@pytest.mark.parametrize("t", [2, 3, 5, 9])
def test_verify_bucket_fused_matches_dense(t):
    """Verify-bucket sweep: the (B, T) verify program under
    use_pallas="fused" returns the same targets/accepts/frontier as the
    dense XLA reference at every reachable T bucket."""
    from distributed_llama_tpu.ops.rope import RopeTables
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                                   shard_params)
    from distributed_llama_tpu.runtime.device_loop import \
        make_batched_verify_loop

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=9)
    mesh = make_mesh(tp=1)
    rope = RopeTables.create(spec)
    b = 2
    rng = np.random.RandomState(t)
    proposals = rng.randint(0, spec.vocab_size, size=(b, t)).astype(np.int32)
    start = np.zeros((b,), np.int32)
    rstate = np.ones((b, 2), np.uint32)
    temp = np.zeros((b,), np.float32)
    topp = np.ones((b,), np.float32)
    ndraft = np.full((b,), t - 1, np.int32)

    def run(p, up):
        loop = make_batched_verify_loop(spec, mesh, p, t, mode="greedy",
                                        use_pallas=up, donate_cache=False)
        kc, vc = init_sharded_kv_cache(spec, mesh, batch=b)
        toks, acc, tok, pos, _rng, _kc, _vc = loop(
            p, rope, proposals, kc, vc, start, rstate, temp, topp, ndraft)
        return (np.asarray(toks).tolist(), np.asarray(acc).tolist(),
                np.asarray(tok).tolist(), np.asarray(pos).tolist())

    base = shard_params(params, mesh, spec)
    want = run(base, False)
    pp = shard_params(
        prepare_for_pallas(params, spec=spec, keep_gate_pair=True),
        mesh, spec)
    got = run(pp, "fused")
    assert got == want
