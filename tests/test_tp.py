"""Tensor-parallel equivalence: sliced execution == unsliced execution.

The reference proves this only for RoPE slices (commands-test.cpp) and stubs out sockets
for block tests; here the whole model runs SPMD on a real 2/4/8-device mesh with actual
collectives, for all three architectures — the multi-device test the reference never
automated (SURVEY.md §4)."""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_tpu.models.forward import forward, init_kv_cache
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec, RopeType
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.parallel import (make_mesh, make_sharded_forward,
                                            shard_params)
from distributed_llama_tpu.parallel.tp import init_sharded_kv_cache
from distributed_llama_tpu.quants import FloatType


def tp_spec(arch=ArchType.LLAMA, **kw):
    defaults = dict(
        arch_type=arch, dim=256, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=8,
        vocab_size=256, seq_len=16, rope_type=RopeType.LLAMA,
    )
    if arch != ArchType.LLAMA:
        defaults.update(n_experts=4, n_active_experts=2, rope_type=RopeType.FALCON)
    if arch == ArchType.GROK1:
        defaults.update(hidden_act=HiddenAct.GELU)
    defaults.update(kw)
    return ModelSpec(**defaults).resolved()


def reference_logits(spec, params, tokens):
    rope = RopeTables.create(spec)
    kc, vc = init_kv_cache(spec)
    logits, _, _ = forward(params, spec, rope, tokens, kc, vc, jnp.int32(0))
    return np.asarray(logits)


def tp_logits(spec, params, tokens, tp, **fwd_kw):
    mesh = make_mesh(tp=tp)
    rope = RopeTables.create(spec)
    sp = shard_params(params, mesh, spec)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    step = make_sharded_forward(spec, mesh, sp, donate_cache=False, **fwd_kw)
    logits, kc2, vc2 = step(sp, rope, tokens, kc, vc, jnp.int32(0))
    return np.asarray(logits), kc2


@pytest.mark.parametrize("tp", [2, 4, 8])
def test_llama_tp_equivalence(tp):
    spec = tp_spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    tokens = jnp.asarray(np.arange(1, 6, dtype=np.int32))[None, :]
    want = reference_logits(spec, params, tokens)
    got, _ = tp_logits(spec, params, tokens, tp)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)


@pytest.mark.parametrize("arch", [ArchType.MIXTRAL, ArchType.GROK1])
def test_moe_tp_equivalence(arch):
    spec = tp_spec(arch)
    params = init_random_params(spec, FloatType.Q40, seed=13)
    tokens = jnp.asarray(np.arange(1, 5, dtype=np.int32))[None, :]
    want = reference_logits(spec, params, tokens)
    got, _ = tp_logits(spec, params, tokens, 4)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)


def test_gqa_tp_up_to_kv_heads():
    """tp == n_kv_heads works (the reference's limit, transformer.cpp:108-111)."""
    spec = tp_spec(n_heads=8, n_kv_heads=4)
    params = init_random_params(spec, FloatType.F32, seed=17)
    tokens = jnp.asarray([[3, 1, 4]])
    want = reference_logits(spec, params, tokens)
    got, _ = tp_logits(spec, params, tokens, 4)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)


@pytest.mark.parametrize("tp,hk", [(8, 4), (8, 2), (4, 1)])
def test_gqa_tp_beyond_kv_heads(tp, hk):
    """tp > n_kv_heads via KV-head replication — the reference's hard limit
    (transformer.cpp:108-111) lifted; gates 405B (8 KV heads) on 16+ chips."""
    spec = tp_spec(n_heads=8, n_kv_heads=hk)
    params = init_random_params(spec, FloatType.Q40, seed=29)
    tokens = jnp.asarray([[3, 1, 4, 1, 5]])
    want = reference_logits(spec, params, tokens)
    got, kc2 = tp_logits(spec, params, tokens, tp)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)
    # cache head axis expanded to tp heads, one per shard
    assert kc2.shape[2] == tp
    assert kc2.sharding.shard_shape(kc2.shape)[2] == 1


def test_kv_replication_with_sequence_parallelism():
    """tp > n_kv_heads on an sp x tp mesh (the pod-scale shape: 405B runs sp x tp)."""
    spec = tp_spec(n_heads=8, n_kv_heads=2)
    params = init_random_params(spec, FloatType.Q40, seed=31)
    tokens = jnp.asarray([[1, 7, 23, 5]])
    want = reference_logits(spec, params, tokens)

    mesh = make_mesh(sp=2, tp=4)
    rope = RopeTables.create(spec)
    sharded = shard_params(params, mesh, spec)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    step = make_sharded_forward(spec, mesh, sharded, donate_cache=False)
    got, _, _ = step(sharded, rope, tokens, kc, vc, jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4, rtol=1e-3)


def test_tp_not_multiple_of_kv_heads_raises():
    """Replication needs tp % n_kv_heads == 0; ragged splits stay an error.

    n_heads=24 keeps n_heads % tp == 0 satisfied, so the failure can only come from
    the tp=8 vs n_kv_heads=3 mismatch — isolating the replication guard."""
    spec = tp_spec(n_heads=24, n_kv_heads=3, dim=768, hidden_dim=768)
    params = init_random_params(spec, FloatType.F32, seed=17)
    tokens = jnp.asarray([[3]])
    with pytest.raises(AssertionError, match="n_kv_heads"):
        tp_logits(spec, params, tokens, 8)


def test_compressed_collectives():
    """Q80-compressed all-reduce (wire-compression parity, tasks.cpp:96-135) stays close
    to the uncompressed result."""
    spec = tp_spec()
    params = init_random_params(spec, FloatType.F32, seed=19)
    tokens = jnp.asarray([[5, 9, 2]])
    want = reference_logits(spec, params, tokens)
    got, _ = tp_logits(spec, params, tokens, 4, compress_collectives=True)
    assert np.max(np.abs(got - want)) < 0.05
    # rank-1 token choice must survive compression
    assert np.argmax(got[0, -1]) == np.argmax(want[0, -1])


def test_kv_cache_stays_sharded():
    spec = tp_spec()
    params = init_random_params(spec, FloatType.F32, seed=23)
    tokens = jnp.asarray([[3, 1]])
    _, kc2 = tp_logits(spec, params, tokens, 4)
    # cache sharding: heads axis split over tp
    shard_shape = kc2.sharding.shard_shape(kc2.shape)
    assert shard_shape[2] == spec.n_kv_heads // 4


def test_llama31_405b_spec_shards_at_full_scale():
    """The real Llama-3.1-405B geometry (126 layers, dim 16384, 8 KV heads) must
    TRACE through the full sharded step on a tp=8 mesh — shape/sharding validation
    via jax.eval_shape with zero weight memory. This is the pod-scale config the
    reference gates at nSlices <= nKvHeads (transformer.cpp:108-111) and the
    SURVEY §7 build-plan step 8 target."""
    import jax
    from distributed_llama_tpu.models.params import block_tensor_shapes
    from distributed_llama_tpu.models.spec import RopeType as RT
    from distributed_llama_tpu.quants import QK, FloatType, QTensor

    spec = ModelSpec(
        arch_type=ArchType.LLAMA, dim=16384, hidden_dim=53248, n_layers=126,
        n_heads=128, n_kv_heads=8, vocab_size=128256, seq_len=2048,
        rope_theta=500000.0, rope_type=RT.LLAMA3_1, rope_scaling_factor=8.0,
        rope_scaling_low_freq_factor=1.0, rope_scaling_high_freq_factor=4.0,
        rope_scaling_orig_max_seq_len=8192).resolved()

    def q40_struct(shape):
        out, in_ = shape[-2], shape[-1]
        lead = shape[:-2]
        return QTensor(
            FloatType.Q40,
            jax.ShapeDtypeStruct((*lead, out, in_ // QK, 16), jnp.uint8),
            jax.ShapeDtypeStruct((*lead, out, in_ // QK), jnp.float16))

    blocks = {}
    for name, (shape, quantized) in block_tensor_shapes(spec).items():
        full = (spec.n_layers, *shape)
        blocks[name] = (q40_struct(full) if quantized
                        else jax.ShapeDtypeStruct(full, jnp.float32))
    params = {
        "embedding": jax.ShapeDtypeStruct((spec.vocab_size, spec.dim), jnp.float32),
        "blocks": blocks,
        "rms_final": jax.ShapeDtypeStruct((spec.dim,), jnp.float32),
        "wcls": q40_struct((spec.vocab_size, spec.dim)),
    }

    mesh = make_mesh(tp=8)
    rope_shape = RopeTables.create(spec)  # real tables are small; build them for real
    from distributed_llama_tpu.parallel.sharding import effective_kv_heads
    hk = effective_kv_heads(spec, 8)
    cache = jax.ShapeDtypeStruct(
        (spec.n_layers, 1, hk, spec.seq_len, spec.head_size), jnp.bfloat16)
    step = make_sharded_forward(spec, mesh, params, dtype=jnp.bfloat16,
                                donate_cache=False, attn_window=256)
    out = jax.eval_shape(step, params, rope_shape,
                         jax.ShapeDtypeStruct((1, 1), jnp.int32), cache, cache,
                         jax.ShapeDtypeStruct((), jnp.int32))
    logits, kc, vc = out
    assert logits.shape == (1, 1, spec.vocab_size)
    assert kc.shape == cache.shape


def test_make_pod_mesh_single_host_layouts():
    """make_pod_mesh (the DCN-aware builder) on one host must accept partial-fill
    tp/sp and infer the rest — the same contract as make_mesh."""
    from distributed_llama_tpu.parallel.mesh import make_pod_mesh

    m = make_pod_mesh(tp=4)  # dp inferred = 2 on the 8-device harness
    assert m.shape == {"dp": 2, "sp": 1, "tp": 4}
    m = make_pod_mesh(sp=2)  # tp inferred with dp defaulting to n_proc (=1)
    assert m.shape == {"dp": 1, "sp": 2, "tp": 4}
    with pytest.raises(AssertionError):
        make_pod_mesh(tp=3)  # 8 devices not divisible
