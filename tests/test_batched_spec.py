"""Batched speculative decoding tests (ISSUE 8; docs/SERVING.md
"Speculative decoding").

The BatchEngine's verify path (runtime/device_loop.py
make_batched_verify_loop) ingests a per-row proposal block in ONE (B, T)
dispatch, computes per-row accepted lengths on device, and rewinds the
(token, position, RNG) carry to each row's verified frontier. Load-bearing
properties:

- spec-on output is BYTE-IDENTICAL to the spec-off batched loop — greedy
  AND seeded-stochastic rows, mixed spec/non-spec rows in one super-step;
- the host sampler's xorshift* stream advances only for DELIVERED tokens
  (stop mid-accepted-block replays exactly the delivered coins);
- context-end: the block length shrinks so live-row writes stay in-cache,
  and output stays identical through the clamp;
- pipeline composition: chained scans after verify dispatches flush/keep
  correctly under flush-storm pressure, with no slot/lease leak;
- accept lengths match the sequential speculative loop: a first-principles
  oracle re-derives each verify turn's draft + accept from the (identical)
  greedy stream, and generate_speculative on the same prompt emits the same
  tokens.
"""

import pytest

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.sampler import Sampler
from distributed_llama_tpu.runtime.speculative import (NgramIndex,
                                                       generate_speculative)

K = 8  # draft cap under test

# greedy decode of the seed-11 tiny model enters a repetitive attractor on
# these n-gram-dense prompts, so verify dispatches engage and accept
REP = [5, 9, 17, 3, 44, 9, 17, 3]
REP2 = [7, 31, 5, 102, 9, 31, 5, 77]


def _spec(seq_len=256):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=seq_len, rope_type=RopeType.LLAMA).resolved()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


@pytest.fixture(scope="module")
def setup():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=K, speculative=K)
    yield spec, params, be
    be.close()


def _run(be, jobs, timeout=300):
    """Submit [(prompt, n, sampler, kw)] together; return ([outs], [reqs])."""
    reqs = [be.submit(list(p), n, s, **kw) for p, n, s, kw in jobs]
    return [r.wait(timeout=timeout) for r in reqs], reqs


def _ab(be, jobs_fn, timeout=300):
    """Run the same job schedule spec-off then spec-on against one engine
    (compiled programs and slot state shared); returns both results."""
    k = be.spec_k
    try:
        be.spec_k = 0
        off = _run(be, jobs_fn(), timeout)
    finally:
        be.spec_k = k
    on = _run(be, jobs_fn(), timeout)
    return off, on


# ------------------------------------------------------------- identity


def test_greedy_identity_and_verify_engaged(setup):
    spec, params, be = setup
    prompts = [[1] + REP * 6, [1, 2] + REP2 * 5]

    def jobs():
        return [(p, 48, _greedy(spec), {}) for p in prompts]

    (off, _), (on, reqs) = _ab(be, jobs)
    assert on == off
    assert sum(r.stats.spec_steps for r in reqs) >= 2, (
        "verify dispatches never engaged — the identity test is vacuous")
    assert sum(r.stats.spec_accepted for r in reqs) >= 1
    for r in reqs:
        assert r.finish == "length"
        assert r.stats.generated_tokens == 48


def test_seeded_stochastic_identity(setup):
    """Sharp-but-stochastic rows (temperature 0.02: near-greedy, so drafts
    match, but EVERY emitted token consumes an xorshift* coin) must emit the
    exact spec-off stream — the device replays coins only for accepted
    tokens and rewinds the RNG carry to the verified frontier. Seed 42
    accepts drafts (pinned by probe); the final sampler state must match
    too, or a later request sharing the sampler would diverge."""
    spec, params, be = setup
    prompt = [1] + REP * 6

    def jobs():
        return [(prompt, 48,
                 Sampler(spec.vocab_size, temperature=0.02, topp=0.9,
                         seed=42), {})]

    (off, off_reqs), (on, reqs) = _ab(be, jobs)
    assert on == off
    assert reqs[0].stats.spec_steps >= 1
    assert reqs[0].stats.spec_accepted >= 1, (
        "no stochastic draft accepted — the RNG-rewind path is untested")
    assert off_reqs[0].sampler.state == reqs[0].sampler.state


def test_mixed_spec_and_nonspec_rows_one_superstep(setup):
    """A repetitive greedy row and a stochastic row share super-steps; both
    must match their spec-off streams even when only one drafts."""
    spec, params, be = setup

    def jobs():
        return [([1] + REP * 6, 40, _greedy(spec), {}),
                ([1, 2] + REP2 * 5, 40,
                 Sampler(spec.vocab_size, temperature=0.8, topp=0.9,
                         seed=7), {})]

    (off, _), (on, reqs) = _ab(be, jobs)
    assert on == off
    assert reqs[0].stats.spec_steps >= 1  # the greedy row speculated


# ------------------------------------------------- stop / rollback / clamp


def _stop_at(j):
    """Positional stop: fires on the (j+1)-th delivered token — lands the
    stop at a chosen stream index regardless of token values."""
    seen = [0]

    def check(_t):
        seen[0] += 1
        return seen[0] - 1 == j

    return check


def test_stop_mid_accepted_block_replays_delivered_coins():
    """A stop landing INSIDE an accepted block cuts delivery at the stop:
    the accepted tail is rolled back (masked slots) and the host sampler
    replays exactly the delivered coins. A fresh serialized engine makes
    the verify cadence fully deterministic, so a probe run's spec_turns
    pick a stop index provably inside an accepted block."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=K, speculative=K,
                     pipeline=False)
    try:
        prompt = [1] + REP * 6
        smp = lambda: Sampler(spec.vocab_size, temperature=0.02, topp=0.9,  # noqa: E731
                              seed=42)
        probe, preqs = _run(be, [(prompt, 48, smp(), {})])
        # need >= 2 accepted: the stop at n0+1 then provably cuts a block
        # whose verified frontier extends past it
        turn = next(t for t in preqs[0].stats.spec_turns if t[2] >= 2)
        n0, _, a0 = turn
        j = n0 + 1  # second token of that block: an accepted draft

        def jobs():
            return [(prompt, 48, smp(), {"stop_check": _stop_at(j)})]

        (off, off_reqs), (on, reqs) = _ab(be, jobs)
        assert on == off
        assert on[0] == probe[0][:j + 1]
        assert reqs[0].finish == "stop"
        assert off_reqs[0].sampler.state == reqs[0].sampler.state
        # the stop cut a block the device had accepted further: the last
        # verify turn's frontier extends past the delivered output
        last = reqs[0].stats.spec_turns[-1]
        assert last[0] + last[2] + 1 > len(on[0]), (last, len(on[0]))
    finally:
        be.close()


def test_context_end_clamp_identity():
    """Rows decoding to the context end: the verify block length shrinks so
    live-row writes stay inside seq_len (falling back to scans for the last
    tokens), and output stays identical through the clamp with finish
    'length' at pos == seq_len."""
    spec = _spec(seq_len=64)
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4, speculative=K)
    try:
        prompt = [1] + REP * 3  # 25 tokens; ~39 of context room left
        def jobs():
            return [(prompt, 64, _greedy(spec), {})]

        (off, _), (on, reqs) = _ab(be, jobs)
        assert on == off
        assert reqs[0].finish == "length"
        assert len(on[0]) == spec.seq_len - len(prompt) + 1
    finally:
        be.close()


def test_pipeline_flush_storm_with_spec_no_leak(setup):
    """1-2 token requests interleaved with repetitive long ones maximize
    chain flush pressure while verifies engage; everything completes
    token-identically and no slot/lease is left pinned."""
    spec, params, be = setup

    def jobs():
        out = []
        for i in range(6):
            out.append(([1, 3 + i] + REP * 4, 1 + (i % 2), _greedy(spec),
                        {}))
        out.append(([1] + REP * 6, 40, _greedy(spec), {}))
        return out

    (off, _), (on, _) = _ab(be, jobs, timeout=600)
    assert on == off
    with be._plock:
        assert all(s.req is None and s.lease is None for s in be._slots)
    assert be.scheduler_alive()


# ------------------------------------------------------------- oracles


@pytest.fixture(scope="module")
def oracle_setup():
    # fresh SERIALIZED engine: no chains, so the verify cadence is a pure
    # function of the token stream — deterministic turns for the oracles
    # (the shared `setup` engine's accept EMA evolves across tests)
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=1, tp=1, superstep=K, speculative=K,
                     pipeline=False)
    yield spec, params, be
    be.close()


def test_accept_length_oracle_first_principles(oracle_setup):
    """Every batched verify turn's (draft, accept) must equal what the
    sequential speculative algorithm would compute at the same stream
    state: draft = prompt-lookup over prompt + out[:n] with the sequential
    cap, accept = leading drafts matching the (identical) greedy stream."""
    spec, params, be = oracle_setup
    prompt = [1] + REP * 6
    n = 48
    (outs, reqs) = _run(be, [(prompt, n, _greedy(spec), {})])
    out, req = outs[0], reqs[0]
    assert req.stats.spec_steps >= 2
    s = spec.seq_len
    for n_out, drafted, accepted in req.stats.spec_turns:
        corpus = prompt + out[:n_out]
        pos = len(prompt) - 1 + n_out  # ingestions at this turn, both loops
        cap = min(K, n - n_out - 1, s - pos - 2)
        draft = NgramIndex(corpus).propose_extended(cap)
        # block buckets may have shrunk a long draft near the context end
        assert drafted <= len(draft)
        want_accept = 0
        for i, d in enumerate(draft[:drafted]):
            if n_out + i < len(out) and d == out[n_out + i]:
                want_accept += 1
            else:
                break
        assert accepted == min(want_accept, drafted), (
            n_out, drafted, accepted, draft, out[n_out:n_out + drafted])


def test_output_matches_sequential_generate_speculative(oracle_setup):
    """The batched verify path and the sequential generate_speculative must
    emit the same greedy tokens for the same prompt (both equal the plain
    sequential stream — the speculative identity), and any verify turn both
    paths take at the same output length must agree on (draft, accept)."""
    spec, params, be = oracle_setup
    prompt = [1] + REP * 6
    n = 40
    (outs, reqs) = _run(be, [(prompt, n, _greedy(spec), {})])
    eng = Engine(spec, params, tp=1)
    seq_out, seq_stats = generate_speculative(eng, list(prompt), n,
                                              _greedy(spec), k=K)
    assert outs[0] == seq_out
    seq_turns = {t[0]: t[1:] for t in seq_stats.spec_turns}
    for n_out, drafted, accepted in reqs[0].stats.spec_turns:
        if n_out in seq_turns:
            assert (drafted, accepted) == seq_turns[n_out], (
                n_out, (drafted, accepted), seq_turns[n_out])
