"""Durable requests (ISSUE 9): journaled mid-stream failover with
token-identical resume, plus the hung-engine supervisor.

- journal units: seed pinning, idempotent token recording across resumed
  (from-zero re-counting) upstreams, exactly-once splicing, remaining
  deadline arithmetic, full-table fallback;
- membership poller backoff: unreachable replicas back off exponentially
  with jitter on the background schedule while explicit polls stay
  immediate, and the down log is capped;
- supervisor: a fault-injected dispatch hang is escalated within the
  threshold (in-flight fails with the RETRIABLE EngineWedged, backend
  re-initializes, /healthz recovers) and a failing re-init parks the
  engine in state "failed";
- live fleet: two REAL in-process replicas + the durable router — a
  mid-stream replica wedge (the supervisor escalation shape) is survived
  with ZERO client-visible failures and byte-identical output for greedy
  AND seeded-stochastic streams, for streaming and non-streaming clients;
  the in-band journal field never leaks to the client; X-Deadline-Ms is
  enforced and an expired budget is an honest 408.
"""

import http.client
import json
import threading
import time

import pytest

from distributed_llama_tpu.apps.api_server import serve
from distributed_llama_tpu.fleet.journal import (JournalEntry, RequestJournal,
                                                 pin_seed)
from distributed_llama_tpu.fleet.membership import Membership
from distributed_llama_tpu.fleet.router import close_router, serve_router
from distributed_llama_tpu.formats.mfile import (load_model,
                                                 params_file_order,
                                                 write_model)
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.obs import metrics as obs_metrics
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.resilience import faults
from distributed_llama_tpu.resilience.errors import (EngineWedged,
                                                     FaultInjected, retriable)
from distributed_llama_tpu.resilience.faults import FaultSpec
from distributed_llama_tpu.resilience.supervisor import EngineSupervisor
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.sampler import Sampler
from distributed_llama_tpu.tokenizer import TemplateType
from distributed_llama_tpu.tokenizer.bpe import Tokenizer

# ----------------------------------------------------------------------
# journal units
# ----------------------------------------------------------------------


def test_pin_seed_fills_and_preserves():
    b = pin_seed({"messages": []})
    assert isinstance(b["seed"], int)
    assert pin_seed({"seed": 42})["seed"] == 42
    assert pin_seed({"seed": None})["seed"] is not None  # null == unset


def test_record_tokens_idempotent_across_resume():
    e = JournalEntry("j", {}, True, None)
    e.record_tokens({"n": 2, "toks": [5, 6]})
    e.record_tokens({"n": 3, "toks": [7]})
    assert e.tokens == [5, 6, 7]
    # a resumed upstream re-counts from zero over tokens we already hold:
    # replayed chunks fold in as no-ops, the tail appends
    e.record_tokens({"n": 2, "toks": [5, 6]})
    assert e.tokens == [5, 6, 7]
    e.record_tokens({"n": 5, "toks": [8, 9]})
    assert e.tokens == [5, 6, 7, 8, 9]
    # malformed journal info never corrupts the entry
    e.record_tokens({"n": "x", "toks": [1]})
    e.record_tokens({})
    assert e.tokens == [5, 6, 7, 8, 9]


def test_splice_exactly_once():
    e = JournalEntry("j", {}, True, None)
    up = 0
    out = []
    for text in ("ab", "cde", "f"):
        up += len(text)
        out.append(e.splice(text, up))
    assert "".join(out) == "abcdef" and e.sent_chars == 6
    # resumed upstream re-emits from zero: everything already sent splices
    # to nothing, the continuation (incl. a chunk STRADDLING the boundary)
    # comes through exactly once
    up = 0
    out = []
    for text in ("abcd", "efgh", "ij"):
        up += len(text)
        out.append(e.splice(text, up))
    assert "".join(out) == "ghij" and e.sent_chars == 10


def test_remaining_deadline_ms():
    e = JournalEntry("j", {}, True, deadline_ms=100.0)
    r = e.remaining_deadline_ms()
    assert r is not None and 0.0 <= r <= 100.0
    e.t0 -= 1.0  # 1s elapsed: budget gone, floor at 0
    assert e.remaining_deadline_ms() == 0.0
    assert JournalEntry("j", {}, True, None).remaining_deadline_ms() is None


def test_journal_full_degrades_to_unjournaled():
    j = RequestJournal(max_inflight=1)
    e1 = j.open({}, True, None)
    assert e1 is not None
    assert j.open({}, True, None) is None  # full: caller uses the plain path
    j.close(e1, "stop")
    assert j.open({}, True, None) is not None


def test_journal_abandon_reclaims_and_is_idempotent():
    """A handler that unwinds without close() (client dropped mid-relay)
    must reclaim its entry — leaked entries would fill the table and
    silently disable durability fleet-wide."""
    j = RequestJournal(max_inflight=2)
    e = j.open({}, True, None)
    j.abandon(e)
    assert j.inflight() == 0 and e.finish == "abandoned"
    j.abandon(e)  # idempotent
    e2 = j.open({}, True, None)
    j.close(e2, "stop")
    j.abandon(e2)  # no-op after a real close: finish is preserved
    assert e2.finish == "stop" and j.inflight() == 0


def test_membership_backoff_never_overflows():
    m = Membership(["127.0.0.1:1"], poll_interval=0.2, poll_timeout=0.2,
                   backoff_cap=5.0)
    rep = m.replicas[0]
    rep.consecutive_failures = 5000  # hours-down replica: 2**5000 territory
    m._note_unreachable(rep)  # must not OverflowError the poller thread
    assert rep.next_poll_t - time.monotonic() <= 5.0


def test_upstream_body_carries_resume_and_streams():
    e = JournalEntry("j", {"stream": False, "seed": 1}, False, None)
    assert e.upstream_body()["stream"] is True  # journal needs the tokens
    assert "resume" not in e.upstream_body()
    e.tokens.extend([4, 5])
    assert e.upstream_body()["resume"] == {"tokens": [4, 5]}


def test_retriable_classification():
    assert retriable(EngineWedged("x"))
    assert retriable(RuntimeError("unclassified server error"))
    assert retriable(FaultInjected("engine blast", scope="engine"))
    assert not retriable(FaultInjected("request blast", scope="request"))
    from distributed_llama_tpu.resilience.errors import (DeadlineExceeded,
                                                         EngineSaturated,
                                                         InvalidRequest)
    assert not retriable(DeadlineExceeded("x"))
    assert not retriable(InvalidRequest("x"))
    assert not retriable(EngineSaturated("x"))


# ----------------------------------------------------------------------
# membership backoff
# ----------------------------------------------------------------------


def test_membership_backoff_on_unreachable():
    # a port nothing listens on: every poll fails fast (connection refused)
    m = Membership(["127.0.0.1:1"], poll_interval=0.2, poll_timeout=0.2,
                   backoff_cap=5.0)
    rep = m.replicas[0]
    m.poll_once()
    assert rep.status == "unreachable" and rep.consecutive_failures == 1
    first_backoff = rep.next_poll_t - time.monotonic()
    assert 0.0 < first_backoff <= 0.2  # base × jitter in [0.5, 1.0)
    for _ in range(6):
        m.poll_once()  # force=True ignores the backoff window
    assert rep.consecutive_failures == 7
    capped = rep.next_poll_t - time.monotonic()
    assert capped <= 5.0  # exponential growth is capped
    assert capped > first_backoff
    # the BACKGROUND schedule honors the window: a skipped replica is not
    # re-probed (failure count frozen)
    before = rep.consecutive_failures
    m.poll_once(force=False)
    assert rep.consecutive_failures == before


def test_membership_down_log_capped(capsys):
    m = Membership(["127.0.0.1:1"], poll_interval=0.1, poll_timeout=0.2,
                   down_log_interval=3600.0)
    for _ in range(5):
        m.poll_once()
    out = capsys.readouterr().out
    # one "unreachable" line for five failed polls, not five
    assert out.count("unreachable") == 1


# ----------------------------------------------------------------------
# supervisor
# ----------------------------------------------------------------------


class _StubEngine:
    def __init__(self, age=99.0, reinit_ok=True):
        self.age = age
        self.reinit_ok = reinit_ok
        self.recovers = 0

    def dispatch_age(self):
        return self.age

    def scheduler_alive(self):
        return True

    def recover_wedged(self, reinit=True):
        self.recovers += 1
        return self.reinit_ok


def test_supervisor_failed_when_reinit_fails():
    sup = EngineSupervisor(_StubEngine(reinit_ok=False), threshold=1.0,
                           poll=0.05)
    sup.check_once()
    assert sup.state == "failed" and not sup.healthy
    sup.check_once()  # failed is terminal: no recovery thrash
    assert sup.engine.recovers == 1


def test_supervisor_gives_up_after_max_recoveries():
    eng = _StubEngine(reinit_ok=True)
    sup = EngineSupervisor(eng, threshold=1.0, poll=0.05, max_recoveries=2)
    for _ in range(5):
        sup.check_once()  # age never improves: consecutive escalations
    assert sup.state == "failed"
    # exactly max_recoveries attempts run, then the engine parks "failed"
    # (the documented contract; no progress between them ever resets)
    assert eng.recovers == 2


@pytest.mark.slow  # tier-1 covers this contract via the fault-matrix
def test_supervisor_recovers_live_engine_hang():
    """The acceptance shape: a deterministically-wedged engine (latency
    fault parking the scheduler in a 600s sleep) recovered by the RUNNING
    supervisor thread within its escalation threshold — the thread-loop
    variant of perf/fault_matrix.py's supervisor cell (which drives
    check_once deterministically and runs in tier-1)."""
    from distributed_llama_tpu.models.spec import RopeType

    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=128, rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4)
    # threshold must exceed the slowest LEGITIMATE dispatch — after the
    # recovery re-init the probe recompiles from scratch, and a threshold
    # under that compile time would spuriously wedge the recovered engine
    sup = EngineSupervisor(be, threshold=6.0, poll=0.2).start()
    try:
        be.generate([1, 7, 23, 5], 4, Sampler(spec.vocab_size, 0.0))  # warm
        with faults.active(FaultSpec("batch.dispatch", kind="latency",
                                     delay_ms=600_000, count=1)):
            req = be.submit([1, 9, 9, 2], 8, Sampler(spec.vocab_size, 0.0))
            with pytest.raises(EngineWedged):
                req.wait(timeout=60)  # the supervisor thread must fire it
        assert sup.recoveries == 1
        deadline = time.monotonic() + 10
        while not sup.healthy and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sup.healthy
        probe = be.submit([1, 2, 3], 4, Sampler(spec.vocab_size, 0.0))
        assert len(probe.wait(timeout=120)) == 4
    finally:
        faults.uninstall()
        sup.stop()
        be.close()


# ----------------------------------------------------------------------
# live durable fleet
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("durable")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=262,
                     seq_len=192).resolved()
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = str(tmp / "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = str(tmp / "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    return mpath, tpath


@pytest.fixture(scope="module")
def fleet(model_files):
    mpath, tpath = model_files
    reps = []
    for _ in range(2):
        lspec, lparams = load_model(mpath, 0)
        be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), slots=2,
                         tp=1, superstep=4)
        srv = serve(None, host="127.0.0.1", port=0,
                    template_type=TemplateType.CHATML, batch_engine=be)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        reps.append((be, srv, srv.server_address[1]))
    router = serve_router([f"127.0.0.1:{p}" for _, _, p in reps],
                          host="127.0.0.1", port=0, poll_interval=0.15,
                          block_bytes=16, retries=2, try_timeout=60.0)
    threading.Thread(target=router.serve_forever, daemon=True).start()
    yield {"reps": reps, "router": router,
           "port": router.server_address[1]}
    close_router(router)
    for be, srv, _p in reps:
        srv.shutdown()
        srv.server_close()
        be.close()


def _body(seed=None, temperature=0.8, stream=True, max_tokens=40,
          user="hello durable"):
    b = {"messages": [
        {"role": "system", "content": "durable shared system prompt"},
        {"role": "user", "content": user}],
        "max_tokens": max_tokens, "temperature": temperature,
        "stream": stream}
    if seed is not None:
        b["seed"] = seed
    return b


def _stream(port, body, on_delta=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request("POST", "/v1/chat/completions", json.dumps(body), hdrs)
        resp = conn.getresponse()
        if resp.status != 200:
            return {"status": resp.status,
                    "body": json.loads(resp.read() or b"{}")}
        text, err, finish, n = [], None, None, 0
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            payload = json.loads(line[6:])
            assert "dllama" not in payload, "journal field leaked to client"
            if "error" in payload:
                err = payload["error"]
                break
            d = payload["choices"][0]["delta"].get("content")
            f = payload["choices"][0].get("finish_reason")
            if f:
                finish = f
            if d:
                text.append(d)
                n += 1
                if on_delta:
                    on_delta(n)
        return {"status": 200, "text": "".join(text), "error": err,
                "finish": finish}
    finally:
        conn.close()


def _wedge_busy_replica(reps, killed):
    for be, _srv, p in reps:
        with be._plock:
            busy = any(s.req is not None for s in be._slots)
        if busy:
            killed.append(p)
            be.recover_wedged()
            return


@pytest.mark.parametrize("temperature,seed", [(0.0, None), (0.8, 1234)])
def test_midstream_wedge_failover_byte_identical(fleet, temperature, seed):
    """Greedy AND seeded-stochastic streams survive a mid-stream replica
    wedge byte-identically — the client never sees the failover."""
    body = _body(seed=seed, temperature=temperature)
    ref = _stream(fleet["port"], dict(body))
    assert ref["error"] is None and ref["status"] == 200
    killed = []
    got = _stream(fleet["port"], dict(body),
                  on_delta=lambda n: (n == 4 and not killed
                                      and _wedge_busy_replica(fleet["reps"],
                                                              killed)))
    assert killed, "wedge never engaged"
    assert got["error"] is None, got
    assert got["text"] == ref["text"]
    assert got["finish"] == ref["finish"]
    snap = obs_metrics.snapshot()
    assert (snap.get("router_resumed_requests_total") or 0) >= 1
    # the resume admission landed on a replica and reported its prefix work
    assert (snap.get("api_resumed_requests_total") or 0) >= 1


def test_nonstream_failover_identical(fleet):
    """Non-streaming clients ride the same journal (the router streams
    upstream regardless): a wedge mid-generation is invisible."""
    body = _body(seed=77, temperature=0.8, stream=True)
    ref = _stream(fleet["port"], dict(body))
    assert ref["error"] is None
    ns = dict(body)
    ns["stream"] = False
    killed = []
    watcher = threading.Thread(
        target=lambda: [time.sleep(0.002) or _wedge_busy_replica(
            fleet["reps"], killed) for _ in range(5000) if not killed],
        daemon=True)
    watcher.start()
    conn = http.client.HTTPConnection("127.0.0.1", fleet["port"], timeout=120)
    conn.request("POST", "/v1/chat/completions", json.dumps(ns),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    data = json.loads(resp.read())
    conn.close()
    killed.append(-1)  # stop the watcher
    assert data["choices"][0]["message"]["content"] == ref["text"]


def test_deadline_expired_is_408(fleet):
    r = _stream(fleet["port"], _body(seed=1),
                headers={"X-Deadline-Ms": "0"})
    assert r["status"] == 408


def test_deadline_nonfinite_is_400(fleet):
    """NaN/inf pass <=0 checks and blow up int() deep in the failover loop
    (where the blast radius is replica ejections) — reject at ingress."""
    for bad in ("nan", "inf", "-inf"):
        r = _stream(fleet["port"], _body(seed=1),
                    headers={"X-Deadline-Ms": bad})
        assert r["status"] == 400, (bad, r)
    assert len(fleet["router"].router_state.membership.in_rotation()) == 2


def test_client_disconnect_does_not_leak_journal(fleet):
    """The regression behind journal.abandon(): a client that drops its SSE
    socket mid-stream unwinds the router handler through a write error —
    the entry must be reclaimed, not leak until the table fills."""
    journal = fleet["router"].router_state.journal
    conn = http.client.HTTPConnection("127.0.0.1", fleet["port"], timeout=60)
    conn.request("POST", "/v1/chat/completions",
                 json.dumps(_body(seed=55, max_tokens=80)),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    resp.readline()  # at least one byte flowed, then drop the socket
    conn.close()
    deadline = time.monotonic() + 30
    while journal.inflight() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert journal.inflight() == 0


def test_deadline_bounds_generation(fleet):
    """X-Deadline-Ms reaches the replica: a long request finishes with
    reason 'deadline' and partial output instead of running to budget."""
    t0 = time.perf_counter()
    r = _stream(fleet["port"],
                _body(seed=2, temperature=0.0, max_tokens=120),
                headers={"X-Deadline-Ms": "400"})
    dt = time.perf_counter() - t0
    assert r["status"] == 200 or r["status"] == 408
    if r["status"] == 200:
        assert r["finish"] == "deadline" or r["error"] is not None or dt < 5.0


def test_resume_rejects_bad_payload(fleet):
    body = _body(seed=3)
    body["resume"] = {"tokens": ["nope"]}
    r = _stream(fleet["port"], body)
    # the router passes a caller-supplied resume through the plain path and
    # the replica validates it: honest 400, never a stall
    assert r["status"] == 400


def test_resume_at_context_wall_finishes_length(fleet):
    """A resume whose prompt ⊕ delivered tokens exactly fills the context —
    the original run ended at the wall right after its last delivered
    token — must finish 'length' with the re-fed text, not 400; one token
    MORE than the context could ever have generated is the malformed case."""
    from distributed_llama_tpu.tokenizer import ChatItem, ChatTemplate

    be = fleet["reps"][0][0]
    tok = be.tokenizer
    tmpl = ChatTemplate(TemplateType.CHATML, tok.chat_template,
                        tok.eos_piece())
    body = _body(seed=9, temperature=0.8, user="wall")
    prompt = tok.encode(tmpl.generate(
        [ChatItem(m["role"], m["content"]) for m in body["messages"]]),
        add_bos=True)
    room = be.spec.seq_len - len(prompt)
    body["resume"] = {"tokens": [5] * room}
    r = _stream(fleet["port"], body)
    assert r["status"] == 200 and r["error"] is None, r
    assert r["finish"] == "length"
    body["resume"] = {"tokens": [5] * (room + 1)}
    assert _stream(fleet["port"], body)["status"] == 400
