"""Continuous-batching engine tests (8-device CPU mesh via conftest).

The batched scheduler must (a) reproduce the single-sequence Engine's greedy tokens
exactly for every concurrent request, (b) actually give batching's throughput win —
2 concurrent clients > 1.5x one client's token rate (the reference serializes requests,
dllama-api.cpp:418-429, so any ratio > 1 is already beyond parity), and (c) reuse KV
prefixes across requests on the same slot (the NaiveCache generalization).
"""

import os
import time

import pytest

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.sampler import Sampler


def _spec(seq_len=128):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=256, seq_len=seq_len,
                     rope_type=RopeType.LLAMA).resolved()


@pytest.fixture(scope="module")
def setup():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=2, tp=2)
    yield spec, params, be
    be.close()


def test_batched_matches_single_engine(setup):
    spec, params, be = setup
    eng = Engine(spec, params, tp=2)
    prompts = [[1, 7, 23, 5], [1, 9, 2]]
    wants = []
    for p in prompts:
        eng.reset()
        out, _ = eng.generate(list(p), 10, Sampler(spec.vocab_size, temperature=0.0))
        wants.append(out)

    reqs = [be.submit(list(p), 10, Sampler(spec.vocab_size, temperature=0.0))
            for p in prompts]
    outs = [r.wait(timeout=120) for r in reqs]
    assert outs == wants
    for r in reqs:
        assert r.finish == "length"
        assert r.stats.generated_tokens == 10


def test_two_concurrent_share_decode_steps(setup):
    """2 concurrent requests must ride the SAME batched decode dispatches — the whole
    point of continuous batching (the reference serializes, dllama-api.cpp:418-429)
    — and with K-step super-steps each dispatch must cover ~K tokens PER ROW.
    Asserted on the scheduler's own dispatch counter, which is deterministic, rather
    than wall-clock time on a shared CI host (the round-4 flake): 2 x n tokens must
    cost ~n/K batched dispatches, not ~2n serialized single steps. A small slack
    absorbs admission skew and host-sampled boundary tokens."""
    spec, params, be = setup
    n = 24
    k = be.superstep
    sampler = lambda: Sampler(spec.vocab_size, temperature=0.0)

    base = be.decode_steps
    sbase = be.super_steps
    reqs = [be.submit([1, 4, 9 + i], n, sampler()) for i in range(2)]
    for r in reqs:
        out = r.wait(timeout=120)
        assert len(out) == n
    steps = be.decode_steps - base
    # shared K-step dispatches: both rows ride each super-step, so ~n/K
    # dispatches total (NOT 2n single steps; n-1 would be sharing without
    # fusing). Mixed prefill+decode steps cover a few boundary tokens too.
    assert steps <= n // k + 4, (steps, n, k)
    assert be.super_steps > sbase


@pytest.mark.skipif(not os.environ.get("DLT_TIMING_TESTS"),
                    reason="wall-clock throughput assert is flaky on shared CPU "
                           "hosts; set DLT_TIMING_TESTS=1 to run")
def test_two_concurrent_beat_single_throughput(setup):
    """2 concurrent requests must finish in well under 2x one request's time (they
    share each decode step). Target from the round-3 verdict: >1.5x throughput."""
    spec, params, be = setup
    n = 24
    sampler = lambda: Sampler(spec.vocab_size, temperature=0.0)
    prompt = [1, 4, 9]

    be.generate(list(prompt), n, sampler())  # warm every compiled shape
    t0 = time.perf_counter()
    be.generate(list(prompt), n, sampler())
    t_single = time.perf_counter() - t0

    t0 = time.perf_counter()
    reqs = [be.submit([1, 4, 9 + i], n, sampler()) for i in range(2)]
    for r in reqs:
        r.wait(timeout=120)
    t_conc = time.perf_counter() - t0

    throughput_ratio = 2 * t_single / t_conc
    assert throughput_ratio > 1.5, (t_single, t_conc, throughput_ratio)


def test_slot_prefix_reuse(setup):
    spec, params, be = setup
    prompt = [1, 5, 6, 7, 8, 9, 10, 11]
    out1 = be.submit(list(prompt), 4, Sampler(spec.vocab_size, temperature=0.0)).wait(120)
    base = be.prefilled_tokens
    # identical prompt again: everything but the final token should come from the slot
    out2 = be.submit(list(prompt), 4, Sampler(spec.vocab_size, temperature=0.0)).wait(120)
    assert out2 == out1
    assert be.prefilled_tokens - base <= 1


def test_max_tokens_and_stop_check(setup):
    spec, params, be = setup
    sampler = Sampler(spec.vocab_size, temperature=0.0)
    full = be.submit([1, 2, 3], 12, Sampler(spec.vocab_size, temperature=0.0)).wait(120)
    stop_at = full[2]
    req = be.submit([1, 2, 3], 12, sampler, stop_check=lambda t: t == stop_at)
    out = req.wait(120)
    assert out == full[:3]
    assert req.finish == "stop"


def test_context_end_finishes_length():
    spec = _spec(seq_len=16)
    params = init_random_params(spec, FloatType.Q40, seed=3)
    be = BatchEngine(spec, params, slots=2, tp=1)
    try:
        req = be.submit([1, 2, 3, 4], 100, Sampler(spec.vocab_size, temperature=0.0))
        out = req.wait(timeout=120)
        assert req.finish == "length"
        # pos never exceeds seq_len; tokens generated till the cache filled
        assert 0 < len(out) <= 16
    finally:
        be.close()


def test_batched_dp_sharded_matches_single_engine():
    """dp=2 x tp=2: cache rows shard over the dp axis (each dp group an independent
    replica of the tp program) and concurrent requests still reproduce the
    single-engine greedy tokens exactly."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    eng = Engine(spec, params, tp=2)
    prompts = [[1, 7, 23, 5], [1, 9, 2], [1, 4], [1, 30, 31, 32, 33]]
    wants = []
    for p in prompts:
        eng.reset()
        out, _ = eng.generate(list(p), 8, Sampler(spec.vocab_size, temperature=0.0))
        wants.append(out)

    be = BatchEngine(spec, params, slots=4, tp=2, dp=2)
    try:
        reqs = [be.submit(list(p), 8, Sampler(spec.vocab_size, temperature=0.0))
                for p in prompts]
        outs = [r.wait(timeout=180) for r in reqs]
    finally:
        be.close()
    assert outs == wants
