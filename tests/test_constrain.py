"""Grammar-constrained decoding tests (ISSUE 17; docs/SERVING.md
"Constrained decoding").

The constrain/ package lowers JSON Schema / regex / EBNF grammars to one
token-level mask automaton; the batch engine applies the mask before the
sampler on every path (host prefill-boundary sample, masked batched scan,
masked verify) and the GrammarProposer drafts forced-transition chains
with guaranteed accept. Load-bearing properties:

- the automaton's per-state masks match a brute-force oracle (the
  enumerated prefix-closure of the grammar's language) on every reachable
  state, for random finite regexes and JSON schemas;
- constrained output is ALWAYS grammar-valid, and identical to the
  unconstrained stream wherever the grammar permits the unconstrained
  token (greedy: the outputs share a prefix up to the first position the
  grammar actually had to veto);
- batched vs sequential, co-batched vs solo, speculation on vs off
  (±GrammarProposer) are all byte-identical — constraining one row
  leaves a co-batched unconstrained row untouched;
- a masked program shape outside the pinned compile manifest fails the
  gate BY NAME (mask=1 in the cache key), never aliasing the unmasked
  pin.
"""

import itertools
import re

import numpy as np
import pytest

from distributed_llama_tpu.constrain import (CompileError, byte_vocab,
                                             compile_grammar, compile_stats,
                                             grammar_hash)
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.resilience.errors import InvalidRequest
from distributed_llama_tpu.runtime.sampler import Sampler

EOS = 2
VOCAB = byte_vocab(256)

# greedy decode of the seed-11 tiny model enters a repetitive attractor on
# this n-gram-dense prompt, so speculative verify dispatches engage
REP = [7, 31, 5, 102, 9, 31, 5, 77]


# ----------------------------------------------------------------------
# automaton vs brute-force oracle
# ----------------------------------------------------------------------

# finite languages over a tiny alphabet: the oracle ENUMERATES the whole
# language with re.fullmatch and walks the prefix closure
FINITE_PATTERNS = [
    ("[ab]{3}", "ab", 3),
    ("(a|bc)d", "abcd", 3),
    ("a?b?c?", "abc", 3),
    ("(ab|ba){1,2}", "ab", 4),
    ("[a-c]{1,3}", "abcd", 3),
    ("aa|ab|b", "ab", 2),
]


def _language(pattern: str, alphabet: str, max_len: int) -> set[bytes]:
    lang = set()
    rx = re.compile(pattern)
    for n in range(max_len + 1):
        for tup in itertools.product(alphabet, repeat=n):
            s = "".join(tup)
            if rx.fullmatch(s):
                lang.add(s.encode())
    return lang


def _prefixes(lang: set[bytes]) -> set[bytes]:
    out = set()
    for s in lang:
        for i in range(len(s) + 1):
            out.add(s[:i])
    return out


def _oracle_check(aut, lang: set[bytes], alphabet: str):
    """Walk every prefix of the language through the automaton and compare
    its mask against the enumerated ground truth: byte b is allowed at
    prefix p iff p+b is still a prefix of some word, EOS iff p is a word."""
    assert lang, "vacuous oracle: empty language"
    prefixes = _prefixes(lang)
    probe = sorted({ord(c) for c in alphabet} | {0x7A, 0x30})  # + 'z','0'
    for p in sorted(prefixes):
        st = 0
        for b in p:
            st = aut.advance(st, b)
            assert st >= 0, f"automaton rejects live prefix {p!r} at {b}"
        mask = aut.mask_bool(st)
        for b in probe:
            want = p + bytes([b]) in prefixes
            assert bool(mask[b]) == want, \
                f"prefix {p!r}: byte {b:#x} allowed={bool(mask[b])} want={want}"
        assert bool(mask[EOS]) == (p in lang), \
            f"prefix {p!r}: EOS allowed={bool(mask[EOS])} want={p in lang}"
        # packed-bitmask row agrees with the delta row it was packed from
        vi = np.arange(aut.vocab_size)
        unpacked = (aut.mask[st][vi >> 5] >> (vi & 31)) & 1
        np.testing.assert_array_equal(unpacked.astype(bool), mask)


@pytest.mark.parametrize("pattern,alphabet,max_len", FINITE_PATTERNS)
def test_regex_mask_matches_bruteforce_oracle(pattern, alphabet, max_len):
    aut, _ = compile_grammar("regex", pattern, VOCAB, eos_id=EOS)
    _oracle_check(aut, _language(pattern, alphabet, max_len), alphabet)


def test_random_regexes_match_oracle():
    """Seeded random finite regexes (literals, classes, bounded reps,
    alternation) against the same enumeration oracle."""
    rng = np.random.default_rng(17)
    for _ in range(12):
        parts = []
        for _ in range(int(rng.integers(1, 4))):
            kind = int(rng.integers(0, 3))
            if kind == 0:
                parts.append("".join(rng.choice(list("abc"),
                                                int(rng.integers(1, 3)))))
            elif kind == 1:
                parts.append("[ab]{%d}" % int(rng.integers(1, 3)))
            else:
                parts.append("(a|b)" + ("?" if rng.integers(0, 2) else ""))
        pattern = "".join(parts)
        aut, _ = compile_grammar("regex", pattern, VOCAB, eos_id=EOS)
        lang = _language(pattern, "abc", 7)
        _oracle_check(aut, lang, "abc")


def test_schema_automaton_language_exact():
    """The enum/bool schema's language is EXACTLY its four canonical
    serializations — nothing else up to the longest word's length."""
    schema = {"type": "object", "properties": {
        "name": {"enum": ["alpha", "beta"]},
        "ok": {"type": "boolean"}}}
    aut, _ = compile_grammar("json_schema", schema, VOCAB, eos_id=EOS)
    words = {b'{"name":"%s","ok":%s}' % (n, o)
             for n in (b"alpha", b"beta") for o in (b"true", b"false")}
    for w in words:
        ok, complete = aut.validate(list(w) + [EOS])
        assert ok and complete, w
    # exhaustive rejection up to max length over the words' own byte set:
    # every accepted string must be one of the four words
    prefixes = _prefixes(words)
    frontier = [(0, b"")]
    seen_words = set()
    while frontier:
        st, p = frontier.pop()
        mask = aut.mask_bool(st)
        if mask[EOS]:
            seen_words.add(p)
        for b in np.flatnonzero(mask):
            if b == EOS:
                continue
            q = p + bytes([int(b)])
            assert q in prefixes, f"automaton admits rogue prefix {q!r}"
            frontier.append((aut.advance(st, int(b)), q))
    assert seen_words == words


def test_ebnf_and_cache_and_errors():
    aut, gh = compile_grammar("grammar", 'root ::= "a" "b" | "c"', VOCAB,
                              eos_id=EOS)
    assert aut.validate(list(b"ab") + [EOS]) == (True, True)
    assert aut.validate(list(b"c") + [EOS]) == (True, True)
    assert aut.validate(list(b"x"))[0] is False
    # LRU cache: the same grammar compiles once
    h0 = compile_stats()["hits"]
    aut2, gh2 = compile_grammar("grammar", 'root ::= "a" "b" | "c"', VOCAB,
                                eos_id=EOS)
    assert gh2 == gh and aut2 is aut
    assert compile_stats()["hits"] == h0 + 1
    assert grammar_hash("grammar", 'root ::= "a" "b" | "c"') == gh
    with pytest.raises(CompileError):
        compile_grammar("regex", "[unclosed", VOCAB, eos_id=EOS)
    with pytest.raises(CompileError):
        compile_grammar("json_schema", {"type": "float64"}, VOCAB, eos_id=EOS)


def test_forced_chain_is_the_singleton_spine():
    """forced_chain walks exactly the singleton-mask states — every drafted
    token is the ONLY allowed token at its state (guaranteed accept)."""
    aut, _ = compile_grammar("regex", "abc(x|y)", VOCAB, eos_id=EOS)
    chain = aut.forced_chain(0, 8)
    assert bytes(chain) == b"abc"  # stops at the branch
    st = 0
    for t in chain:
        mask = aut.mask_bool(st)
        assert int(mask.sum()) == 1 and mask[t]
        st = aut.advance(st, t)


# ----------------------------------------------------------------------
# engine: masked decode/verify identity + validity
# ----------------------------------------------------------------------

K = 8


def _spec():
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=256, rope_type=RopeType.LLAMA).resolved()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


def _stoch(spec, seed=7):
    return Sampler(spec.vocab_size, temperature=0.8, topp=0.9, seed=seed)


@pytest.fixture(scope="module")
def setup():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4, speculative=K)
    yield spec, params, be
    be.close()


def _grammar():
    schema = {"type": "object", "properties": {
        "name": {"enum": ["alpha", "beta"]},
        "ok": {"type": "boolean"}}}
    return compile_grammar("json_schema", schema, VOCAB, eos_id=EOS)


def _branchy():
    # no singleton-mask states after position 0 -> the GrammarProposer
    # never drafts, so constrained rows ride the masked SCAN path
    return compile_grammar("regex", "[a-z]{24}", VOCAB, eos_id=EOS)


def _valid(aut, out):
    """Grammar-validity of an engine output: the stream up to the first
    EOS must be accepted; EOS then repeats (the done-state self-loop)."""
    if EOS in out:
        i = out.index(EOS)
        assert set(out[i:]) == {EOS}, "post-EOS tokens escaped the mask"
        ok, complete = aut.validate(out[: i + 1])
        assert ok and complete, bytes(out[:i])
    else:
        ok, _ = aut.validate(out)
        assert ok, bytes(out)


def test_greedy_constrained_valid_and_minimal_intervention(setup):
    """Constrained greedy output is grammar-valid, and agrees with the
    unconstrained stream up to the FIRST position where the grammar
    actually vetoed the unconstrained argmax — masking never rewrites a
    token the grammar permits."""
    spec, _, be = setup
    aut, gh = _grammar()
    prompt = [1, 5, 9]
    plain = be.submit(list(prompt), 28, _greedy(spec)).wait(timeout=300)
    cons = be.submit(list(prompt), 28, _greedy(spec), constraint=aut,
                     constraint_hash=gh).wait(timeout=300)
    _valid(aut, cons)
    st = 0
    for i, (c, u) in enumerate(zip(cons, plain)):
        if c != u:
            assert not aut.allows(st, u), (
                f"step {i}: grammar permits unconstrained token {u} "
                f"but masking replaced it with {c}")
            break
        st = aut.advance(st, c)
        if c == EOS:
            break


def test_stochastic_constrained_valid_and_deterministic(setup):
    spec, _, be = setup
    aut, gh = _grammar()
    prompt = [1, 5, 9]
    outs = [be.submit(list(prompt), 28, _stoch(spec, seed=23),
                      constraint=aut, constraint_hash=gh).wait(timeout=300)
            for _ in range(2)]
    _valid(aut, outs[0])
    assert outs[0] == outs[1], "seeded constrained decode is not reproducible"


def test_cobatched_rows_are_isolated(setup):
    """One constrained + one unconstrained row in the same super-steps:
    the unconstrained row is byte-identical to its solo run (a masked
    program with the universal row-0 state is a no-op), and the
    constrained row is byte-identical to ITS solo run."""
    spec, _, be = setup
    aut, gh = _grammar()
    solo_plain = be.submit(list(REP), 24, _greedy(spec)).wait(timeout=300)
    solo_cons = be.submit([1, 5, 9], 24, _greedy(spec), constraint=aut,
                          constraint_hash=gh).wait(timeout=300)
    rc = be.submit([1, 5, 9], 24, _greedy(spec), constraint=aut,
                   constraint_hash=gh)
    rp = be.submit(list(REP), 24, _greedy(spec))
    assert rc.wait(timeout=300) == solo_cons
    assert rp.wait(timeout=300) == solo_plain


def _drafted(label: str) -> float:
    from distributed_llama_tpu.obs import metrics
    snap = metrics.REGISTRY.snapshot()
    counts = snap.get("batch_spec_proposer_drafted_total", {})
    if not isinstance(counts, dict):
        return 0.0
    return sum(v for k, v in counts.items() if label in k)


def test_speculation_on_off_identity_with_grammar_proposer(setup):
    """±GrammarProposer: speculation off vs on (grammar drafting forced
    chains through the masked verify path) is byte-identical, greedy and
    seeded-stochastic, and the grammar proposer actually drafted."""
    spec, _, be = setup
    aut, gh = _grammar()

    def jobs():
        # fresh samplers each run: the engine advances the host xorshift
        # stream per delivered token, so a Sampler is single-use state
        return [([1, 5, 9], _greedy(spec)), ([1, 5, 9], _stoch(spec, seed=31))]

    k = be.spec_k
    try:
        be.spec_k = 0
        off = [be.submit(list(p), 26, s, constraint=aut,
                         constraint_hash=gh).wait(timeout=300)
               for p, s in jobs()]
    finally:
        be.spec_k = k
    d0 = _drafted("grammar")
    on = [be.submit(list(p), 26, s, constraint=aut,
                    constraint_hash=gh).wait(timeout=300)
          for p, s in jobs()]
    assert on == off, "grammar-proposed verify diverged from plain decode"
    assert _drafted("grammar") > d0, \
        "vacuous: the grammar proposer never drafted"
    for out in on:
        _valid(aut, out)


def test_branchy_grammar_rides_masked_scan(setup):
    """A grammar with no forced chains is served by the masked SCAN
    program (GrammarProposer abstains); output is valid and deterministic,
    and degrade never fired."""
    spec, _, be = setup
    aut, gh = _branchy()
    deg0 = be.constrain_degraded
    outs = [be.submit([1, 9], 30, _greedy(spec), constraint=aut,
                      constraint_hash=gh).wait(timeout=300)
            for _ in range(2)]
    assert outs[0] == outs[1]
    _valid(aut, outs[0])
    assert be.constrain_degraded == deg0
    stats = be.constrain_stats()
    assert stats["active_rows"] == 0, "constraint table leaked a region"


def test_grammar_too_large_is_an_honest_reject(setup):
    """An automaton that cannot fit the constraint table is refused at
    submit (client-visible InvalidRequest), never silently degraded."""
    spec, params, _ = setup
    aut, gh = _grammar()
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                     constrain_states=4)
    try:
        with pytest.raises(InvalidRequest):
            be.submit([1, 5, 9], 8, _greedy(spec), constraint=aut,
                      constraint_hash=gh)
        # the engine still serves unconstrained work afterwards
        out = be.submit([1, 5, 9], 8, _greedy(spec)).wait(timeout=300)
        assert len(out) == 8
    finally:
        be.close()


# ----------------------------------------------------------------------
# compile-manifest: masked buckets are pinned, rogues named
# ----------------------------------------------------------------------

def test_constrain_off_manifest_masked_bucket_fails_gate():
    """ISSUE 17 satellite: the mask flag is part of the program cache key —
    a masked verify T bucket outside the pinned set must fail the gate BY
    NAME (mask=1 in the key), never alias onto the unmasked pin. The
    factory call alone records the build (jit traces lazily)."""
    from distributed_llama_tpu.analysis import compile_audit
    from distributed_llama_tpu.parallel.mesh import make_mesh
    from distributed_llama_tpu.runtime import device_loop

    pinned = compile_audit.load_manifest()
    assert pinned is not None, "perf/compile_manifest.json missing"
    assert any(",mask=1]" in k for k in pinned["programs"]), \
        "manifest lost its masked program pins"
    spec = compile_audit.scenario_spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    audit = compile_audit.CompileAudit()
    with audit:
        device_loop.make_batched_verify_loop(
            spec, make_mesh(tp=1), params, 9, mode="greedy",
            attn_window=None, kv_block_tokens=16, masked=True)
    findings = compile_audit.diff_manifest(audit.manifest(), pinned)
    assert findings, "gate missed the rogue masked T bucket"
    key = "verify[t=9,mode=greedy,window=None,paged=16,mask=1]"
    assert any(key in f.message for f in findings), \
        [f.message for f in findings]
    assert all(f.rule == "compile-manifest" for f in findings)
