"""Golden block tests ported from the REFERENCE's own test suite.

Round 1 validated numerics against an independently written numpy oracle — good, but
self-referential (both sides share one author's reading of the reference). These tests
anchor to the reference's *recorded outputs* instead:

- Llama: the 4096-float golden table from /root/reference/src/llama2-tasks-test.cpp:12-525
  (extracted verbatim into tests/data/llama2_block_golden.npy), produced by a 1-layer
  dim-4096 block forward over xorshift*-seeded F32 weights (state 800000010, each draw
  / 120.0; llama2-tasks-test.cpp:527-608).
- Grok-1: the spot windows at [0:4), [256:260), [5012:5016) from
  /root/reference/src/grok1-tasks-test.cpp:13-15 (1-layer dim-6144 8-expert MoE block,
  state 123456789, draws / 100.0, input additionally / 78.38367176906169f).

Weight streams are regenerated bit-exactly with the native xorshift* port
(native.xorshift_f32_fill). Stream order follows the reference tests' fill order, which
for Llama is rms vectors FIRST then matmul weights (the test writes rmsData before
mmData from one stream, llama2-tasks-test.cpp:561-566), while Grok fills the block
region sequentially in .m tensor order (wq,wk,wv,wo,router,[up,gate,down]xE,norms;
transformer.cpp:498-523).

Both reference tests run at pos=0 with the final-norm/logits tasks skipped, so these
call the per-layer block function directly. Tolerances are the reference's own
(1e-5 / 3.5e-5, "Optimization may cause some differences").
"""

import functools
import os

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_tpu import native
from distributed_llama_tpu.models.forward import _block
from distributed_llama_tpu.models.spec import ArchType, HiddenAct, ModelSpec, RopeType
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import FloatType, QTensor

DATA = os.path.join(os.path.dirname(__file__), "data")

needs_native = pytest.mark.skipif(
    not native.available(),
    reason="native xorshift stream unavailable (sequential 200M-draw stream)")


class Stream:
    """Sequential view over the reference tests' single xorshift* draw stream."""

    def __init__(self, state: int, div: float):
        self.state = state
        self.div = div

    def take(self, *shape) -> np.ndarray:
        n = int(np.prod(shape))
        vals, self.state = native.xorshift_f32_fill(self.state, n, self.div)
        return vals.reshape(shape)


def run_block(spec: ModelSpec, bp: dict, x: np.ndarray) -> np.ndarray:
    rope = RopeTables.create(spec)
    kc = jnp.zeros((1, 1, spec.n_kv_heads, spec.seq_len, spec.head_size), jnp.float32)
    vc = jnp.zeros_like(kc)
    block = functools.partial(
        _block, spec=spec, rope=rope, start_pos=jnp.int32(0),
        positions=jnp.zeros((1,), jnp.int32), axis_name=None, sp_axis_name=None,
        sp_size=1, use_pallas=False, compress=False, window=None)
    bp = {k: (v if isinstance(v, QTensor) else jnp.asarray(v)) for k, v in bp.items()}
    (x_out, _, _), _ = block((jnp.asarray(x)[None, None, :], kc, vc),
                             (bp, jnp.int32(0)))
    return np.asarray(x_out)[0, 0]


@needs_native
def test_llama_block_matches_reference_golden():
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=4096, hidden_dim=11008, n_layers=1,
                     n_heads=32, n_kv_heads=32, vocab_size=32000, seq_len=2048,
                     rope_type=RopeType.LLAMA, rope_theta=10000.0).resolved()
    s = Stream(800000010, 120.0)
    bp = {}
    # the reference test fills the trailing rms region first, then the matmul region
    # (llama2-tasks-test.cpp:561-566), so the draw order is norms -> weights
    bp["rms_att"] = s.take(spec.dim)
    bp["rms_ffn"] = s.take(spec.dim)
    for name, out_dim, in_dim in (
            ("wq", spec.dim, spec.dim), ("wk", spec.kv_dim, spec.dim),
            ("wv", spec.kv_dim, spec.dim), ("wo", spec.dim, spec.dim),
            ("w1", spec.hidden_dim, spec.dim), ("w2", spec.dim, spec.hidden_dim),
            ("w3", spec.hidden_dim, spec.dim)):
        bp[name] = QTensor.from_float(s.take(out_dim, in_dim), FloatType.F32)
    x = s.take(spec.dim)

    got = run_block(spec, bp, x)
    want = np.load(os.path.join(DATA, "llama2_block_golden.npy"))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=0)


# grok1-tasks-test.cpp:13-15 — the reference's recorded spot windows
GROK_GOLDEN = {
    0: [0.00940248929, 0.0191232786, 0.0147766126, 0.0102868658],
    256: [0.0191071425, 0.0134582901, 0.0146755828, 0.019181719],
    5012: [0.0126675405, 0.0169415697, 0.0183475353, 0.0182626117],
}


@needs_native
def test_grok1_block_matches_reference_golden():
    spec = ModelSpec(arch_type=ArchType.GROK1, dim=6144, hidden_dim=1024, n_layers=1,
                     n_heads=48, n_kv_heads=8, vocab_size=1024, seq_len=8192,
                     n_experts=8, n_active_experts=2, hidden_act=HiddenAct.GELU,
                     rope_type=RopeType.FALCON, rope_theta=10000.0).resolved()
    s = Stream(123456789, 100.0)
    bp = {}
    bp["wq"] = QTensor.from_float(s.take(spec.dim, spec.dim), FloatType.F32)
    bp["wk"] = QTensor.from_float(s.take(spec.kv_dim, spec.dim), FloatType.F32)
    bp["wv"] = QTensor.from_float(s.take(spec.kv_dim, spec.dim), FloatType.F32)
    bp["wo"] = QTensor.from_float(s.take(spec.dim, spec.dim), FloatType.F32)
    bp["router"] = QTensor.from_float(s.take(spec.n_experts, spec.dim), FloatType.F32)
    ups, gates, downs = [], [], []
    for _ in range(spec.n_experts):
        ups.append(s.take(spec.hidden_dim, spec.dim))
        gates.append(s.take(spec.hidden_dim, spec.dim))
        downs.append(s.take(spec.dim, spec.hidden_dim))
    bp["moe_up"] = QTensor.from_float(np.stack(ups), FloatType.F32)
    bp["moe_gate"] = QTensor.from_float(np.stack(gates), FloatType.F32)
    bp["moe_down"] = QTensor.from_float(np.stack(downs), FloatType.F32)
    bp["rms_att"] = s.take(spec.dim)
    bp["rms_ffn"] = s.take(spec.dim)
    bp["rms_moe"] = s.take(spec.dim)
    bp["rms_ffn2"] = s.take(spec.dim)
    # the reference test divides x by the embedding scale, which grokMulInput then
    # multiplies back (grok1-tasks-test.cpp:73); net block input is the raw /100 draw —
    # _block runs post-embedding-scale, so feed the raw draws directly. The /78.38f
    # round trip is f32-exact to well below the 3.5e-5 tolerance.
    x = s.take(spec.dim)

    got = run_block(spec, bp, x)
    for off, want in GROK_GOLDEN.items():
        np.testing.assert_allclose(got[off:off + 4], np.asarray(want, np.float32),
                                   atol=3.5e-5, rtol=0)
