"""Observability subsystem unit tests (obs/trace.py + obs/metrics.py):
Prometheus exposition golden, histogram bucket boundaries, concurrent-writer
stress, Chrome-trace schema + span nesting."""

import json
import threading

from distributed_llama_tpu.obs.metrics import (
    DEFAULT_TIME_BUCKETS, Registry, log_buckets)
from distributed_llama_tpu.obs.trace import Tracer
from distributed_llama_tpu.obs import trace as trace_mod


# ----------------------------------------------------------------------
# metrics
# ----------------------------------------------------------------------

def test_prometheus_exposition_golden():
    """Exact text-format golden: HELP/TYPE lines, label rendering, histogram
    bucket/sum/count suffixes, +Inf, trailing newline. Pinned so any format
    drift is a conscious change (Prometheus parsers are strict)."""
    reg = Registry()
    c = reg.counter("dlt_tokens_total", "Tokens served")
    c.inc(3)
    g = reg.gauge("dlt_slots", "Slot state", labelnames=("state",))
    g.labels(state="used").set(2)
    g.labels(state="free").set(6)
    h = reg.histogram("dlt_wait_seconds", "Queue wait", buckets=(0.01, 0.1, 1))
    h.observe(0.05)
    h.observe(0.05)
    h.observe(5.0)  # overflow -> +Inf only
    expected = (
        "# HELP dlt_slots Slot state\n"
        "# TYPE dlt_slots gauge\n"
        'dlt_slots{state="free"} 6\n'
        'dlt_slots{state="used"} 2\n'
        "# HELP dlt_tokens_total Tokens served\n"
        "# TYPE dlt_tokens_total counter\n"
        "dlt_tokens_total 3\n"
        "# HELP dlt_wait_seconds Queue wait\n"
        "# TYPE dlt_wait_seconds histogram\n"
        'dlt_wait_seconds_bucket{le="0.01"} 0\n'
        'dlt_wait_seconds_bucket{le="0.1"} 2\n'
        'dlt_wait_seconds_bucket{le="1"} 2\n'
        'dlt_wait_seconds_bucket{le="+Inf"} 3\n'
        "dlt_wait_seconds_sum 5.1\n"
        "dlt_wait_seconds_count 3\n"
    )
    assert reg.render() == expected


def test_histogram_bucket_boundaries():
    """A value exactly on a bucket bound lands IN that bucket (Prometheus
    `le` semantics: cumulative count of observations <= bound)."""
    reg = Registry()
    h = reg.histogram("b_seconds", "x", buckets=(1.0, 10.0))
    h.observe(1.0)   # == first bound -> le="1" bucket
    h.observe(1.0001)  # just past -> le="10" only
    h.observe(10.0)  # == second bound
    h.observe(11.0)  # overflow
    snap = h.snapshot()
    assert snap["buckets"] == {"1": 1, "10": 2}
    assert snap["overflow"] == 1
    assert snap["count"] == 4
    text = h.render()
    assert 'b_seconds_bucket{le="1"} 1' in text
    assert 'b_seconds_bucket{le="10"} 3' in text  # cumulative
    assert 'b_seconds_bucket{le="+Inf"} 4' in text


def test_log_buckets_shape():
    """Fixed log-scale layout: exact decade anchors, monotone, covers hi."""
    b = log_buckets(1e-3, 10.0, per_decade=4)
    assert b[0] == 1e-3 and b[-1] >= 10.0
    assert all(x < y for x, y in zip(b, b[1:]))
    for anchor in (1e-3, 1e-2, 1e-1, 1.0, 10.0):
        assert anchor in b
    # the default latency buckets span 100 µs .. 100 s
    assert DEFAULT_TIME_BUCKETS[0] == 1e-4 and DEFAULT_TIME_BUCKETS[-1] == 100


def test_labels_idempotent_and_isolated():
    reg = Registry()
    c = reg.counter("r_total", "x", labelnames=("route",))
    c.labels(route="/a").inc()
    c.labels(route="/a").inc()
    c.labels(route="/b").inc(5)
    assert c.labels(route="/a").value == 2
    assert c.labels(route="/b").value == 5
    # get-or-create returns the same family
    assert reg.counter("r_total", "x", labelnames=("route",)) is c


def test_concurrent_writers_metrics():
    """8 threads hammering one counter + one histogram: no lost updates, no
    torn histogram state (count == sum of bucket counts incl. overflow)."""
    reg = Registry()
    c = reg.counter("stress_total", "x")
    h = reg.histogram("stress_seconds", "x", buckets=(0.5,))
    N, T = 2000, 8

    def work(i):
        for j in range(N):
            c.inc()
            h.observe(0.25 if (i + j) % 2 else 0.75)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == N * T
    snap = h.snapshot()
    assert snap["count"] == N * T
    assert snap["buckets"]["0.5"] + snap["overflow"] == N * T
    assert abs(snap["sum"] - N * T * 0.5) < 1e-6


# ----------------------------------------------------------------------
# trace
# ----------------------------------------------------------------------

def test_chrome_trace_schema_and_nesting():
    """Exported JSON is Chrome trace-event format: every span is a complete
    ("X") event with µs ts/dur, and a child span's interval nests strictly
    inside its parent's."""
    tr = Tracer(capacity=128)
    with tr.span("parent", {"req": 1}):
        with tr.span("child_a"):
            pass
        with tr.span("child_b"):
            pass
    doc = json.loads(json.dumps(tr.to_chrome_trace()))  # round-trips json
    evs = doc["traceEvents"]
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(spans) == {"parent", "child_a", "child_b"}
    for e in spans.values():
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["dur"] >= 0
    p, a, b = spans["parent"], spans["child_a"], spans["child_b"]
    assert p["args"] == {"req": 1}
    # nesting: children inside the parent, in order
    assert p["ts"] <= a["ts"] and a["ts"] + a["dur"] <= p["ts"] + p["dur"]
    assert p["ts"] <= b["ts"] and b["ts"] + b["dur"] <= p["ts"] + p["dur"]
    assert a["ts"] + a["dur"] <= b["ts"]
    # thread metadata present for the emitting thread
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in evs)


def test_trace_ring_buffer_bounded():
    tr = Tracer(capacity=10)
    for i in range(25):
        with tr.span(f"s{i}"):
            pass
    evs = [e for e in tr.events() if e["ph"] == "X"]
    assert len(evs) == 10
    assert evs[0]["name"] == "s15" and evs[-1]["name"] == "s24"  # oldest dropped
    assert tr.dropped_events == 15
    assert tr.to_chrome_trace()["otherData"]["dropped_events"] == 15


def test_disabled_tracer_is_noop():
    """Module-level span() with no tracer installed returns the shared no-op
    and records nothing once one IS installed later."""
    trace_mod.uninstall()
    s1 = trace_mod.span("x")
    s2 = trace_mod.span("y", {"a": 1})
    assert s1 is s2  # the shared singleton: no per-call allocation
    with s1:
        pass
    tr = trace_mod.install(capacity=8)
    try:
        with trace_mod.span("real"):
            pass
        assert [e["name"] for e in tr.events() if e["ph"] == "X"] == ["real"]
    finally:
        trace_mod.uninstall()


def test_concurrent_writer_spans():
    """Spans from many threads interleave without loss (buffer big enough)
    and each carries its own thread id."""
    tr = Tracer(capacity=10000)
    N, T = 200, 8

    def work(i):
        for j in range(N):
            with tr.span(f"t{i}"):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = [e for e in tr.events() if e["ph"] == "X"]
    assert len(evs) == N * T
    by_thread = {}
    for e in evs:
        by_thread.setdefault(e["name"], set()).add(e["tid"])
    assert len(by_thread) == T
    for tids in by_thread.values():
        assert len(tids) == 1  # each logical thread kept one tid

    doc = tr.to_chrome_trace()
    json.loads(json.dumps(doc))  # schema survives a full round-trip
    # one thread_name metadata event per DISTINCT tid seen (the OS may reuse
    # idents of already-joined threads, so distinct tids can be < T)
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(meta) == len({e["tid"] for e in evs})


def test_instant_events():
    tr = Tracer(capacity=8)
    tr.instant("marker", {"k": "v"})
    evs = tr.events()
    inst = [e for e in evs if e["ph"] == "i"]
    assert len(inst) == 1 and inst[0]["name"] == "marker"
    assert inst[0]["args"] == {"k": "v"}
