"""Observability subsystem unit tests (obs/trace.py + obs/metrics.py +
obs/reqctx.py + obs/flight.py): Prometheus exposition golden, histogram
bucket boundaries, concurrent-writer stress, Chrome-trace schema + span
nesting, W3C traceparent round-trips, trace-id stamping, tracer
replace-mid-span, flight-recorder ring bounds + concurrency, and the
multi-process Chrome-trace merge."""

import json
import threading

from distributed_llama_tpu.obs import flight as flight_mod
from distributed_llama_tpu.obs import reqctx
from distributed_llama_tpu.obs import trace as trace_mod
from distributed_llama_tpu.obs.flight import FlightRecorder
from distributed_llama_tpu.obs.metrics import (
    DEFAULT_TIME_BUCKETS, Registry, log_buckets)
from distributed_llama_tpu.obs.trace import Tracer, merge_chrome_traces


# ----------------------------------------------------------------------
# metrics
# ----------------------------------------------------------------------

def test_prometheus_exposition_golden():
    """Exact text-format golden: HELP/TYPE lines, label rendering, histogram
    bucket/sum/count suffixes, +Inf, trailing newline. Pinned so any format
    drift is a conscious change (Prometheus parsers are strict)."""
    reg = Registry()
    c = reg.counter("dlt_tokens_total", "Tokens served")
    c.inc(3)
    g = reg.gauge("dlt_slots", "Slot state", labelnames=("state",))
    g.labels(state="used").set(2)
    g.labels(state="free").set(6)
    h = reg.histogram("dlt_wait_seconds", "Queue wait", buckets=(0.01, 0.1, 1))
    h.observe(0.05)
    h.observe(0.05)
    h.observe(5.0)  # overflow -> +Inf only
    expected = (
        "# HELP dlt_slots Slot state\n"
        "# TYPE dlt_slots gauge\n"
        'dlt_slots{state="free"} 6\n'
        'dlt_slots{state="used"} 2\n'
        "# HELP dlt_tokens_total Tokens served\n"
        "# TYPE dlt_tokens_total counter\n"
        "dlt_tokens_total 3\n"
        "# HELP dlt_wait_seconds Queue wait\n"
        "# TYPE dlt_wait_seconds histogram\n"
        'dlt_wait_seconds_bucket{le="0.01"} 0\n'
        'dlt_wait_seconds_bucket{le="0.1"} 2\n'
        'dlt_wait_seconds_bucket{le="1"} 2\n'
        'dlt_wait_seconds_bucket{le="+Inf"} 3\n'
        "dlt_wait_seconds_sum 5.1\n"
        "dlt_wait_seconds_count 3\n"
    )
    assert reg.render() == expected


def test_histogram_bucket_boundaries():
    """A value exactly on a bucket bound lands IN that bucket (Prometheus
    `le` semantics: cumulative count of observations <= bound)."""
    reg = Registry()
    h = reg.histogram("b_seconds", "x", buckets=(1.0, 10.0))
    h.observe(1.0)   # == first bound -> le="1" bucket
    h.observe(1.0001)  # just past -> le="10" only
    h.observe(10.0)  # == second bound
    h.observe(11.0)  # overflow
    snap = h.snapshot()
    assert snap["buckets"] == {"1": 1, "10": 2}
    assert snap["overflow"] == 1
    assert snap["count"] == 4
    text = h.render()
    assert 'b_seconds_bucket{le="1"} 1' in text
    assert 'b_seconds_bucket{le="10"} 3' in text  # cumulative
    assert 'b_seconds_bucket{le="+Inf"} 4' in text


def test_log_buckets_shape():
    """Fixed log-scale layout: exact decade anchors, monotone, covers hi."""
    b = log_buckets(1e-3, 10.0, per_decade=4)
    assert b[0] == 1e-3 and b[-1] >= 10.0
    assert all(x < y for x, y in zip(b, b[1:]))
    for anchor in (1e-3, 1e-2, 1e-1, 1.0, 10.0):
        assert anchor in b
    # the default latency buckets span 100 µs .. 100 s
    assert DEFAULT_TIME_BUCKETS[0] == 1e-4 and DEFAULT_TIME_BUCKETS[-1] == 100


def test_labels_idempotent_and_isolated():
    reg = Registry()
    c = reg.counter("r_total", "x", labelnames=("route",))
    c.labels(route="/a").inc()
    c.labels(route="/a").inc()
    c.labels(route="/b").inc(5)
    assert c.labels(route="/a").value == 2
    assert c.labels(route="/b").value == 5
    # get-or-create returns the same family
    assert reg.counter("r_total", "x", labelnames=("route",)) is c


def test_concurrent_writers_metrics():
    """8 threads hammering one counter + one histogram: no lost updates, no
    torn histogram state (count == sum of bucket counts incl. overflow)."""
    reg = Registry()
    c = reg.counter("stress_total", "x")
    h = reg.histogram("stress_seconds", "x", buckets=(0.5,))
    N, T = 2000, 8

    def work(i):
        for j in range(N):
            c.inc()
            h.observe(0.25 if (i + j) % 2 else 0.75)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == N * T
    snap = h.snapshot()
    assert snap["count"] == N * T
    assert snap["buckets"]["0.5"] + snap["overflow"] == N * T
    assert abs(snap["sum"] - N * T * 0.5) < 1e-6


# ----------------------------------------------------------------------
# trace
# ----------------------------------------------------------------------

def test_chrome_trace_schema_and_nesting():
    """Exported JSON is Chrome trace-event format: every span is a complete
    ("X") event with µs ts/dur, and a child span's interval nests strictly
    inside its parent's."""
    tr = Tracer(capacity=128)
    with tr.span("parent", {"req": 1}):
        with tr.span("child_a"):
            pass
        with tr.span("child_b"):
            pass
    doc = json.loads(json.dumps(tr.to_chrome_trace()))  # round-trips json
    evs = doc["traceEvents"]
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(spans) == {"parent", "child_a", "child_b"}
    for e in spans.values():
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["dur"] >= 0
    p, a, b = spans["parent"], spans["child_a"], spans["child_b"]
    assert p["args"] == {"req": 1}
    # nesting: children inside the parent, in order
    assert p["ts"] <= a["ts"] and a["ts"] + a["dur"] <= p["ts"] + p["dur"]
    assert p["ts"] <= b["ts"] and b["ts"] + b["dur"] <= p["ts"] + p["dur"]
    assert a["ts"] + a["dur"] <= b["ts"]
    # thread metadata present for the emitting thread
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in evs)


def test_trace_ring_buffer_bounded():
    tr = Tracer(capacity=10)
    for i in range(25):
        with tr.span(f"s{i}"):
            pass
    evs = [e for e in tr.events() if e["ph"] == "X"]
    assert len(evs) == 10
    assert evs[0]["name"] == "s15" and evs[-1]["name"] == "s24"  # oldest dropped
    assert tr.dropped_events == 15
    assert tr.to_chrome_trace()["otherData"]["dropped_events"] == 15


def test_disabled_tracer_is_noop():
    """Module-level span() with no tracer installed returns the shared no-op
    and records nothing once one IS installed later."""
    trace_mod.uninstall()
    s1 = trace_mod.span("x")
    s2 = trace_mod.span("y", {"a": 1})
    assert s1 is s2  # the shared singleton: no per-call allocation
    with s1:
        pass
    tr = trace_mod.install(capacity=8)
    try:
        with trace_mod.span("real"):
            pass
        assert [e["name"] for e in tr.events() if e["ph"] == "X"] == ["real"]
    finally:
        trace_mod.uninstall()


def test_concurrent_writer_spans():
    """Spans from many threads interleave without loss (buffer big enough)
    and each carries its own thread id."""
    tr = Tracer(capacity=10000)
    N, T = 200, 8

    def work(i):
        for j in range(N):
            with tr.span(f"t{i}"):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = [e for e in tr.events() if e["ph"] == "X"]
    assert len(evs) == N * T
    by_thread = {}
    for e in evs:
        by_thread.setdefault(e["name"], set()).add(e["tid"])
    assert len(by_thread) == T
    for tids in by_thread.values():
        assert len(tids) == 1  # each logical thread kept one tid

    doc = tr.to_chrome_trace()
    json.loads(json.dumps(doc))  # schema survives a full round-trip
    # one thread_name metadata event per DISTINCT tid seen (the OS may reuse
    # idents of already-joined threads, so distinct tids can be < T)
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(meta) == len({e["tid"] for e in evs})


def test_instant_events():
    tr = Tracer(capacity=8)
    tr.instant("marker", {"k": "v"})
    evs = tr.events()
    inst = [e for e in evs if e["ph"] == "i"]
    assert len(inst) == 1 and inst[0]["name"] == "marker"
    assert inst[0]["args"] == {"k": "v"}


# ----------------------------------------------------------------------
# reqctx: W3C trace-context
# ----------------------------------------------------------------------

def test_traceparent_roundtrip():
    ctx = reqctx.new_context("req-1")
    hdr = ctx.to_traceparent()
    assert len(hdr) == 55 and hdr.startswith("00-")
    parsed = reqctx.parse_traceparent(hdr)
    assert parsed.trace_id == ctx.trace_id
    assert parsed.span_id == ctx.span_id
    assert parsed.flags == ctx.flags
    assert parsed.request_id == ""  # request id is serving-local, not wire


def test_traceparent_rejects_malformed():
    bad = [None, "", "garbage", "00-abc-def-01",
           "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # all-zero trace id
           "00-" + "1" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
           "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",   # reserved version
           "00-" + "1" * 32 + "-" + "2" * 16 + "-01-x",  # v00: exactly 4 fields
           "00-" + "g" * 32 + "-" + "2" * 16 + "-01"]   # non-hex
    for h in bad:
        assert reqctx.parse_traceparent(h) is None, h


def test_traceparent_future_version_forward_compat():
    """W3C forward compat: a version > 00 header parses by its first four
    fields, trailing fields ignored — upstream traces join, never fork."""
    tid, sid = "a1" * 16, "b2" * 8
    got = reqctx.parse_traceparent(f"01-{tid}-{sid}-01-future-fields")
    assert got is not None and got.trace_id == tid and got.span_id == sid
    assert reqctx.parse_traceparent(f"42-{tid}-{sid}-00").trace_id == tid


def test_child_and_adopt_keep_trace_id():
    ctx = reqctx.new_context()
    child = ctx.child(request_id="req-9")
    assert child.trace_id == ctx.trace_id
    assert child.span_id != ctx.span_id
    assert child.request_id == "req-9"
    adopted = reqctx.adopt(ctx.to_traceparent(), request_id="req-a")
    assert adopted.trace_id == ctx.trace_id
    assert adopted.span_id != ctx.span_id  # a fresh hop, not the parent's
    fresh = reqctx.adopt("not a header")
    assert fresh.trace_id != ctx.trace_id  # malformed -> originate


def test_use_binds_and_restores():
    assert reqctx.current() is None
    c1, c2 = reqctx.new_context("a"), reqctx.new_context("b")
    with reqctx.use(c1):
        assert reqctx.current() is c1
        with reqctx.use(c2):
            assert reqctx.current() is c2
        with reqctx.use(None):  # explicit clear between per-request regions
            assert reqctx.current() is None
        assert reqctx.current() is c1
    assert reqctx.current() is None


def test_spans_stamp_active_trace_id():
    """Any span/instant recorded while a context is bound carries its trace
    id — the mechanism that attributes scheduler-thread events per request."""
    tr = Tracer(capacity=32)
    ctx = reqctx.new_context("req-x")
    with reqctx.use(ctx):
        with tr.span("batch.prefill", {"chunk": 8}):
            pass
        tr.instant("batch.row_delivered", {"slot": 0})
    with tr.span("engine.idle"):  # outside any context: no stamp
        pass
    evs = {e["name"]: e for e in tr.events() if e["ph"] in ("X", "i")}
    assert evs["batch.prefill"]["args"]["trace_id"] == ctx.trace_id
    assert evs["batch.prefill"]["args"]["chunk"] == 8  # caller args intact
    assert evs["batch.row_delivered"]["args"]["trace_id"] == ctx.trace_id
    assert "trace_id" not in evs["engine.idle"].get("args", {})


# ----------------------------------------------------------------------
# trace: install() replace-mid-span + process identity + fleet merge
# ----------------------------------------------------------------------

def test_install_replace_mid_span_records_to_new_tracer():
    """Regression (ISSUE 7 small fix): install() used to strand in-flight
    module-level spans in the orphaned predecessor's buffer; they must
    record through the CURRENTLY installed tracer at exit."""
    try:
        t1 = trace_mod.install(capacity=16)
        span = trace_mod.span("long_lived")
        span.__enter__()
        t2 = trace_mod.install(capacity=16)  # replaced mid-span
        span.__exit__(None, None, None)
        assert [e["name"] for e in t1.events() if e["ph"] == "X"] == []
        recorded = [e for e in t2.events() if e["ph"] == "X"]
        assert [e["name"] for e in recorded] == ["long_lived"]
        # the span entered BEFORE t2's epoch: its ts is negative relative to
        # t2 (same monotonic clock), so wall_start_unix + ts still names the
        # true absolute start — the merge-alignment invariant
        ev = recorded[0]
        assert ev["ts"] <= 0 and ev["ts"] + ev["dur"] >= 0
        # uninstalled mid-span: the event is dropped, never crashes
        span2 = trace_mod.span("dropped")
        span2.__enter__()
        trace_mod.uninstall()
        span2.__exit__(None, None, None)
    finally:
        trace_mod.uninstall()


def test_tracer_pid_and_process_name():
    import os

    tr = Tracer(capacity=16, process_name="api_server 1.2.3.4:9990")
    with tr.span("s"):
        pass
    doc = tr.to_chrome_trace()
    assert doc["otherData"]["pid"] == os.getpid()
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert all(e["pid"] == os.getpid() for e in spans)  # no hardcoded pid 1
    pname = [e for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert pname and pname[0]["args"]["name"] == "api_server 1.2.3.4:9990"


def test_merge_chrome_traces_aligns_and_separates_pids():
    """Two processes with the same OS pid and skewed wall clocks merge into
    one doc with distinct pids and wall-aligned timestamps."""
    a = {"traceEvents": [
            {"name": "router.proxy", "ph": "X", "ts": 100.0, "dur": 5.0,
             "pid": 42, "tid": 1, "args": {"trace_id": "t1"}}],
         "otherData": {"wall_start_unix": 1000.0, "dropped_events": 2}}
    b = {"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 42,
             "args": {"name": "stale"}},
            {"name": "batch.super_step", "ph": "X", "ts": 50.0, "dur": 3.0,
             "pid": 42, "tid": 7, "args": {"trace_id": "t1"}}],
         "otherData": {"wall_start_unix": 1001.0, "dropped_events": 1}}
    doc = merge_chrome_traces([("router", a), ("replica h:1", b)])
    json.loads(json.dumps(doc))  # stays valid JSON
    evs = doc["traceEvents"]
    spans = {e["name"]: e for e in evs if e["ph"] == "X"}
    # distinct pids per source despite the OS-pid collision
    assert spans["router.proxy"]["pid"] != spans["batch.super_step"]["pid"]
    # wall alignment: b started 1 s after a, so its ts shifts by 1e6 µs
    assert spans["router.proxy"]["ts"] == 100.0
    assert spans["batch.super_step"]["ts"] == 50.0 + 1e6
    # one process_name per source, the merge's own label (not the stale one)
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {"router", "replica h:1"}
    assert doc["otherData"]["dropped_events"] == 3
    assert len(doc["otherData"]["processes"]) == 2


# ----------------------------------------------------------------------
# flight recorder
# ----------------------------------------------------------------------

def test_flight_ring_eviction_bound():
    rec = FlightRecorder(capacity=10, live_capacity=8)
    for i in range(30):
        rec.start(f"r{i}", trace_id=f"t{i}")
        rec.event(f"r{i}", "admitted", slot=0)
        rec.finish(f"r{i}", "length")
    listing = rec.requests()
    assert len(listing["completed"]) == 10
    assert listing["evicted"] == 20
    assert listing["completed"][0]["id"] == "r29"  # newest first
    assert rec.get("r0") is None  # rotated out
    got = rec.get("r29")
    assert got["finish"] == "length"
    assert [e["event"] for e in got["events"]] == ["admitted"]
    # live-table bound: unfinished records cannot grow without limit
    for i in range(40):
        rec.event(f"live{i}", "x")
    assert len(rec.requests()["live"]) <= 8
    assert rec.evicted_live >= 32


def test_flight_lookup_by_trace_id_and_slowest():
    rec = FlightRecorder(capacity=8)
    rec.start("req-a", trace_id="a" * 32)
    rec.finish("req-a", "stop", e2e_ms=50.0)
    rec.start("req-b", trace_id="b" * 32)
    rec.finish("req-b", "stop", e2e_ms=500.0)
    assert rec.get("a" * 32)["id"] == "req-a"  # trace-id fallback
    slow = rec.requests(slowest=1)["completed"]
    assert len(slow) == 1 and slow[0]["id"] == "req-b"


def test_flight_events_capped_per_record():
    rec = FlightRecorder(capacity=4, max_events=5)
    for i in range(20):
        rec.event("r", "super_step", k=8)
    got = rec.get("r")
    assert len(got["events"]) == 5
    assert got["events_dropped"] == 15  # truncation is honest


def test_flight_concurrent_writers_stress():
    """8 threads × 50 requests each, events + finish interleaved with reads:
    no lost records beyond the ring bound, no exceptions, consistent
    summaries."""
    rec = FlightRecorder(capacity=64, live_capacity=512)
    T, N = 8, 50
    errors = []

    def work(t):
        try:
            for i in range(N):
                rid = f"w{t}-{i}"
                rec.start(rid, trace_id=f"tid{t}-{i}")
                for j in range(4):
                    rec.event(rid, "super_step", k=8, delivered=j)
                rec.requests(slowest=3)  # concurrent reader
                rec.finish(rid, "length", e2e_ms=float(i))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=work, args=(t,)) for t in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    listing = rec.requests()
    assert len(listing["completed"]) == 64  # exactly the ring bound
    assert listing["evicted"] == T * N - 64
    for summary in listing["completed"]:
        full = rec.get(summary["id"])
        assert full["finish"] == "length" and len(full["events"]) == 4


def test_flight_slow_log_exemplars(tmp_path):
    """Only completions over threshold land in the JSONL, once each, and
    only when the finish carries request-level numbers (e2e_ms/error)."""
    out = tmp_path / "slow.jsonl"
    rec = FlightRecorder(capacity=8, slow_log=str(out), slow_threshold=0.1)
    rec.start("fast")
    rec.finish("fast", "stop", e2e_ms=5.0)
    rec.start("slow")
    rec.event("slow", "admitted")
    rec.finish("slow", "length")            # engine-side: no api numbers yet
    rec.finish("slow", None, e2e_ms=450.0, ttft_ms=120.0)  # api completes
    rec.finish("slow", None, e2e_ms=450.0)  # double-finish: no second line
    rec.start("broken")
    rec.finish("broken", "error", error="boom", e2e_ms=200.0)
    # an errored request is an exemplar even BELOW the latency threshold —
    # a 200 ms fault-killed request is the primary debugging target
    rec.start("fast-broken")
    rec.finish("fast-broken", "error", error="crash", e2e_ms=5.0)
    rec.close()
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert [ln["id"] for ln in lines] == ["slow", "broken", "fast-broken"]
    assert lines[0]["ttft_ms"] == 120.0
    assert [e["event"] for e in lines[0]["events"]] == ["admitted"]
    assert lines[2]["error"] == "crash" and lines[2]["e2e_ms"] == 5.0


def test_flight_drop_discards_shed_requests(tmp_path):
    """Admission sheds (503 bursts) are dropped, not finished: they must
    not occupy the completed ring nor append slow-log exemplars."""
    out = tmp_path / "slow.jsonl"
    rec = FlightRecorder(capacity=4, slow_log=str(out), slow_threshold=0.1)
    rec.start("real")
    rec.finish("real", "stop", e2e_ms=500.0)
    for i in range(100):  # saturation burst
        rec.start(f"shed-{i}")
        rec.drop(f"shed-{i}")
    listing = rec.requests()
    assert [s["id"] for s in listing["completed"]] == ["real"]
    assert listing["live"] == [] and rec.get("shed-0") is None
    rec.close()
    lines = out.read_text().splitlines() if out.exists() else []
    assert len(lines) == 1  # only the real completion


def test_flight_module_level_noop_and_ctx_resolution():
    """Module hooks are no-ops with no recorder installed; with one, a None
    rid resolves through the bound trace context (the engine call sites)."""
    flight_mod.uninstall()
    flight_mod.event("x", "e")   # no recorder: must not raise
    flight_mod.finish("x")
    rec = flight_mod.install(capacity=8)
    try:
        ctx = reqctx.new_context("req-ctx")
        with reqctx.use(ctx):
            flight_mod.event(None, "prefill", tokens=4)
            flight_mod.finish(None, "stop")
        got = rec.get("req-ctx")
        assert got["finish"] == "stop"
        assert got["events"][0]["event"] == "prefill"
        flight_mod.event(None, "orphan")  # no ctx: dropped, not crashed
        assert rec.get("") is None
    finally:
        flight_mod.uninstall()


def test_flight_eviction_counters_consistent_with_listing():
    """Regression for a lock-guard finding (docs/ANALYSIS.md): requests()
    used to read `evicted_done`/`evicted_live` AFTER releasing the table
    lock, so a listing racing a finish could pair a pre-eviction completed
    list with a post-eviction count. The counters are now snapshotted in the
    same critical section; this drives concurrent finishers against readers
    and asserts the final listing accounts for every completion exactly."""
    rec = FlightRecorder(capacity=4, live_capacity=64)
    n_threads, n_each = 6, 50
    barrier = threading.Barrier(n_threads + 1)
    stop = threading.Event()

    def finisher(k: int):
        barrier.wait()
        for i in range(n_each):
            rid = f"r{k}-{i}"
            rec.start(rid)
            rec.event(rid, "step")
            rec.finish(rid, "stop")

    violations: list[str] = []

    def reader():
        # violations collected into a list the MAIN thread asserts on —
        # an assert raised inside a daemon thread would be swallowed by
        # threading's excepthook and the test would pass vacuously
        barrier.wait()
        while not stop.is_set():
            r = rec.requests()
            # within one locked snapshot the ring bound always holds
            if len(r["completed"]) > rec.capacity:
                violations.append(f"ring over capacity: {len(r['completed'])}")
            if r["evicted"] < 0 or r["evicted_live"] < 0:
                violations.append(f"negative counter: {r['evicted']}, "
                                  f"{r['evicted_live']}")

    threads = [threading.Thread(target=finisher, args=(k,))
               for k in range(n_threads)]
    rt = threading.Thread(target=reader, daemon=True)
    rt.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    rt.join(timeout=5)
    assert not violations, violations[:3]
    final = rec.requests()
    total = n_threads * n_each
    assert len(final["completed"]) == rec.capacity
    # exact accounting: every finish either sits in the ring or was counted
    # out of it — the invariant the same-critical-section snapshot pins
    assert final["evicted"] == total - rec.capacity
    assert final["evicted_live"] == 0
