"""Measured collective-traffic accounting (parallel/hlo_stats.py).

Replaces round 1's print-the-model-as-if-measured defect: the S/R columns now come
from exact accounting of the compiled step program's collectives (the reference
measured socket bytes per token, src/socket.cpp:280-285)."""

import pytest
import jax
import jax.numpy as jnp

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.parallel.hlo_stats import (collective_traffic,
                                                      jaxpr_collective_traffic)
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.compat import shard_map


def test_hlo_text_parser():
    hlo = """
  HloModule jit_step
  %x.1 = f32[4,256]{1,0} parameter(0)
  %all-reduce.1 = f32[256]{0} all-reduce(f32[256]{0} %y), replica_groups={{0,1,2,3}}, to_apply=%add
  %ag = (f32[64]{0}, f32[256]{0}) all-gather-start(f32[64]{0} %z), replica_groups={{0,1,2,3}}
  %ag2 = f32[256]{0} all-gather-done((f32[64]{0}, f32[256]{0}) %ag)
  %cp = s8[128]{0} collective-permute(s8[128]{0} %w), source_target_pairs={{0,1}}
"""
    t = collective_traffic(hlo, default_group_size=4)
    assert t.counts == {"all-reduce": 1, "all-gather": 1, "collective-permute": 1}
    assert t.payload_bytes["all-reduce"] == 256 * 4
    assert t.payload_bytes["all-gather"] == 256 * 4  # result element of the tuple
    assert t.payload_bytes["collective-permute"] == 128
    want = 2 * 3 / 4 * 1024 + 3 / 4 * 1024 + 128
    assert abs(t.sent_bytes_per_device - want) < 1e-6


def test_jaxpr_walker_counts_scan_iterations():
    from jax.sharding import PartitionSpec as P

    from distributed_llama_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tp=4)

    def f(x):
        def body(c, _):
            return c + jax.lax.psum(x, "tp"), None

        out, _ = jax.lax.scan(body, jnp.zeros_like(x), None, length=3)
        return jax.lax.all_gather(out, "tp", tiled=True)

    sm = shard_map(f, mesh=mesh, in_specs=(P("tp"),), out_specs=P(),
                   check_vma=False)
    closed = jax.make_jaxpr(sm)(jnp.ones((8,), jnp.float32))
    t = jaxpr_collective_traffic(closed, dict(mesh.shape))
    assert t.counts["all-reduce"] == 3  # psum inside the scan body, length 3
    assert t.counts["all-gather"] == 1
    # per-shard psum payload: (2,) f32 = 8 B x 3 iterations
    assert t.payload_bytes["all-reduce"] == 3 * 2 * 4
    assert t.payload_bytes["all-gather"] == 8 * 4


@pytest.fixture(scope="module")
def tp4_engine():
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=256, hidden_dim=256, n_layers=2,
                     n_heads=8, n_kv_heads=8, vocab_size=256, seq_len=16,
                     rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.F32, seed=7)
    return Engine(spec, params, tp=4)


def test_engine_measured_traffic(tp4_engine):
    eng = tp4_engine
    t = eng.collective_stats()
    spec = eng.spec
    # the step's semantic collectives: 2 psums per layer (attention-out, ffn-out)
    # + the logits all-gather
    assert t.counts["all-reduce"] == 2 * spec.n_layers
    assert t.counts["all-gather"] == 1
    assert t.payload_bytes["all-reduce"] == 2 * spec.n_layers * spec.dim * 4
    assert t.payload_bytes["all-gather"] == spec.vocab_size * 4
    want_sent = (2 * 3 / 4 * t.payload_bytes["all-reduce"]
                 + 3 / 4 * t.payload_bytes["all-gather"])
    assert abs(t.sent_bytes_per_device - want_sent) < 1e-6


def test_generate_stats_use_measured_traffic(tp4_engine):
    from distributed_llama_tpu.runtime.sampler import Sampler

    eng = tp4_engine
    eng.reset()
    eng.collective_stats()  # computed -> generate() stats switch to measured
    _, stats = eng.generate([1, 2], 3, Sampler(eng.spec.vocab_size, temperature=0.0))
    assert stats.traffic_source == "measured"
    assert stats.sent_kbytes_per_token == pytest.approx(
        eng.collective_stats().sent_bytes_per_device / 1024.0)


def test_cond_counts_heaviest_branch_only():
    from jax.sharding import PartitionSpec as P

    from distributed_llama_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tp=4)

    def f(x, flag):
        return jax.lax.cond(
            flag,
            lambda x: jax.lax.psum(x, "tp"),                   # 8 B payload
            lambda x: jax.lax.psum(x[:1], "tp").repeat(2),     # 4 B payload
            x)

    sm = shard_map(f, mesh=mesh, in_specs=(P("tp"), P()), out_specs=P("tp"),
                   check_vma=False)
    closed = jax.make_jaxpr(sm)(jnp.ones((8,), jnp.float32), jnp.bool_(True))
    t = jaxpr_collective_traffic(closed, dict(mesh.shape))
    # one branch executes: the heavier (8 B) psum is counted once, not both summed
    assert t.counts["all-reduce"] == 1
    assert t.payload_bytes["all-reduce"] == 2 * 4


def test_device_loop_stats_measure_loop_program(tp4_engine):
    from distributed_llama_tpu.runtime.sampler import Sampler

    eng = tp4_engine
    eng.reset()
    eng.collective_stats()  # opt into measurement
    _, stats = eng.generate_chunked([1, 2], 4,
                                    Sampler(eng.spec.vocab_size, temperature=0.0),
                                    chunk=4)
    assert stats.traffic_source == "measured"
    lt = eng._loop_traffics[(4, "greedy")]
    assert stats.sent_kbytes_per_token == pytest.approx(
        lt.sent_bytes_per_device / 4 / 1024.0)
    # per-token bytes of the loop program match the per-token host step
    assert stats.sent_kbytes_per_token == pytest.approx(
        eng.collective_stats().sent_bytes_per_device / 1024.0, rel=0.01)


def test_modeled_traffic_labeled():
    """Without a collective_stats() call the analytic model is used and says so."""
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=64, n_layers=1,
                     n_heads=2, n_kv_heads=2, vocab_size=64, seq_len=8,
                     rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.F32, seed=9)
    eng = Engine(spec, params, tp=2)
    from distributed_llama_tpu.runtime.sampler import Sampler

    _, stats = eng.generate([1], 2, Sampler(spec.vocab_size, temperature=0.0))
    assert stats.traffic_source == "modeled"
    assert stats.sent_kbytes_per_token > 0


def test_compiled_hlo_cross_check(tp4_engine):
    """The optimized-HLO parser must see the same collective KINDS the jaxpr
    accounting predicts (counts differ by loop semantics: the jaxpr walker
    multiplies scan bodies by trip count, the HLO text counts instructions)."""
    eng = tp4_engine
    jx = eng.collective_stats()
    hl = eng.compiled_collective_stats()
    assert set(hl.counts), "optimized module shows no collectives at tp=4"
    # every lowered collective kind is one the jaxpr model knows about, and the
    # logits all-gather (outside any loop) appears in both with identical count
    assert set(hl.counts) <= set(jx.counts) | {"all-reduce"}
    assert "all-gather" in hl.counts and "all-gather" in jx.counts
    assert hl.counts["all-gather"] == jx.counts["all-gather"]
    assert hl.sent_bytes_per_device > 0
