"""Prompt-lookup speculative decoding (runtime/speculative.py).

The load-bearing property is greedy EXACTNESS: generate_speculative must emit
token-for-token what the sequential generate() loop emits — acceptance only
changes how many dispatches it takes, never the tokens. (Beyond-reference
feature; no counterpart in /root/reference.)"""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.sampler import Sampler
from distributed_llama_tpu.runtime.speculative import NgramIndex, propose_ngram

SPEC = dict(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
            n_heads=4, n_kv_heads=2, vocab_size=96, seq_len=256)


@pytest.fixture(scope="module")
def spec_params():
    spec = ModelSpec(**SPEC).resolved()
    return spec, init_random_params(spec, FloatType.Q40, seed=21)


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


# ---------------------------------------------------------------- propose


def test_propose_ngram_copies_continuation():
    toks = [5, 6, 7, 8, 9, 1, 2, 5, 6, 7]  # tail [5,6,7] seen at index 0
    assert propose_ngram(toks, 4) == [8, 9, 1, 2]
    assert propose_ngram(toks, 2) == [8, 9]


def test_propose_ngram_most_recent_match_wins():
    toks = [1, 2, 3, 1, 2, 4, 1, 2]
    # tail [1,2]: occurrences at 0 (-> 3) and 3 (-> 4); most recent wins
    assert propose_ngram(toks, 1) == [4]


def test_propose_ngram_no_match():
    assert propose_ngram([1, 2, 3, 4, 5, 6, 7, 8], 4) == []
    assert propose_ngram([], 4) == []
    assert propose_ngram([1], 4) == []


def test_propose_ngram_prefers_longer_ngram():
    # tail ...,2,3 matches at idx 1 (-> 9); longer tail [1,2,3] matches
    # at idx 0 (-> 9 too) — crafted so the 3-gram and 2-gram disagree:
    toks = [1, 2, 3, 9, 2, 3, 7, 1, 2, 3]
    assert propose_ngram(toks, 1) == [9]  # 3-gram [1,2,3] -> 9, not 2-gram -> 7


def test_ngram_index_matches_bruteforce_incrementally():
    """NgramIndex.propose must equal propose_ngram at EVERY append point —
    the incremental dict replaces the O(len*max_ngram) full-history rescan
    with O(max_ngram) lookups, answers unchanged."""
    rs = np.random.RandomState(3)
    toks = rs.randint(0, 6, size=400).tolist()  # small alphabet: dense matches
    idx = NgramIndex(toks[:5])
    for i in range(5, len(toks)):
        for k in (1, 4, 8):
            assert idx.propose(k) == propose_ngram(toks[:i], k), (i, k)
        idx.append(toks[i])
    # non-repetitive and degenerate corpora too
    idx = NgramIndex([])
    assert idx.propose(4) == propose_ngram([], 4) == []
    for i, t in enumerate(range(50, 90)):
        idx.append(t)
        assert idx.propose(4) == propose_ngram(list(range(50, 51 + i)), 4)


def test_ngram_index_seeded_corpus_matches_bruteforce():
    """Constructor-seeded corpus (the history_tokens path) behaves like
    append-built."""
    toks = [3, 7, 11] * 10 + [5, 3, 7]
    a = NgramIndex(list(toks))
    b = NgramIndex([])
    b.extend(toks)
    for k in (1, 3, 8):
        assert a.propose(k) == b.propose(k) == propose_ngram(toks, k)


# ------------------------------------------------------------- exactness


def _compare(engine_a, engine_b, prompt, n, spec, stop_eos=None):
    kw = {}
    if stop_eos is not None:
        kw["stop_check"] = lambda t: t == stop_eos
    out_seq, _ = engine_a.generate(prompt, n, _greedy(spec), **kw)
    out_spec, st = engine_b.generate_speculative(prompt, n, _greedy(spec), **kw)
    assert out_seq == out_spec
    return st


def test_speculative_matches_sequential(spec_params):
    spec, params = spec_params
    a = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    b = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    # repetitive prompt: n-gram drafts exist from the start
    prompt = [3, 7, 11, 3, 7, 11, 3, 7, 11, 3, 7]
    st = _compare(a, b, prompt, 48, spec)
    assert st.generated_tokens == 48
    assert st.spec_steps <= 48  # never MORE dispatches than sequential
    # tiny greedy models cycle; the lookup must exploit that at least once
    assert st.spec_accepted > 0


def test_speculative_matches_on_nonrepetitive_prompt(spec_params):
    spec, params = spec_params
    a = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    b = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    prompt = list(range(20, 60))  # no repeated n-gram in the prompt
    _compare(a, b, prompt, 32, spec)


def test_speculative_stop_check_matches(spec_params):
    """Stop token honored identically, and the post-stop cache frontier lets
    a follow-up turn continue exactly like the sequential engine."""
    spec, params = spec_params
    a = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    b = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    prompt = [3, 7, 11, 3, 7, 11, 3, 7]
    out_seq, _ = a.generate(prompt, 40, _greedy(spec))
    eos = out_seq[10]  # a token the run actually emits mid-stream
    a2 = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    st = _compare(a2, b, prompt, 40, spec, stop_eos=eos)
    assert a2.pos == b.pos, "post-stop cache frontier diverged"
    assert st.generated_tokens == len(
        [t for t in out_seq[:out_seq.index(eos) + 1]])


def test_speculative_on_paged_engine(spec_params):
    """Speculation composes with the paged cache: seek() rewinds the hot
    ring; tokens still match the plain sequential engine past the cold
    boundary."""
    spec, params = spec_params
    a = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    b = Engine(spec, dict(params), tp=1, dtype=jnp.float32,
               kv_cache_storage="host", kv_cache_resident=64)
    prompt = [3, 7, 11, 3, 7, 11] * 12  # prefill 72 > resident 64
    _compare(a, b, prompt, 40, spec)


def test_speculative_context_end_matches(spec_params):
    """At the context boundary the sequential loop stops emitting once
    pos reaches seq_len; an accepted draft must not emit one token more
    (the draft cap is room-1, not room)."""
    spec = ModelSpec(**dict(SPEC, seq_len=32)).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=21)
    a = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    b = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    prompt = [3, 7, 11] * 9 + [3]  # 28 tokens; only 4 positions remain
    out_seq, _ = a.generate(prompt, 10, _greedy(spec))
    out_spec, _ = b.generate_speculative(prompt, 10, _greedy(spec))
    assert out_seq == out_spec
    assert len(out_seq) <= spec.seq_len - len(prompt) + 1


def test_speculative_on_sharded_engine(spec_params):
    """Speculation composes with tp x sp sharding: rollback rides the ring's
    live_end masking; tokens match the unsharded sequential engine."""
    spec, params = spec_params
    a = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    b = Engine(spec, dict(params), tp=2, sp=2, dtype=jnp.float32)
    prompt = [3, 7, 11, 3, 7, 11, 3, 7]
    _compare(a, b, prompt, 32, spec)


def test_speculative_history_tokens_prefix_reuse(spec_params):
    """The api_server path: prompt_tokens is a reuse delta while
    history_tokens carries the full conversation for the proposer — output
    must equal decoding the delta without history (exactness is independent
    of the draft corpus)."""
    spec, params = spec_params
    full = [3, 7, 11] * 8
    a = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    a.prefill(full[:20])
    out_a, _ = a.generate(full[20:], 24, _greedy(spec))
    b = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    b.prefill(full[:20])
    out_b, st = b.generate_speculative(full[20:], 24, _greedy(spec),
                                       history_tokens=full)
    assert out_a == out_b
    assert st.spec_accepted > 0  # the full-history corpus produced drafts


def test_speculative_rejects_sampling(spec_params):
    spec, params = spec_params
    b = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    with pytest.raises(AssertionError):
        b.generate_speculative([1, 2, 3], 4,
                               Sampler(spec.vocab_size, temperature=0.7))


def test_generate_with_dispatches_speculative(spec_params):
    spec, params = spec_params
    a = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    b = Engine(spec, dict(params), tp=1, dtype=jnp.float32)
    prompt = [3, 7, 11, 3, 7, 11, 3, 7]
    out_seq, _ = a.generate(prompt, 24, _greedy(spec))
    out_spec, st = b.generate_with(prompt, 24, _greedy(spec), speculative_k=6)
    assert out_seq == out_spec
    assert hasattr(st, "spec_steps")


# ----------------------------------------------- memory bound / extension


def test_ngram_index_entry_cap_bounds_memory():
    """The _last dicts gain one entry per unique n-gram for the life of the
    index; a long-lived batched serving slot must not grow without bound.
    An adversarial stream of unique grams must keep total entries at or
    under max_entries at every step (ISSUE 8 satellite)."""
    cap = 512
    idx = NgramIndex([], max_entries=cap)
    rs = np.random.RandomState(3)
    for i in range(20_000):
        # wide token range: nearly every gram is unique
        idx.append(int(rs.randint(0, 1_000_000)))
        assert idx.entries <= cap, (i, idx.entries)
    assert sum(len(d) for d in idx._last.values()) == idx.entries


def test_ngram_index_cap_keeps_recent_matches():
    """After eviction rebuilds from the tail window, grams INSIDE the window
    still propose exactly like the brute force over that suffix would —
    recency is what prompt-lookup uses, so that's what the cap preserves."""
    cap = 256
    idx = NgramIndex([], max_entries=cap)
    rs = np.random.RandomState(5)
    stream = [int(t) for t in rs.randint(0, 1_000_000, 5000)]
    pat = [42, 43, 44, 45, 46, 47]
    tail = stream + pat + [int(t) for t in rs.randint(0, 1_000_000, 8)] + pat[:4]
    idx.extend(tail)
    # the tail 4-gram [42,43,44,45] recurs inside the rebuilt window
    assert idx.propose(2) == [46, 47]


def test_propose_extended_unrolls_cycles():
    """Most-recent-wins clips the continuation at the end of the list on a
    cyclic tail; propose_extended re-proposes from the virtually extended
    sequence and must unroll the cycle to the full k."""
    cyc = [9, 5, 7]
    idx = NgramIndex([1, 2, 3] + cyc * 6)
    k = 8
    got = idx.propose_extended(k)
    assert len(got) == k
    # the draft continues the cycle exactly
    want = (cyc * 5)[:k]
    start = cyc.index(got[0])
    assert got == (cyc[start:] + cyc * 3)[:k], (got, want)


def test_propose_extended_matches_propose_when_unclipped():
    """When the most recent occurrence has a full-length continuation,
    propose_extended adds nothing beyond propose()."""
    toks = [5, 6, 7, 8, 9, 10, 11, 12, 5, 6, 7]
    idx = NgramIndex(toks)
    assert idx.propose_extended(3) == idx.propose(3) == [8, 9, 10]
