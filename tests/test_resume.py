"""Durable-resume unit tests (ISSUE 9, no server): the two properties the
mid-stream failover protocol rests on.

1. Sampler RNG fast-forward (runtime/sampler.py): every stochastic sample()
   draws exactly one xorshift* coin and greedy draws none, so a fresh
   sampler fast-forwarded by k continues the uninterrupted coin stream
   byte-identically — property-tested over random seeds, stop positions k,
   and greedy/stochastic parameter mixes.
2. Engine-level resume (runtime/batch_engine.py): submitting
   prompt ⊕ out[:k] with the remaining budget and a fast-forwarded sampler
   regenerates out[k:] exactly — the forced-prefix admission the api
   server's `resume` payload rides, including mixed greedy/stochastic rows
   co-batched with ordinary requests.
"""

import random

import numpy as np
import pytest

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.sampler import Sampler

VOCAB = 97


def _logits_at(step: int, salt: int) -> np.ndarray:
    """Deterministic per-step logits — a stand-in model whose 'generation'
    depends only on the step index, so resume-at-k needs no KV state."""
    rng = np.random.default_rng(step * 1000003 + salt)
    return rng.normal(0.0, 3.0, VOCAB).astype(np.float32)


@pytest.mark.parametrize("temperature,topp", [
    (0.0, 0.9),     # greedy: zero coins consumed
    (0.8, 0.9),     # nucleus: one coin per token
    (1.2, 1.0),     # plain multinomial (topp disabled): one coin per token
    (0.3, 0.05),    # tiny nucleus: still exactly one coin per token
])
def test_fast_forward_resume_matches_uninterrupted(temperature, topp):
    rnd = random.Random(hash((temperature, topp)) & 0xFFFF)
    for trial in range(20):
        seed = rnd.randrange(1, 2**31)
        n = rnd.randrange(4, 40)
        k = rnd.randrange(0, n + 1)
        salt = rnd.randrange(1000)
        full = Sampler(VOCAB, temperature, topp, seed)
        ref = [full.sample(_logits_at(i, salt)) for i in range(n)]
        resumed = Sampler(VOCAB, temperature, topp, seed)
        resumed.fast_forward(k)
        cont = [resumed.sample(_logits_at(i, salt)) for i in range(k, n)]
        assert cont == ref[k:], (trial, seed, n, k)
        # the states converge too: a later resume-of-the-resume stays exact
        assert resumed.state == full.state


def test_fast_forward_greedy_is_noop():
    s = Sampler(VOCAB, 0.0, 0.9, 1234)
    s.fast_forward(50)
    assert s.state == np.uint64(1234)


def test_fast_forward_equals_consumed_coins():
    """fast_forward(k) lands on exactly the state after k sample() calls —
    the invariant that makes the resume count 'delivered tokens', not some
    sampler-internal number."""
    for seed in (1, 7, 0xDEADBEEF):
        s = Sampler(VOCAB, 0.9, 0.9, seed)
        for i in range(13):
            s.sample(_logits_at(i, 0))
        ff = Sampler(VOCAB, 0.9, 0.9, seed)
        ff.fast_forward(13)
        assert ff.state == s.state


# ----------------------------------------------------------------------
# engine-level forced-prefix resume
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine():
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=160, rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4)
    yield spec, be
    be.close()


PROMPT = [1, 7, 23, 5, 40, 9]
GEN = 20


def _run(be, spec, prompt, gen, temperature, seed, ff=0, resume_tokens=0):
    s = Sampler(spec.vocab_size, temperature, 0.9, seed)
    s.fast_forward(ff)
    req = be.submit(list(prompt), gen, s, resume_tokens=resume_tokens)
    return req.wait(timeout=300), req


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_engine_resume_at_k_byte_identical(engine, temperature):
    spec, be = engine
    ref, _ = _run(be, spec, PROMPT, GEN, temperature, seed=99)
    assert len(ref) == GEN
    for k in (1, 7, GEN - 1):
        cont, req = _run(be, spec, PROMPT + ref[:k], GEN - k, temperature,
                         seed=99, ff=k, resume_tokens=k)
        assert cont == ref[k:], (temperature, k)
        # the admission counted the resume and reported its reuse reading
        assert req.resume_tokens == k
        assert req.stats.reused_tokens >= 0


def test_engine_resume_mixed_rows_concurrent(engine):
    """A resumed stochastic request co-batched with a fresh greedy one:
    both finish token-identical to their solo references (the resume's
    fast-forwarded RNG must survive super-step batching + rollback)."""
    spec, be = engine
    ref_s, _ = _run(be, spec, PROMPT, GEN, 0.8, seed=7)
    ref_g, _ = _run(be, spec, [1, 3, 3, 8], GEN, 0.0, seed=0)
    k = 6
    s1 = Sampler(spec.vocab_size, 0.8, 0.9, 7)
    s1.fast_forward(k)
    r1 = be.submit(PROMPT + ref_s[:k], GEN - k, s1, resume_tokens=k)
    r2 = be.submit([1, 3, 3, 8], GEN, Sampler(spec.vocab_size, 0.0, 0.9, 0))
    assert r1.wait(timeout=300) == ref_s[k:]
    assert r2.wait(timeout=300) == ref_g


def test_engine_resume_budget_exhausted(engine):
    """Resuming at k == total budget generates nothing and finishes
     'length' — the resumed run stops exactly where the original would."""
    spec, be = engine
    ref, _ = _run(be, spec, PROMPT, GEN, 0.8, seed=42)
    cont, req = _run(be, spec, PROMPT + ref, 0, 0.8, seed=42, ff=GEN,
                     resume_tokens=GEN)
    assert cont == []
    assert req.finish == "length"
