"""End-to-end engine tests on the CPU mesh: load .m/.t from disk, generate, check
determinism, chunked-prefill equivalence, stats, and context-overflow handling."""

import numpy as np
import pytest

from distributed_llama_tpu.formats.mfile import params_file_order, write_model
from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.engine import Engine, collective_kbytes_per_token
from distributed_llama_tpu.runtime.sampler import Sampler


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("engine")
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=2, vocab_size=262, seq_len=64).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=9)
    mpath = str(tmp / "model.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.Q40)

    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + [b" ", b"ab", b"cd"]
    scores = [0.0] * 259 + [-1.0, -2.0, -3.0]
    tpath = str(tmp / "tok.t")
    write_tokenizer(tpath, TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2,
                                         max_token_length=4))
    return mpath, tpath


def test_engine_load_and_generate(model_files):
    mpath, tpath = model_files
    eng = Engine.load(mpath, tpath, tp=2)
    sampler = Sampler(eng.spec.vocab_size, temperature=0.0)
    prompt = eng.tokenizer.encode("ab", add_bos=True)
    out, stats = eng.generate(prompt, 10, sampler)
    assert len(out) == 10
    assert stats.prompt_tokens == len(prompt)
    assert stats.generated_tokens == 10
    assert stats.avg_token_ms > 0
    assert stats.sent_kbytes_per_token > 0

    # determinism: same prompt, fresh engine state -> same tokens
    eng.reset()
    out2, _ = eng.generate(prompt, 10, sampler)
    assert out == out2


def test_engine_chunked_prefill_equals_stepwise(model_files):
    mpath, tpath = model_files
    eng = Engine.load(mpath, tpath, tp=1)
    prompt = list(range(3, 20))  # 17 tokens: exercises 8+8+1 chunking

    eng.reset()
    logits_chunked = eng.prefill(prompt)

    eng2 = Engine.load(mpath, tpath, tp=1)
    for t in prompt:
        logits_step = eng2.infer_chunk([t])
    np.testing.assert_allclose(logits_chunked, logits_step, atol=2e-4, rtol=1e-3)


def test_engine_context_overflow(model_files):
    mpath, tpath = model_files
    eng = Engine.load(mpath, tpath, tp=1)
    with pytest.raises(ValueError, match="context overflow"):
        eng.infer_chunk(list(range(100)))  # seq_len is 64


def test_engine_generation_stops_at_context_end(model_files):
    mpath, tpath = model_files
    eng = Engine.load(mpath, tpath, tp=1, max_seq_len=0)
    sampler = Sampler(eng.spec.vocab_size, temperature=0.0)
    out, stats = eng.generate([1, 5, 6], 1000, sampler)
    assert eng.pos <= eng.spec.seq_len
    assert len(out) <= eng.spec.seq_len


def test_engine_tp_matches_single(model_files):
    mpath, tpath = model_files
    sampler = Sampler(262, temperature=0.0)
    eng1 = Engine.load(mpath, tpath, tp=1)
    out1, _ = eng1.generate([1, 9, 8, 7], 8, sampler)
    eng2 = Engine.load(mpath, tpath, tp=2, compress_collectives=False)
    out2, _ = eng2.generate([1, 9, 8, 7], 8, sampler)
    assert out1 == out2


def test_collective_bytes_model():
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=4096, hidden_dim=14336, n_layers=32,
                     n_heads=32, n_kv_heads=8, vocab_size=128256, seq_len=2048).resolved()
    full = collective_kbytes_per_token(spec, 4, compress=False)
    comp = collective_kbytes_per_token(spec, 4, compress=True)
    assert full > comp > 0
    assert collective_kbytes_per_token(spec, 1, False) == 0.0


def test_collective_estimate_matches_measured_tp2():
    """The analytic S/R model must agree with the MEASURED jaxpr accounting
    of the compiled decode step (hlo_stats) on the CPU tp2 mesh — in BOTH
    compression modes. The compressed case is the regression this pins: the
    old single-phase quantized_psum all_gathered the full quantized payload
    (n_dev x what the 34/32 model claimed); the two-phase scatter-reduce +
    gather form in parallel/collectives.py makes the estimate true."""
    from distributed_llama_tpu.models.spec import RopeType
    from distributed_llama_tpu.obs import metrics

    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=128, hidden_dim=256,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=64, rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.F32, seed=3)
    for compress in (False, True):
        eng = Engine(spec, params, tp=2, compress_collectives=compress)
        measured = eng.collective_stats().sent_bytes_per_device
        est = collective_kbytes_per_token(spec, 2, compress) * 1024.0
        assert measured == pytest.approx(est, rel=0.01), (compress, measured, est)
    # compressed wire bytes actually dropped vs the fp path
    assert (collective_kbytes_per_token(spec, 2, True)
            < collective_kbytes_per_token(spec, 2, False))
    # collective_stats published the measured numbers as gauges
    # (hlo_stats.publish_traffic) for /metrics
    snap = metrics.snapshot().get("collective_sent_bytes_per_device") or {}
    assert any("decode_t1" in k for k in snap), sorted(snap)


def test_window_bucket_transitions_match_full(monkeypatch):
    """A generation that crosses window buckets (16 -> 32 -> full) must emit exactly
    the tokens of an engine that never windows: bucket growth only changes which dead
    cache positions are read."""
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=256, seq_len=64).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=19)

    full = Engine(spec, params, tp=2)  # seq_len 64 <= default _WINDOW_MIN: never windows
    sampler = Sampler(spec.vocab_size, temperature=0.0)
    want, _ = full.generate([1, 7, 23], 40, sampler)

    monkeypatch.setattr(Engine, "_WINDOW_MIN", 16)
    windowed = Engine(spec, params, tp=2)
    got, _ = windowed.generate([1, 7, 23], 40, Sampler(spec.vocab_size, temperature=0.0))
    assert got == want
    # multiple buckets were actually compiled (16 and 32 at least, then full)
    assert {16, 32} <= {w for w in windowed._steps if w is not None}
