"""Model-based speculative drafting tests (ISSUE 15; docs/SERVING.md
"Model-based drafting").

The draft/ subsystem loads a second small sharded model co-resident on the
target's mesh and drafts k tokens per row in one scan dispatch behind the
Proposer protocol (runtime/speculative.py); the target's existing verify
path accepts or rejects the drafts. Load-bearing properties:

- drafter-backed output is BYTE-IDENTICAL to the spec-off batched loop —
  greedy AND seeded-stochastic rows (proposals never affect correctness);
- the drafter's catch-up + draft scan reproduces the draft model's own
  sequential greedy stream exactly (the proposal-quality contract), and
  accepted-draft pushes advance its frontier for free (spec_tail hits);
- a SELF-draft (drafter == target) accepts every draft on greedy rows —
  the first-principles oracle for the frontier/catch-up bookkeeping;
- rows the drafter cannot serve (its context is shorter than the target's)
  fall back to n-gram drafting IN THE SAME BATCH;
- the adaptive per-row k controller converges against a fixed-accept-rate
  stub: full acceptance ramps to the cap, zero acceptance disengages with
  the slow-reprobe horizon, partial acceptance settles in small buckets;
- durable resume and preemption re-admission run byte-identical with a
  live drafter attached;
- a drafter scan-length bucket outside the pinned compile manifest fails
  the tier-1 gate by name (recompile creep).
"""

import time

import pytest

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.batch_engine import BatchEngine
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.sampler import Sampler
from distributed_llama_tpu.runtime.speculative import AdaptiveK

K = 8

REP = [5, 9, 17, 3, 44, 9, 17, 3]
OPEN = [1, 17, 93, 4, 55, 201, 8, 41, 113, 29]


def _spec(seq_len=256, dim=64, n_layers=2):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=dim, hidden_dim=2 * dim,
                     n_layers=n_layers, n_heads=4, n_kv_heads=4,
                     vocab_size=256, seq_len=seq_len,
                     rope_type=RopeType.LLAMA).resolved()


def _tiny_drafter_spec(seq_len=256):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=32, hidden_dim=64,
                     n_layers=1, n_heads=2, n_kv_heads=2, vocab_size=256,
                     seq_len=seq_len, rope_type=RopeType.LLAMA).resolved()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


def _run(be, jobs, timeout=300):
    reqs = [be.submit(list(p), n, s, **kw) for p, n, s, kw in jobs]
    return [r.wait(timeout=timeout) for r in reqs], reqs


def _ab(be, jobs_fn, timeout=300):
    """Same schedule spec-off then spec-on (drafter live) on one engine."""
    k = be.spec_k
    try:
        be.spec_k = 0
        off = _run(be, jobs_fn(), timeout)
    finally:
        be.spec_k = k
    on = _run(be, jobs_fn(), timeout)
    return off, on


@pytest.fixture(scope="module")
def self_draft():
    """Target drafting for itself: accept is 1.0 on greedy rows by
    construction — the strongest exercise of the frontier bookkeeping."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=K, speculative=K,
                     draft_model=(spec, params))
    assert be.drafter is not None
    yield spec, params, be
    be.close()


# ------------------------------------------------------------- identity


def test_greedy_identity_with_model_drafter(self_draft):
    spec, params, be = self_draft

    def jobs():
        return [(OPEN, 32, _greedy(spec), {}),
                ([1] + REP * 4, 32, _greedy(spec), {})]

    (off, _), (on, reqs) = _ab(be, jobs)
    assert on == off
    assert sum(r.stats.spec_steps for r in reqs) >= 2
    assert sum(r.stats.spec_accepted for r in reqs) >= 8, (
        "the model drafter never meaningfully accepted — vacuous identity")


def test_seeded_stochastic_identity_with_model_drafter(self_draft):
    """Stochastic rows sample with the request's real coins; drafts come
    from the drafter's greedy argmax. Identity and final sampler state must
    hold regardless of what was proposed."""
    spec, params, be = self_draft

    def jobs():
        return [(OPEN, 32, Sampler(spec.vocab_size, temperature=0.8,
                                   topp=0.9, seed=42), {}),
                ([1] + REP * 4, 32,
                 Sampler(spec.vocab_size, temperature=0.02, topp=0.9,
                         seed=7), {})]

    (off, off_reqs), (on, reqs) = _ab(be, jobs)
    assert on == off
    for a, b in zip(off_reqs, reqs):
        assert a.sampler.state == b.sampler.state


def test_self_draft_accepts_every_greedy_draft():
    """First-principles oracle: when the drafter IS the target, every
    drafted token equals the target's greedy choice, so accepted == drafted
    on every verify turn of a greedy row — any miss is a frontier/catch-up
    bookkeeping defect, not a model property."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=1, tp=1, superstep=K, speculative=K,
                     pipeline=False, draft_model=(spec, params))
    try:
        (outs, reqs) = _run(be, [(OPEN, 32, _greedy(spec), {})])
        req = reqs[0]
        assert req.stats.spec_steps >= 3
        for n_out, drafted, accepted in req.stats.spec_turns:
            assert accepted == drafted, (n_out, drafted, accepted)
        # with full acceptance the stream advances drafted+1 per turn
        assert req.stats.spec_accepted >= 18
    finally:
        be.close()


def test_drafter_scan_matches_sequential_greedy():
    """Proposal-quality contract: the catch-up + draft scan must emit
    exactly the draft model's own sequential greedy continuation (the
    drafter's KV state after attach/catch-up is the sequential state)."""
    from distributed_llama_tpu.draft.drafter import ModelDrafter

    dspec = _tiny_drafter_spec()
    dparams = init_random_params(dspec, FloatType.Q40, seed=5)
    eng = Engine(dspec, dparams, tp=1)
    drafter = ModelDrafter(dspec, dparams, mesh=eng.mesh, slots=2,
                           target_spec=dspec, k_cap=K)
    prompt = list(OPEN)
    drafter.attach(0, prompt)
    drafts = drafter.propose_batch({0: 6})[0]
    seq_out, _ = eng.generate(list(prompt), 6, _greedy(dspec))
    assert drafts == seq_out, (drafts, seq_out)
    # accepted pushes advance the frontier for free (spec_tail hits) and a
    # fresh propose continues the same greedy stream
    for t in seq_out:
        drafter.push(0, t)
    st = drafter._rows[0]
    assert st.frontier == len(prompt) + 5  # 5 fed-back drafts' KV reused
    drafts2 = drafter.propose_batch({0: 4})[0]
    eng2 = Engine(dspec, dparams, tp=1)
    seq2, _ = eng2.generate(prompt + seq_out, 4, _greedy(dspec))
    assert drafts2 == seq2, (drafts2, seq2)


def test_mixed_model_and_ngram_rows_one_batch():
    """A row whose context exceeds the DRAFTER's (shorter) seq_len falls
    back to n-gram drafting while its neighbor keeps model drafts — in the
    same engine, same verify dispatches, identical output."""
    spec = _spec(seq_len=256)
    params = init_random_params(spec, FloatType.Q40, seed=11)
    dspec = _spec(seq_len=48)  # drafter context shorter than the target's
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=K, speculative=K,
                     draft_model=(dspec, params))
    try:
        long_prompt = [1, 2] + REP * 6  # 50 tokens: already past dseq-k

        # spy on the mux: last_src is cleared at detach, so capture the
        # per-row proposal sources as dispatches actually plan
        seen: set[str] = set()
        orig = be.proposer.propose_batch

        def spy(want):
            out = orig(want)
            seen.update(be.proposer.last_src[r] for r in out)
            return out

        be.proposer.propose_batch = spy

        def jobs():
            return [(OPEN, 32, _greedy(spec), {}),
                    (long_prompt, 32, _greedy(spec), {})]

        (off, _), (on, reqs) = _ab(be, jobs)
        assert on == off
        assert "model" in seen and "ngram" in seen, seen
    finally:
        be.close()


# ------------------------------------------------------------- adaptive k


class _StubAccept:
    """Drive AdaptiveK like the engine would, with a fixed true accept
    length: each turn drafts k_for(row) and accepts min(k, true)."""

    def __init__(self, ak: AdaptiveK, row: int, true_accept: int):
        self.ak, self.row, self.true = ak, row, true_accept
        self.ak.attach(row)
        self.probes = 0

    def turn(self):
        k = self.ak.k_for(self.row)
        if k <= 0:
            self.ak.tick(self.row)
            return 0
        self.probes += 1
        self.ak.observe(self.row, k, min(k, self.true))
        return k


def test_adaptive_k_full_accept_rides_the_cap():
    ak = AdaptiveK(8)
    st = _StubAccept(ak, 0, true_accept=99)
    ks = [st.turn() for _ in range(20)]
    assert ks[0] == 8 and all(k == 8 for k in ks), ks


def test_adaptive_k_zero_accept_disengages_with_slow_reprobe():
    ak = AdaptiveK(8)
    st = _StubAccept(ak, 0, true_accept=0)
    ks = [st.turn() for _ in range(120)]
    assert 0 in ks, "never disengaged"
    # after the initial collapse, probes are rare (the slow-reprobe
    # horizon) and tiny (smallest bucket)
    tail = ks[20:]
    assert sum(1 for k in tail if k > 0) <= len(tail) // 4, tail
    assert all(k <= 1 for k in tail), tail


def test_adaptive_k_partial_accept_settles_in_small_buckets():
    ak = AdaptiveK(8)
    st = _StubAccept(ak, 0, true_accept=2)
    ks = [st.turn() for _ in range(40)]
    tail = ks[10:]
    assert all(1 <= k <= 4 for k in tail), tail  # never back at the cap
    assert any(k >= 2 for k in tail)


def test_adaptive_k_detach_forgets_row():
    ak = AdaptiveK(8)
    ak.attach(0)
    ak.observe(0, 8, 0)
    ak.detach(0)
    assert 0 not in ak.stats()
    assert ak.k_for(0) == 8  # unattached rows get fixed-k behavior


# ------------------------------------------------- resume / preempt


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_durable_resume_with_live_drafter(self_draft, temperature):
    """A mid-stream failover re-admission (prompt ⊕ delivered, sampler
    fast-forwarded) must continue byte-identical with the drafter live —
    the proposer re-attaches whole and re-prefills its own KV."""
    spec, params, be = self_draft
    prompt, gen, cut = list(OPEN), 36, 11

    def sampler():
        return Sampler(spec.vocab_size, temperature, 0.9, 77)

    ref, _ = _run(be, [(prompt, gen, sampler(), {})])
    smp = sampler()
    smp.fast_forward(cut if temperature else 0)
    resumed = be.submit(prompt + ref[0][:cut], gen - cut, smp,
                        resume_tokens=cut)
    assert resumed.wait(timeout=300) == ref[0][cut:]


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_preemption_resumes_byte_identical_with_drafter(temperature):
    """ISSUE 15: the tenancy preemption path (slot handed to an
    interactive arrival, batch request re-admitted later) composes with a
    live drafter — detach/attach rides the same admission hooks."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    be = BatchEngine(spec, params, slots=1, tp=1, superstep=4, speculative=4,
                     draft_model=(spec, params))
    try:
        prompt, gen, seed = [1, 9, 9, 2], 48, 1234

        def sampler():
            return Sampler(spec.vocab_size, temperature, 0.9, seed)

        ref = be.submit(list(prompt), gen, sampler(),
                        klass="batch").wait(timeout=300)
        victim = be.submit(list(prompt), gen, sampler(), klass="batch")
        while len(victim.out) < 9:
            time.sleep(0.003)
        inter = be.submit([1, 2, 3], 4, _greedy(spec), klass="interactive")
        assert inter.wait(timeout=300) is not None
        out = victim.wait(timeout=300)
        assert victim.preemptions >= 1, "the preemption never engaged"
        assert out == ref
    finally:
        be.close()


def test_spec_stats_reports_proposer_and_per_row_k(self_draft):
    """The /v1/stats speculative block (BatchEngine.spec_stats): engine
    accept counters + proposer health + the adaptive per-row k
    breakdown."""
    spec, params, be = self_draft
    _run(be, [(OPEN, 16, _greedy(spec), {})])
    s = be.spec_stats()
    assert s["k"] == K
    assert s["proposer"]["model"] is True
    assert s["proposer"]["disabled"] is False
    assert "drafter" in s["proposer"]
    assert s["adaptive"]["k_cap"] == K
    assert s["adaptive"]["buckets"] == [1, 2, 4, 8]
    be.spec_k = 0
    assert be.spec_stats() is None
    be.spec_k = K


# ------------------------------------------------- degradation / manifest


def test_drafter_vocab_mismatch_degrades_to_ngram():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    bad = ModelSpec(arch_type=ArchType.LLAMA, dim=32, hidden_dim=64,
                    n_layers=1, n_heads=2, n_kv_heads=2, vocab_size=128,
                    seq_len=256, rope_type=RopeType.LLAMA).resolved()
    bparams = init_random_params(bad, FloatType.Q40, seed=5)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4, speculative=4,
                     draft_model=(bad, bparams))
    try:
        assert be.drafter is None  # load degraded, engine still serves
        out = be.submit([1] + REP * 3, 16, _greedy(spec)).wait(timeout=300)
        assert len(out) == 16
    finally:
        be.close()


def test_drafter_off_manifest_bucket_fails_gate():
    """ISSUE 15 CI satellite: a drafter scan-length bucket the scheduler
    never mints must fail the compile-manifest gate with the offending
    cache key named — adaptive-k churn is pinned, anything else is
    recompile creep."""
    from distributed_llama_tpu.analysis import compile_audit
    from distributed_llama_tpu.parallel.mesh import make_mesh

    pinned = compile_audit.load_manifest()
    assert pinned is not None, "perf/compile_manifest.json missing"
    dspec = _tiny_drafter_spec()
    dparams = init_random_params(dspec, FloatType.Q40, seed=5)
    audit = compile_audit.CompileAudit()
    with audit:
        from distributed_llama_tpu.draft.drafter import ModelDrafter

        drafter = ModelDrafter(dspec, dparams, mesh=make_mesh(tp=1),
                               slots=2, target_spec=dspec, k_cap=K)
        drafter._loop(7)  # a bucket no pinned scenario dispatches
    findings = compile_audit.diff_manifest(audit.manifest(), pinned)
    assert any("draft_scan[s=7]" in f.message for f in findings), (
        [f.message for f in findings])
    assert all(f.rule == "compile-manifest" for f in findings)
