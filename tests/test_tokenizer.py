"""Tokenizer, chat-template, EOS-detector, and sampler tests.

Mirrors the reference test strategy (src/tokenizer-test.cpp: template auto-detection +
EosDetector state machine cases) plus BPE merge behavior and xorshift sampler parity.
"""

import numpy as np

from distributed_llama_tpu.formats.tfile import TokenizerData
from distributed_llama_tpu.runtime.sampler import Sampler, _random_u32
from distributed_llama_tpu.tokenizer import (
    ChatItem,
    ChatTemplate,
    EosDetector,
    EosResult,
    TemplateType,
    Tokenizer,
)


def make_sp_tokenizer():
    """Sentencepiece-like vocab: 3 specials, 256 byte tokens, then merge pieces."""
    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [f"<0x{i:02X}>".encode() for i in range(256)]
    extra = [(b" ", -1.0), (b"h", -2.0), (b"e", -2.0), (b"l", -2.0), (b"o", -2.0),
             (b"he", -3.0), (b"ll", -4.0), (b"hell", -5.0), (b"hello", -6.0),
             (b" hello", -6.5)]
    scores = [0.0] * len(vocab)
    for piece, score in extra:
        vocab.append(piece)
        scores.append(score)
    return Tokenizer(TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2,
                                   max_token_length=8))


def test_encode_greedy_merges():
    tok = make_sp_tokenizer()
    ids = tok.encode("hello", add_bos=True)
    # bos, dummy-prefix space merged with hello -> " hello"
    assert ids[0] == tok.bos_id
    pieces = [tok.vocab[i] for i in ids[1:]]
    assert b"".join(pieces) == b" hello"
    assert pieces == [b" hello"]  # best merge chain reaches the full-word token


def test_encode_byte_fallback():
    tok = make_sp_tokenizer()
    ids = tok.encode("z")  # 'z' not in vocab -> byte fallback +3
    assert ids[-1] == ord("z") + 3


def test_encode_utf8_multibyte():
    tok = make_sp_tokenizer()
    ids = tok.encode("é")  # 2-byte codepoint, not in vocab -> two byte-fallback tokens
    raw = "é".encode()
    assert ids[-2:] == [raw[0] + 3, raw[1] + 3]


def test_decode_bos_space_strip():
    tok = make_sp_tokenizer()
    ids = tok.encode("hello", add_bos=True)
    assert tok.decode(ids) == "hello"  # leading dummy-space stripped after BOS


def test_decode_byte_tokens():
    tok = make_sp_tokenizer()
    ids = tok.encode("zq")
    assert tok.decode(ids).endswith("zq")


def test_chat_template_detection():
    # the three auto-detection cases of tokenizer-test.cpp:14-25
    t = ChatTemplate(TemplateType.UNKNOWN, "{%[INST]%}", "</s>")
    assert t.type == TemplateType.LLAMA2
    t = ChatTemplate(TemplateType.UNKNOWN, "{{'<|start_header_id|>'}}", "<|eot_id|>")
    assert t.type == TemplateType.LLAMA3
    t = ChatTemplate(TemplateType.UNKNOWN, "<|user|>...", "</s>")
    assert t.type == TemplateType.ZEPHYR
    t = ChatTemplate(TemplateType.UNKNOWN, "x<|im_start|>y", "<|im_end|>")
    assert t.type == TemplateType.CHATML


def test_chat_template_llama3_render():
    t = ChatTemplate(TemplateType.LLAMA3, None, "<|eot_id|>")
    out = t.generate([ChatItem("system", "sys"), ChatItem("user", "hi")])
    assert out == ("<|start_header_id|>system<|end_header_id|>\n\nsys<|eot_id|>"
                   "<|start_header_id|>user<|end_header_id|>\n\nhi<|eot_id|>"
                   "<|start_header_id|>assistant<|end_header_id|>\n\n")


def test_chat_template_llama2_system_fold():
    t = ChatTemplate(TemplateType.LLAMA2, None, "</s>")
    out = t.generate([ChatItem("system", "S"), ChatItem("user", "U")])
    assert out == "[INST] <<SYS>>\nS\n<</SYS>>\n\nU [/INST]</s>"


def test_eos_detector_exact_stop():
    d = EosDetector(2, [b"<stop>"])
    assert d.append(5, b"<stop>") == EosResult.EOS
    assert d.get_delta() is None


def test_eos_detector_split_across_tokens():
    d = EosDetector(2, [b"<stop>"])
    assert d.append(5, b"<st") == EosResult.MAYBE_EOS
    assert d.append(6, b"op>") == EosResult.EOS
    assert d.get_delta() is None


def test_eos_detector_false_alarm_flushes():
    d = EosDetector(2, [b"<stop>"])
    assert d.append(5, b"<st") == EosResult.MAYBE_EOS
    assert d.append(6, b"uck") == EosResult.NOT_EOS
    assert d.get_delta() == b"<stuck"


def test_eos_detector_padding_left():
    # text before the stop within the padding window still matches
    d = EosDetector(2, [b"<stop>"], padding_left=2)
    assert d.append(5, b"ab<stop>") == EosResult.EOS
    assert d.get_delta() == b"ab"


def test_eos_detector_eos_token_short_circuit():
    d = EosDetector(2, [b"<stop>"])
    assert d.append(7, b"text") == EosResult.NOT_EOS
    d.clear()
    assert d.append(2, b"</s>") == EosResult.EOS
    assert d.get_delta() is None


def test_eos_detector_overlong_buffer_not_eos():
    d = EosDetector(2, [b"<stop>"])
    assert d.append(5, b"this is much longer than the stop") == EosResult.NOT_EOS


def test_xorshift_parity():
    """xorshift* must match the reference algorithm (utils.cpp:79-90) step by step."""
    state = np.uint64(12345)

    def c_impl(s):
        s ^= s >> 12
        s &= 0xFFFFFFFFFFFFFFFF
        s ^= (s << 25) & 0xFFFFFFFFFFFFFFFF
        s ^= s >> 27
        return s, ((s * 0x2545F4914F6CDD1D) & 0xFFFFFFFFFFFFFFFF) >> 32

    s_py = 12345
    for _ in range(10):
        state, got = _random_u32(state)
        s_py, want = c_impl(s_py)
        assert got == want and int(state) == s_py


def test_sampler_greedy():
    s = Sampler(10, temperature=0.0)
    logits = np.zeros(10, np.float32)
    logits[7] = 5.0
    assert s.sample(logits) == 7


def test_sampler_seeded_deterministic():
    logits = np.random.RandomState(0).randn(100).astype(np.float32) * 3
    a = Sampler(100, temperature=0.8, topp=0.9, seed=42)
    b = Sampler(100, temperature=0.8, topp=0.9, seed=42)
    seq_a = [a.sample(logits.copy()) for _ in range(20)]
    seq_b = [b.sample(logits.copy()) for _ in range(20)]
    assert seq_a == seq_b
    # and topp restricts to high-probability tokens
    probs = np.exp(logits / 0.8 - np.max(logits / 0.8))
    probs /= probs.sum()
    order = np.argsort(-probs)
    top_mass, nucleus = 0.0, set()
    for i in order:
        nucleus.add(int(i))
        top_mass += probs[i]
        if top_mass > 0.9:
            break
    assert set(seq_a) <= nucleus


def test_sampler_topp_off_uses_mult():
    logits = np.zeros(4, np.float32)
    s = Sampler(4, temperature=1.0, topp=0.0, seed=7)
    counts = np.bincount([s.sample(logits.copy()) for _ in range(200)], minlength=4)
    assert (counts > 20).all()  # roughly uniform
