"""On-device decode loop tests (8-device CPU mesh via conftest).

The scan-based device loop must reproduce the host generation loop exactly under greedy
sampling (the host loop is itself tied to the reference's generate driver), and
device_sample must honor the reference Sampler's temperature/top-p semantics."""

import numpy as np
import jax
import jax.numpy as jnp

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.quants import FloatType
from distributed_llama_tpu.runtime.device_loop import device_sample
from distributed_llama_tpu.runtime.engine import Engine
from distributed_llama_tpu.runtime.sampler import Sampler


def _spec():
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                     n_heads=4, n_kv_heads=4, vocab_size=256, seq_len=64,
                     rope_type=RopeType.LLAMA).resolved()


def test_device_loop_matches_host_greedy():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    eng = Engine(spec, params, tp=2)
    prompt = [1, 7, 23, 5]
    sampler = Sampler(spec.vocab_size, temperature=0.0)
    want, _ = eng.generate(list(prompt), 12, sampler)

    eng.reset()
    got, stats = eng.generate_chunked(list(prompt), 12, sampler, chunk=5)
    assert got == want
    assert stats.generated_tokens == 12
    assert stats.prompt_tokens == len(prompt)

    # continuation state: pos advanced exactly by prompt-1 prefill + generated count
    assert eng.pos == len(prompt) - 1 + 12


def test_device_loop_stop_check_midchunk():
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    eng = Engine(spec, params, tp=1)
    sampler = Sampler(spec.vocab_size, temperature=0.0)
    prompt = [1, 7, 23, 5]
    full, _ = eng.generate(list(prompt), 12, sampler)
    stop_at = full[3]

    eng.reset()
    got, _ = eng.generate_chunked(list(prompt), 12, sampler, chunk=8,
                                  stop_check=lambda t: t == stop_at)
    assert got == full[:4]
    assert eng.pos == len(prompt) - 1 + 4


def test_device_loop_context_end_tail():
    """Near seq_len the chunked loop must clamp to the context like the host loop
    (finishing via the per-token fallback, with no tail-sized recompile)."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    eng = Engine(spec, params, tp=1)
    sampler = Sampler(spec.vocab_size, temperature=0.0)
    prompt = [1, 7, 23, 5]
    room = spec.seq_len - (len(prompt) - 1)
    want, _ = eng.generate(list(prompt), room + 10, sampler)

    eng.reset()
    got, _ = eng.generate_chunked(list(prompt), room + 10, sampler, chunk=16)
    assert got == want
    assert eng.pos <= spec.seq_len
    # only the full-size chunk (plus mode) was ever compiled for the scan loop
    assert all(c == 16 for c, _, _ in eng._decode_loops)


def test_device_sample_greedy_and_topp():
    key = jax.random.PRNGKey(0)
    logits = jnp.asarray(np.random.RandomState(0).randn(128).astype(np.float32)) * 3

    greedy = device_sample(logits, key, jnp.float32(0.0), jnp.float32(0.9))
    assert int(greedy) == int(np.argmax(np.asarray(logits)))

    # top-p: every sampled token must lie in the nucleus the host sampler would build
    probs = np.exp(np.asarray(logits) / 0.7)
    probs /= probs.sum()
    order = np.argsort(-probs)
    csum = np.cumsum(probs[order])
    nucleus = set(order[: int(np.argmax(csum > 0.8)) + 1].tolist())
    for i in range(20):
        t = int(device_sample(logits, jax.random.fold_in(key, i), jnp.float32(0.7),
                              jnp.float32(0.8)))
        assert t in nucleus

    # topp >= 1 takes the plain multinomial branch and still returns a valid id
    t = int(device_sample(logits, key, jnp.float32(1.3), jnp.float32(1.0)))
    assert 0 <= t < 128


def test_device_loop_with_sp_striped_matches_host():
    """Chunked device-loop generation on an sp=2 mesh (striped deferred cache)
    must reproduce the tp-only host loop exactly — the loop carries the sharded
    caches through its scan across both cache disciplines."""
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    sampler = Sampler(spec.vocab_size, temperature=0.0)
    prompt = [1, 7, 23, 5]

    ref = Engine(spec, params, tp=1)
    want, _ = ref.generate(list(prompt), 12, sampler)

    for cw in (None, "inscan"):  # None = auto (deferred/striped)
        eng = Engine(spec, params, tp=2, sp=2, cache_write=cw)
        got, _ = eng.generate_chunked(list(prompt), 12,
                                      Sampler(spec.vocab_size, temperature=0.0),
                                      chunk=5)
        assert got == want, (cw, got, want)
