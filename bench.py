#!/usr/bin/env python
"""Benchmark: Llama-2-7B-shaped Q40 single-chip decode throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}. Extra fields:
    weight_gb      — HBM bytes decode must stream per token (weights + scales)
    achieved_gbps  — weight_gb / measured step time (lower bound on attained bandwidth)
    ms_per_token   — mean decode step wall time (over --steps dispatches)

Baseline: the reference's best published single-node Llama-2-7B number — 101.81 ms/token
(9.82 tok/s) on a GCP c3d-highcpu-30 VM (reference README.md:129-131, BASELINE.md).
vs_baseline > 1.0 means this framework on one TPU chip beats that.

Weights are synthesized directly on device in the 4-bit split-plane kernel layout
(random packed nibbles + f16 block scales — the reference's exact Q40 HBM density,
0.5625 B/weight, src/quants.hpp:17-20). Decode cost is layout/bandwidth-bound and
independent of weight values, so this measures exactly what a converted checkpoint
costs. --layout i8 benches the older int8-plane kernel for comparison.

Usage: python bench.py [--small] [--steps N] [--tp N] [--layout i4p|i8]
                       [--device-loop N] [--window W]
                       [--batch B --superstep K]   (serving throughput mode)
                       [--workload shared-prefix]  (prefix-cache TTFT mode)
                       [--workload chaos]          (fault-injection resilience mode)

--workload shared-prefix drives the BatchEngine scheduler with a synthetic
multi-request workload (one common system prompt + distinct user turns) twice
— prefix cache ON vs OFF — and reports per-request TTFT p50/p95 for both plus
the cache's measured `prefix_hit_rate` (docs/PREFIX_CACHE.md). This is a
scheduler/cache workload bench (random Q40 weights via init_random_params),
not a kernel-layout bench.

--batch B runs the BatchEngine's hot path — the batched K-step device loop
(runtime/device_loop.py make_batched_decode_loop) over B cache rows — and
reports `aggregate_decode_tok_s` (B rows x K tokens per dispatch / wall time)
alongside per-stream tok/s. Decode is HBM-bound, so aggregate throughput
should scale ~linearly with B until the batch turns compute-bound; the
serving trajectory tracks B ∈ {1, 4, 8}.
"""

import argparse
import functools
import json
import os
import sys
import time
import zlib

import jax

# sitecustomize imports jax before this script runs, freezing the platform choice;
# honor an explicit JAX_PLATFORMS from the caller (e.g. cpu CI smoke runs)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from distributed_llama_tpu.models.params import (  # noqa: E402
    block_tensor_shapes, decode_stream_bytes)
from distributed_llama_tpu.models.spec import (  # noqa: E402
    ArchType, HiddenAct, ModelSpec, RopeType)
from distributed_llama_tpu.ops.rope import RopeTables  # noqa: E402
from distributed_llama_tpu.parallel.mesh import make_mesh  # noqa: E402
from distributed_llama_tpu.parallel.tp import (  # noqa: E402
    init_sharded_kv_cache, make_sharded_forward, shard_params)
from distributed_llama_tpu.obs import trace as obs_trace  # noqa: E402
from distributed_llama_tpu.ops.matmul import kernel_selections  # noqa: E402
from distributed_llama_tpu.ops.pallas_prologue import (  # noqa: E402
    prologue_supported)
from distributed_llama_tpu.fleet.client import completion_request  # noqa: E402
from distributed_llama_tpu.quants import QK, FloatType, QTensor  # noqa: E402

BASELINE_TOK_S = 1000.0 / 101.81  # Llama-2-7B, 1x GCP c3d VM (reference README.md:131)

# --- warm-runner handoff protocol (shared with perf/persistent_bench.py, which
# imports these — single source of truth for paths and expiries) ---
REPO_DIR = os.path.dirname(os.path.abspath(__file__))

# Persistent compilation cache: the half-alive tunnel's windows close faster
# than a cold bench can init + compile (~20-40s); once the warm runner has
# compiled a config, a fresh driver bench.py reuses the serialized executable
# and only pays init. Harmless when cold (a miss just compiles normally).
#
# Called from main() (and the warm runner), NOT at import: enabling it as an
# import side effect leaked the cache into every importer — pytest's
# collection imports bench (tests/test_bench_synth.py), which switched the
# WHOLE test process onto the cache and poisoned the paged-cache tests:
# executables whose programs embed per-layer pure_callbacks (paged cold
# attention) round-trip through serialization with stale host-callback
# bindings, yielding garbage logits on a warm-cache run (flaky
# test_paged_server_multi_turn_consistency) and an occasional
# munmap_chunk abort at interpreter teardown.
def enable_compilation_cache():
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(REPO_DIR, "perf", ".jax_cache"))
    except Exception as _e:  # older jax without the knob: run uncached
        print(f"# compilation cache unavailable: {_e}", file=sys.stderr)
    else:
        try:  # tuning knob only — cache stays active at the default threshold
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass
# runner -> driver result. DLT_HANDOFF_PATH overrides so tests exercise the
# protocol against a scratch file instead of clobbering (and deleting!) a real
# runner-published hardware result — which a test teardown did on 2026-07-31.
HANDOFF_LATEST = (os.environ.get("DLT_HANDOFF_PATH")
                  or os.path.join(REPO_DIR, "BENCH_latest.json"))
# Git-TRACKED mirror of the handoff: the 03:15 UTC container restart wiped
# every gitignored file including BENCH_latest.json, losing the published
# window-2 result from the only process that had one. The runner publishes to
# both and commits the mirror, so a restart (or a dead tunnel at driver-capture
# time) can no longer erase the round's hardware evidence. Tests point
# DLT_HANDOFF_PATH at a scratch file, which also disables the mirror.
_tracked_env = os.environ.get("DLT_HANDOFF_TRACKED_PATH")
if _tracked_env is not None:
    HANDOFF_TRACKED = _tracked_env or None  # "" disables the mirror (tests)
else:
    # independent of DLT_HANDOFF_PATH: relocating the primary handoff must not
    # silently turn the restart defense off
    HANDOFF_TRACKED = os.path.join(REPO_DIR, "perf", "BENCH_handoff.json")
# driver -> runner "pause"; the literal relative path is mirrored in
# perf/_bench_lib.sh's touch_sentinel (shell can't import this constant without
# paying a jax import) — keep the two in sync
SENTINEL = os.path.join(REPO_DIR, "perf", ".driver_bench_active")
SENTINEL_EXPIRY_S = 1800  # crashed driver's sentinel stops pausing the runner
BUSY_MARKER = os.path.join(REPO_DIR, "perf", ".warm_runner_busy")  # runner -> driver "mid-config"
MAX_HANDOFF_AGE_S = 20 * 3600  # a handoff result older than this round is refused
HANDOFF_PREFER_AGE_S = 2 * 3600  # fresh enough to prefer over waiting out a busy runner


def read_handoff():
    """Parse the freshest readable handoff (BENCH_latest.json, then the tracked
    mirror); returns (payload, age_s) or (None, None) when neither exists or
    parses (timestamps coerced — hand-edited string values must degrade, not
    crash)."""
    best = (None, None)
    for path in (HANDOFF_LATEST, HANDOFF_TRACKED):
        if not path:
            continue
        try:
            with open(path) as f:
                payload = json.load(f)
            age = time.time() - float(payload["captured_unix"])
        except (OSError, KeyError, ValueError, TypeError):
            continue
        if age < -3600:
            continue  # far-future stamp: corrupt/hand-edited, never serve it
        age = max(age, 0.0)  # modest clock skew must not beat every real file
        if best[1] is None or age < best[1]:
            best = (payload, age)
    return best

def _pct(sorted_vals, q):
    """Percentile from an ascending list (None when empty) — p50/p95/p99
    share one indexing convention across every workload report."""
    if not sorted_vals:
        return None
    return sorted_vals[min(int(len(sorted_vals) * q), len(sorted_vals) - 1)]


def _pct_ms(sorted_vals, q):
    v = _pct(sorted_vals, q)
    return round(v * 1e3, 2) if v is not None else None


def write_latency_log(path, samples):
    """--latency-log out.jsonl: raw per-request samples (request id, ttft,
    e2e, tokens, replica) so offline percentile analysis doesn't depend on
    the pre-chosen p50/p95/p99 cuts."""
    with open(path, "w") as f:
        for s in samples:
            f.write(json.dumps(s) + "\n")
    print(f"# wrote {len(samples)} latency samples to {path}",
          file=sys.stderr)


LLAMA2_7B = dict(arch_type=ArchType.LLAMA, dim=4096, hidden_dim=11008, n_layers=32,
                 n_heads=32, n_kv_heads=32, vocab_size=32000, seq_len=2048,
                 rope_type=RopeType.LLAMA)
SMALL = dict(arch_type=ArchType.LLAMA, dim=512, hidden_dim=1408, n_layers=4,
             n_heads=8, n_kv_heads=8, vocab_size=32000, seq_len=256,
             rope_type=RopeType.LLAMA)

# overhead-bound CI geometry (the fault-matrix / pipeline-overlap tiny
# model, longer context): per-dispatch overhead dominates the matmul
# columns, which is the CPU stand-in for the TPU's HBM-bandwidth-bound
# decode — the regime where a (B, 1+k) verify block costs ~one decode step.
# The repetition workload defaults to it on CPU: SMALL's dim-512 x 32k-vocab
# matmuls are COMPUTE-bound on a 2-core box (a T-wide dispatch costs ~T
# steps), which structurally underreports the speculative win the TPU sees.
TINY_REP = dict(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
                n_heads=4, n_kv_heads=4, vocab_size=256, seq_len=512,
                rope_type=RopeType.LLAMA)

# BASELINE.json config counterparts that fit (or are layer-scaled to fit) one 16 GB
# chip. MoE geometries keep the real per-layer shape — the honest per-layer decode
# cost — with n_layers cut to fit HBM; the metric name records the cut.
ARCHS = {
    "llama2_7b": LLAMA2_7B,
    "tinyllama_1_1b": dict(arch_type=ArchType.LLAMA, dim=2048, hidden_dim=5632,
                           n_layers=22, n_heads=32, n_kv_heads=4, vocab_size=32000,
                           seq_len=2048, rope_type=RopeType.LLAMA),
    "llama3_8b": dict(arch_type=ArchType.LLAMA, dim=4096, hidden_dim=14336,
                      n_layers=32, n_heads=32, n_kv_heads=8, vocab_size=128256,
                      seq_len=2048, rope_theta=500000.0, rope_type=RopeType.LLAMA),
    "mixtral_8x7b_l8": dict(arch_type=ArchType.MIXTRAL, dim=4096, hidden_dim=14336,
                            n_layers=8, n_heads=32, n_kv_heads=8, vocab_size=32000,
                            seq_len=2048, n_experts=8, n_active_experts=2,
                            rope_type=RopeType.FALCON),
    "grok1_l2": dict(arch_type=ArchType.GROK1, dim=6144, hidden_dim=32768,
                     n_layers=2, n_heads=48, n_kv_heads=8, vocab_size=131072,
                     seq_len=2048, n_experts=8, n_active_experts=2,
                     hidden_act=HiddenAct.GELU, rope_type=RopeType.FALCON),
}


# jax.random.randint generates uint32 random bits, a 4x-the-final-bytes device
# transient for narrow dtypes. The round-5 merged matvec groups stack layers AND
# group members into one tensor (w13 at 7B i8: 32x22016x4096 = 2.9 GB final,
# 11.6 GB transient), which RESOURCE_EXHAUSTs the chip during synthesis — the
# r5 matrix's --layout i8 failure in a fresh process. Cap the transient by
# generating in slices along axis 0 into a donated (in-place) buffer.
_RAND_TRANSIENT_BUDGET = 1 << 30  # max uint32 bytes per generation call


@functools.partial(jax.jit, donate_argnums=0, static_argnames="axis")
def _fill_slice(buf, chunk, i, axis=0):
    return jax.lax.dynamic_update_slice_in_dim(buf, chunk, i, axis=axis)


def _randint_chunked(key, shape, lo, hi, dtype):
    import math

    if 4 * math.prod(shape) <= _RAND_TRANSIENT_BUDGET or len(shape) < 2:
        return jax.random.randint(key, shape, lo, hi, dtype)
    row_bytes = 4 * math.prod(shape[1:])
    if row_bytes > _RAND_TRANSIENT_BUDGET:
        # one axis-0 slice still blows the budget (MoE (L, E, N, K) stacks):
        # recurse per slice
        buf = jnp.zeros(shape, dtype)
        for i in range(shape[0]):
            key, sub = jax.random.split(key)
            chunk = _randint_chunked(sub, shape[1:], lo, hi, dtype)
            buf = _fill_slice(buf, chunk[None], i)
            del chunk
        return buf
    # maximal slabs under the budget — NOT one dispatch per row (a (131072, d)
    # wcls would otherwise make 131k tunnel round-trips)
    rows_per = max(1, _RAND_TRANSIENT_BUDGET // row_bytes)
    buf = jnp.zeros(shape, dtype)
    for i in range(0, shape[0], rows_per):
        key, sub = jax.random.split(key)
        n = min(rows_per, shape[0] - i)
        chunk = jax.random.randint(sub, (n, *shape[1:]), lo, hi, dtype)
        buf = _fill_slice(buf, chunk, i)
        del chunk
    return buf


def synth_q40(key, shape, layout: str):
    """Random Q40 tensor synthesized on device, already in the kernel's layout."""
    out, in_ = shape[-2], shape[-1]
    lead = shape[:-2]
    k1, k2 = jax.random.split(key)
    if layout == "i4p":
        data = _randint_chunked(k1, (*lead, out, in_ // 2), 0, 256, jnp.uint8)
        scales = jax.lax.bitcast_convert_type(
            (jax.random.uniform(k2, (*lead, out, in_ // QK), jnp.float32) * 0.01
             + 0.001).astype(jnp.float16), jnp.int16)  # i4p carries f16 BIT PATTERNS
        return QTensor(FloatType.Q40, data, scales, layout="i4p")
    if layout == "i8":
        vals = _randint_chunked(k1, (*lead, out, in_), -8, 8, jnp.int8)
        scales = (jax.random.uniform(k2, (*lead, out, in_ // QK), jnp.float32) * 0.01
                  + 0.001)
        return QTensor(FloatType.Q40, vals, scales, layout="i8")
    packed = _randint_chunked(k1, (*lead, out, in_ // QK, 16), 0, 256, jnp.uint8)
    scales = (jax.random.uniform(k2, (*lead, out, in_ // QK), jnp.float32) * 0.01
              + 0.001).astype(jnp.float16)
    return QTensor(FloatType.Q40, packed, scales)


def synth_params(spec: ModelSpec, layout: str, fuse: bool = True, tp: int = 1,
                 keep_gate_pair: bool = False):
    from distributed_llama_tpu.models.params import _FUSE_GROUPS
    from distributed_llama_tpu.parallel.sharding import effective_kv_heads

    key = jax.random.PRNGKey(0)
    shapes = dict(block_tensor_shapes(spec))
    if fuse:
        # merged matvec groups: synthesize the fused shapes directly (random
        # weights need no interleaving), derived from the canonical
        # models/params.py _FUSE_GROUPS table so bench measures the same fusion
        # production applies — including its eligibility rules (QKV fusion is
        # skipped under KV-head replication, which rewrites wk/wv at shard time)
        for fused_name, members in _FUSE_GROUPS.items():
            if not all(n in shapes for n in members):
                continue
            if fused_name == "wqkv" and effective_kv_heads(spec, tp) != spec.n_kv_heads:
                continue
            if fused_name == "w13" and keep_gate_pair:
                # the gated-epilogue kernel fuses across the SEPARATE w1/w3
                # pair (prepare_for_pallas keep_gate_pair) — merging them
                # here would shape-gate it off in every --fused-matmul run
                continue
            lead = shapes[members[0]][0][:-2]  # MoE stacks carry an E axis
            rows = sum(shapes[n][0][-2] for n in members)
            in_dim = shapes[members[0]][0][-1]
            shapes[fused_name] = (((*lead, rows, in_dim)), True)
            for n in members:
                del shapes[n]
    blocks = {}
    for name, (shape, quantized) in shapes.items():
        key, sub = jax.random.split(key)
        full = (spec.n_layers, *shape)
        if quantized:
            blocks[name] = synth_q40(sub, full, layout)
            if name in _FUSE_GROUPS:
                import dataclasses

                # stamp the interleave provenance shard_params validates
                blocks[name] = dataclasses.replace(blocks[name], row_groups=tp)
        else:
            blocks[name] = jnp.ones(full, jnp.float32)
    key, k1, k2 = jax.random.split(key, 3)
    return {
        "embedding": jax.random.normal(k1, (spec.vocab_size, spec.dim), jnp.float32) * 0.02,
        "blocks": blocks,
        "rms_final": jnp.ones((spec.dim,), jnp.float32),
        "wcls": synth_q40(k2, (spec.vocab_size, spec.dim), layout),
    }




def shared_prefix_workload(args, spec):
    """--workload shared-prefix: TTFT with the prefix cache on vs off.

    One warm request establishes the shared prefix, then `--requests - 1`
    followers (same system prompt, distinct user turns) are submitted
    concurrently; TTFT is submit() -> first on_token. The identical schedule
    runs against a cache-on and a cache-off BatchEngine; compiled shapes are
    warmed by the leading request in both, so the delta isolates what the
    cache buys: the followers' shared-prefix prefill."""
    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.obs import flight as obs_flight
    from distributed_llama_tpu.quants import FloatType as _FTy
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    n_req = max(args.requests, 2)
    gen = 4  # decoded tokens per request: enough to stream, TTFT-dominated
    shared_len = args.shared_prefix
    if shared_len + 8 + gen >= spec.seq_len:
        shared_len = spec.seq_len - 8 - gen
    assert shared_len >= 16, f"seq_len {spec.seq_len} too small for the workload"
    rng = np.random.default_rng(0)
    shared = [1] + [int(t) for t in
                    rng.integers(2, spec.vocab_size, shared_len - 1)]
    prompts = [shared + [2 + i, 3 + i, 4 + i] for i in range(n_req)]
    params = init_random_params(spec, _FTy.Q40, seed=0)
    # default: every follower gets a slot immediately, so TTFT isolates the
    # prefill the cache removes instead of queue wait behind busy slots
    B = args.batch if args.batch > 0 else min(max(n_req - 1, 2), 8)
    # flight recorder: per-request engine-side timelines give the E2E
    # percentiles and the --latency-log samples without per-request threads.
    # The finally guarantees the process-global recorder is removed and the
    # samples gathered so far are flushed even when a request fails mid-run.
    rec = obs_flight.install(max(4 * n_req, 64))
    samples = []
    out = {}
    try:
        # three arms on the identical schedule: "on" = paged KV + directory
        # (the default serving config), "off" = cache disabled, "dense" =
        # the --no-paged-kv contiguous layout whose admission seed SCATTERS
        # pool rows host→device — the baseline the seed_bytes column
        # compares against (docs/PAGED_KV.md)
        for label, on, paged in (("on", True, True), ("off", False, True),
                                 ("dense", True, False)):
            # the dense arm exists for the seed-cost columns only (its TTFT
            # is not reported): a warm + 2 seeded followers suffice, keeping
            # the 3-arm bench's wall time near the old 2-arm run's
            arm_req = n_req if label != "dense" else min(n_req, 3)
            be = BatchEngine(spec, params, slots=B,
                             superstep=max(args.superstep, 1), tp=args.tp,
                             prefix_cache=on, paged_kv=paged)
            try:
                be.generate(list(prompts[0]), gen,
                            Sampler(spec.vocab_size, temperature=0.0))
                ttfts = {}
                t0s = {}

                def on_tok(i):
                    def cb(_t, i=i):
                        if i not in ttfts:
                            ttfts[i] = time.perf_counter() - t0s[i]
                    return cb

                reqs = []
                for i in range(1, arm_req):
                    t0s[i] = time.perf_counter()
                    reqs.append(be.submit(
                        list(prompts[i]), gen,
                        Sampler(spec.vocab_size, temperature=0.0),
                        on_token=on_tok(i), rid=f"bench-{label}-{i}"))
                t_all0 = time.perf_counter()
                for r in reqs:
                    r.wait(timeout=600)
                e2e = time.perf_counter() - t_all0
                # per-request E2E from the flight recorder (submit ->
                # engine finish), the per-request number the wall clock
                # above can't give
                req_e2e = []
                for i, r in enumerate(reqs, start=1):
                    fr = rec.get(f"bench-{label}-{i}") or {}
                    if fr.get("e2e_ms") is not None:
                        req_e2e.append(fr["e2e_ms"] / 1e3)
                    samples.append({"request_id": f"bench-{label}-{i}",
                                    "cache": label,
                                    "tenant": "default",
                                    "class": "interactive",
                                    "ttft_s": ttfts.get(i),
                                    "e2e_s": fr.get("e2e_ms", 0.0) / 1e3
                                    or None,
                                    "tokens": len(r.out), "replica": None})
                req_e2e.sort()
                lat = sorted(ttfts.values())
                out[label] = {
                    "ttft_p50_ms": _pct_ms(lat, 0.50),
                    "ttft_p95_ms": _pct_ms(lat, 0.95),
                    "ttft_p99_ms": _pct_ms(lat, 0.99),
                    "e2e_p99_ms": _pct_ms(req_e2e, 0.99),
                    "e2e_s": round(e2e, 3),
                }
                out[label]["prefix_seed_ms"] = round(be.seed_ms, 3)
                out[label]["seed_bytes_transferred"] = be.seed_bytes
                if on and paged:
                    st = be.prefix_cache.stats()
                    out["prefix_hit_rate"] = round(st["hit_rate"], 3)
                    out["lookup_hit_rate"] = round(st["lookup_hit_rate"], 3)
                    out["hit_tokens"] = st["hit_tokens"]
                    out["pool_blocks"] = st["pool_blocks"]
                    # ISSUE 12 acceptance, asserted IN-RUN: an admission
                    # with a radix prefix hit moves ZERO host→device KV
                    # bytes on the paged path (block-table remap only)
                    assert st["hit_tokens"] > 0, "no radix hit in the run"
                    assert be.seed_bytes == 0, (
                        f"paged admission moved {be.seed_bytes} KV bytes "
                        "host→device (remap must move none)")
                elif on and not paged:
                    st = be.prefix_cache.stats()
                    assert st["hit_tokens"] == 0 or be.seed_bytes > 0, (
                        "dense baseline seeded without any byte transfer?")
            finally:
                be.close()
    finally:
        obs_flight.uninstall()
        if args.latency_log and samples:
            write_latency_log(args.latency_log, samples)
    print(json.dumps({
        "metric": "shared_prefix_ttft_p50_ms",
        "value": out["on"]["ttft_p50_ms"], "unit": "ms", "vs_baseline": None,
        "ttft_p95_ms": out["on"]["ttft_p95_ms"],
        "ttft_p99_ms": out["on"]["ttft_p99_ms"],
        "e2e_p99_ms": out["on"]["e2e_p99_ms"],
        "ttft_off_p50_ms": out["off"]["ttft_p50_ms"],
        "ttft_off_p95_ms": out["off"]["ttft_p95_ms"],
        "ttft_off_p99_ms": out["off"]["ttft_p99_ms"],
        "ttft_speedup_p50": round(
            out["off"]["ttft_p50_ms"] / max(out["on"]["ttft_p50_ms"], 1e-9), 3),
        "e2e_s_on": out["on"]["e2e_s"], "e2e_s_off": out["off"]["e2e_s"],
        "prefix_hit_rate": out["prefix_hit_rate"],
        "lookup_hit_rate": out["lookup_hit_rate"],
        "hit_tokens": out["hit_tokens"], "pool_blocks": out["pool_blocks"],
        # paged-vs-dense admission seeding cost (docs/PAGED_KV.md): the
        # paged remap moves ZERO KV bytes (asserted above); the dense
        # scatter baseline pays the full fetched span per seeded admission
        "prefix_seed_ms": out["on"]["prefix_seed_ms"],
        "seed_bytes_transferred": out["on"]["seed_bytes_transferred"],
        "prefix_seed_ms_dense": out["dense"]["prefix_seed_ms"],
        "seed_bytes_dense": out["dense"]["seed_bytes_transferred"],
        "requests": n_req, "shared_prefix": shared_len, "batch": B,
        "superstep": max(args.superstep, 1),
    }))


def long_context_workload(args):
    """--workload shared-prefix --long-context: the KV-capacity↔slot-count
    decoupling demo (docs/PAGED_KV.md). A 4-slot engine gets a device pool
    holding ~1.25 contexts' worth of blocks — the DENSE layout at the same
    KV byte budget would cap every slot at ~pool/4 tokens — and ONE request
    runs a context ~3x that dense-equivalent per-slot capacity to the
    context wall, while short co-batched requests keep being served. The
    run FAILS (nonzero exit via assert) if the long request cannot finish
    at full length."""
    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.quants import FloatType as _FTy
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    slots, bt = 4, 16
    spec = ModelSpec(**dict(TINY_REP, seq_len=1024)).resolved()
    w = spec.seq_len // bt  # blocks per full context
    pool_blocks = w + w // 4 + 2  # ~1.25 contexts + scratch/spare
    params = init_random_params(spec, _FTy.Q40, seed=0)
    be = BatchEngine(spec, params, slots=slots, superstep=max(args.superstep, 1),
                     tp=args.tp, kv_block_tokens=bt, kv_pool_blocks=pool_blocks)
    assert be.kv_pool is not None
    dense_equiv_per_slot = pool_blocks * bt // slots
    rng = np.random.default_rng(0)
    long_prompt = [1] + [int(t) for t in
                         rng.integers(2, spec.vocab_size, 799)]
    gen = spec.seq_len - len(long_prompt)  # decode to the context wall
    try:
        t0 = time.perf_counter()
        req = be.submit(list(long_prompt), gen,
                        Sampler(spec.vocab_size, temperature=0.0))
        shorts = [be.submit([1, 7 + i, 9], 8,
                            Sampler(spec.vocab_size, temperature=0.0))
                  for i in range(3)]
        out = req.wait(timeout=1200)
        for r in shorts:
            r.wait(timeout=1200)
        dt = time.perf_counter() - t0
        ctx = len(long_prompt) + len(out)
        assert req.finish == "length" and ctx >= spec.seq_len, (
            req.finish, ctx)
        assert ctx > dense_equiv_per_slot, "demo geometry broken"
        elem = be._eng.k_cache.dtype.itemsize
        blk_bytes = (2 * spec.n_layers * spec.n_kv_heads * bt
                     * spec.head_size * elem)
        print(json.dumps({
            "metric": "long_context_tokens", "value": ctx, "unit": "tokens",
            "vs_baseline": None,
            "dense_equiv_per_slot_tokens": dense_equiv_per_slot,
            "context_vs_dense_per_slot": round(ctx / dense_equiv_per_slot, 2),
            "slots": slots, "seq_len": spec.seq_len,
            "kv_pool_blocks": pool_blocks, "block_tokens": bt,
            "kv_pool_bytes": pool_blocks * blk_bytes,
            "dense_layout_bytes": slots * (spec.seq_len // bt) * blk_bytes,
            "short_requests_served": len(shorts),
            "e2e_s": round(dt, 3),
        }))
    finally:
        be.close()


def _write_fleet_model(outdir: str) -> tuple[str, str]:
    """Tiny real-format checkpoint + chatml byte-level tokenizer for the fleet
    replicas (the examples/make_tiny_model.py pattern, sized for fast CPU
    startup: the fleet bench measures ROUTING + cache locality, not kernels)."""
    from distributed_llama_tpu.formats.mfile import params_file_order, write_model
    from distributed_llama_tpu.formats.tfile import TokenizerData, write_tokenizer
    from distributed_llama_tpu.models.params import init_random_params

    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=2, vocab_size=262,
                     seq_len=512, rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.F32, seed=6)
    mpath = os.path.join(outdir, "fleet.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = [b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(256)] + \
        [b"<|im_start|>", b"<|im_end|>", b" "]
    scores = [0.0] * 259 + [-1.0, -1.0, -1.5]
    tpath = os.path.join(outdir, "fleet.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=260,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    return mpath, tpath


def _fleet_free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _fleet_get_json(port, path, timeout=10):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _spawn_fleet_replicas(tmp, mpath, tpath, ports, extra_argv=(),
                          trace_dir=None, per_replica_argv=None,
                          per_replica_env=None):
    """Launch one api_server subprocess per port (tiny fleet checkpoint,
    CPU), env-scrubbed so chaos config never leaks into acceptance
    replicas. Shared by the shared-prefix, chaos, and mixed-context fleet
    benches — the startup machinery must not drift between them.
    `per_replica_argv` adds per-index flags (the mixed-context bench's
    --role split); `per_replica_env` overrides env vars per index AFTER
    the scrub (the gray-failure bench's victim-only sustained-latency
    DLLAMA_FAULTS). Returns (procs, logs)."""
    import subprocess

    repo_root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root,
               DLT_HANDOFF_PATH="", DLLAMA_FAULTS="", DLLAMA_FAULT_SEED="")
    procs, logs = [], []
    for i, port in enumerate(ports):
        log = open(os.path.join(tmp, f"replica_{port}.log"), "w")
        logs.append(log)
        own = tuple(per_replica_argv[i]) if per_replica_argv else ()
        own_env = (dict(env, **per_replica_env[i])
                   if per_replica_env and per_replica_env[i] else env)
        argv = [sys.executable, "-m", "distributed_llama_tpu.apps.api_server",
                "--model", mpath, "--tokenizer", tpath, "--chat-template",
                "chatml", "--host", "127.0.0.1", "--port", str(port),
                "--batch", "2", "--superstep", "4", *extra_argv, *own]
        if trace_dir is not None:
            # replica-side tracing: the router's GET /v1/trace pulls each
            # replica's live buffer into the merged Perfetto file
            argv += ["--trace", os.path.join(trace_dir, f"trace_{port}.json")]
        procs.append(subprocess.Popen(
            argv, env=own_env, stdout=log, stderr=subprocess.STDOUT,
            cwd=repo_root))
    return procs, logs


def _await_fleet_healthy(procs, ports, tmp, timeout_s=300):
    deadline = time.time() + timeout_s
    for port, proc in zip(ports, procs):
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replica :{port} died during startup "
                    f"(see {tmp}/replica_{port}.log)")
            try:
                if _fleet_get_json(port, "/healthz", timeout=2)[0] == 200:
                    break
            except OSError:
                pass
            if time.time() > deadline:
                raise RuntimeError(f"replica :{port} never became healthy")
            time.sleep(0.5)


def fleet_shared_prefix_workload(args, spec):
    """--workload shared-prefix --replicas N [--routing affinity|random]
    [--kill-replica]: the fleet-tier acceptance bench (docs/FLEET.md).

    Launches N real api_server subprocesses (tiny synthetic checkpoint, CPU)
    plus the in-process fleet router, then drives G shared-prefix request
    groups through the router: one warm request per group, then concurrent
    streaming followers. Reports fleet tok/s (delivered deltas / wall), TTFT
    p50/p95, the AGGREGATE prefix-hit-rate summed over every replica's
    /v1/stats prefix_cache counters, and the router's routes-by-reason
    split. `--routing random` is the A/B control (affinity must beat it);
    `--kill-replica` SIGTERMs one replica mid-run — graceful drain + router
    failover must complete EVERY request with no client-visible failure."""
    import signal
    import subprocess
    import tempfile
    import threading

    from distributed_llama_tpu.fleet.router import close_router, serve_router
    from distributed_llama_tpu.obs import metrics as obs_metrics

    n_rep = args.replicas
    tmp = tempfile.mkdtemp(prefix="dlt_fleet_")
    mpath, tpath = _write_fleet_model(tmp)
    ports = [_fleet_free_port() for _ in range(n_rep)]
    if args.trace_fleet and obs_trace.current() is None:
        # the router runs in THIS process: its proxy spans must record for
        # the merged fleet trace (replicas get --trace below)
        obs_trace.install(process_name="router")
    procs, logs = _spawn_fleet_replicas(
        tmp, mpath, tpath, ports, extra_argv=("--drain-timeout", "60"),
        trace_dir=tmp if args.trace_fleet else None)
    _get_json = _fleet_get_json

    router = None
    try:
        _await_fleet_healthy(procs, ports, tmp)
        router = serve_router([f"127.0.0.1:{p}" for p in ports],
                              host="127.0.0.1", port=0, policy=args.routing,
                              poll_interval=0.5, block_bytes=32, retries=2,
                              try_timeout=120.0, seed=0)
        rport = router.server_address[1]
        threading.Thread(target=router.serve_forever, daemon=True).start()

        rng = np.random.default_rng(0)
        # more groups than any replica has slots (2 each): slots churn across
        # groups, so reuse flows through the RADIX pool (counted in
        # hit_tokens) rather than the same-slot resident rewind (which the
        # cache reports as unused_hits). The group count is a CONSTANT —
        # fleet-size-independent — so --replicas 1 (the single-replica
        # baseline) and --replicas N run the IDENTICAL request schedule;
        # only the routing changes, which is exactly what the acceptance
        # comparison isolates
        groups = 8
        # ~args.shared_prefix chars -> ~that many tokens via the byte-fallback
        # tokenizer; budget under the replica seq_len (512)
        sys_len = min(args.shared_prefix, 320)
        systems = ["".join(rng.choice(list("abcdefgh rstlne"))
                           for _ in range(sys_len)) for _ in range(groups)]
        gen = 8
        followers = max(args.requests - 1, 4)  # per group, measured phase

        def one_request(system, user, results, idx, headers=None):
            # shared incremental-SSE driver (fleet/client.py): TTFT is the
            # first delta's true arrival time; rid/replica are the serving
            # identity for --latency-log and the flight-recorder check
            body = {"messages": [{"role": "system", "content": system},
                                 {"role": "user", "content": user}],
                    "max_tokens": gen, "temperature": 0, "stream": True}
            r = completion_request(rport, body, timeout=180, headers=headers)
            if r["error"] is not None or r["status"] != 200:
                results[idx] = {"error": r["error"]
                                or f"status {r['status']}"}
                return
            results[idx] = {"ttft": r["ttft"], "deltas": r["deltas"],
                            "e2e": r["e2e"], "rid": r["rid"],
                            "replica": r["replica"]}

        # warm phase: one request per group, sequential — inserts each
        # group's system prompt into SOME replica's cache and (affinity
        # mode) records the route
        warm = [None] * groups
        for g, system in enumerate(systems):
            one_request(system, f"warm {g}", warm, g)
            assert "error" not in (warm[g] or {"error": "no result"}), warm[g]

        victim_stats = {}
        kill_at = None
        if args.kill_replica:
            kill_at = (groups * followers) // 2

        # measured phase: followers interleaved across groups, concurrent
        reqs = [(g, f) for f in range(followers) for g in range(groups)]
        results = [None] * len(reqs)
        threads = []
        t_all0 = time.perf_counter()
        sem = threading.Semaphore(2 * n_rep)  # fleet-wide client concurrency
        # the SAMPLED request (--trace-fleet acceptance): send an explicit
        # client traceparent on follower 0 so its known trace id can be
        # asserted in both the router's proxy span and the serving replica's
        # engine spans inside the merged trace
        sampled_tid = os.urandom(16).hex()
        sampled_hdr = {"traceparent": f"00-{sampled_tid}-{os.urandom(8).hex()}-01"}

        def run_one(i, g, f):
            with sem:
                one_request(systems[g], f"follower {f} of group {g}",
                            results, i,
                            headers=sampled_hdr if i == 0 else None)

        for i, (g, f) in enumerate(reqs):
            if kill_at is not None and i == kill_at:
                # mid-bench replica kill: snapshot its cache counters, then
                # SIGTERM (graceful drain -> router reroutes; in-flight
                # requests finish on the draining replica)
                _, victim_stats = _get_json(ports[0], "/v1/stats", timeout=10)
                procs[0].send_signal(signal.SIGTERM)
            t = threading.Thread(target=run_one, args=(i, g, f))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=300)
        wall = time.perf_counter() - t_all0

        failed = [(i, r) for i, r in enumerate(results)
                  if r is None or "error" in r]
        ttfts = sorted(r["ttft"] for r in results
                       if r and r.get("ttft") is not None)
        e2es = sorted(r["e2e"] for r in results
                      if r and r.get("e2e") is not None)
        deltas = sum(r.get("deltas", 0) for r in results if r)

        if args.latency_log:
            write_latency_log(args.latency_log, [
                {"request_id": (r or {}).get("rid"), "group": g,
                 "follower": f, "tenant": "default",
                 "class": "interactive",
                 "ttft_s": (r or {}).get("ttft"),
                 "e2e_s": (r or {}).get("e2e"),
                 "tokens": (r or {}).get("deltas"),
                 "replica": (r or {}).get("replica"),
                 "error": (r or {}).get("error")}
                for (g, f), r in zip(reqs, results)])

        # --trace-fleet acceptance: pull the router's fleet-merged Perfetto
        # trace, write it, and verify end-to-end attribution — the sampled
        # request's router proxy span AND its replica-side engine events
        # carry the trace id the client sent, and the serving replica's
        # flight recorder returns that request's full timeline
        trace_info = None
        if args.trace_fleet:
            _, doc = _get_json(rport, "/v1/trace", timeout=60)
            with open(args.trace_fleet, "w") as f:
                json.dump(doc, f)
            evs = doc.get("traceEvents", [])
            router_spans = [
                e for e in evs if e.get("name") == "router.proxy"
                and (e.get("args") or {}).get("trace_id") == sampled_tid]
            engine_evs = [
                e for e in evs
                if (e.get("args") or {}).get("trace_id") == sampled_tid
                and str(e.get("name", "")).startswith(("batch.", "engine."))]
            r0 = results[0] or {}
            timeline = None
            if r0.get("rid") and r0.get("replica"):
                try:
                    st, body = _get_json(
                        int(r0["replica"].rsplit(":", 1)[1]),
                        f"/v1/requests/{r0['rid']}", timeout=10)
                    timeline = body if st == 200 else None
                except OSError:
                    timeline = None
            tl_events = [e.get("event")
                         for e in (timeline or {}).get("events", [])]
            trace_info = {
                "out": args.trace_fleet, "events": len(evs),
                "processes": len((doc.get("otherData") or {})
                                 .get("processes", [])),
                "sampled_trace_id": sampled_tid,
                "sampled_request_id": r0.get("rid"),
                "sampled_replica": r0.get("replica"),
                "router_proxy_spans": len(router_spans),
                "replica_engine_events": len(engine_evs),
                "flight_timeline_events": len(tl_events),
                "flight_has_queue_and_steps": (
                    "admitted" in tl_events
                    and any(e in ("super_step", "prefill_chunk")
                            for e in tl_events)),
                "ok": bool(router_spans and engine_evs
                           and timeline is not None
                           and timeline.get("finish") is not None
                           and "admitted" in tl_events),
            }

        # aggregate prefix-hit-rate over every replica (the victim from its
        # pre-kill snapshot; survivors live — the victim is NEVER polled
        # live, even while it is still draining, or its counters would be
        # summed twice)
        hit_tok = resident_tok = prompt_tok = 0.0
        per_replica_hits = {}
        stats_sources = ([(f"127.0.0.1:{ports[0]}", victim_stats)]
                         if victim_stats else [])
        for port, proc in zip(ports, procs):
            if victim_stats and port == ports[0]:
                continue
            if proc.poll() is None:
                try:
                    stats_sources.append(
                        (f"127.0.0.1:{port}",
                         _get_json(port, "/v1/stats", timeout=10)[1]))
                except OSError:
                    pass
        for rep_id, st in stats_sources:
            pc = st.get("prefix_cache") or {}
            hit_tok += pc.get("hit_tokens", 0)
            resident_tok += pc.get("resident_tokens", 0)
            prompt_tok += pc.get("prompt_tokens", 0)
            per_replica_hits[rep_id] = {
                "reuse_rate": round(pc.get("reuse_rate", 0.0), 3),
                "hit_tokens": pc.get("hit_tokens", 0),
                "resident_tokens": pc.get("resident_tokens", 0)}
        routes = {k.split("=")[1].strip('"}'): v for k, v in
                  (obs_metrics.snapshot().get("router_routes_total")
                   or {}).items()}
        print(json.dumps({
            "metric": "fleet_shared_prefix_tok_s",
            "value": round(deltas / wall, 2) if wall else 0.0,
            "unit": "tok/s", "vs_baseline": None,
            "routing": args.routing, "replicas": n_rep,
            "killed_replica": bool(args.kill_replica),
            "failed_requests": len(failed),
            "failures": [f"{i}: {r}" for i, r in failed[:5]],
            "requests": len(reqs), "groups": groups,
            "followers_per_group": followers,
            "ttft_p50_ms": _pct_ms(ttfts, 0.50),
            "ttft_p95_ms": _pct_ms(ttfts, 0.95),
            "ttft_p99_ms": _pct_ms(ttfts, 0.99),
            "e2e_p50_ms": _pct_ms(e2es, 0.50),
            "e2e_p95_ms": _pct_ms(e2es, 0.95),
            "e2e_p99_ms": _pct_ms(e2es, 0.99),
            "trace_fleet": trace_info,
            # reuse = pool hits + resident rewinds: WHICH mechanism skipped a
            # request's prefill is a slot-scheduling accident (the same sticky
            # route lands either way), so the acceptance metric sums both;
            # prefix_hit_rate (pool only) is kept for the PR 3 comparison
            "prefix_reuse_rate": round(
                (hit_tok + resident_tok) / prompt_tok, 3)
            if prompt_tok else 0.0,
            "prefix_hit_rate": round(hit_tok / prompt_tok, 3)
            if prompt_tok else 0.0,
            "per_replica": per_replica_hits,
            "routes": routes,
            "shared_prefix_chars": sys_len, "gen_tokens": gen,
        }))
        if failed:
            sys.exit(1)
        if (trace_info is not None and not trace_info["ok"]
                and not args.kill_replica):
            # acceptance gate: a merged trace without end-to-end attribution
            # (or a missing flight timeline) is a failure, not a warning
            sys.exit(1)
    finally:
        if router is not None:
            close_router(router)
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=90)
            except subprocess.TimeoutExpired:
                proc.kill()
        for log in logs:
            log.close()


def mixed_context_workload(args, spec):
    """--workload mixed-context: the disaggregation acceptance A/B
    (docs/DISAGG.md). Co-scheduled LONG prefills (unique ~290-char system
    prompts, 4 decode tokens) and SHORT streaming decode chains (24
    tokens) drive two 2-replica fleets on an IDENTICAL schedule:

    - **disaggregated** — replica 0 `--role prefill`, replica 1
      `--role decode`, router `--disagg-threshold` armed: every long
      prefills on replica 0, ships its KV blocks over /v1/kv, and decodes
      on replica 1 alongside the shorts (whose dispatches stay narrow);
    - **monolithic** — both replicas `both`, splitter off: long prefill
      chunks ride mixed (B, 64) dispatches WITH co-batched short rows,
      inflating their inter-token gaps (the exact pathology ISSUE 13
      names).

    Reports short-chain decode TPOT p50/p95 per arm and gates in-run:
    zero failed requests in both arms, every measured long actually split
    and imported, the decode replica re-prefilled ZERO shipped tokens
    (`disagg_reprefill_tokens_total == 0`), and disaggregated TPOT p95
    strictly below monolithic."""
    import subprocess
    import tempfile
    import threading

    from distributed_llama_tpu.fleet.router import close_router, serve_router

    tmp = tempfile.mkdtemp(prefix="dlt_disagg_")
    mpath, tpath = _write_fleet_model(tmp)
    rounds = max(args.requests, 6)
    shorts_per_round = 3
    gen_short, gen_long = 24, 4
    long_chars, threshold = 288, 48

    rng = np.random.default_rng(0)
    alpha = list("abcdefgh rstlne")
    # unique prompts, identical across arms: longs share NO prefix (each
    # pays a full prefill), shorts stay under the split threshold
    long_sys = ["".join(rng.choice(alpha) for _ in range(long_chars))
                for _ in range(rounds + 1)]
    short_user = ["ask " + "".join(rng.choice(alpha) for _ in range(12))
                  + f" q{i}" for i in range((rounds + 1) * shorts_per_round)]

    def run_arm(disagg: bool) -> dict:
        ports = [_fleet_free_port() for _ in range(2)]
        roles = ((("--role", "prefill"), ("--role", "decode"))
                 if disagg else None)
        procs, logs = _spawn_fleet_replicas(tmp, mpath, tpath, ports,
                                            per_replica_argv=roles)
        router = None
        failures: list[str] = []
        shorts: list[tuple] = []  # (ttft_s, tpot_s)
        long_e2es: list[float] = []
        try:
            _await_fleet_healthy(procs, ports, tmp)
            router = serve_router(
                [f"127.0.0.1:{p}" for p in ports], host="127.0.0.1",
                port=0, poll_interval=0.5, block_bytes=32, retries=2,
                try_timeout=300.0,
                disagg_threshold=threshold if disagg else 0)
            rport = router.server_address[1]
            threading.Thread(target=router.serve_forever,
                             daemon=True).start()

            def long_req(i, record):
                body = {"messages": [
                    {"role": "system", "content": long_sys[i]},
                    {"role": "user", "content": "go"}],
                    "max_tokens": gen_long, "temperature": 0,
                    "stream": False}
                r = completion_request(rport, body, timeout=600)
                if r["error"] is not None or r["status"] != 200:
                    failures.append(f"long {i}: status {r['status']} "
                                    f"{str(r['error'])[:120]}")
                elif record:
                    long_e2es.append(r["e2e"])

            def short_req(i, record):
                body = {"messages": [
                    {"role": "user", "content": short_user[i]}],
                    "max_tokens": gen_short, "temperature": 0,
                    "stream": True}
                r = completion_request(rport, body, timeout=600)
                if r["error"] is not None or r["status"] != 200:
                    failures.append(f"short {i}: "
                                    f"{r['error'] or r['status']}")
                    return
                if record and r["deltas"] > 1:
                    shorts.append((r["ttft"], r["tpot"]))

            def run_round(r, record):
                ths = [threading.Thread(target=long_req, args=(r, record))]
                ths += [threading.Thread(
                    target=short_req,
                    args=(r * shorts_per_round + s, record))
                    for s in range(shorts_per_round)]
                ths[0].start()
                time.sleep(0.05)  # the long admission lands first
                for t in ths[1:]:
                    t.start()
                for t in ths:
                    t.join(timeout=600)

            run_round(rounds, record=False)  # warm: compiles every shape
            for r in range(rounds):
                run_round(r, record=True)

            rep_stats = []
            for port in ports:
                try:
                    rep_stats.append(_fleet_get_json(port, "/v1/stats",
                                                     timeout=10)[1])
                except OSError:
                    rep_stats.append({})
            return {"failures": failures, "shorts": shorts,
                    "long_e2es": long_e2es, "rep_stats": rep_stats}
        finally:
            if router is not None:
                close_router(router)
            for proc in procs:
                if proc.poll() is None:
                    proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=90)
                except subprocess.TimeoutExpired:
                    proc.kill()
            for log in logs:
                log.close()

    from distributed_llama_tpu.obs import metrics as obs_metrics

    def split_count():
        fam = (obs_metrics.snapshot()
               .get("router_disagg_requests_total") or {})
        return fam.get('{outcome="split"}', 0) or 0

    s0 = split_count()
    dis = run_arm(disagg=True)
    dis_splits = split_count() - s0
    mono = run_arm(disagg=False)

    def pcts(arm):
        tpots = sorted(t for _ttft, t in arm["shorts"])
        ttfts = sorted(t for t, _tpot in arm["shorts"])
        return {
            "short_requests": len(arm["shorts"]),
            "decode_tpot_p50_ms": _pct_ms(tpots, 0.50),
            "decode_tpot_p95_ms": _pct_ms(tpots, 0.95),
            "ttft_p50_ms": _pct_ms(ttfts, 0.50),
            "ttft_p95_ms": _pct_ms(ttfts, 0.95),
            "long_e2e_p50_ms": _pct_ms(sorted(arm["long_e2es"]), 0.50),
            "failed": len(arm["failures"]),
            "failures": arm["failures"][:5],
        }

    def metric_sum(stats_list, name, label=None):
        total = 0.0
        for st in stats_list:
            fam = (st.get("metrics") or {}).get(name)
            if fam is None:
                continue
            if isinstance(fam, dict):
                total += (fam.get(label, 0) or 0) if label \
                    else sum(fam.values())
            else:
                total += fam
        return total

    imported = metric_sum(dis["rep_stats"], "disagg_import_requests_total",
                          '{outcome="imported"}')
    reprefill = metric_sum(dis["rep_stats"], "disagg_reprefill_tokens_total")
    da, ma = pcts(dis), pcts(mono)
    problems = []
    if dis["failures"] or mono["failures"]:
        problems.append(f"client-visible failures: disagg "
                        f"{dis['failures'][:3]}, mono {mono['failures'][:3]}")
    # every measured long (plus the warm one) must have split and imported
    if dis_splits < rounds:
        problems.append(f"only {dis_splits}/{rounds} longs split")
    if imported < rounds:
        problems.append(f"only {imported:.0f}/{rounds} imports landed")
    if reprefill != 0:
        problems.append(f"streamed admissions re-prefilled {reprefill:.0f} "
                        "shipped tokens (want 0)")
    if not (da["decode_tpot_p95_ms"] and ma["decode_tpot_p95_ms"]
            and da["decode_tpot_p95_ms"] < ma["decode_tpot_p95_ms"]):
        problems.append(
            f"disaggregated decode TPOT p95 {da['decode_tpot_p95_ms']} ms "
            f"not strictly better than monolithic "
            f"{ma['decode_tpot_p95_ms']} ms")
    print(json.dumps({
        "metric": "mixed_context_decode_tpot_p95_ms",
        "value": da["decode_tpot_p95_ms"], "unit": "ms",
        "vs_baseline": None,
        "monolithic_tpot_p95_ms": ma["decode_tpot_p95_ms"],
        "tpot_p95_speedup": (round(ma["decode_tpot_p95_ms"]
                                   / da["decode_tpot_p95_ms"], 2)
                             if da["decode_tpot_p95_ms"]
                             and ma["decode_tpot_p95_ms"] else None),
        "disaggregated": da, "monolithic": ma,
        "rounds": rounds, "shorts_per_round": shorts_per_round,
        "long_prompt_chars": long_chars, "disagg_threshold": threshold,
        "longs_split": dis_splits, "imports": imported,
        "reprefill_tokens": reprefill,
        "problems": problems,
    }))
    if problems:
        sys.exit(1)


def batched_engine_bench(args, spec):
    """--batch B --pipeline/--no-pipeline: serving decode throughput measured
    through the REAL BatchEngine scheduler — admission, device dispatch, and
    the host-side block delivery (EOS scan, callbacks, sampler resync) that
    pipelined super-steps overlap with the next dispatch — rather than the
    raw device loop. B concurrent greedy requests decode --steps tokens
    each; aggregate_decode_tok_s = delivered tokens / wall. Also reports the
    batch_dispatch_gap_seconds delta (mean + p50) for the run: the
    device-idle gap pipelining exists to remove (docs/SERVING.md)."""
    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.obs import metrics as obs_metrics
    from distributed_llama_tpu.quants import FloatType as _FTy
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    B, K = args.batch, max(args.superstep, 1)
    gen = max(args.steps, 4 * K)
    prompts = [[1, 5 + i, 9, 2 + (i % 40)] for i in range(B)]
    if len(prompts[0]) + gen + 1 >= spec.seq_len:
        gen = spec.seq_len - len(prompts[0]) - 2
    params = init_random_params(spec, _FTy.Q40, seed=0)
    be = BatchEngine(spec, params, slots=B, superstep=K, tp=args.tp,
                     pipeline=bool(args.pipeline), prefix_cache=False,
                     speculative=args.speculative,
                     paged_kv=not args.no_paged_kv)

    def _gap_state():
        h = obs_metrics.snapshot().get("batch_dispatch_gap_seconds") or {}
        return h.get("count", 0), h.get("sum", 0.0), dict(h.get("buckets", {}))

    try:
        # warm round with the MEASURED shape — B concurrent requests — so the
        # timed region recompiles nothing (concurrent prefill admission and
        # the chained-input dispatch layout both differ from a sequential
        # single-request warmup)
        warm = [be.submit(list(p), max(2 * K, 4),
                          Sampler(spec.vocab_size, temperature=0.0))
                for p in prompts]
        for r in warm:
            r.wait(timeout=600)
        c0, s0, b0 = _gap_state()
        f0 = sum((obs_metrics.snapshot().get(
            "batch_pipeline_flushes_total") or {}).values())
        t0 = time.perf_counter()
        reqs = [be.submit(list(p), gen,
                          Sampler(spec.vocab_size, temperature=0.0))
                for p in prompts]
        done = [r.wait(timeout=600) for r in reqs]
        wall = time.perf_counter() - t0
        c1, s1, b1 = _gap_state()
    finally:
        be.close()
    tokens = sum(len(d) for d in done)
    n_gap = max(c1 - c0, 1)
    gap_mean_ms = (s1 - s0) / n_gap * 1e3
    # p50 by cumulative bucket walk over the run's delta counts
    half, acc, p50 = (c1 - c0) / 2.0, 0, None
    for le in sorted(b1, key=float):
        acc += b1[le] - b0.get(le, 0)
        if acc >= half and p50 is None:
            p50 = float(le)
    flushes = sum((obs_metrics.snapshot().get(
        "batch_pipeline_flushes_total") or {}).values()) - f0
    spec_tag = f"spec{args.speculative}" if args.speculative else ""
    print(json.dumps({
        # speculation is part of the metric identity: a spec-on run must
        # never land on a spec-off run's BENCH trajectory
        "metric": (f"b{B}k{K}{spec_tag}_engine_decode_"
                   + ("pipelined" if args.pipeline else "serialized")),
        "value": round(tokens / wall, 3), "unit": "tok/s",
        "vs_baseline": None,
        "aggregate_decode_tok_s": round(tokens / wall, 3),
        "tokens": tokens, "wall_s": round(wall, 3),
        "dispatch_gap_ms_mean": round(gap_mean_ms, 4),
        "dispatch_gap_ms_p50_le": (round(p50 * 1e3, 4)
                                   if p50 is not None else None),
        "pipeline": bool(args.pipeline), "pipeline_flushes": flushes,
        "batch": B, "superstep": K, "steps": gen,
        "speculative": args.speculative,
    }))


def repetition_workload(args, spec):
    """--workload repetition: batched speculative decoding A/B
    (docs/SERVING.md "Speculative decoding"). Code/JSON-shaped prompts with
    heavy n-gram reuse drive the REAL BatchEngine scheduler on an identical
    schedule spec-off and spec-on (--speculative K per-row draft-verify
    blocks), interleaved over several measured rounds to decorrelate the
    shared-core noise of a CPU box, and report median aggregate decode
    tok/s both ways plus the accept rate and verify-dispatch count. The two
    modes must emit byte-identical greedy tokens — the speculative identity
    is asserted here, not just in tests."""
    import statistics

    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.quants import FloatType as _FTy
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    B = args.batch if args.batch > 0 else 4
    K = max(args.superstep, 1)
    sk = max(args.speculative, 0)
    pipeline = True if args.pipeline is None else bool(args.pipeline)
    # JSON/code-shaped prompts: a "key": value, record pattern over a small
    # token alphabet, repeated with per-row variation — the n-gram-dense
    # regime prompt lookup exists for
    record = [11, 87, 4, 302, 9, 87, 4, 177, 9, 87, 4, 302, 9, 55]
    prompts = [[1, 3 + 2 * i] + (record * 4)[:52] for i in range(B)]
    gen = max(args.steps, 120)
    gen = min(gen, spec.seq_len - len(prompts[0]) - 2)
    params = init_random_params(spec, _FTy.Q40, seed=0)
    be = BatchEngine(spec, params, slots=B, superstep=K, tp=args.tp,
                     pipeline=pipeline, prefix_cache=False,
                     speculative=sk or 8, paged_kv=not args.no_paged_kv)

    def round_(spec_on):
        be.spec_k = (sk or 8) if spec_on else 0
        v0 = be.verify_steps
        t0 = time.perf_counter()
        reqs = [be.submit(list(p), gen,
                          Sampler(spec.vocab_size, temperature=0.0))
                for p in prompts]
        outs = [r.wait(timeout=600) for r in reqs]
        wall = time.perf_counter() - t0
        tokens = sum(len(o) for o in outs)
        return {"tok_s": tokens / wall, "tokens": tokens, "outs": outs,
                "verify": be.verify_steps - v0,
                "drafted": sum(r.stats.spec_drafted for r in reqs),
                "accepted": sum(r.stats.spec_accepted for r in reqs)}

    rounds = 3
    try:
        round_(False)  # warm: scan + prefill programs
        if sk:
            round_(True)  # warm: verify programs (every block bucket)
        offs, ons = [], []
        for _ in range(rounds):  # interleaved A/B: drift hits both arms
            offs.append(round_(False))
            if sk:
                ons.append(round_(True))
    finally:
        be.close()
    off_tok_s = statistics.median(r["tok_s"] for r in offs)
    out = {
        "metric": f"b{B}k{K}spec{sk}_repetition_decode",
        "value": 0.0, "unit": "tok/s", "vs_baseline": None,
        "spec_off_tok_s": round(off_tok_s, 3),
        "tokens_per_round": offs[0]["tokens"], "rounds": rounds,
        "batch": B, "superstep": K, "speculative": sk,
        "pipeline": pipeline, "gen": gen,
        "model": (f"dim{spec.dim}_voc{spec.vocab_size}"
                  f"_L{spec.n_layers}_s{spec.seq_len}"),
    }
    if sk:
        on_tok_s = statistics.median(r["tok_s"] for r in ons)
        drafted = ons[-1]["drafted"]
        out.update({
            "value": round(on_tok_s, 3),
            "spec_on_tok_s": round(on_tok_s, 3),
            "speedup": round(on_tok_s / off_tok_s, 3),
            "accept_rate": (round(ons[-1]["accepted"] / drafted, 3)
                            if drafted else None),
            "verify_dispatches": ons[-1]["verify"],
            "drafted": drafted, "accepted": ons[-1]["accepted"],
            "identical": all(r["outs"] == offs[0]["outs"] for r in ons),
        })
    else:
        out["value"] = round(off_tok_s, 3)
    print(json.dumps(out))
    if sk and not out["identical"]:
        print("❌ spec-on output diverged from spec-off", file=sys.stderr)
        sys.exit(1)


def spec_suite_workload(args, spec):
    """--workload spec-suite: the model-drafting acceptance A/B/C
    (docs/SERVING.md "Model-based drafting"). Four seeded workload
    generators — chat, code, json, open-ended — drive the REAL BatchEngine
    on an identical schedule under three proposer modes interleaved per
    round on ONE engine (off / ngram / model), with byte-identity asserted
    in-run across all three modes for every request (greedy AND
    seeded-stochastic rows) and per-workload accept rate + aggregate decode
    tok/s reported per mode.

    Drafter construction: real draft models work because distillation makes
    a small model approximate a big one. With synthetic random weights no
    independent small model predicts the target, so the suite BUILDS the
    alignment structurally: the target's layers past the first are damped
    (~no-op residual contributions) and the drafter is the target's 1-layer
    prefix — a 1/n_layers-cost drafter whose greedy argmax tracks the
    target's, the same role TINY_REP's n-gram density plays for the
    repetition bench. n-gram drafting still wins the json (repetition)
    workload; the model drafter's claim — gated in-run — is beating ngram
    tok/s on >= 2 of the NON-repetition workloads (chat/code/open-ended),
    where prompt lookup goes dry but a drafter keeps verify blocks full."""
    import statistics
    from dataclasses import replace as _replace

    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.quants import FloatType as _FTy, QTensor
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    B = args.batch if args.batch > 0 else 4
    K = max(args.superstep, 1)
    sk = max(args.speculative, 0) or 8
    pipeline = True if args.pipeline is None else bool(args.pipeline)
    V = spec.vocab_size
    gen = min(max(args.steps, 48), spec.seq_len - 80)

    base = init_random_params(spec, _FTy.Q40, seed=0)

    def rebuild(params, damp_from=None, trunc=None, damp=0.05):
        out = {"embedding": params["embedding"],
               "rms_final": params["rms_final"], "wcls": params["wcls"],
               "blocks": {}}
        for name, t in params["blocks"].items():
            if isinstance(t, QTensor):
                f = np.array(t.dequantize(dtype=np.float32))
                if damp_from is not None:
                    f[damp_from:] = f[damp_from:] * damp
                if trunc is not None:
                    f = f[:trunc]
                out["blocks"][name] = QTensor.from_float(f, t.ftype)
            else:
                out["blocks"][name] = t if trunc is None else t[:trunc]
        return out

    tparams = rebuild(base, damp_from=1)
    dspec = _replace(spec, n_layers=1)
    dparams = rebuild(base, damp_from=1, trunc=1)

    # ---- seeded workload generators: B prompts each ----
    def gen_chat(rng):
        # role-templated turns: fixed template tokens around random content
        turns = []
        for _ in range(3):
            turns += [2, 200, 201] + list(rng.integers(5, V, 6)) + [202, 203]
        return [1] + turns

    def gen_code(rng):
        # keyword/indent line pattern with per-line variation: moderate
        # n-gram reuse (between json's density and chat's dryness)
        lines = []
        kw = [40, 41, 42, 43]
        for i in range(4):
            lines += [10, kw[i % 4], 60, int(rng.integers(64, 128)), 61, 9]
        return [1] + lines * 2

    def gen_json(rng):
        # the repetition bench's record shape: n-gram-dense
        record = [11, 87, 4, 302 % V, 9, 87, 4, 177, 9, 87, 4, 302 % V, 9,
                  55]
        return [1, int(rng.integers(3, 30))] + (record * 4)[:40]

    def gen_open(rng):
        # open-ended: no structure at all — prompt lookup goes dry here
        return [1] + list(rng.integers(3, V, 24))

    gens = {"chat": gen_chat, "code": gen_code, "json": gen_json,
            "open-ended": gen_open}
    suites = {}
    for w, g in gens.items():
        # crc32, not hash(): builtin str hashing is SipHash-randomized per
        # process, which would quietly unseed the "seeded" generators
        rng = np.random.default_rng(zlib.crc32(w.encode()))
        suites[w] = [[int(t) for t in g(rng)] for _ in range(B)]

    def sampler_for(j, mixed):
        # identity rounds carry seeded-stochastic rows next to greedy ones
        # (the verify path's byte-identity contract covers both); timed
        # rounds run all-greedy — a temperature-0.8 row samples far from
        # ANY drafter's argmax, so its accept is ~0 by construction and it
        # rides verify dispatches at 1 token/turn, measuring the scheduler
        # mix instead of the proposers under comparison
        if not mixed or j % 2 == 0:
            return Sampler(V, temperature=0.0)
        return Sampler(V, temperature=0.8, topp=0.9, seed=7000 + j)

    be = BatchEngine(spec, tparams, slots=B, superstep=K, tp=args.tp,
                     pipeline=pipeline, prefix_cache=False, speculative=sk,
                     draft_model=(dspec, dparams),
                     paged_kv=not args.no_paged_kv)
    drafter = be.proposer.drafter
    assert drafter is not None, "drafter failed to load"

    def set_mode(mode):
        # one engine for every round (shared compiled programs, shared
        # slots): proposer switched between rounds while idle
        be.spec_k = 0 if mode == "off" else sk
        be.proposer.drafter = drafter if mode == "model" else None

    def round_(w, mode, mixed=False):
        set_mode(mode)
        v0 = be.verify_steps
        t0 = time.perf_counter()
        reqs = [be.submit(list(p), gen, sampler_for(j, mixed))
                for j, p in enumerate(suites[w])]
        outs = [r.wait(timeout=600) for r in reqs]
        wall = time.perf_counter() - t0
        tokens = sum(len(o) for o in outs)
        drafted = sum(r.stats.spec_drafted for r in reqs)
        accepted = sum(r.stats.spec_accepted for r in reqs)
        return {"tok_s": tokens / wall, "tokens": tokens, "outs": outs,
                "verify": be.verify_steps - v0, "drafted": drafted,
                "accepted": accepted}

    MODES = ("off", "ngram", "model")
    rounds = 3
    results = {w: {m: [] for m in MODES} for w in gens}
    mismatches = []
    try:
        for w in gens:  # warm every program each mode touches
            for m in MODES:
                round_(w, m)
        # identity sweep: greedy AND seeded-stochastic rows must emit the
        # same bytes under every proposer mode (asserted in-run)
        for w in gens:
            ref = None
            for m in MODES:
                r = round_(w, m, mixed=True)
                if ref is None:
                    ref = r["outs"]
                elif r["outs"] != ref:
                    mismatches.append((w, m, "mixed"))
        # timed sweep: interleaved rounds so box drift hits all arms
        # equally; identity asserted here too (all-greedy rows)
        for _ in range(rounds):
            for w in gens:
                ref = None
                for m in MODES:
                    r = round_(w, m)
                    results[w][m].append(r)
                    if ref is None:
                        ref = r["outs"]
                    elif r["outs"] != ref:
                        mismatches.append((w, m))
    finally:
        be.close()

    out = {"metric": f"b{B}k{K}spec{sk}_spec_suite", "unit": "tok/s",
           "vs_baseline": None, "batch": B, "superstep": K,
           "speculative": sk, "pipeline": pipeline, "gen": gen,
           "rounds": rounds, "identical": not mismatches,
           "model": (f"dim{spec.dim}_voc{spec.vocab_size}"
                     f"_L{spec.n_layers}_s{spec.seq_len}"),
           "drafter": f"dim{dspec.dim}_L{dspec.n_layers}",
           "workloads": {}}
    model_wins = []
    for w in gens:
        block = {}
        for m in MODES:
            rs = results[w][m]
            drafted = sum(r["drafted"] for r in rs)
            accepted = sum(r["accepted"] for r in rs)
            block[m] = {
                "tok_s": round(statistics.median(r["tok_s"] for r in rs), 3),
                "accept_rate": (round(accepted / drafted, 3)
                                if drafted else None),
                "verify_dispatches": rs[-1]["verify"],
            }
        block["speedup_model_vs_ngram"] = round(
            block["model"]["tok_s"] / block["ngram"]["tok_s"], 3)
        if w != "json" and block["speedup_model_vs_ngram"] > 1.0:
            model_wins.append(w)
        out["workloads"][w] = block
    out["model_beats_ngram_on"] = model_wins
    out["value"] = round(statistics.median(
        out["workloads"][w]["model"]["tok_s"] for w in gens), 3)
    print(json.dumps(out))
    ok = True
    if mismatches:
        print(f"❌ output diverged across proposer modes: {mismatches}",
              file=sys.stderr)
        ok = False
    if len(model_wins) < 2:
        print("❌ model drafting beat ngram on "
              f"{model_wins} — need >= 2 non-repetition workloads",
              file=sys.stderr)
        ok = False
    if not ok:
        sys.exit(1)


def structured_workload(args, spec):
    """--workload structured: grammar-constrained decoding A/B
    (docs/SERVING.md "Constrained decoding"). Two seeded structured-output
    workloads — json records and tool calls, each pinned to a compiled
    grammar — drive the REAL BatchEngine on an identical constrained
    schedule under four proposer modes interleaved per round on ONE engine
    (off / ngram / model / grammar). Asserted IN-RUN for every request:
    the output is grammar-valid, and byte-identical across all four modes
    (the mask is applied before the sampler on every path, so the proposer
    can only change SPEED, never bytes). The headline claim — gated — is
    speedup_grammar_vs_ngram >= 1.0: forced-transition chains are
    guaranteed accepts, so grammar drafting can only fill verify blocks
    the n-gram index leaves empty."""
    import statistics
    from dataclasses import replace as _replace

    from distributed_llama_tpu.constrain import byte_vocab, compile_grammar
    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.quants import FloatType as _FTy, QTensor
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    B = args.batch if args.batch > 0 else 4
    K = max(args.superstep, 1)
    sk = max(args.speculative, 0) or 8
    pipeline = True if args.pipeline is None else bool(args.pipeline)
    V = spec.vocab_size
    gen = min(max(args.steps, 56), spec.seq_len - 80)

    # the spec-suite's structurally-aligned drafter (damped target layers,
    # 1-layer prefix drafter) so the "model" arm is a real contender
    base = init_random_params(spec, _FTy.Q40, seed=0)

    def rebuild(params, damp_from=None, trunc=None, damp=0.05):
        out = {"embedding": params["embedding"],
               "rms_final": params["rms_final"], "wcls": params["wcls"],
               "blocks": {}}
        for name, t in params["blocks"].items():
            if isinstance(t, QTensor):
                f = np.array(t.dequantize(dtype=np.float32))
                if damp_from is not None:
                    f[damp_from:] = f[damp_from:] * damp
                if trunc is not None:
                    f = f[:trunc]
                out["blocks"][name] = QTensor.from_float(f, t.ftype)
            else:
                out["blocks"][name] = t if trunc is None else t[:trunc]
        return out

    tparams = rebuild(base, damp_from=1)
    dspec = _replace(spec, n_layers=1)
    dparams = rebuild(base, damp_from=1, trunc=1)

    cv = byte_vocab(V)
    grammars = {
        # long literal key spans between short branch points: the shape
        # real json-mode traffic has (keys forced, values chosen)
        "json": compile_grammar("json_schema", {
            "type": "object", "properties": {
                "sensor": {"enum": ["alpha", "beta", "gamma"]},
                "ok": {"type": "boolean"},
                "status": {"enum": ["ok", "degraded", "failed"]},
            }}, cv, eos_id=2),
        "tool-call": compile_grammar("json_schema", {
            "type": "object", "properties": {
                "name": {"enum": ["get_weather", "get_time", "search_web"]},
                "arguments": {"enum": ["{}", "{\"q\":1}", "{\"q\":2}"]},
            }}, cv, eos_id=2),
    }
    suites = {}
    for w in grammars:
        rng = np.random.default_rng(zlib.crc32(w.encode()))
        suites[w] = [[1] + [int(t) for t in rng.integers(3, V, 8)]
                     for _ in range(B)]

    def sampler_for(j, mixed):
        # identity rounds carry seeded-stochastic rows next to greedy ones
        # (masked sampling covers both); timed rounds run all-greedy, same
        # rationale as the spec-suite bench
        if not mixed or j % 2 == 0:
            return Sampler(V, temperature=0.0)
        return Sampler(V, temperature=0.8, topp=0.9, seed=9000 + j)

    be = BatchEngine(spec, tparams, slots=B, superstep=K, tp=args.tp,
                     pipeline=pipeline, prefix_cache=False, speculative=sk,
                     draft_model=(dspec, dparams),
                     paged_kv=not args.no_paged_kv)
    drafter = be.proposer.drafter
    assert drafter is not None, "drafter failed to load"

    def set_mode(mode):
        # one engine for every round (shared compiled programs, shared
        # constraint table): proposers switched between rounds while idle
        be.spec_k = 0 if mode == "off" else sk
        be.proposer.drafter = drafter if mode == "model" else None
        be.proposer.grammar = (be.grammar_proposer if mode == "grammar"
                               else None)

    def check_valid(w, out):
        aut, _ = grammars[w]
        if 2 in out:
            i = out.index(2)
            assert set(out[i:]) == {2}, f"{w}: post-EOS tokens escaped"
            ok, complete = aut.validate(out[: i + 1])
            assert ok and complete, f"{w}: invalid output {bytes(out[:i])!r}"
        else:
            assert aut.validate(out)[0], f"{w}: invalid prefix {bytes(out)!r}"

    def round_(w, mode, mixed=False):
        set_mode(mode)
        aut, gh = grammars[w]
        t0 = time.perf_counter()
        reqs = [be.submit(list(p), gen, sampler_for(j, mixed),
                          constraint=aut, constraint_hash=gh)
                for j, p in enumerate(suites[w])]
        outs = [r.wait(timeout=600) for r in reqs]
        wall = time.perf_counter() - t0
        for o in outs:
            check_valid(w, o)
        drafted = sum(r.stats.spec_drafted for r in reqs)
        accepted = sum(r.stats.spec_accepted for r in reqs)
        return {"tok_s": sum(len(o) for o in outs) / wall, "outs": outs,
                "drafted": drafted, "accepted": accepted}

    MODES = ("off", "ngram", "model", "grammar")
    rounds = 3
    results = {w: {m: [] for m in MODES} for w in grammars}
    mismatches = []
    try:
        for w in grammars:  # warm every program each mode touches
            for m in MODES:
                round_(w, m)
        # identity sweep: greedy AND seeded-stochastic rows must emit the
        # same bytes under every proposer mode (asserted in-run)
        for w in grammars:
            ref = None
            for m in MODES:
                r = round_(w, m, mixed=True)
                if ref is None:
                    ref = r["outs"]
                elif r["outs"] != ref:
                    mismatches.append((w, m, "mixed"))
        # timed sweep: interleaved rounds so box drift hits all arms
        # equally; identity asserted here too (all-greedy rows)
        for _ in range(rounds):
            for w in grammars:
                ref = None
                for m in MODES:
                    r = round_(w, m)
                    results[w][m].append(r)
                    if ref is None:
                        ref = r["outs"]
                    elif r["outs"] != ref:
                        mismatches.append((w, m))
        degraded = be.constrain_degraded
    finally:
        be.close()

    out = {"metric": f"b{B}k{K}spec{sk}_structured", "unit": "tok/s",
           "vs_baseline": None, "batch": B, "superstep": K,
           "speculative": sk, "pipeline": pipeline, "gen": gen,
           "rounds": rounds, "identical": not mismatches,
           "constrain_degraded": degraded,
           "model": (f"dim{spec.dim}_voc{spec.vocab_size}"
                     f"_L{spec.n_layers}_s{spec.seq_len}"),
           "workloads": {}}
    speedups = []
    for w in grammars:
        block = {}
        for m in MODES:
            rs = results[w][m]
            drafted = sum(r["drafted"] for r in rs)
            accepted = sum(r["accepted"] for r in rs)
            block[m] = {
                "tok_s": round(statistics.median(r["tok_s"] for r in rs), 3),
                "accept_rate": (round(accepted / drafted, 3)
                                if drafted else None),
            }
        block["speedup_grammar_vs_ngram"] = round(
            block["grammar"]["tok_s"] / block["ngram"]["tok_s"], 3)
        speedups.append(block["speedup_grammar_vs_ngram"])
        out["workloads"][w] = block
    out["speedup_grammar_vs_ngram"] = round(
        statistics.median(speedups), 3)
    out["value"] = round(statistics.median(
        out["workloads"][w]["grammar"]["tok_s"] for w in grammars), 3)
    print(json.dumps(out))
    ok = True
    if mismatches:
        print(f"❌ output diverged across proposer modes: {mismatches}",
              file=sys.stderr)
        ok = False
    if degraded:
        print(f"❌ {degraded} rows degraded to unconstrained decoding "
              "during a clean bench", file=sys.stderr)
        ok = False
    if out["speedup_grammar_vs_ngram"] < 1.0:
        print("❌ grammar drafting lost to ngram on constrained traffic: "
              f"{out['speedup_grammar_vs_ngram']}x", file=sys.stderr)
        ok = False
    if not ok:
        sys.exit(1)


def chaos_workload(args, spec):
    """--workload chaos: resilience cost of the unhappy path
    (docs/ROBUSTNESS.md). The identical concurrent-request schedule runs
    twice against one warmed BatchEngine — fault-free baseline, then with a
    --fault-rate (default 1%) injected TRANSIENT failure probability on
    every scheduler device dispatch (the retry-with-backoff path) — and
    reports survivor aggregate throughput degradation plus per-request TTFT
    p95 for both. Every request is expected to COMPLETE in both runs: a
    transient fault is retried, not surfaced; completion counts are emitted
    so a retry-path regression shows up as failed_requests > 0."""
    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.quants import FloatType as _FTy
    from distributed_llama_tpu.resilience import faults as _faults
    from distributed_llama_tpu.resilience.faults import FaultSpec
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    n_req = max(args.requests, 2)
    gen = 24  # decoded tokens per request
    rng = np.random.default_rng(0)
    prompts = [[1] + [int(t) for t in rng.integers(2, spec.vocab_size, 12)]
               for _ in range(n_req)]
    params = init_random_params(spec, _FTy.Q40, seed=0)
    B = args.batch if args.batch > 0 else min(max(n_req // 2, 2), 8)
    be = BatchEngine(spec, params, slots=B,
                     superstep=max(args.superstep, 1), tp=args.tp,
                     paged_kv=not args.no_paged_kv)
    out = {}
    samples = []
    try:
        # warm every compiled shape so both runs measure dispatch, not compile
        be.generate(list(prompts[0]), gen,
                    Sampler(spec.vocab_size, temperature=0.0))
        for label in ("baseline", "chaos"):
            plan = None
            if label == "chaos":
                plan = _faults.install(
                    [FaultSpec("batch.dispatch", kind="transient",
                               prob=args.fault_rate)], seed=7)
            try:
                ttfts, t0s, reqs = {}, {}, []

                def on_tok(i):
                    def cb(_t, i=i):
                        if i not in ttfts:
                            ttfts[i] = time.perf_counter() - t0s[i]
                    return cb

                t_all0 = time.perf_counter()
                for i in range(n_req):
                    t0s[i] = time.perf_counter()
                    reqs.append(be.submit(
                        list(prompts[i]), gen,
                        Sampler(spec.vocab_size, temperature=0.0),
                        on_token=on_tok(i)))
                failed = 0
                tokens = 0
                for i, r in enumerate(reqs):
                    err = None
                    try:
                        tokens += len(r.wait(timeout=600))
                    except Exception as ex:
                        failed += 1
                        err = repr(ex)
                    samples.append({"request_id": r.rid, "phase": label,
                                    "tenant": r.tenant, "class": r.klass,
                                    "ttft_s": ttfts.get(i), "e2e_s": None,
                                    "tokens": len(r.out), "replica": None,
                                    "error": err})
                e2e = time.perf_counter() - t_all0
            finally:
                _faults.uninstall()
            lat = sorted(ttfts.values())
            out[label] = {
                "tok_s": round(tokens / e2e, 3),
                # None, not a crash, when every request died pre-first-token
                # (e.g. --fault-rate 1.0 exhausts every dispatch's retries)
                "ttft_p95_ms": _pct_ms(lat, 0.95),
                "ttft_p99_ms": _pct_ms(lat, 0.99),
                "failed_requests": failed,
                "injected": plan.fired() if plan is not None else 0,
            }
    finally:
        be.close()
    if args.latency_log:
        write_latency_log(args.latency_log, samples)
    base, chaos = out["baseline"], out["chaos"]
    print(json.dumps({
        "metric": "chaos_survivor_tok_s",
        "value": chaos["tok_s"], "unit": "tok/s", "vs_baseline": None,
        "baseline_tok_s": base["tok_s"],
        "degradation_pct": round(
            100.0 * (1.0 - chaos["tok_s"] / max(base["tok_s"], 1e-9)), 2),
        "ttft_p95_ms": chaos["ttft_p95_ms"],
        "ttft_p99_ms": chaos["ttft_p99_ms"],
        "ttft_p95_baseline_ms": base["ttft_p95_ms"],
        "ttft_p99_baseline_ms": base["ttft_p99_ms"],
        "fault_rate": args.fault_rate,
        "injected_faults": chaos["injected"],
        "failed_requests": chaos["failed_requests"],
        "failed_requests_baseline": base["failed_requests"],
        "requests": n_req, "gen_tokens": gen, "batch": B,
        "superstep": max(args.superstep, 1),
    }))


def chaos_fleet_workload(args, spec):
    """--workload chaos --replicas N --kill-replica: the durable-request
    acceptance bench (docs/FLEET.md "Resume protocol"). Launches N real
    api_server subprocesses + the in-process DURABLE router, runs the
    identical request schedule twice — fault-free reference, then with one
    replica SIGKILLed (hard, no drain: the mid-stream failure graceful
    SIGTERM would hide) once the marker stream has delivered a few tokens —
    and asserts IN-RUN that every chaos-phase request completed with output
    byte-identical to its reference (greedy AND seeded-stochastic rows).
    Reports the resumed-request count from the router journal and the
    resume re-prefill prefix-cache reuse rate summed over the surviving
    replicas (nonzero = resume cost ≈ one suffix prefill, the tentpole's
    cost claim)."""
    import subprocess
    import tempfile
    import threading

    from distributed_llama_tpu.fleet.router import close_router, serve_router
    from distributed_llama_tpu.obs import metrics as obs_metrics

    n_rep = args.replicas
    if n_rep < 2:
        print("❌ --workload chaos --kill-replica needs --replicas >= 2 "
              "(a killed singleton has no survivor to resume on)",
              file=sys.stderr)
        sys.exit(2)
    tmp = tempfile.mkdtemp(prefix="dlt_chaos_fleet_")
    mpath, tpath = _write_fleet_model(tmp)
    ports = [_fleet_free_port() for _ in range(n_rep)]
    procs, logs = _spawn_fleet_replicas(
        tmp, mpath, tpath, ports,
        extra_argv=("--supervisor-threshold", "120"))
    _get_json = _fleet_get_json

    n_req = max(args.requests, 6)
    gen = 32
    system = "fleet chaos shared system prompt abcb abcb abcb"

    def req_body(i):
        # greedy AND seeded-stochastic rows, streaming AND non-streaming —
        # every combination must survive the kill token-identically
        return {"messages": [
            {"role": "system", "content": system},
            {"role": "user", "content": f"request {i} ab ab ab ab"}],
            "max_tokens": gen, "stream": i % 3 != 2,
            "temperature": 0.0 if i % 2 == 0 else 0.8,
            "seed": 1000 + i}

    def one_request(rport, i, results, on_delta=None):
        r = completion_request(rport, req_body(i), timeout=300,
                               on_delta=on_delta)
        if r["error"] is not None or r["status"] != 200:
            results[i] = {"error": r["error"] or f"status {r['status']}"}
            return
        results[i] = {"text": r["text"], "finish": r["finish"],
                      "replica": r["replica"]}

    router = None
    try:
        _await_fleet_healthy(procs, ports, tmp)
        router = serve_router([f"127.0.0.1:{p}" for p in ports],
                              host="127.0.0.1", port=0, poll_interval=0.5,
                              block_bytes=32, retries=2, try_timeout=300.0,
                              durable=True)
        rport = router.server_address[1]
        threading.Thread(target=router.serve_forever, daemon=True).start()

        def run_phase(kill: bool):
            results = [None] * n_req
            killed = []

            def on_marker_delta(n, replica):
                # SIGKILL the replica serving the marker stream once real
                # output has flowed — a hard mid-stream death, the case the
                # journal + resume machinery exists for
                if kill and n == 3 and not killed and replica:
                    victim_port = int(replica.rsplit(":", 1)[1])
                    killed.append(replica)
                    procs[ports.index(victim_port)].kill()
            threads = []
            sem = threading.Semaphore(2 * n_rep)

            def run_one(i):
                with sem:
                    one_request(rport, i, results,
                                on_delta=on_marker_delta if i == 0 else None)
            t0 = time.perf_counter()
            for i in range(n_req):
                t = threading.Thread(target=run_one, args=(i,))
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=600)
            return results, killed, time.perf_counter() - t0

        ref, _, _ = run_phase(kill=False)
        ref_failed = [(i, r) for i, r in enumerate(ref)
                      if r is None or "error" in r]
        if ref_failed:
            print(f"❌ fault-free reference phase failed: {ref_failed[:3]}",
                  file=sys.stderr)
            sys.exit(1)
        resumed0 = (obs_metrics.snapshot()
                    .get("router_resumed_requests_total") or 0)
        chaos, killed, wall = run_phase(kill=True)
        failed = [(i, r) for i, r in enumerate(chaos)
                  if r is None or "error" in r]
        diverged = [i for i, (a, b) in enumerate(zip(ref, chaos))
                    if a and b and "error" not in b
                    and a["text"] != b["text"]]
        snap = obs_metrics.snapshot()
        resumed = (snap.get("router_resumed_requests_total") or 0) - resumed0
        # resume re-prefill reuse over the SURVIVING replicas: the resumed
        # requests' prompt ⊕ delivered prefixes vs what their admissions
        # actually re-ran (slot rewind + radix pool seed)
        reused = prefix = 0.0
        for port, proc in zip(ports, procs):
            if proc.poll() is not None:
                continue
            try:
                st, body = _get_json(port, "/v1/stats", timeout=10)
            except OSError:
                continue
            m = (body or {}).get("metrics") or {}
            reused += m.get("api_resume_reused_tokens_total", 0) or 0
            prefix += m.get("api_resume_prefix_tokens_total", 0) or 0
        reuse_rate = round(reused / prefix, 3) if prefix else 0.0
        print(json.dumps({
            "metric": "chaos_kill_replica_resumed_requests",
            "value": int(resumed), "unit": "requests", "vs_baseline": None,
            "replicas": n_rep, "requests": n_req, "gen_tokens": gen,
            "killed_replica": killed[0] if killed else None,
            "failed_requests": len(failed),
            "failures": [f"{i}: {r}" for i, r in failed[:5]],
            "diverged_requests": diverged,
            "identical": not failed and not diverged,
            "resume_prefix_reuse_rate": reuse_rate,
            "resume_reused_tokens": int(reused),
            "resume_prefix_tokens": int(prefix),
            "wall_s": round(wall, 2),
        }))
        # in-run acceptance gates (ISSUE 9): a kill that never engaged, a
        # client-visible failure, a diverged resume, or a resume that
        # re-prefilled everything from scratch all fail the bench
        if not killed:
            print("❌ the kill never engaged (marker stream finished first)",
                  file=sys.stderr)
            sys.exit(1)
        if failed or diverged:
            print(f"❌ {len(failed)} failed, {len(diverged)} diverged",
                  file=sys.stderr)
            sys.exit(1)
        if resumed < 1:
            print("❌ no request was resumed — the kill was not mid-stream",
                  file=sys.stderr)
            sys.exit(1)
        if reuse_rate <= 0.0:
            print("❌ resume re-prefill hit nothing in the prefix cache",
                  file=sys.stderr)
            sys.exit(1)
    finally:
        if router is not None:
            close_router(router)
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=90)
            except subprocess.TimeoutExpired:
                proc.kill()
        for log in logs:
            log.close()


def chaos_degrade_workload(args, spec):
    """--workload chaos --replicas N --degrade-replica: the GRAY-failure
    acceptance bench (docs/FLEET.md "Gray-failure resilience"). Two real
    fleets run the IDENTICAL seeded schedule through identically-armed
    routers (probation + adaptive timeouts + bounded hedging): first a
    healthy baseline, then a fleet whose replica 0 carries a SUSTAINED
    8-10x request-latency injection (`DLLAMA_FAULTS` duration window in
    that subprocess only — it answers healthz ok while serving slow, the
    gray shape the router must detect from outcomes alone). Gates IN-RUN:

    - 0 client-visible failures in the degraded phase;
    - degraded-fleet TTFT p99 <= 2x the healthy baseline (plus one hedge
      delay + timer-noise floor — the victim's UN-governed latency is the
      9x injection, far past the gate either way);
    - hedge spend within the armed budget (the bench arms a CI-scale
      budget: in a 2-replica fleet HALF of cold picks hit the victim,
      nothing like production's 1/N share under the 5% default);
    - the victim observed ENTERING probation while slow and REJOINING
      after the injection window expires (canary-driven).

    Emits TTFT/TPOT p50/p95/p99 both ways plus hedge/probation counters in
    the standard BENCH json."""
    import http.client
    import subprocess
    import tempfile
    import threading

    from distributed_llama_tpu.fleet.latency import GrayConfig
    from distributed_llama_tpu.fleet.router import close_router, serve_router
    from distributed_llama_tpu.obs import metrics as obs_metrics

    n_rep = args.replicas
    if n_rep < 2:
        print("❌ --workload chaos --degrade-replica needs --replicas >= 2 "
              "(a degraded singleton has nowhere to hedge or fail over)",
              file=sys.stderr)
        sys.exit(2)
    n_req = max(args.requests, 24)
    gen = 16
    degrade_window_s = 60.0

    def req_body(i):
        # unique LEADING system prompts: the affinity key is
        # block-granular, so a shared prefix would pin the whole schedule
        # to one replica and the victim would see no traffic to be judged
        # on; greedy AND seeded-stochastic rows, all streaming (TTFT and
        # TPOT are client-side first-delta/delta-gap timings)
        return {"messages": [
            {"role": "system", "content": f"d{i:03d} gray degrade system"},
            {"role": "user", "content": "ab ab ab ab"}],
            "max_tokens": gen, "stream": True,
            "temperature": 0.0 if i % 2 == 0 else 0.8,
            "seed": 2000 + i}

    def one_request(rport, i, results):
        r = completion_request(rport, req_body(i), timeout=300)
        if r["error"] is not None or r["status"] != 200:
            results[i] = {"error": r["error"]
                          or f"status {r['status']}"}
            return
        results[i] = {"ttft": r["ttft"], "tpot": r["tpot"], "error": None}

    def warm_replica(port):
        # direct (router-bypassing) compile warm: a cold XLA build is tens
        # of seconds on CPU and would smear both phases' percentiles
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        try:
            for temperature in (0.0, 0.8):
                conn.request("POST", "/v1/chat/completions", json.dumps({
                    "messages": [
                        {"role": "system", "content": "warm system"},
                        {"role": "user", "content": "ab ab"}],
                    "max_tokens": 8, "stream": False, "seed": 7,
                    "temperature": temperature},),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    raise RuntimeError(f"warm of :{port} failed "
                                       f"({resp.status})")
        finally:
            conn.close()

    hedge_pct, hedge_burst = 0.25, 8.0

    def bench_gray_config(hedge_delay):
        # CI-scale arming: fast detection (6 samples, 3x median), a FIXED
        # hedge delay (adaptive p95 defers itself when HALF the fleet is
        # slow — the 2-replica pathology), and a budget sized for a
        # schedule where ~half of cold picks hit the victim. The delay
        # must sit ABOVE healthy TTFB (or healthy picks hedge too and
        # drain the budget the victim picks need) and far below the
        # injected delay: the degraded phase pins it from the measured
        # healthy p95.
        return GrayConfig(eject_multiple=3.0, min_samples=6,
                          probation_exits=3, canary_every=4,
                          quorum_frac=0.5, min_lat_samples=12,
                          hedge=True, hedge_delay=hedge_delay,
                          hedge_pct=hedge_pct, hedge_burst=hedge_burst)

    def labeled(snap, name):
        return {k.split('"')[1]: v
                for k, v in (snap.get(name) or {}).items()}

    def run_phase(label, victim_env, hedge_delay, window_s=0.0):
        tmp = tempfile.mkdtemp(prefix=f"dlt_gray_{label}_")
        mpath, tpath = _write_fleet_model(tmp)
        ports = [_fleet_free_port() for _ in range(n_rep)]
        procs, logs = _spawn_fleet_replicas(
            tmp, mpath, tpath, ports,
            per_replica_env=[victim_env if i == 0 else None
                             for i in range(n_rep)])
        router = None
        out = {"label": label}
        try:
            _await_fleet_healthy(procs, ports, tmp)
            # non-victim replicas warm first: the victim's fault window
            # starts at ITS first request, so it is warmed last and the
            # schedule starts immediately after
            for port in ports[1:]:
                warm_replica(port)
            tw = time.perf_counter()
            warm_replica(ports[0])
            out["warm_victim_s"] = round(time.perf_counter() - tw, 2)
            router = serve_router([f"127.0.0.1:{p}" for p in ports],
                                  host="127.0.0.1", port=0,
                                  poll_interval=0.3, block_bytes=32,
                                  retries=2, try_timeout=120.0, durable=True,
                                  gray=bench_gray_config(hedge_delay))
            rport = router.server_address[1]
            threading.Thread(target=router.serve_forever,
                             daemon=True).start()
            state = router.router_state
            victim = state.membership.by_id(f"127.0.0.1:{ports[0]}")
            probation = {"entered": False, "exited_after_entry": False,
                         "stop": False}

            def watch():
                seen = False
                while not probation["stop"]:
                    if victim.degraded:
                        seen = probation["entered"] = True
                    elif seen:
                        probation["exited_after_entry"] = True
                    time.sleep(0.05)
            watcher = threading.Thread(target=watch, daemon=True)
            watcher.start()

            results = [None] * n_req
            sem = threading.Semaphore(3)

            def run_one(i):
                with sem:
                    one_request(rport, i, results)
            snap0 = obs_metrics.snapshot()
            t0 = time.perf_counter()
            threads = [threading.Thread(target=run_one, args=(i,))
                       for i in range(n_req)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            wall = time.perf_counter() - t0
            if victim_env is not None:
                # keep outcome evidence flowing until probation entry,
                # then until the injection window has expired and the
                # canary trickle rejoins the victim
                probe_res = {}
                i = n_req
                deadline = time.monotonic() + 120
                while (not probation["entered"]
                       and time.monotonic() < deadline):
                    one_request(rport, i, probe_res)
                    i += 1
                deadline = time.monotonic() + 120 + window_s
                while ((victim.degraded or not
                        probation["exited_after_entry"])
                       and time.monotonic() < deadline):
                    one_request(rport, i, probe_res)
                    i += 1
                out["probe_requests"] = i - n_req
                out["probe_failures"] = sum(
                    1 for r in probe_res.values()
                    if r is None or r.get("error") is not None)
            probation["stop"] = True
            watcher.join(timeout=5)
            snap1 = obs_metrics.snapshot()
            hedges0 = labeled(snap0, "router_hedges_total")
            hedges1 = labeled(snap1, "router_hedges_total")
            prob0 = labeled(snap0, "router_probation_total")
            prob1 = labeled(snap1, "router_probation_total")
            ttfts = sorted(r["ttft"] for r in results
                           if r and r.get("error") is None
                           and r.get("ttft") is not None)
            tpots = sorted(r["tpot"] for r in results
                           if r and r.get("error") is None
                           and r.get("tpot") is not None)
            budget = state.hedge_budget.stats()
            out.update({
                "failed": [(i, r) for i, r in enumerate(results)
                           if r is None or r.get("error") is not None],
                "wall_s": round(wall, 2),
                "ttft_p50_ms": _pct_ms(ttfts, 0.50),
                "ttft_p95_ms": _pct_ms(ttfts, 0.95),
                "ttft_p99_ms": _pct_ms(ttfts, 0.99),
                "tpot_p50_ms": _pct_ms(tpots, 0.50),
                "tpot_p95_ms": _pct_ms(tpots, 0.95),
                "tpot_p99_ms": _pct_ms(tpots, 0.99),
                "hedges": {k: int((hedges1.get(k) or 0)
                                  - (hedges0.get(k) or 0))
                           for k in ("launched", "won", "denied", "canary")},
                "probation": {k: int((prob1.get(k) or 0)
                                     - (prob0.get(k) or 0))
                              for k in ("enter", "exit")},
                "hedge_budget": budget,
                "probation_entered": probation["entered"],
                "probation_exited": probation["exited_after_entry"],
                "degraded_roster_now": [r.id for r in
                                        state.membership.replicas
                                        if r.degraded],
            })
            return out
        finally:
            if router is not None:
                close_router(router)
            for proc in procs:
                if proc.poll() is None:
                    proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=90)
                except subprocess.TimeoutExpired:
                    proc.kill()
            for log in logs:
                log.close()

    # healthy baseline: hedge delay parked above any plausible healthy
    # TTFB on this box (we have no measurement yet; a delay under healthy
    # latency would hedge ordinary picks)
    healthy = run_phase("healthy", None, hedge_delay=1.0)
    if healthy["failed"]:
        print(f"❌ healthy baseline phase failed: {healthy['failed'][:3]}",
              file=sys.stderr)
        sys.exit(1)
    # sustained 8-10x: the injected stall is ~9x the measured healthy
    # median request time, floored so it dwarfs CI timer noise
    delay_ms = max(9.0 * healthy["ttft_p50_ms"], 1000.0)
    # degraded-phase hedge delay pinned from the MEASURED healthy tail:
    # above p95 (healthy picks almost never hedge, preserving budget for
    # victim picks) and far below the injection
    hedge_delay = min(max(1.5 * healthy["ttft_p95_ms"] / 1000.0, 0.3), 1.5)
    # the victim's fault window opens at its FIRST request — its own two
    # compile-warm requests. Size the window from the healthy phase's
    # MEASURED victim warm (plus the injected stall those warms now pay)
    # so a slow box cannot burn the injection before the schedule starts
    window_s = (degrade_window_s + 2.0 * healthy["warm_victim_s"]
                + 2.0 * delay_ms / 1000.0)
    degraded = run_phase("degraded", {
        "DLLAMA_FAULTS":
            f"api.request:latency:1::{delay_ms:.0f}:{window_s:.0f}",
        "DLLAMA_FAULT_SEED": "7"}, hedge_delay=hedge_delay,
        window_s=window_s)

    # the p99 gate: 2x healthy, floored by one hedge delay + p50 service
    # + timer noise (a hedged victim pick LEGITIMATELY costs delay+service;
    # on a fast box 2x p99 alone can be smaller than that)
    gate_ms = max(2.0 * healthy["ttft_p99_ms"],
                  healthy["ttft_p99_ms"] + hedge_delay * 1000.0 + 400.0)
    budget = degraded["hedge_budget"]
    allowance = budget["cap"] + hedge_pct * budget["noted"]
    print(json.dumps({
        "metric": "chaos_degrade_ttft_p99_ms",
        "value": degraded["ttft_p99_ms"], "unit": "ms",
        "vs_baseline": None,
        "replicas": n_rep, "requests": n_req, "gen_tokens": gen,
        "injected_delay_ms": round(delay_ms, 1),
        "injected_window_s": round(window_s, 1),
        "hedge_delay_ms": round(hedge_delay * 1000.0, 1),
        "ttft_gate_ms": round(gate_ms, 2),
        "healthy": {k: healthy[k] for k in
                    ("ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
                     "tpot_p50_ms", "tpot_p95_ms", "tpot_p99_ms",
                     "wall_s", "hedges")},
        "degraded": {k: degraded[k] for k in
                     ("ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
                      "tpot_p50_ms", "tpot_p95_ms", "tpot_p99_ms",
                      "wall_s", "hedges", "probation", "probe_requests",
                      "probe_failures", "probation_entered",
                      "probation_exited")},
        "hedge_budget": budget,
        "hedge_allowance": round(allowance, 2),
        "failed_requests": len(degraded["failed"]),
        "failures": [f"{i}: {r}" for i, r in degraded["failed"][:5]],
    }))
    # in-run acceptance gates (ISSUE 14)
    if degraded["failed"] or degraded.get("probe_failures"):
        print(f"❌ client-visible failures in the degraded phase: "
              f"{degraded['failed'][:3]} "
              f"(+{degraded.get('probe_failures', 0)} probe)",
              file=sys.stderr)
        sys.exit(1)
    if degraded["ttft_p99_ms"] > gate_ms:
        print(f"❌ degraded TTFT p99 {degraded['ttft_p99_ms']}ms over the "
              f"gate {gate_ms:.0f}ms (healthy p99 "
              f"{healthy['ttft_p99_ms']}ms)", file=sys.stderr)
        sys.exit(1)
    if degraded["hedges"]["launched"] < 1:
        print("❌ vacuous: no hedge launched in the degraded phase",
              file=sys.stderr)
        sys.exit(1)
    # gate the LAUNCH-SITE counter, not budget["spent"]: TokenBudget keeps
    # spent <= cap + rate*noted by construction, so gating its own ledger
    # would be tautological — a regression that launches duplicate tries
    # without spending a token must still fail here
    if degraded["hedges"]["launched"] > allowance:
        print(f"❌ hedges launched {degraded['hedges']['launched']} over "
              f"the configured allowance {allowance:.1f}", file=sys.stderr)
        sys.exit(1)
    if not degraded["probation_entered"]:
        print("❌ the victim never entered gray-failure probation",
              file=sys.stderr)
        sys.exit(1)
    if not degraded["probation_exited"] or degraded["degraded_roster_now"]:
        print("❌ the victim never rejoined after the injection window "
              f"expired (roster {degraded['degraded_roster_now']})",
              file=sys.stderr)
        sys.exit(1)


def trace_workload(args, spec):
    """--workload trace: the multi-tenant SLO acceptance bench
    (docs/SERVING.md "Multi-tenant serving"). A seeded trace-driven load
    generator — bursty arrivals (on/off-modulated exponential gaps),
    heavy-tailed lognormal prompt/output lengths, a configurable tenant mix
    — drives one BatchEngine at ~`--overload`x (default 2x) its MEASURED
    sustained capacity, and the BENCH json gates the SLO story in-run:

    - interactive TTFT p95 within 1.5x of its uncontended value, plus an
      absolute floor of the documented admission window (two in-flight
      K-step dispatches = 2*K*B/capacity wall seconds — milliseconds on
      accelerators, dominant on a 2-core CI box) and 30 ms timer noise;
    - ZERO failed interactive requests (batch sheds first: queue-full
      evictions displace batch, preemption frees slots at super-step
      boundaries);
    - batch-class sheds carry honest drain-derived Retry-After (503), the
      quota-capped tenant sees 429s with bucket-derived Retry-After;
    - every backlogged unthrottled tenant's delivered-token share within
      ε of its configured weight (gold:silver:bronze = 3:2:1; the
      quota-capped fourth tenant is excluded — its share is bound by its
      bucket, not its weight, and WFQ redistributes what it cannot use).

    Phases: calibrate (measure capacity tok/s + drain), uncontended
    interactive TTFT baseline, then the overload trace. One engine, shapes
    warmed by calibration, so the phases compare scheduling — not compiles.
    """
    from distributed_llama_tpu.models.params import init_random_params
    from distributed_llama_tpu.quants import FloatType as _FTy
    from distributed_llama_tpu.resilience.errors import (EngineSaturated,
                                                         QuotaExceeded)
    from distributed_llama_tpu.resilience.tenancy import TenantRegistry
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.runtime.sampler import Sampler

    rng = np.random.default_rng(args.seed if hasattr(args, "seed") else 0)
    B = args.batch if args.batch > 0 else 4
    K = max(args.superstep, 1)
    weights = {"gold": 3.0, "silver": 2.0, "bronze": 1.0}
    reg = TenantRegistry.parse(
        "gold:weight=3;silver:weight=2;bronze:weight=1;capped:weight=1")
    params = init_random_params(spec, _FTy.Q40, seed=0)
    be = BatchEngine(spec, params, slots=B, superstep=K, tp=args.tp,
                     tenants=reg, max_queue=4 * B,
                     paged_kv=not args.no_paged_kv)
    greedy = lambda: Sampler(spec.vocab_size, temperature=0.0)  # noqa: E731

    def lens(n, mean_log, sigma, lo, hi):
        return np.clip(np.exp(rng.normal(mean_log, sigma, n)).astype(int),
                       lo, hi)

    out = {}
    try:
        # --- phase 1: calibrate sustained capacity (also warms shapes) ---
        # prompt lengths span the PREFILL_CHUNKS buckets (64/8/1) the
        # heavy-tailed trace will hit: a cold (B, 64) prefill compile
        # landing MID-TRACE would stall the scheduler ~1s and corrupt the
        # interactive TTFT gate with XLA time, not scheduling time
        # (production pre-warms; perf/compile_manifest.json pins shapes)
        def cal_round(plens):
            cal = [be.submit(
                [1] + [int(t) for t in rng.integers(2, 200, plens[
                    i % len(plens)])],
                24, greedy(), klass="batch") for i in range(2 * B)]
            t0 = time.perf_counter()
            toks = sum(len(r.wait(timeout=600)) for r in cal)
            return toks / (time.perf_counter() - t0)

        cal_round([150, 80, 24, 10])  # warm compiles across chunk buckets
        cap_tok_s = cal_round([24, 10, 17, 31])  # measure capacity, not XLA

        # --- phase 2: uncontended interactive TTFT baseline ---
        def run_interactive(tenant):
            t_sub = time.perf_counter()
            first = [None]

            def on_tok(_t):
                if first[0] is None:
                    first[0] = time.perf_counter() - t_sub
            r = be.submit([1] + [int(t) for t in rng.integers(2, 200, 7)],
                          8, greedy(), on_token=on_tok, tenant=tenant,
                          klass="interactive")
            r.wait(timeout=600)
            return first[0]

        unc = sorted(filter(None, (run_interactive("gold")
                                   for _ in range(20))))
        unc_p95 = _pct(unc, 0.95)

        # --- phase 3: the overload trace ---
        mean_gen = 20.0
        batch_rps = args.overload * cap_tok_s / mean_gen  # offered, total
        duration = args.duration
        n_batch = int(batch_rps * duration)
        if n_batch > 1500:  # bound the host-side submit work, say so
            print(f"# arrival cap: {n_batch} -> 1500 batch arrivals "
                  f"(duration shrinks to keep the {args.overload}x rate)",
                  file=sys.stderr)
            n_batch = 1500
            duration = n_batch / batch_rps
        events = []  # (t, tenant, klass, prompt_len, gen)
        share = 1.0 / (len(weights) + 1)  # equal demand incl. capped
        for tenant in (*weights, "capped"):
            t = 0.0
            rate = batch_rps * share
            n = 0
            while t < duration and n < n_batch:
                # bursty: on/off modulation — arrivals at 2.5x the mean
                # rate during the first 40% of each second, silent after
                gap = rng.exponential(1.0 / (2.5 * rate))
                t += gap
                if (t % 1.0) > 0.4:
                    t = np.floor(t) + 1.0  # skip to the next burst window
                if t >= duration:
                    break
                events.append((t, tenant, "batch", 0, 0))
                n += 1
        # heavy-tailed lengths, assigned after the count is known
        plens = lens(len(events), 2.2, 0.8, 4, max(spec.seq_len // 3, 8))
        glens = lens(len(events), 2.8, 0.9, 4, 48)
        events = [(t, tn, kl, int(p), int(g)) for (t, tn, kl, _p, _g), p, g
                  in zip(events, plens, glens)]
        # interactive trickle: gold + silver, one every ~0.6 s each (enough
        # samples that the p95 gate reads a distribution, not one outlier)
        for tenant in ("gold", "silver"):
            t = 0.3
            while t < duration:
                events.append((t, tenant, "interactive", 8, 8))
                t += 0.6
        events.sort(key=lambda e: e[0])
        # quota for the capped tenant: half its offered token rate, so the
        # bucket MUST throttle under the sustained trace
        capped_tok_s = batch_rps * share * mean_gen
        reg.set_quota("capped", rate=0.5 * capped_tok_s,
                      burst=capped_tok_s)

        recs = []
        t_start = time.perf_counter()
        for (t_at, tenant, klass, plen, gen) in events:
            now = time.perf_counter() - t_start
            if t_at > now:
                time.sleep(t_at - now)
            rec = {"tenant": tenant, "class": klass, "gen": gen,
                   "t_sub": time.perf_counter(), "first": None,
                   "last": None, "n": 0, "shed": None, "retry_after": None}

            def on_tok(_t, rec=rec):
                now = time.perf_counter()
                if rec["first"] is None:
                    rec["first"] = now
                rec["last"] = now
                rec["n"] += 1
            try:
                rec["req"] = be.submit(
                    [1] + [int(x) for x in rng.integers(2, 200, plen)],
                    gen, greedy(), on_token=on_tok, tenant=tenant,
                    klass=klass)
            except QuotaExceeded as e:
                rec["shed"] = "quota"
                rec["retry_after"] = e.retry_after
            except EngineSaturated as e:
                rec["shed"] = "saturated"
                rec["retry_after"] = e.retry_after
            recs.append(rec)
        for rec in recs:
            if rec["shed"] is None:
                try:
                    rec["req"].wait(timeout=600)
                except Exception as e:
                    rec["shed"] = f"error: {e!r}"

        # --- analysis + gates ---
        def pct_block(rs):
            ttft = sorted(r["first"] - r["t_sub"] for r in rs
                          if r["first"] is not None)
            tpot = sorted((r["last"] - r["first"]) / (r["n"] - 1)
                          for r in rs
                          if r["first"] is not None and r["n"] > 1)
            e2e = sorted(r["last"] - r["t_sub"] for r in rs
                         if r["last"] is not None)
            return {
                "requests": len(rs),
                "completed": sum(1 for r in rs if r["shed"] is None),
                "shed": sum(1 for r in rs if r["shed"] is not None),
                "ttft_p50_ms": _pct_ms(ttft, 0.50),
                "ttft_p95_ms": _pct_ms(ttft, 0.95),
                "ttft_p99_ms": _pct_ms(ttft, 0.99),
                "tpot_p50_ms": _pct_ms(tpot, 0.50),
                "tpot_p95_ms": _pct_ms(tpot, 0.95),
                "tpot_p99_ms": _pct_ms(tpot, 0.99),
                "e2e_p95_ms": _pct_ms(e2e, 0.95),
            }

        per_tenant = {}
        for tenant in (*weights, "capped"):
            per_tenant[tenant] = {
                klass: pct_block([r for r in recs if r["tenant"] == tenant
                                  and r["class"] == klass])
                for klass in ("interactive", "batch")
                if any(r["tenant"] == tenant and r["class"] == klass
                       for r in recs)}
        inter = [r for r in recs if r["class"] == "interactive"]
        batch = [r for r in recs if r["class"] == "batch"]
        inter_failed = [r for r in recs if r["class"] == "interactive"
                        and r["shed"] is not None]
        batch_shed = [r for r in batch if r["shed"] == "saturated"]
        quota_shed = [r for r in recs if r["shed"] == "quota"]
        delivered = {t: sum(r["n"] for r in batch if r["tenant"] == t
                            and r["shed"] is None) for t in weights}
        total_delivered = max(sum(delivered.values()), 1)
        total_w = sum(weights.values())
        shares = {t: delivered[t] / total_delivered for t in weights}
        share_err = {t: abs(shares[t] - weights[t] / total_w)
                     for t in weights}
        inter_ttft = sorted(r["first"] - r["t_sub"] for r in inter
                            if r["first"] is not None)
        inter_p95 = _pct(inter_ttft, 0.95)
        # admission-latency bound (docs/SERVING.md): an interactive arrival
        # waits out at most the in-flight dispatch pair (pipelined depth 2)
        # before preemption/class-priority get it a slot. The largest
        # single dispatch is either a K-step super-step (K*B tokens) or a
        # max-chunk prefill (PREFILL_CHUNKS[0] positions, with riders), so
        # the window is 2*(chunk + K*B)/capacity wall seconds. On
        # accelerators that is milliseconds and the gate tends to pure
        # 1.5x; on a 2-core CI box the dispatch window dominates a ~50 ms
        # uncontended TTFT, so the gate adds it (plus 30 ms timer noise) as
        # the absolute floor — a multi-second queueing pathology (e.g. the
        # cold-compile stall this bench caught during development) still
        # fails by an order of magnitude.
        from distributed_llama_tpu.runtime.engine import PREFILL_CHUNKS

        adm_window = (2.0 * (PREFILL_CHUNKS[0] + K * B)
                      / max(cap_tok_s, 1e-9))
        ttft_gate = (unc_p95 is not None and inter_p95 is not None
                     and inter_p95 <= max(1.5 * unc_p95,
                                          unc_p95 + adm_window + 0.030))
        gates = {
            "zero_failed_interactive": not inter_failed,
            "interactive_ttft_within_1_5x": bool(ttft_gate),
            "batch_sheds_honest": bool(batch_shed) and all(
                r["retry_after"] and 0.0 < r["retry_after"] <= 60.0
                for r in batch_shed),
            "quota_throttles_honest": bool(quota_shed) and all(
                r["retry_after"] and r["retry_after"] > 0.0
                for r in quota_shed),
            "shares_within_eps": all(e <= 0.12 for e in share_err.values()),
        }
        out = {
            "metric": "trace_interactive_ttft_p95_ms",
            "value": round(inter_p95 * 1e3, 2) if inter_p95 else None,
            "unit": "ms", "vs_baseline": None,
            "uncontended_ttft_p95_ms": round(unc_p95 * 1e3, 2)
            if unc_p95 else None,
            "ttft_ratio": round(inter_p95 / unc_p95, 3)
            if inter_p95 and unc_p95 else None,
            "admission_window_ms": round(adm_window * 1e3, 2),
            "capacity_tok_s": round(cap_tok_s, 1),
            "overload": args.overload,
            "duration_s": round(duration, 2),
            "arrivals": len(recs),
            "interactive_requests": len(inter),
            "interactive_failed": len(inter_failed),
            "batch_shed": len(batch_shed),
            "quota_throttled": len(quota_shed),
            "retry_after_p50_s": _pct(sorted(
                r["retry_after"] for r in batch_shed
                if r["retry_after"] is not None), 0.5),
            "tenant_shares": {t: round(s, 3) for t, s in shares.items()},
            "tenant_share_target": {t: round(w / total_w, 3)
                                    for t, w in weights.items()},
            "tenant_share_err": {t: round(e, 3)
                                 for t, e in share_err.items()},
            "per_tenant": per_tenant,
            "gates": gates,
            "batch": B, "superstep": K,
        }
        print(json.dumps(out))
        if args.latency_log:
            write_latency_log(args.latency_log, [
                {"request_id": (r.get("req").rid if r.get("req") is not None
                                else None),
                 "tenant": r["tenant"], "class": r["class"],
                 "ttft_s": (r["first"] - r["t_sub"])
                 if r["first"] is not None else None,
                 "e2e_s": (r["last"] - r["t_sub"])
                 if r["last"] is not None else None,
                 "tokens": r["n"], "replica": None, "shed": r["shed"],
                 "retry_after_s": r["retry_after"]} for r in recs])
        if not all(gates.values()):
            print(f"❌ SLO gates failed: "
                  f"{[k for k, v in gates.items() if not v]}",
                  file=sys.stderr)
            sys.exit(1)
    finally:
        be.close()


def vs_baseline(args, tok_s: float):
    """Ratio vs the reference's published number — which exists only for the
    Llama-2-7B single-node config (README.md:131). Other archs report null rather
    than a ratio against the wrong model's baseline."""
    if args.arch == "llama2_7b" and not args.small:
        return round(tok_s / BASELINE_TOK_S, 3)
    return None


def metric_name(args) -> str:
    if getattr(args, "batch", 0) > 0:
        # B and K are part of the metric identity: the serving trajectory
        # tracks aggregate tok/s per (B, K) point across rounds. K mirrors
        # the bench loop's clamp (max(superstep, 1)) so the label always
        # names the configuration actually measured.
        kind = f"b{args.batch}k{max(args.superstep, 1)}_decode"
    else:
        kind = ("prefill" if args.prefill > 0
                else "paged_decode" if getattr(args, "kv_paged", 0) > 0
                else "decode")
    if args.small:
        return (f"small_{kind}_tok_s" if kind == "prefill"
                else f"small_q40_{kind}_tok_s")
    return f"{args.arch}_q40_{kind}_tok_s"


_sentinel_owned = False  # did THIS process write the driver sentinel?


def _exit_now(code: int):
    """Exit WITHOUT running atexit/teardown. A probe that timed out leaves a
    half-initialized PJRT client whose shutdown hooks can block forever against
    a wedged tunnel — observed 2026-07-31 04:10: bench printed its JSON line,
    then hung in interpreter teardown until the caller's 300 s watchdog killed
    it (losing the rc). Never called under DLT_WARM_RUNNER (in-process bench
    must raise SystemExit, not kill the runner). Removes the driver sentinel
    ONLY if this process created it — a test-mode subprocess must not delete a
    real concurrent driver's pause marker."""
    try:
        sys.stdout.flush()
        sys.stderr.flush()
        if _sentinel_owned and os.path.exists(SENTINEL):
            os.remove(SENTINEL)
    except OSError:
        pass
    os._exit(code)


def probe_backend(timeout_s: float | None = None) -> tuple[str | None, str]:
    """Resolve the backend AND fence a tiny op under a watchdog. The axon tunnel can
    wedge such that even backend initialization hangs forever (observed 2026-07-29:
    >4 h outage) or crawl so init takes minutes (2026-07-30 half-alive mode);
    without this, a bench run would hang instead of reporting. Returns
    (backend name or None, failure description). DLT_PROBE_TIMEOUT overrides."""
    import threading

    if timeout_s is None:
        timeout_s = float(os.environ.get("DLT_PROBE_TIMEOUT", 600))

    got: list[str] = []
    err: list[str] = []

    def probe():
        try:
            b = jax.default_backend()  # triggers PJRT/tunnel init
            np.asarray(jnp.ones((4,)) + 1)
            got.append(b)
        except Exception as e:
            err.append(f"device init/probe raised: {e!r}")

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if got:
        return got[0], ""
    return None, (err[0] if err else
                  f"backend init / a trivial fenced op did not complete within "
                  f"{timeout_s:.0f} s (known axon outage mode; see perf/PROFILE.md)")


def main():
    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true", help="tiny model (CI smoke)")
    ap.add_argument("--arch", choices=sorted(ARCHS), default="llama2_7b",
                    help="which BASELINE.json config shape to bench")
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--layout", choices=("i4p", "i8"), default="i4p")
    ap.add_argument("--cache-write", choices=("inscan", "deferred"), default="deferred",
                    help="KV cache discipline: 'inscan' carries the caches through "
                         "the layer scan with per-layer in-place updates; 'deferred' "
                         "keeps them loop-invariant and commits all layers' new rows "
                         "in one top-level write (kills the carry copies the round-4 "
                         "trace found)")
    ap.add_argument("--window", type=int, default=256,
                    help="attention window bucket (cache positions decode reads)")
    ap.add_argument("--device-loop", type=int, default=0, metavar="N",
                    help="use the on-device scan loop, N tokens per dispatch")
    ap.add_argument("--batch", type=int, default=0, metavar="B",
                    help="serving-throughput mode: B cache rows decode through "
                         "the batched K-step device loop (BatchEngine's hot "
                         "path); reports aggregate_decode_tok_s = B*K/dispatch")
    ap.add_argument("--superstep", type=int, default=8, metavar="K",
                    help="decode steps fused per dispatch in --batch mode")
    ap.add_argument("--pipeline", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="with --batch: drive the REAL BatchEngine scheduler "
                         "(admission + host-side block delivery) instead of "
                         "the raw device loop, with pipelined super-steps on "
                         "(--pipeline) or off (--no-pipeline) — the A/B "
                         "surface for docs/SERVING.md \"Pipelined decode\". "
                         "Omit for the raw-loop headline measurement")
    ap.add_argument("--prefill", type=int, default=0, metavar="T",
                    help="bench chunked prefill throughput at chunk size T instead "
                         "of decode")
    ap.add_argument("--workload",
                    choices=("shared-prefix", "chaos", "repetition",
                             "spec-suite", "structured", "trace",
                             "mixed-context"),
                    default=None,
                    help="scenario mode: 'shared-prefix' drives the BatchEngine "
                         "with a common-system-prompt multi-request workload and "
                         "reports TTFT p50/p95 + prefix_hit_rate, cache on vs "
                         "off; 'chaos' runs the same schedule fault-free vs "
                         "with --fault-rate injected transient dispatch "
                         "failures and reports survivor-throughput degradation "
                         "+ TTFT p95 (docs/ROBUSTNESS.md); 'repetition' drives "
                         "n-gram-dense (code/JSON-shaped) prompts through the "
                         "batched scheduler spec-off vs --speculative K and "
                         "reports tok/s both ways + accept rate "
                         "(docs/SERVING.md \"Speculative decoding\"); "
                         "'trace' drives the multi-tenant scheduler at "
                         "--overload x measured capacity with seeded bursty "
                         "arrivals, heavy-tailed lengths, and a weighted "
                         "tenant mix, gating the SLO story in-run "
                         "(docs/SERVING.md \"Multi-tenant serving\"); "
                         "'mixed-context' A/Bs a role-split disaggregated "
                         "2-replica fleet against a monolithic one under "
                         "co-scheduled long prefills + short decode chains, "
                         "gating decode TPOT p95 and the zero-re-prefill "
                         "claim in-run (docs/DISAGG.md); 'spec-suite' runs "
                         "chat/code/json/open-ended generators through one "
                         "engine with proposer=off/ngram/model rounds "
                         "interleaved, asserting byte-identity in-run and "
                         "reporting per-workload accept rate + tok/s "
                         "(docs/SERVING.md \"Model-based drafting\")")
    ap.add_argument("--overload", type=float, default=2.0, metavar="X",
                    help="trace workload: offered batch load as a multiple "
                         "of the engine's measured sustained capacity")
    ap.add_argument("--duration", type=float, default=10.0, metavar="S",
                    help="trace workload: overload-phase length (arrivals "
                         "capped at 1500; the cap shortens the phase, "
                         "never thins the rate)")
    ap.add_argument("--speculative", type=int, default=0, metavar="S",
                    help="batched speculative decoding (--batch / --workload "
                         "repetition): draft up to S tokens per row from the "
                         "slot's n-gram index and verify each row's block in "
                         "ONE (B, 1+S) dispatch (docs/SERVING.md)")
    ap.add_argument("--fault-rate", type=float, default=0.01, metavar="P",
                    help="chaos workload: per-dispatch transient-failure "
                         "injection probability (retried by the scheduler)")
    ap.add_argument("--requests", type=int, default=5, metavar="N",
                    help="shared-prefix workload: total requests (1 warm + N-1 "
                         "concurrent followers)")
    ap.add_argument("--replicas", type=int, default=0, metavar="N",
                    help="shared-prefix workload: run the FLEET tier — N real "
                         "api_server subprocesses fronted by the in-process "
                         "prefix-affinity router (docs/FLEET.md); reports "
                         "fleet tok/s, TTFT p50/p95 and the aggregate "
                         "prefix-hit-rate over all replicas")
    ap.add_argument("--routing", choices=("affinity", "random"),
                    default="affinity",
                    help="fleet replica selection: 'affinity' (prefix-"
                         "locality, least-loaded fallback) vs the 'random' "
                         "A/B control")
    ap.add_argument("--kill-replica", action="store_true",
                    help="fleet workload: SIGTERM one replica halfway through "
                         "the measured phase — graceful drain + router "
                         "failover must complete every request (exit 1 on any "
                         "client-visible failure)")
    ap.add_argument("--degrade-replica", action="store_true",
                    help="chaos fleet workload: run the identical schedule "
                         "against a healthy fleet and one whose replica 0 "
                         "serves under a sustained 8-10x injected latency "
                         "while answering healthz ok (the GRAY failure, "
                         "docs/FLEET.md) — gates 0 failures, TTFT p99 <= 2x "
                         "healthy, hedge spend in budget, probation "
                         "entry + rejoin")
    ap.add_argument("--shared-prefix", type=int, default=192, metavar="T",
                    help="shared-prefix workload: tokens in the common system "
                         "prompt (clamped to fit seq_len)")
    ap.add_argument("--no-paged-kv", action="store_true",
                    help="escape hatch: run BatchEngine workloads on the "
                         "dense contiguous per-slot KV caches instead of the "
                         "device block pool + tables (docs/PAGED_KV.md) — "
                         "the A/B control for the paged columns")
    ap.add_argument("--long-context", action="store_true",
                    help="shared-prefix workload variant: demonstrate the "
                         "paged pool's KV-capacity↔slot-count decoupling — "
                         "one request runs a context LONGER than slot-count × "
                         "the dense-equivalent per-slot capacity at the same "
                         "KV memory budget (docs/PAGED_KV.md)")
    ap.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler trace of the timed region here")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="record per-dispatch spans of the timed region and "
                         "write Chrome trace-event JSON (obs/trace.py; open "
                         "in ui.perfetto.dev)")
    ap.add_argument("--trace-fleet", default=None, metavar="OUT.json",
                    help="with --replicas N: enable tracing on the router "
                         "AND every replica subprocess, pull the router's "
                         "GET /v1/trace at the end, and write ONE merged "
                         "Perfetto file where a request's router proxy span "
                         "and its replica engine spans share a trace id "
                         "(docs/OBSERVABILITY.md); also verifies a sampled "
                         "request's flight-recorder timeline end-to-end")
    ap.add_argument("--latency-log", default=None, metavar="OUT.jsonl",
                    help="workload modes: dump raw per-request samples "
                         "(request id, ttft, e2e, tokens, replica) as JSONL "
                         "for offline percentile analysis")
    ap.add_argument("--no-fuse", action="store_true",
                    help="keep wq/wk/wv and w1/w3 as separate kernel launches "
                         "instead of the merged wqkv/w13 groups (A/B lever)")
    ap.add_argument("--prologue", action="store_true",
                    help="fused rmsnorm+quantize prologue kernels "
                         "(ops/pallas_prologue.py) feeding the inline-Xexp "
                         "matvec variants — opt-in until the hardware A/B lands")
    ap.add_argument("--kv-paged", type=int, default=0, metavar="R",
                    help="bench the paged (out-of-core) KV cache: hot ring of "
                         "R positions + host cold store, decode timed with "
                         "~128 cold positions (runtime/paged_cache.py). "
                         "Documents the capacity valve's real per-token cost")
    ap.add_argument("--prefill-kernel", action="store_true",
                    help="fused dequant-matmul for M>1 (ops/pallas_q4_mm.py): "
                         "weights stream once at 4-bit density instead of the "
                         "XLA dequant path — opt-in until the hardware A/B lands")
    ap.add_argument("--fused-matmul", action="store_true",
                    help="batched fused-epilogue kernels (use_pallas='fused', "
                         "the Engine --fused-matmul / DLT_FUSED_MATMUL lever): "
                         "everything --prefill-kernel enables plus the "
                         "residual-add and silu·mul gate-pair epilogues; keeps "
                         "w1/w3 as the separate pair the gated kernel needs")
    args = ap.parse_args()

    if args.trace:
        # NOTE: obs_trace is the MODULE-level import — a local re-import here
        # would make the name local to main() and crash every non---trace run
        # at the span sites (the make_sharded_forward shadowing bug's twin)
        tracer = obs_trace.install()
        import atexit

        # normal exits only — _exit_now (wedged-tunnel escape) skips atexit
        # by design, and a trace of a wedged run would be empty anyway
        atexit.register(lambda: tracer.dump(args.trace))

    # headline = every semantics-bearing flag at its parser default (derived,
    # not duplicated, so a default change can't silently desync the gate;
    # --steps only changes averaging, not what is measured) AND no
    # behavior-altering DLT_* env (the fallback drill must never be able to
    # report the healthy headline number as its own result)
    is_headline = all(
        getattr(args, k) == ap.get_default(k)
        for k in ("small", "arch", "prefill", "device_loop", "layout", "tp",
                  "window", "cache_write", "no_fuse", "prologue",
                  "prefill_kernel", "fused_matmul", "kv_paged", "batch",
                  "superstep", "trace",
                  "workload", "pipeline", "replicas", "speculative")
    ) and not os.environ.get("DLT_FORCE_I4P_FAILURE")
    if args.batch > 0 and (args.prefill > 0 or args.device_loop > 0
                           or args.kv_paged > 0):
        ap.error("--batch is its own mode (batched K-step decode); combine "
                 "only with --superstep/--steps/--arch/--layout/--tp")
    if args.workload and (args.prefill > 0 or args.device_loop > 0
                          or args.kv_paged > 0):
        ap.error(f"--workload {args.workload} is its own mode; combine only "
                 "with --small/--arch/--batch/--superstep/--requests/"
                 "--shared-prefix/--fault-rate/--speculative/--tp")
    if args.speculative and not (args.workload in ("repetition",
                                                   "spec-suite",
                                                   "structured")
                                 or args.batch > 0):
        ap.error("--speculative S applies to the batched scheduler: combine "
                 "with --batch B (engine mode) or --workload "
                 "repetition/spec-suite/structured")
    if args.replicas and args.workload not in ("shared-prefix", "chaos"):
        ap.error("--replicas N is the fleet tier of "
                 "--workload shared-prefix / chaos (docs/FLEET.md); N=1 is "
                 "the single-replica baseline the acceptance compares "
                 "against")
    if args.kill_replica and not args.replicas:
        ap.error("--kill-replica requires --replicas N")
    if args.degrade_replica and (args.workload != "chaos"
                                 or not args.replicas):
        ap.error("--degrade-replica is the gray-failure mode of "
                 "--workload chaos --replicas N (docs/FLEET.md "
                 "\"Gray-failure resilience\")")
    if args.degrade_replica and args.kill_replica:
        ap.error("--degrade-replica and --kill-replica are separate "
                 "chaos modes; run them as two bench invocations")
    if (args.workload == "chaos" and args.replicas
            and not args.kill_replica and not args.degrade_replica):
        ap.error("--workload chaos --replicas N needs a fleet chaos mode: "
                 "--kill-replica (mid-stream SIGKILL + durable resume) or "
                 "--degrade-replica (sustained gray degradation); the "
                 "in-process fault-rate chaos bench takes no --replicas)")
    if args.trace_fleet and not args.replicas:
        ap.error("--trace-fleet requires --replicas N (the fleet tier of "
                 "--workload shared-prefix)")
    if args.latency_log and not args.workload:
        ap.error("--latency-log applies to --workload modes (per-request "
                 "samples need a request workload)")
    if args.kv_paged > 0 and args.tp > 1:
        # before any mesh/device work so the error beats a mesh-size crash
        ap.error("--kv-paged is single-chip (the paged step is an unsharded "
                 "program; Engine enforces the same)")

    skip_probe = False
    if (not os.environ.get("DLT_WARM_RUNNER")
            and not os.environ.get("DLT_HANDOFF_PATH")  # test scratch mode:
            # a test subprocess must not announce itself as THE driver bench —
            # a full pytest run was pausing the real warm runner for the
            # sentinel's whole 180 s foreign-grace tail per test file
            and os.environ.get("JAX_PLATFORMS") != "cpu"):
        # announce this process to the warm runner (perf/persistent_bench.py) so
        # it pauses its refresh loop — the tunnel wedges under concurrent jobs.
        # Removed on exit; a crash leaves it to the runner's mtime expiry.
        import atexit
        import threading

        def _touch():
            global _sentinel_owned
            try:
                with open(SENTINEL, "w") as f:
                    f.write(str(time.time()))
                _sentinel_owned = True
            except OSError:
                pass

        def _keepalive():  # a 7B run can exceed the mtime expiry; refresh
            while True:
                time.sleep(300)
                _touch()

        _touch()
        threading.Thread(target=_keepalive, daemon=True).start()
        atexit.register(lambda: os.path.exists(SENTINEL) and os.remove(SENTINEL))

        # two-way handshake: if the runner is MID-CONFIG it cannot yield until the
        # config finishes; wait (bounded) for its busy marker to clear rather than
        # probing into a tunnel that already has a job on it. When a FRESH handoff
        # already exists AND this is the headline config (the only one the
        # handoff can serve), cap the wait short and report the runner's recent
        # measurement instead of gambling a long wait (or a concurrent probe)
        # against the driver's own watchdog — a killed bench leaves no output.
        busy_env = os.environ.get("DLT_BUSY_WAIT")
        busy_wait = float(busy_env) if busy_env is not None else 1500.0
        _, handoff_age = read_handoff()
        fresh_handoff = (handoff_age is not None
                         and handoff_age < HANDOFF_PREFER_AGE_S)
        can_serve_from_handoff = fresh_handoff and is_headline
        if can_serve_from_handoff and busy_env is None:
            # an EXPLICIT DLT_BUSY_WAIT means the operator wants the live
            # measurement; only the default wait is capped by a fresh handoff
            busy_wait = min(busy_wait, 120.0)
        deadline = time.time() + busy_wait
        while True:
            try:
                busy = (time.time() - os.path.getmtime(BUSY_MARKER)
                        <= SENTINEL_EXPIRY_S)
            except OSError:
                busy = False  # no marker: runner idle or paused
            if not busy:
                break
            if time.time() >= deadline:
                if can_serve_from_handoff:
                    skip_probe = True  # never probe into the runner's live job
                    fail = ("warm runner still mid-config after bounded wait; "
                            "reporting its handoff")
                break
            print("# warm runner mid-config; waiting for it to yield...",
                  file=sys.stderr)
            time.sleep(15)

    if not skip_probe:
        backend, fail = probe_backend()
    else:
        backend = None
    if backend is None:
        # Handoff fallback: the warm runner (perf/persistent_bench.py) publishes
        # its most recent headline result to BENCH_latest.json. A dead tunnel at
        # driver-capture time then still yields a truthful, timestamped hardware
        # number (with explicit provenance) instead of value 0.0. Gated to the
        # exact headline config so a non-headline variant can never silently
        # report the headline's number.
        if is_headline:
            # re-read: the runner may have published a NEWER result during the
            # probe's timeout window
            payload, age = read_handoff()
            try:
                if payload is None:
                    raise ValueError("missing or malformed")
                if age > MAX_HANDOFF_AGE_S:
                    raise ValueError(f"stale: captured {age / 3600:.1f} h ago")
                out = dict(payload["result"])
                out["provenance"] = "warm-runner"
                out["warm_runner_argv"] = payload.get("argv")
                out["age_s"] = round(age, 1)
                out["captured_at"] = payload.get("captured_at")
                out["probe_failure_at_capture"] = fail[:200]
                print(json.dumps(out))
                if os.environ.get("DLT_WARM_RUNNER"):
                    return
                _exit_now(0)
            except (KeyError, ValueError, TypeError) as e:
                fail += f" | BENCH_latest.json unusable: {e!r}"
        print(json.dumps({
            "metric": metric_name(args), "value": 0.0, "unit": "tok/s",
            "vs_baseline": 0.0,
            "error": f"TPU unreachable: {fail}",
        }))
        if os.environ.get("DLT_WARM_RUNNER"):
            sys.exit(2)
        _exit_now(2)

    on_tpu = backend == "tpu"
    spec = ModelSpec(**(SMALL if args.small else ARCHS[args.arch])).resolved()
    if args.workload == "shared-prefix":
        if args.long_context:
            # paged capacity decoupling demo (docs/PAGED_KV.md): a context
            # longer than slot-count x the dense-equivalent per-slot
            # capacity fits, because KV capacity is the POOL, not B slots
            long_context_workload(args)
        elif args.replicas >= 1:
            # --replicas 1 is the single-replica fleet baseline: the SAME
            # request schedule + router proxy, so the N>=2 comparison isolates
            # routing (docs/FLEET.md); 0 = the in-process PR 3 workload
            fleet_shared_prefix_workload(args, spec)
        else:
            shared_prefix_workload(args, spec)
        return
    if args.workload == "chaos":
        if args.replicas >= 1 and args.degrade_replica:
            # gray-failure fleet chaos (docs/FLEET.md "Gray-failure
            # resilience"): identical schedule vs a healthy fleet and one
            # with a sustained-slow replica — probation + hedging gated
            chaos_degrade_workload(args, spec)
        elif args.replicas >= 1:
            # fleet chaos (docs/FLEET.md "Resume protocol"): real replica
            # subprocesses + the durable router, SIGKILL one mid-stream —
            # every request must complete with resumed outputs byte-identical
            chaos_fleet_workload(args, spec)
        else:
            chaos_workload(args, spec)
        return
    if args.workload == "mixed-context":
        # fixed 2-replica topology per arm (a prefill/decode pair IS the
        # minimal disaggregated fleet; the monolithic control mirrors it)
        mixed_context_workload(args, spec)
        return
    if args.workload == "repetition":
        if not on_tpu and not args.small and args.arch == "llama2_7b":
            # CPU default: the overhead-bound tiny geometry (see TINY_REP) —
            # pass --small/--arch to force a specific shape instead
            spec = ModelSpec(**TINY_REP).resolved()
        repetition_workload(args, spec)
        return
    if args.workload == "spec-suite":
        if not on_tpu and not args.small and args.arch == "llama2_7b":
            # CPU default: a COMPUTE-bound geometry (dim 256, L4) — the
            # drafting win is target-step/drafter-step cost asymmetry, and
            # TINY_REP's dim-64 steps are all dispatch overhead, where an
            # L1 drafter step costs the same as an L4 target step and no
            # drafter can win (the same reasoning that sizes the
            # repetition bench the opposite way)
            spec = ModelSpec(**dict(TINY_REP, dim=256, hidden_dim=512,
                                    n_layers=4)).resolved()
        spec_suite_workload(args, spec)
        return
    if args.workload == "structured":
        if not on_tpu and not args.small and args.arch == "llama2_7b":
            # CPU default: the spec-suite's COMPUTE-bound geometry — the
            # grammar-drafting win is the same target-step/proposer-cost
            # asymmetry the model drafter needs (forced chains just make
            # the proposer free and the accept certain)
            spec = ModelSpec(**dict(TINY_REP, dim=256, hidden_dim=512,
                                    n_layers=4)).resolved()
        structured_workload(args, spec)
        return
    if args.workload == "trace":
        if not on_tpu and not args.small and args.arch == "llama2_7b":
            # same CPU default as repetition: the trace bench measures
            # SCHEDULING policy, which the tiny geometry exercises at
            # realistic queue depths in seconds instead of minutes
            spec = ModelSpec(**TINY_REP).resolved()
        trace_workload(args, spec)
        return
    if args.batch > 0 and args.pipeline is not None:
        batched_engine_bench(args, spec)
        return
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    layout = args.layout if on_tpu else "planar"
    window = min(max(args.window, 64), spec.seq_len)
    # keep the documented start_pos + T <= attn_window contract: grow the bucket to
    # cover every decoded position (warm steps + timed steps, or the loop dispatches)
    chunked = args.device_loop if args.device_loop > 0 else (
        max(args.superstep, 1) if args.batch > 0 else 0)
    steps_end = 4 + args.steps if chunked <= 0 else (
        chunked * (max(args.steps // chunked, 1) + 1))
    while window < min(steps_end, spec.seq_len):
        window *= 2
    window = None if window >= spec.seq_len else window

    mesh = make_mesh(tp=args.tp)
    rope = RopeTables.create(spec)
    state = {}

    if args.kv_paged > 0:
        # paged-cache rung: mirrors Engine's two-phase drive (plain deferred
        # step while the ring fills, paged step once cold history exists) so
        # the timed region measures exactly what a user of
        # --kv-cache-storage host pays per token. No fallback ladder — a
        # lowering failure here is an explicit error record, not a downgrade.
        # NOTE: make_sharded_forward comes from the MODULE-level import; a
        # function-local re-import here made it a local name of main() and
        # broke every non-paged bench path with an unbound-free-variable
        # NameError (the shadowing bug the smoke-lint satellite exists for).
        from distributed_llama_tpu.runtime.paged_cache import (  # noqa: E402
            HostKVStore, init_ring_cache, make_paged_step)

        resident = max(64, (args.kv_paged + 63) // 64 * 64)
        cold_target = min(128, spec.seq_len - resident - args.steps - 66)
        if cold_target < 64:
            ap.error(f"--kv-paged {resident}: ring + >=64 cold + timed steps "
                     f"must fit seq_len {spec.seq_len}")
        params = shard_params(synth_params(spec, layout, tp=args.tp), mesh, spec)
        state.update(wbytes=decode_stream_bytes(params, spec))
        store = HostKVStore(spec, resident, storage="host",
                           dtype=(np.float32 if dtype == jnp.float32
                                  else np.dtype(jnp.bfloat16)))
        kc, vc = init_ring_cache(spec, resident, dtype=dtype)
        warm_step = make_sharded_forward(spec, mesh, params, dtype=dtype,
                                         use_pallas=on_tpu, donate_cache=True,
                                         attn_window=None,
                                         cache_write="deferred")
        paged_step = make_paged_step(spec, store, dtype=dtype,
                                     use_pallas=on_tpu)
        toks64 = jnp.ones((1, 64), jnp.int32)
        pos = 0
        while pos + 64 <= resident:  # fill the ring callback-free
            logits, kc, vc = warm_step(params, rope, toks64, kc, vc,
                                       jnp.int32(pos))
            store.append(np.asarray(kc[:, :, :, pos:pos + 64]),
                         np.asarray(vc[:, :, :, pos:pos + 64]), pos)
            pos += 64
        while pos < resident + cold_target:  # build real cold history
            logits, kc, vc, (kr, vr) = paged_step(params, rope, toks64, kc, vc,
                                                  jnp.int32(pos))
            store.append(np.asarray(kr), np.asarray(vr), pos)
            pos += 64
        tokp = jnp.asarray([[1]], jnp.int32)
        for _ in range(2):  # compile + warm the T=1 paged program
            logits, kc, vc, (kr, vr) = paged_step(params, rope, tokp, kc, vc,
                                                  jnp.int32(pos))
            store.append(np.asarray(kr), np.asarray(vr), pos)
            pos += 1
        np.asarray(logits[0, 0, 0])
        t0 = time.perf_counter()
        for _ in range(args.steps):
            logits, kc, vc, (kr, vr) = paged_step(params, rope, tokp, kc, vc,
                                                  jnp.int32(pos))
            store.append(np.asarray(kr), np.asarray(vr), pos)
            pos += 1
        np.asarray(logits[0, 0, 0])
        dt = (time.perf_counter() - t0) / args.steps
        cold = pos - resident
        print(json.dumps({
            "metric": metric_name(args),
            "value": round(1.0 / dt, 3), "unit": "tok/s", "vs_baseline": None,
            "ms_per_token": round(dt * 1e3, 3), "resident": resident,
            "cold_positions": cold, "layout": layout,
            "weight_gb": round(state["wbytes"] / 1e9, 3),
            "achieved_gbps": round(state["wbytes"] / 1e9 / dt, 1),
            "cold_gb_per_token": round(
                spec.n_layers * 2 * spec.n_kv_heads * cold * spec.head_size
                * store.k.itemsize / 1e9, 3),
        }))
        return

    def build(lay):
        params = shard_params(
            synth_params(spec, lay, fuse=not args.no_fuse, tp=args.tp,
                         keep_gate_pair=args.fused_matmul), mesh, spec)
        state.update(params=params, layout=lay,
                     wbytes=decode_stream_bytes(params, spec))
        kc, vc = init_sharded_kv_cache(spec, mesh, batch=max(args.batch, 1),
                                       dtype=dtype)
        if lay == "i4p" and os.environ.get("DLT_FORCE_I4P_FAILURE"):
            # fallback-path drill: fail AFTER the full i4p set + caches occupy HBM,
            # exactly like a real lowering failure — proves the except-path drops
            # every reference (incl. the traceback's frames, which pinned ~4 GB in
            # round 3) before the i8 rebuild. Run on hardware:
            #   DLT_FORCE_I4P_FAILURE=1 python bench.py --steps 4
            raise RuntimeError("forced i4p failure (DLT_FORCE_I4P_FAILURE drill)")
        return params, kc, vc

    def compile_with_fallback(make_and_warm):
        """Build + compile down a degradation ladder so an unattended driver run
        records a downgraded number (with fallback_reason) instead of crashing.
        Rungs are (layout, cache_write, prologue); with the defaults (i4p,
        deferred, no prologue):

            (i4p, deferred, -)
            -> (i4p, inscan, -)   # deferred path / fused attention failed to lower
            -> (i8, deferred, -)  # the 4-bit kernel failed to lower
            -> (i8, inscan, -)    # both failed

        With --prologue, one extra rung (i4p, deferred, prologue) sits on top:
        a prologue-kernel lowering failure drops ONLY the prologue first.

        Each failed attempt's parameter set must be FULLY dropped before the next so
        peak HBM holds one set. `state.pop("params")` alone is not enough: the caught
        exception's __traceback__ frames pin `params`/`kc`/`vc` locals of build() and
        make_and_warm(), which kept ~4 GB of i4p arrays alive through the i8 rebuild
        and turned round 3's lowering failure into RESOURCE_EXHAUSTED
        (BENCH_r03.json). Capture the message only, clear the traceback, and
        gc.collect() before re-synthesizing."""
        kern = "fused" if args.fused_matmul else args.prefill_kernel
        ladder = [(layout, args.cache_write, args.prologue, kern)]
        if kern:
            # dequant-matmul failure alone: drop it first, keep everything else
            ladder.append((layout, args.cache_write, args.prologue, False))
        if args.prologue:
            # prologue-kernel failure alone: drop it next
            ladder.append((layout, args.cache_write, False, False))
        if args.cache_write != "inscan":
            # deferred/fused-attention failure: keep the better 4-bit layout
            ladder.append((layout, "inscan", False, False))
        if layout == "i4p":
            if args.cache_write != "inscan":
                # q4-kernel failure alone: keep the deferred discipline
                ladder.append(("i8", args.cache_write, False, False))
            ladder.append(("i8", "inscan", False, False))
        reasons = []
        for attempt, (lay, cw, prol, pk) in enumerate(ladder):
            state["cache_write"] = cw
            state["prologue"] = prol
            # pk is the kernel-policy rung: "fused" > True ("all") > False;
            # off-TPU both collapse to the interpret-gated boolean
            state["use_pallas"] = (
                ("fused" if pk == "fused" else "all") if (pk and on_tpu)
                else on_tpu)
            try:
                return make_and_warm(*build(lay))
            except Exception as e:
                reasons.append(
                    f"{lay}/{cw}{'/prologue' if prol else ''}"
                    f"{'/fused-matmul' if pk == 'fused' else '/prefill-kernel' if pk else ''}: "
                    f"{type(e).__name__}: {e}"[:200])
                e.__traceback__ = None
                del e  # drop the exception (and its frame refs) entirely
                import gc

                sys.last_value = sys.last_traceback = None  # REPL hooks stash these
                if attempt == len(ladder) - 1:
                    raise RuntimeError(" | ".join(reasons)) from None
                print(f"# {reasons[-1]}; retrying with {ladder[attempt + 1]}",
                      file=sys.stderr)
                state["fallback_reason"] = " | ".join(reasons)[:400]
                state.pop("params", None)
                # drop compiled executables + any cached constants referencing the
                # failed rung's buffers before re-synthesizing (BENCH_r03's
                # RESOURCE_EXHAUSTED came from exactly this overlap)
                jax.clear_caches()
                gc.collect()

    # NOTE: on the axon TPU tunnel, block_until_ready() returns before the device is
    # actually done; only a device->host transfer is an honest fence. Materialize a
    # logit on the host to close each timed region.
    tok = jnp.asarray([[1]], jnp.int32)

    import contextlib
    profile_ctx = (jax.profiler.trace(args.profile_dir) if args.profile_dir
                   else contextlib.nullcontext())

    if args.prefill > 0:
        # prefill throughput: repeated T-token chunks walking the context (the
        # reference prefills strictly token-by-token, dllama.cpp:163-167; chunked
        # prefill is a claimed capability win — this measures it)
        t_chunk = args.prefill
        if t_chunk > spec.seq_len // 2:
            ap.error(f"--prefill {t_chunk} too large: compile + timed chunks must "
                     f"fit seq_len {spec.seq_len}")
        # compile chunk + n_disp timed chunks must fit the context
        n_disp = max(min(args.steps, spec.seq_len // t_chunk - 1), 1)
        pwindow = 1 << max((t_chunk * (n_disp + 1) - 1).bit_length(), 8)
        pwindow = None if pwindow >= spec.seq_len else pwindow
        toks = jnp.ones((1, t_chunk), jnp.int32)

        def warm_prefill(params, kc, vc):
            step = make_sharded_forward(spec, mesh, params, dtype=dtype,
                                        use_pallas=state["use_pallas"],
                                        donate_cache=True,
                                        attn_window=pwindow,
                                        cache_write=state["cache_write"],
                                        fused_prologue=state["prologue"])
            logits, kc, vc = step(params, rope, toks, kc, vc, jnp.int32(0))  # compile
            np.asarray(logits[0, 0, 0])
            return step, params, kc, vc

        step, params, kc, vc = compile_with_fallback(warm_prefill)
        pos = t_chunk
        with profile_ctx:
            t0 = time.perf_counter()
            for _ in range(n_disp):
                logits, kc, vc = step(params, rope, toks, kc, vc, jnp.int32(pos))
                pos += t_chunk
            np.asarray(logits[0, 0, 0])
            dt_all = time.perf_counter() - t0
        tok_s = n_disp * t_chunk / dt_all
        out = {
            "metric": metric_name(args), "value": round(tok_s, 1), "unit": "tok/s",
            "vs_baseline": vs_baseline(args, tok_s),
            "chunk": t_chunk, "weight_gb": round(state["wbytes"] / 1e9, 3),
            "layout": state["layout"], "cache_write": state["cache_write"],
            "ms_per_chunk": round(dt_all / n_disp * 1e3, 2),
            "prologue": False,  # prologue is decode-only (t == 1)
        }
        # report the EFFECTIVE kernel engagement: the dequant-matmul gates
        # per-weight (q4_mm_supported), so an A/B record must say how much of
        # the weight bytes actually took the kernel, not what was requested
        if state["use_pallas"] in ("all", "fused"):  # ops/matmul FUSED_POLICIES
            from distributed_llama_tpu.ops.pallas_q4_mm import q4_mm_supported

            eng_b = tot_b = 0
            tensors = list(state["params"]["blocks"].values()) + [
                state["params"]["wcls"]]
            for w in tensors:
                if not (isinstance(w, QTensor)
                        and w.ftype in (FloatType.Q40, FloatType.Q80)):
                    continue
                nb_bytes = w.nbytes()
                tot_b += nb_bytes
                # kernel sees the per-layer (and per-expert) 2-D slice
                d2 = QTensor(w.ftype, w.data.reshape(-1, w.data.shape[-1]),
                             None, layout=w.layout, groups=w.groups)
                if q4_mm_supported(d2, t_chunk):
                    eng_b += nb_bytes
            out["prefill_kernel"] = eng_b == tot_b and tot_b > 0
            out["prefill_kernel_coverage"] = round(eng_b / max(tot_b, 1), 3)
        else:
            out["prefill_kernel"] = False
        if "fallback_reason" in state:
            out["fallback_reason"] = state["fallback_reason"]
        if args.profile_dir:
            out["profiled"] = True
        print(json.dumps(out))
        return

    if args.batch > 0:
        # serving-throughput mode: the BatchEngine hot path (batched K-step
        # device loop, all B rows active) measured standalone. One dispatch =
        # B*K decoded tokens and ONE host sync.
        from distributed_llama_tpu.runtime.device_loop import (
            make_batched_decode_loop)

        B, K = args.batch, max(args.superstep, 1)
        zeros = np.zeros((B,), np.float32)
        rng = np.zeros((B, 2), np.uint32)
        ones_tok = np.ones((B,), np.int32)
        full_budget = np.full((B,), K, np.int32)

        def warm_bloop(params, kc, vc):
            loop = make_batched_decode_loop(
                spec, mesh, params, K, mode="greedy", dtype=dtype,
                use_pallas=state["use_pallas"], attn_window=window,
                cache_write=state["cache_write"],
                fused_prologue=state["prologue"])
            toks, _tok, _pos, _, kc, vc = loop(
                params, rope, ones_tok, kc, vc, np.zeros((B,), np.int32),
                rng, zeros, zeros + 0.9, full_budget)  # compile + warm
            np.asarray(toks)
            return loop, params, kc, vc

        loop, params, kc, vc = compile_with_fallback(warm_bloop)
        pos = K
        n_disp = max(args.steps // K, 1)
        with profile_ctx:
            t0 = time.perf_counter()
            for _ in range(n_disp):
                with obs_trace.span("bench.super_step", {"B": B, "K": K}):
                    toks, _tok, _pos, _, kc, vc = loop(
                        params, rope, ones_tok, kc, vc,
                        np.full((B,), pos, np.int32), rng, zeros,
                        zeros + 0.9, full_budget)
                pos += K
            np.asarray(toks)
            dt_disp = (time.perf_counter() - t0) / n_disp
        per_stream = K / dt_disp
        aggregate = B * per_stream
        out = {
            "metric": metric_name(args),
            "value": round(aggregate, 3), "unit": "tok/s",
            "vs_baseline": None,  # aggregate metric, not the 1-stream baseline
            "aggregate_decode_tok_s": round(aggregate, 3),
            "per_stream_tok_s": round(per_stream, 3),
            "batch": B, "superstep": K,
            "ms_per_dispatch": round(dt_disp * 1e3, 3),
            "ms_per_token_per_stream": round(dt_disp / K * 1e3, 3),
            "weight_gb": round(state["wbytes"] / 1e9, 3),
            "achieved_gbps": round(state["wbytes"] / 1e9 / (dt_disp / K), 1),
            "layout": state["layout"], "cache_write": state["cache_write"],
            "attn_window": window or spec.seq_len, "steps": args.steps,
            # which lowering each traced dispatch shape ACTUALLY took
            # (ops/matmul.py selection registry) — an A/B record claiming
            # --fused-matmul must show q4_mm/q4_gated_mm here, not a silent
            # xla-fallback (docs/SERVING.md "Kernel selection")
            "kernel_policy": str(state["use_pallas"]),
            "kernels": sorted(set(kernel_selections().values())),
        }
        if "fallback_reason" in state:
            out["fallback_reason"] = state["fallback_reason"]
        if args.profile_dir:
            out["profiled"] = True
        print(json.dumps(out))
        return

    if args.device_loop > 0:
        from distributed_llama_tpu.runtime.device_loop import make_decode_loop

        chunk = args.device_loop
        key = jax.random.PRNGKey(0)

        def warm_loop(params, kc, vc):
            loop = make_decode_loop(spec, mesh, params, chunk, mode="greedy",
                                    dtype=dtype, use_pallas=state["use_pallas"],
                                    attn_window=window,
                                    cache_write=state["cache_write"],
                                    fused_prologue=state["prologue"])
            toks, _, kc, vc = loop(params, rope, 1, kc, vc, 0, key)  # compile + warm
            np.asarray(toks)
            return loop, params, kc, vc

        loop, params, kc, vc = compile_with_fallback(warm_loop)
        pos = chunk
        n_disp = max(args.steps // chunk, 1)
        with profile_ctx:
            t0 = time.perf_counter()
            for _ in range(n_disp):
                toks, _, kc, vc = loop(params, rope, 1, kc, vc, pos, key)
                pos += chunk
            np.asarray(toks)
            dt = (time.perf_counter() - t0) / (n_disp * chunk)
    else:
        def warm_step(params, kc, vc):
            step = make_sharded_forward(spec, mesh, params, dtype=dtype,
                                        use_pallas=state["use_pallas"],
                                        donate_cache=True,
                                        attn_window=window,
                                        cache_write=state["cache_write"],
                                        fused_prologue=state["prologue"])
            logits, kc, vc = step(params, rope, tok, kc, vc, jnp.int32(0))  # compile
            np.asarray(logits[0, 0, 0])
            return step, params, kc, vc

        step, params, kc, vc = compile_with_fallback(warm_step)
        for i in range(3):  # warm steps
            logits, kc, vc = step(params, rope, tok, kc, vc, jnp.int32(1 + i))
        np.asarray(logits[0, 0, 0])

        with profile_ctx:
            t0 = time.perf_counter()
            pos = 4
            for _ in range(args.steps):
                with obs_trace.span("bench.decode_step", {"pos": pos}):
                    logits, kc, vc = step(params, rope, tok, kc, vc,
                                          jnp.int32(pos))
                pos += 1
            np.asarray(logits[0, 0, 0])
            dt = (time.perf_counter() - t0) / args.steps

    tok_s = 1.0 / dt
    out = {
        "metric": metric_name(args),
        "value": round(tok_s, 3),
        "unit": "tok/s",
        "vs_baseline": vs_baseline(args, tok_s),
        "ms_per_token": round(dt * 1e3, 3),
        "weight_gb": round(state["wbytes"] / 1e9, 3),
        "achieved_gbps": round(state["wbytes"] / 1e9 / dt, 1),
        "layout": state["layout"],
        "cache_write": state["cache_write"],
        "attn_window": window or spec.seq_len,
        "device_loop": args.device_loop,
        "steps": args.steps,
        # report the EFFECTIVE matvec-group fusion: --fused-matmul keeps the
        # w1/w3 pair split for the gated-epilogue kernel, so a record saying
        # "fused" there would claim a merge that never happened
        "fused": bool(not args.no_fuse and not args.fused_matmul),
        # report the EFFECTIVE prologue state: forward() re-gates it off for
        # non-pallas runs and unsupported dims, and an A/B record claiming a
        # lever that never engaged would corrupt the comparison
        "prologue": bool(state["prologue"] and on_tpu
                         and prologue_supported(spec.dim)),
        "kernel_policy": str(state["use_pallas"]),
        "kernels": sorted(set(kernel_selections().values())),
    }
    if "fallback_reason" in state:
        out["fallback_reason"] = state["fallback_reason"]
    if args.profile_dir:
        # a profiler-instrumented run is NOT comparable to the clean headline —
        # mark it so metric-keyed JSONL consumers cannot silently pick it up
        out["profiled"] = True
    print(json.dumps(out))


if __name__ == "__main__":
    main()
