#!/bin/bash
# Round-5 hardware runbook — manual/backup path for the full measurement
# sequence, serialized (concurrent TPU jobs wedge the axon tunnel; PROFILE.md).
# The PRIMARY capture path this round is perf/persistent_bench.py, which runs
# the same matrix in-process against a warm backend and publishes the headline
# to BENCH_latest.json for the driver handoff; use this script when a human (or
# a fresh shell) wants the sweep without the warm runner.
#   bash perf/r5_hw.sh [outfile]
set -o pipefail
# shared run()/err_record() helpers; resolve before the cd so any invocation cwd works
source "$(cd "$(dirname "$0")" && pwd)/_bench_lib.sh"
cd "$(dirname "$0")/.."
OUT="${1:-perf/r5_hw_results.jsonl}"
: > "$OUT"

# 1. headline with the deferred cache discipline (default)
run python bench.py --steps 32
# 2. cache-write A/B: the carry-copy question
run python bench.py --steps 32 --cache-write inscan
# 3. device-loop amortization
run python bench.py --steps 32 --device-loop 8
run python bench.py --steps 64 --device-loop 32
# 4. prefill at two chunk sizes
run python bench.py --prefill 64 --steps 16
run python bench.py --prefill 128 --steps 16
# 5. forced-failure fallback drill (must print an i8 line with fallback_reason)
run env DLT_FORCE_I4P_FAILURE=1 python bench.py --steps 4
# 6. the full sweep (window sweep, other archs, microbench, collectives)
bash perf/sweep.sh
echo "r5 hw runbook complete -> $OUT + perf/sweep_results.jsonl"
