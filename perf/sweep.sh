#!/bin/bash
# One-shot hardware measurement sweep — run on a live TPU chip to collect every
# pending A/B (see perf/PROFILE.md). Each line is a JSON record; tee everything
# into perf/sweep_results.jsonl for analysis.
#
#   bash perf/sweep.sh [outfile]
#
# Every emitted line is valid JSON (command markers are {"section":"cmd",...}
# records, not '#' comments), and a command that dies still leaves an explicit
# {"section":"error",...} record instead of silently vanishing from the file.
set -e -o pipefail
# shared run()/run_all()/err_record() helpers (watchdog + stderr-tail records);
# resolve before the cd so any invocation cwd works
source "$(cd "$(dirname "$0")" && pwd)/_bench_lib.sh"
cd "$(dirname "$0")/.."
OUT="${1:-perf/sweep_results.jsonl}"
: > "$OUT"

# platform characteristics (dispatch overhead, streaming ceiling, kernel GB/s,
# windowed-vs-full attention) — includes the i4p vs i4p-inline vs i8 kernel A/B
run_all python perf/microbench.py

# quantized_psum numerics + quantize/dequant compute cost on the 8-way virtual CPU
# mesh (one real chip has no ICI; the record carries mesh=cpu so it cannot be
# mistaken for an ICI time)
run_all env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python perf/microbench.py --section collectives

# headline decode: 4-bit kernel, windowed attention, host loop
run python bench.py --steps 64

# kernel layout A/B at the model level
run python bench.py --steps 64 --layout i8

# merged projection launches A/B (wqkv/w13 fusion, default on)
run python bench.py --steps 64 --no-fuse

# fused rmsnorm+quantize prologue kernels (opt-in until this A/B lands)
run python bench.py --steps 64 --prologue

# cache-write discipline A/B (deferred = default; inscan carries the caches
# through the layer scan — the round-4 trace blamed its carry copies for a
# third of the step)
run python bench.py --steps 64 --cache-write inscan

# window sweep: growing live-context cost (watchdog grows the bucket as needed)
run python bench.py --steps 64 --window 2048

# device loop: dispatch amortization after the carry-based cache redesign
run python bench.py --steps 64 --device-loop 8
run python bench.py --steps 64 --device-loop 32

# prefill throughput (chunked prefill is a capability win over the reference;
# cost model in perf/PROFILE.md)
run python bench.py --prefill 64 --steps 16
run python bench.py --prefill 128 --steps 16
run python bench.py --prefill 64 --steps 16 --prefill-kernel
run python bench.py --prefill 128 --steps 16 --prefill-kernel

# the other BASELINE.json configs
run python bench.py --arch tinyllama_1_1b --steps 64
run python bench.py --arch llama3_8b --steps 64
run python bench.py --arch mixtral_8x7b_l8 --steps 32
run python bench.py --arch grok1_l2 --steps 32

echo "sweep complete -> $OUT"
