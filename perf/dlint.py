#!/usr/bin/env python
"""Unified static-analysis runner (docs/ANALYSIS.md) — the tier-1 lint gate.

Passes (distributed_llama_tpu/analysis/):

  compile / dead-import        repo-wide byte-compile + unused-import lint
  lock-guard / lock-blocking   `# guards:` lock-discipline checker
  hot-sync / hot-impure        `# hot-path` host-sync + trace-purity lint
  metric-docs / fault-docs     inventory drift vs OBSERVABILITY/ROBUSTNESS
  bad-suppression              reasonless `# dlint: ignore[...]` markers
  compile-manifest             (--compile-gate) tiny-model recompile audit
                               vs the pinned perf/compile_manifest.json

Usage:

  python perf/dlint.py                     # static passes, text output
  python perf/dlint.py --json out.json     # + machine-readable artifact
  python perf/dlint.py --compile-gate      # + the runtime compile audit
  python perf/dlint.py --update-manifest   # re-pin the compile manifest
                                           # (union-merge; review the diff)

Exit 0 when every finding is suppressed (each suppression carries a written
reason), 1 otherwise. Tier-1 wiring: tests/test_dlint.py gates at zero
unsuppressed findings.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# the compile gate drives the real engine: keep it off any accelerator a
# stray environment would grab (callers may still override explicitly)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the findings/suppressions summary as JSON "
                         "(BENCH-artifact convention: perf/DLINT.json)")
    ap.add_argument("--compile-gate", action="store_true",
                    help="also run the tiny-model compile-manifest audit "
                         "(imports jax, ~tens of seconds on CPU)")
    ap.add_argument("--manifest", metavar="PATH", default=None,
                    help="pinned manifest path (default "
                         "perf/compile_manifest.json)")
    ap.add_argument("--update-manifest", action="store_true",
                    help="re-run the audit scenario and re-pin the manifest "
                         "(union-merged with the existing pin)")
    args = ap.parse_args(argv)

    if args.update_manifest:
        from distributed_llama_tpu.analysis import compile_audit

        manifest = compile_audit.update_manifest(args.manifest)
        path = args.manifest or compile_audit.MANIFEST_PATH
        n_sigs = sum(len(p["signatures"])
                     for p in manifest["programs"].values())
        print(f"pinned {len(manifest['programs'])} programs / {n_sigs} "
              f"dispatch signatures -> {path}")
        print("review the manifest diff like a lockfile before committing")
        return 0

    from distributed_llama_tpu.analysis import runner

    report = runner.run(compile_gate=args.compile_gate,
                        manifest_path=args.manifest)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            fh.write(report.to_json() + "\n")
    for f in report.unsuppressed:
        print(f.format(), file=sys.stderr)
    print(report.format_text().splitlines()[-1])
    return 1 if report.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
