#!/usr/bin/env python
"""Sequence-parallel (--sp ring) step-cost measurement.

Runs the sharded decode step on the 8-device virtual CPU mesh (JAX_PLATFORMS=cpu
+ xla_force_host_platform_device_count=8 — set by this script) and compares:

    sp=1 tp=2            — baseline TP-only step
    sp=2 tp=2, inscan    — ring path with the cache carried through the scan
    sp=2 tp=2, deferred  — ring path with loop-invariant caches + window commit

CPU-mesh times are NOT hardware numbers (no ICI; ppermute is a memcpy), but the
inscan-vs-deferred delta isolates exactly the carry-copy overhead the deferred
discipline removes, and the analytical budget in perf/PROFILE.md extrapolates the
HBM terms to a real chip. Emits one JSON line per config.

    python perf/sp_cost.py [--dim 512] [--layers 8] [--seq 1024] [--steps 20]
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec, RopeType
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.parallel.mesh import make_mesh
from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                               make_sharded_forward, shard_params)
from distributed_llama_tpu.quants import FloatType


def run_config(spec, params, rope, *, sp, tp, cache_write, steps, pos0,
               window=None):
    mesh = make_mesh(sp=sp, tp=tp)
    sparams = shard_params(params, mesh, spec)
    step = make_sharded_forward(spec, mesh, sparams, donate_cache=True,
                                cache_write=cache_write, attn_window=window)
    kc, vc = init_sharded_kv_cache(spec, mesh)
    tok = jnp.asarray([[1]], jnp.int32)
    # warm/compile + advance to pos0 so the ring walks a realistic live region
    logits, kc, vc = step(sparams, rope, tok, kc, vc, jnp.int32(0))
    np.asarray(logits[0, 0, 0])
    for i in range(3):
        logits, kc, vc = step(sparams, rope, tok, kc, vc, jnp.int32(1 + i))
    np.asarray(logits[0, 0, 0])

    t0 = time.perf_counter()
    pos = pos0
    for _ in range(steps):
        logits, kc, vc = step(sparams, rope, tok, kc, vc, jnp.int32(pos))
        pos += 1
    np.asarray(logits[0, 0, 0])
    dt_ms = (time.perf_counter() - t0) / steps * 1e3
    return dt_ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=args.dim,
                     hidden_dim=args.dim * 11 // 4 // 32 * 32,
                     n_layers=args.layers, n_heads=args.dim // 64,
                     n_kv_heads=args.dim // 64, vocab_size=2048,
                     seq_len=args.seq, rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.F32, seed=0)
    rope = RopeTables.create(spec)
    # quarter-context: live region fits the seq//2 window bucket of the windowed
    # configs (contract: start_pos + steps <= window) while the full-cache
    # configs still walk 4x the live columns
    pos0 = args.seq // 4

    configs = [
        dict(sp=1, tp=2, cache_write="deferred"),
        dict(sp=1, tp=2, cache_write="inscan"),
        dict(sp=2, tp=2, cache_write="deferred"),
        dict(sp=2, tp=2, cache_write="inscan"),
        dict(sp=4, tp=2, cache_write="deferred"),
        dict(sp=4, tp=2, cache_write="inscan"),
        # windowed striped ring (deferred-only capability): rotations move
        # ceil(window/sp) slots instead of the full shard
        dict(sp=2, tp=2, cache_write="deferred", window=args.seq // 2),
        dict(sp=4, tp=2, cache_write="deferred", window=args.seq // 2),
    ]
    for cfg in configs:
        ms = run_config(spec, params, rope, steps=args.steps, pos0=pos0, **cfg)
        print(json.dumps({"section": "sp_cost", "mesh": "cpu8",
                          "dim": args.dim, "layers": args.layers,
                          "seq": args.seq, "pos": pos0, **cfg,
                          "ms_per_step": round(ms, 2)}), flush=True)


if __name__ == "__main__":
    main()
