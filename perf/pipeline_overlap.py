#!/usr/bin/env python
"""Smoke: pipelined super-steps must actually hide host delivery work, and a
flush-heavy workload must never wedge the scheduler (ISSUE 5 CI gate).

Two assertions against live CPU-mesh BatchEngines:

1. OVERLAP — the same decode workload (per-token host callback doing real
   work, the streaming/delivery cost pipelining exists to hide) runs against
   a pipelined and an unpipelined engine; the mean device-idle gap
   (`batch_dispatch_gap_seconds` delta per engine) with pipelining must be
   < 50% of the unpipelined gap. Chained issues record a literal 0 gap, so
   this fails only if the pipeline stops engaging.

2. FLUSH-STORM SAFETY — a stream of 1-token (and boundary-2-token) requests,
   interleaved with mid-block stop_check stops, maximizes schedule
   divergence: every block ends a request, so chains flush or never form.
   All requests must complete, no slot/lease may leak, and the scheduler
   thread must survive.

Run: JAX_PLATFORMS=cpu python perf/pipeline_overlap.py
Prints one JSON line (bench.py convention); exit 0 pass, 1 fail.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_llama_tpu.models.params import init_random_params  # noqa: E402
from distributed_llama_tpu.models.spec import (ArchType, ModelSpec,  # noqa: E402
                                               RopeType)
from distributed_llama_tpu.obs import metrics  # noqa: E402
from distributed_llama_tpu.quants import FloatType  # noqa: E402
from distributed_llama_tpu.runtime.batch_engine import BatchEngine  # noqa: E402
from distributed_llama_tpu.runtime.sampler import Sampler  # noqa: E402

GEN = 64  # decoded tokens per request in the overlap phase
CALLBACK_S = 0.0005  # per-token host work (emulated streaming/delivery cost)


def _spec(seq_len=256):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=seq_len, rope_type=RopeType.LLAMA).resolved()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


def _gap_state():
    h = metrics.snapshot().get("batch_dispatch_gap_seconds") or {}
    return h.get("count", 0), h.get("sum", 0.0)


def measure_gap(spec, params, pipeline: bool) -> tuple[float, int]:
    """Mean device-idle gap (seconds) over one warmed decode run, and the
    number of gap observations it covered."""
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=8,
                     pipeline=pipeline, prefix_cache=False)
    try:
        def slow_token(_t):  # the host work the pipeline should hide
            t_end = time.perf_counter() + CALLBACK_S
            while time.perf_counter() < t_end:
                pass

        be.generate([1, 7, 23, 5], 2 * be.superstep, _greedy(spec))  # warm
        c0, s0 = _gap_state()
        r = be.submit([1, 7, 23, 5], GEN, _greedy(spec), on_token=slow_token)
        out = r.wait(timeout=300)
        c1, s1 = _gap_state()
        assert len(out) == GEN, (pipeline, len(out))
        n = max(c1 - c0, 1)
        return (s1 - s0) / n, c1 - c0
    finally:
        be.close()


def flush_storm(spec, params) -> list[str]:
    """1-token request stream against a pipelined engine: every super-step
    block ends its request, so any chained dispatch is flushed (or chaining
    is declined for admission). Asserts completion + zero leaks."""
    problems: list[str] = []
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=8, pipeline=True,
                     prefix_cache=False)

    def want_tokens(i: int) -> int:
        return (1, 2, 4)[i % 3]  # 4 = mid-block stop (stop_check at token 4)

    try:
        reqs = []
        for i in range(48):
            stop = None
            if i % 3 == 2:  # 16-token ask stopped mid-block by its 4th token
                stop = lambda t, seen=[]: (seen.append(t) or len(seen) >= 4)
            reqs.append(be.submit([1, 3 + (i % 50), 7],
                                  16 if stop else want_tokens(i),
                                  _greedy(spec), stop_check=stop))
        for i, r in enumerate(reqs):
            try:
                out = r.wait(timeout=120)
                if len(out) != want_tokens(i):
                    problems.append(f"req {i}: {len(out)} tokens, "
                                    f"wanted {want_tokens(i)}")
            except Exception as e:
                problems.append(f"req {i}: {e!r}")
        if not be.scheduler_alive():
            problems.append("scheduler thread DIED during the storm")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with be._plock:
                leaked = [s for s in be._slots
                          if s.req is not None or s.lease is not None]
            if not leaked and not be._pending and be._queue.empty():
                break
            time.sleep(0.01)
        else:
            problems.append("slot/lease leak after the storm")
    finally:
        be.close()
    return problems


def main() -> int:
    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    gap_off, n_off = measure_gap(spec, params, pipeline=False)
    gap_on, n_on = measure_gap(spec, params, pipeline=True)
    ratio = gap_on / max(gap_off, 1e-12)
    ok_gap = ratio < 0.5
    problems = flush_storm(spec, params)
    flushes = sum((metrics.snapshot().get("batch_pipeline_flushes_total")
                   or {}).values())
    ok = ok_gap and not problems
    print(json.dumps({
        "metric": "pipeline_gap_ratio", "value": round(ratio, 4),
        "unit": "fraction", "pass": ok, "threshold": 0.5,
        "gap_on_us": round(gap_on * 1e6, 1),
        "gap_off_us": round(gap_off * 1e6, 1),
        "gap_samples": [n_off, n_on],
        "storm_problems": len(problems), "pipeline_flushes": flushes,
    }))
    if not ok_gap:
        print(f"FAIL: pipelined mean gap {gap_on * 1e6:.0f} µs is {ratio:.0%} "
              f"of the unpipelined {gap_off * 1e6:.0f} µs (budget 50%)",
              file=sys.stderr)
    for p in problems:
        print(f"FAIL: {p}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
