#!/usr/bin/env python
"""Kernel & platform microbenchmarks — the evidence base for bench.py's numbers.

Measures, on whatever backend JAX resolves (designed for the single TPU chip):
  1. dispatch       — per-dispatch overhead of the host->device link (sync round trip
                      and async chained), which bounds the per-token host-loop cost
  2. stream         — steady-state HBM read bandwidth via a scan over stacked weights
                      (single-op timings are meaningless when dispatch overhead is
                      milliseconds; the scan amortizes it away)
  3. matvec:q4/q8   — the two decode matvec kernels (ops/pallas_q4.py packed nibbles at
                      0.5625 B/weight vs ops/pallas_q8.py int8 planes at 1.125 B/weight)
                      on the Llama-2-7B hot shapes, reported as achieved GB/s
  4. prefill_mm     — fused 4-bit dequant-matmul (ops/pallas_q4_mm.py) vs the XLA
                      dequant+dot path at prefill widths (weight GB/s)
  5. prologue       — fused rmsnorm+quantize kernels vs their XLA formulation
  6. attention      — windowed vs full-seq_len cache read cost at 7B head geometry

Each result prints as one JSON line. Timing uses a device->host transfer as the fence:
on the axon TPU tunnel block_until_ready() returns early (see bench.py).

Usage: python perf/microbench.py [--section dispatch|stream|matvec|prefill_mm|
                                  prologue|attention|collectives] [--quick]
"""

import argparse
import functools
import json
import os
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_llama_tpu.quants import QK, FloatType, QTensor  # noqa: E402
from distributed_llama_tpu.compat import shard_map


def fence(x):
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(x)[0].ravel()[0]))


def timed(fn, *args, reps=10):
    fence(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn(*args)
    fence(out)
    return (time.perf_counter() - t0) / reps


def emit(**kw):
    print(json.dumps(kw))


def sec_dispatch(reps):
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    fence(f(x))
    t0 = time.perf_counter()
    for _ in range(reps):
        fence(f(x))
    emit(section="dispatch", kind="sync_roundtrip",
         ms=round((time.perf_counter() - t0) / reps * 1e3, 3))
    y = x
    t0 = time.perf_counter()
    for _ in range(reps):
        y = f(y)
    fence(y)
    emit(section="dispatch", kind="async_chained",
         ms=round((time.perf_counter() - t0) / reps * 1e3, 3))


def sec_stream(reps):
    """Steady-state HBM read bandwidth, two probes per dtype family:

    - matvec probes (bf16/int8 dot per scanned layer): what a DECODE layer attains,
      including the dot's lowering cost. Round 3 published the int8 number (87-173
      GB/s) as if it were bandwidth — it is not: XLA's int8 matvec lowering is
      compute-bound, which this section now makes explicit by...
    - raw probes (bitcast to i32 lanes, reduce): pure read bandwidth with a trivial
      VPU reduction — the actual streaming ceiling for that operand size.
    """
    L, n, k = 32, 11008, 4096
    for dt_, name, bpe in ((jnp.bfloat16, "bf16_matvec", 2), (jnp.int8, "int8_matvec", 1)):
        w = jnp.ones((L, n, k), dt_)
        x = jnp.ones((k,), jnp.bfloat16)

        def body(c, wl):
            if dt_ == jnp.int8:
                y = jax.lax.dot_general(wl, c.astype(jnp.int8)[:, None],
                                        (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.int32)
                return c, y.astype(jnp.bfloat16).sum()
            return c, (wl @ c).sum()

        g = jax.jit(lambda w, x: jax.lax.scan(body, x, w)[1].sum())
        dt = timed(g, w, x, reps=reps)
        gb = L * n * k * bpe / 1e9
        emit(section="stream", dtype=name, gb=round(gb, 2), ms=round(dt * 1e3, 2),
             gbps=round(gb / dt, 1))
    for src, name in ((jnp.bfloat16, "bf16_raw"), (jnp.int8, "int8_raw"),
                      (jnp.uint8, "uint8_raw")):
        lanes = 4 // jnp.dtype(src).itemsize
        w = jnp.ones((L, n, k), src)

        def body_raw(c, wl, lanes=lanes):
            as_i32 = jax.lax.bitcast_convert_type(
                wl.reshape(n, k // lanes, lanes), jnp.int32)
            return c + jnp.sum(as_i32, dtype=jnp.int32).astype(jnp.float32), None

        g = jax.jit(lambda w: jax.lax.scan(body_raw, jnp.float32(0), w)[0])
        dt = timed(g, w, reps=reps)
        gb = w.nbytes / 1e9
        emit(section="stream", dtype=name, gb=round(gb, 2), ms=round(dt * 1e3, 2),
             gbps=round(gb / dt, 1))


def _rand_q40(n, k, seed=0):
    rng = np.random.RandomState(seed)
    return QTensor.from_float((rng.randn(n, k) * 0.05).astype(np.float32),
                              FloatType.Q40)


def sec_matvec(reps):
    """q4 vs q8 decode kernels on the 7B hot shapes (single dispatch per call;
    the async chain in timed() amortizes dispatch overhead)."""
    on_tpu = jax.default_backend() == "tpu"
    shapes = [(4096, 4096), (11008, 4096), (4096, 11008), (32000, 4096)]
    for n, k in shapes:
        w = _rand_q40(min(n, 4096) if not on_tpu else n, k)
        w_i4p = jax.tree_util.tree_map(jnp.asarray, w.to_i4p_layout())
        for layout in ("i4p", "i4p-inline", "i8"):
            wl = (jax.tree_util.tree_map(jnp.asarray, w.to_i8_layout())
                  if layout == "i8" else w_i4p)
            x = jnp.ones((1, 1, k), jnp.bfloat16)
            if layout == "i8":
                from distributed_llama_tpu.ops.pallas_q8 import q8_matvec as mv

                g = jax.jit(functools.partial(mv, interpret=not on_tpu))
            else:
                from distributed_llama_tpu.ops.pallas_q4 import q4_matvec

                g = jax.jit(functools.partial(
                    q4_matvec, interpret=not on_tpu,
                    inline_xexp=layout == "i4p-inline"))
            dt = timed(g, x, wl, reps=reps)
            bytes_ = wl.data.nbytes + wl.scales.nbytes
            emit(section="matvec", layout=layout, n=wl.shape[0], k=k,
                 ms=round(dt * 1e3, 3), gbps=round(bytes_ / 1e9 / dt, 1))


def sec_prefill_mm(reps):
    """Fused 4-bit dequant-matmul (ops/pallas_q4_mm.py) vs the XLA dequant+dot
    path on the 7B hot shapes at prefill widths — isolates whether XLA
    materializes the bf16 operands (the prefill cost model's open question,
    perf/PROFILE.md) and what the kernel's effective weight GB/s is."""
    from distributed_llama_tpu.ops.matmul import qmatmul
    from distributed_llama_tpu.ops.pallas_q4_mm import q4_matmul, q4_mm_supported

    on_tpu = jax.default_backend() == "tpu"
    shapes = [(4096, 4096), (11008, 4096), (4096, 11008)]
    for n, k in shapes:
        w = _rand_q40(min(n, 2048) if not on_tpu else n, k)
        wl = jax.tree_util.tree_map(jnp.asarray, w.to_i4p_layout())
        for m in (16, 64, 128):
            x = jnp.ones((m, k), jnp.bfloat16)
            bytes_ = wl.data.nbytes + wl.scales.nbytes
            if q4_mm_supported(wl, m):
                g = jax.jit(functools.partial(q4_matmul, interpret=not on_tpu))
                dt = timed(g, x, wl, reps=reps)
                emit(section="prefill_mm", path="kernel", m=m, n=wl.shape[0],
                     k=k, ms=round(dt * 1e3, 3),
                     weight_gbps=round(bytes_ / 1e9 / dt, 1))
            g = jax.jit(functools.partial(qmatmul, use_pallas=False))
            dt = timed(g, x, wl, reps=reps)
            emit(section="prefill_mm", path="xla_dequant", m=m, n=wl.shape[0],
                 k=k, ms=round(dt * 1e3, 3),
                 weight_gbps=round(bytes_ / 1e9 / dt, 1))


def sec_prologue(reps):
    """Fused rmsnorm+quantize prologue kernels vs their XLA formulation at the
    7B activation widths — the per-launch cost these kernels exist to remove."""
    from distributed_llama_tpu.ops.kernels import rmsnorm
    from distributed_llama_tpu.ops.pallas_prologue import (quantize_q80_row,
                                                           rmsnorm_quantize_q80)
    from distributed_llama_tpu.ops.pallas_q8 import _quantize_row

    on_tpu = jax.default_backend() == "tpu"
    for k in (4096, 11008):
        x = jnp.ones((1, 1, k), jnp.bfloat16)
        wn = jnp.ones((k,), jnp.float32)

        g = jax.jit(functools.partial(rmsnorm_quantize_q80, eps=1e-5,
                                      interpret=not on_tpu))
        dt = timed(lambda a, b: g(a, b)[0], x, wn, reps=reps)
        emit(section="prologue", op="rmsnorm_q80_kernel", k=k,
             ms=round(dt * 1e3, 4))

        def xla_form(a, b):
            xb = rmsnorm(a, b, 1e-5)
            return _quantize_row(xb.reshape(k), k // 32)[0]

        dt = timed(jax.jit(xla_form), x, wn, reps=reps)
        emit(section="prologue", op="rmsnorm_q80_xla", k=k,
             ms=round(dt * 1e3, 4))

        gq = jax.jit(functools.partial(quantize_q80_row, interpret=not on_tpu))
        dt = timed(lambda a: gq(a)[0], x, reps=reps)
        emit(section="prologue", op="quantize_kernel", k=k, ms=round(dt * 1e3, 4))


def sec_attention(reps):
    """Cache read cost: full 2048-window vs 256-window at 7B geometry, per layer."""
    from distributed_llama_tpu.ops.attention import gqa_attention

    b, hq, hk, hs = 1, 32, 32, 128
    q = jnp.ones((b, 1, hq, hs), jnp.bfloat16)
    for s in (2048, 256):
        kc = jnp.ones((b, hk, s, hs), jnp.bfloat16)
        vc = jnp.ones_like(kc)
        pos = jnp.asarray([100 % s], jnp.int32)
        g = jax.jit(lambda q, kc, vc, p: gqa_attention(q, kc, vc, p))
        dt = timed(g, q, kc, vc, pos, reps=reps)
        gb = 2 * kc.nbytes / 1e9
        emit(section="attention", window=s, ms=round(dt * 1e3, 3),
             cache_gb=round(gb, 3), gbps=round(gb / dt, 1))


def sec_collectives(reps):
    """quantized_psum (Q80-compressed all-reduce, the reference's wire compression
    tasks.cpp:96-135) vs plain psum: numerics always; time only as a relative number
    on whatever mesh is available. One real chip has no ICI, so run this section
    under the virtual CPU mesh (JAX_PLATFORMS=cpu
    XLA_FLAGS=--xla_force_host_platform_device_count=8) for an 8-way ring; the
    wall-clock there measures the EXTRA COMPUTE of quantize/dequantize, not wire
    time — labeled mesh="cpu" so nobody mistakes it for an ICI measurement."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_llama_tpu.parallel.collectives import psum, quantized_psum
    from distributed_llama_tpu.parallel.mesh import AXIS_TP, make_mesh

    n_dev = len(jax.devices())
    if n_dev < 2:
        emit(section="collectives", skipped=f"need >=2 devices, have {n_dev}",
             note="run under the 8-device virtual CPU mesh for numerics/compute cost")
        return
    mesh = make_mesh(tp=n_dev)
    dim = 4096
    rng = np.random.RandomState(0)
    parts = rng.randn(n_dev, dim).astype(np.float32) * 0.1
    x = jax.device_put(jnp.asarray(parts), NamedSharding(mesh, P(AXIS_TP)))
    want = parts.sum(0)

    for name, fn in (("psum", psum), ("quantized_psum",
                                      lambda v, ax: quantized_psum(v, ax))):
        g = jax.jit(shard_map(lambda v: fn(v, AXIS_TP), mesh=mesh,
                              in_specs=P(AXIS_TP), out_specs=P(AXIS_TP)))
        out = np.asarray(jax.device_get(g(x).addressable_shards[0].data))[0]
        rel = float(np.abs(out - want).max() / (np.abs(want).max() + 1e-9))
        dt = timed(g, x, reps=reps)
        emit(section="collectives", op=name, mesh=jax.default_backend(),
             n_dev=n_dev, dim=dim, rel_err=round(rel, 6), ms=round(dt * 1e3, 3))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--section", default=None,
                    choices=["dispatch", "stream", "matvec", "prefill_mm",
                             "prologue", "attention", "collectives"])
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    reps = 3 if args.quick else 10
    emit(section="meta", backend=jax.default_backend(),
         device=str(jax.devices()[0]))
    secs = {"dispatch": sec_dispatch, "stream": sec_stream, "matvec": sec_matvec,
            "prefill_mm": sec_prefill_mm, "prologue": sec_prologue,
            "attention": sec_attention, "collectives": sec_collectives}
    for name, fn in secs.items():
        if args.section in (None, name):
            fn(reps)


if __name__ == "__main__":
    main()
