#!/usr/bin/env python
"""Bisect the --prologue Mosaic failure (r5 matrix: tpu_compile_helper crash).

The --prologue flag switches TWO things at once: the fused rmsnorm+quantize
prologue kernels (ops/pallas_prologue.py) and the inline-Xexp matvec variants
(pallas_q4/_q8 _matvec_kernel_inline, routed via ops.matmul.qmatmul_q80). The
ladder's fallback_reason can't say which one crashed the Mosaic remote-compile
helper, so this probe compiles each piece separately at a 7B-ish decode shape
and prints one JSON line per piece.

Run serialized with the warm runner: this script holds the driver sentinel
(perf/.driver_bench_active) so perf/persistent_bench.py pauses while it owns
the tunnel (concurrent TPU jobs wedge the axon tunnel — perf/PROFILE.md).

    python perf/probe_prologue.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if __name__ == "__main__":
    # standalone: announce as a foreign bench BEFORE the heavy jax/package
    # imports so the warm runner pauses during the whole import+init window
    # (in-process callers — perf/persistent_bench.py — serialize themselves
    # and import main() directly, never taking the sentinel)
    import atexit

    from bench import SENTINEL

    with open(SENTINEL, "w") as f:
        f.write(str(os.getpid()))
    atexit.register(lambda: os.path.exists(SENTINEL) and os.remove(SENTINEL))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from distributed_llama_tpu.quants import QK, FloatType, QTensor  # noqa: E402
from distributed_llama_tpu.ops import pallas_prologue  # noqa: E402
from distributed_llama_tpu.ops.pallas_q4 import q4_matvec  # noqa: E402
from distributed_llama_tpu.ops.matmul import qmatmul_q80  # noqa: E402

N, K = 4096, 4096  # 7B attention-proj shape; the failing config's hot case


def _to_jnp(t: QTensor) -> QTensor:
    return jax.tree_util.tree_map(jnp.asarray, t)


def piece(name, fn):
    t0 = time.time()
    try:
        out = fn()
        np.asarray(jax.tree_util.tree_leaves(out)[0])  # honest fence
        rec = {"piece": name, "ok": True, "s": round(time.time() - t0, 1)}
    except Exception as e:
        rec = {"piece": name, "ok": False, "s": round(time.time() - t0, 1),
               "error": f"{type(e).__name__}: {e}"[:400]}
    print(json.dumps(rec), flush=True)
    return rec["ok"]


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((1, K)), jnp.float32)
    w_norm = jnp.ones((K,), jnp.float32)
    xq = jnp.ones((1, K), jnp.int8)
    sx = jnp.ones((1, K // QK), jnp.float32)

    w40 = QTensor.from_float((rng.standard_normal((N, K)) * 0.05).astype(np.float32),
                             FloatType.Q40)
    wi4 = _to_jnp(w40.to_i4p_layout())
    wi8 = _to_jnp(w40.to_i8_layout())

    piece("quantize_q80_row", lambda: pallas_prologue.quantize_q80_row(x))
    piece("rmsnorm_quantize_q80", lambda: pallas_prologue.rmsnorm_quantize_q80(
        x, w_norm, 1e-5))
    piece("q4_matvec_inline", lambda: q4_matvec(x, wi4, inline_xexp=True))
    piece("q8_inline_via_qmatmul", lambda: qmatmul_q80(xq, sx, wi8))
    piece("q4_inline_via_qmatmul", lambda: qmatmul_q80(xq, sx, wi4))
    # the proven non-inline baseline, as a tunnel-health control
    piece("q4_matvec_control", lambda: q4_matvec(x, wi4, inline_xexp=False))


if __name__ == "__main__":
    main()
