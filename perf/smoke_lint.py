#!/usr/bin/env python
"""Thin shim over distributed_llama_tpu/analysis/ (ISSUE 10).

The three original smoke passes — compileall, dead-import lint, metric-docs
drift — migrated into the unified static-analysis subsystem:

    compile / dead-import  -> analysis/smoke.py
    metric-docs            -> analysis/drift.py
    runner / CLI           -> analysis/runner.py + perf/dlint.py

This module keeps the original function surface (string findings, same
names) so tier-1's tests/test_smoke_lint.py and any git hooks calling
`python perf/smoke_lint.py` keep working unchanged. New passes (lock
discipline, hot-path syncs, fault-point drift, the compile-manifest gate)
live behind `perf/dlint.py` only — this shim stays frozen.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_llama_tpu.analysis import core as _core  # noqa: E402
from distributed_llama_tpu.analysis import drift as _drift  # noqa: E402
from distributed_llama_tpu.analysis import smoke as _smoke  # noqa: E402

REPO = _core.REPO
_OBS_DOC = _drift.OBS_DOC


def repo_py_files() -> list[str]:
    return _core.repo_py_files()


def _fmt(f) -> str:
    loc = f"{f.path}:{f.line}" if f.line else f.path
    return f"{loc}: {f.message}"


def check_compile(files: list[str]) -> list[str]:
    return [_fmt(f) for f in _smoke.check_compile(files)]


def check_dead_imports(files: list[str]) -> list[str]:
    return [_fmt(f) for f in _smoke.check_dead_imports(
        _core.load_sources(files))]


def _fallback_dead_imports(path: str, src: str) -> list[str]:
    """Original signature kept for tests: lint one (path, source) pair with
    the conservative AST fallback."""
    import ast

    relpath = os.path.relpath(path, REPO)
    try:
        tree = ast.parse(src)
    except SyntaxError:
        tree = None
    source = _core.Source(path, relpath, src, src.splitlines(), tree)
    return [_fmt(f) for f in _smoke.fallback_dead_imports(source)]


def collect_metric_names(files: list[str] | None = None
                         ) -> list[tuple[str, str]]:
    if files is None:
        sources = _core.load_sources(_core.package_py_files())
    else:
        sources = _core.load_sources(files)
    regs = _drift.collect_metric_registrations(sources, package_only=False)
    return sorted({(name, path) for name, path, _line in regs})


def check_metric_docs() -> list[str]:
    sources = _core.load_sources(_core.package_py_files())
    return [_fmt(f) for f in _drift.check_metric_docs(sources)]


def main() -> int:
    files = repo_py_files()
    errors = (check_compile(files) + check_dead_imports(files)
              + check_metric_docs())
    for e in errors:
        print(e, file=sys.stderr)
    print(f"smoke_lint: {len(files)} files, {len(errors)} finding(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
