#!/usr/bin/env python
"""Repo-wide syntax + dead-import smoke (wired into tier-1 via
tests/test_smoke_lint.py).

Two passes over every .py file in the repo:

1. **compileall** — byte-compiles everything, so a syntax error in a
   rarely-imported app path (the class of defect that survives a test suite
   importing only what it tests) fails tier-1 instead of the first prod run.
2. **dead-import lint** — pyflakes when available; otherwise a conservative
   AST fallback: an import-bound name is flagged only when its identifier
   appears NOWHERE else in the file text (docstrings and `__all__` strings
   count as uses, `# noqa` on the import line opts out), so false positives
   are structurally impossible for any name the file mentions at all.

Run directly (`python perf/smoke_lint.py`) for CI/git-hook use: exit 0 clean,
1 with findings on stderr.
"""

from __future__ import annotations

import ast
import compileall
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# directories holding first-party python (skips caches, .git, jax caches)
_SCAN_DIRS = ("distributed_llama_tpu", "tests", "perf", "examples")
_TOP_FILES = ("bench.py", "launch.py", "__graft_entry__.py")


def repo_py_files() -> list[str]:
    out = []
    for d in _SCAN_DIRS:
        for root, dirs, files in os.walk(os.path.join(REPO, d)):
            dirs[:] = [x for x in dirs if not x.startswith((".", "__pycache__"))]
            out.extend(os.path.join(root, f) for f in files if f.endswith(".py"))
    out.extend(os.path.join(REPO, f) for f in _TOP_FILES
               if os.path.exists(os.path.join(REPO, f)))
    return sorted(out)


def check_compile(files: list[str]) -> list[str]:
    errors = []
    for f in files:
        # quiet=2 silences listings; failure prints to stderr AND returns False
        if not compileall.compile_file(f, quiet=2, force=False):
            errors.append(f"{os.path.relpath(f, REPO)}: failed to byte-compile")
    return errors


def _pyflakes_check(files: list[str]) -> list[str] | None:
    """Full pyflakes run when the tool is importable; None = unavailable."""
    try:
        from pyflakes.api import checkPath
        from pyflakes.reporter import Reporter
    except ImportError:
        return None
    import io

    out, err = io.StringIO(), io.StringIO()
    rep = Reporter(out, err)
    n = 0
    for f in files:
        n += checkPath(f, rep)
    if n == 0:
        return []
    lines = [ln for ln in (out.getvalue() + err.getvalue()).splitlines() if ln]
    # only unused-import findings gate; other pyflakes classes are advisory
    return [ln for ln in lines if "imported but unused" in ln]


def _fallback_dead_imports(path: str, src: str) -> list[str]:
    """Names bound by import statements that the file never mentions again."""
    if os.path.basename(path) == "__init__.py":
        return []  # re-export surface: unused-looking imports are the point
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []  # the compile pass reports this
    lines = src.splitlines()
    findings = []
    bound: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound.append(((a.asname or a.name.split(".")[0]), node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound.append(((a.asname or a.name), node.lineno))
    for name, lineno in bound:
        line = lines[lineno - 1] if lineno - 1 < len(lines) else ""
        if "noqa" in line:
            continue
        # a name is "used" if it appears anywhere else in the file at all
        # (code, strings, __all__, docstrings) — maximally conservative
        uses = len(re.findall(rf"\b{re.escape(name)}\b", src))
        if uses <= 1:
            findings.append(f"{os.path.relpath(path, REPO)}:{lineno}: "
                            f"'{name}' imported but unused")
    return findings


def check_dead_imports(files: list[str]) -> list[str]:
    via_pyflakes = _pyflakes_check(files)
    if via_pyflakes is not None:
        return via_pyflakes
    findings = []
    for f in files:
        with open(f, encoding="utf-8") as fh:
            findings.extend(_fallback_dead_imports(f, fh.read()))
    return findings


def main() -> int:
    files = repo_py_files()
    errors = check_compile(files) + check_dead_imports(files)
    for e in errors:
        print(e, file=sys.stderr)
    print(f"smoke_lint: {len(files)} files, {len(errors)} finding(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
