#!/usr/bin/env python
"""Repo-wide syntax + dead-import + metric-docs smoke (wired into tier-1 via
tests/test_smoke_lint.py).

Three passes:

1. **compileall** — byte-compiles every .py, so a syntax error in a
   rarely-imported app path (the class of defect that survives a test suite
   importing only what it tests) fails tier-1 instead of the first prod run.
2. **dead-import lint** — pyflakes when available; otherwise a conservative
   AST fallback: an import-bound name is flagged only when its identifier
   appears NOWHERE else in the file text (docstrings and `__all__` strings
   count as uses, `# noqa` on the import line opts out), so false positives
   are structurally impossible for any name the file mentions at all.
3. **metric-docs drift lint** — statically collects every
   `metrics.counter/gauge/histogram("name", ...)` registration in the
   `distributed_llama_tpu` package and fails when any name is absent from
   docs/OBSERVABILITY.md's inventory. The doc rotted silently once (PR 2's
   inventory missed later additions until a reviewer diffed by hand); now a
   metric cannot ship undocumented.

Run directly (`python perf/smoke_lint.py`) for CI/git-hook use: exit 0 clean,
1 with findings on stderr.
"""

from __future__ import annotations

import ast
import compileall
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# directories holding first-party python (skips caches, .git, jax caches)
_SCAN_DIRS = ("distributed_llama_tpu", "tests", "perf", "examples")
_TOP_FILES = ("bench.py", "launch.py", "__graft_entry__.py")


def repo_py_files() -> list[str]:
    out = []
    for d in _SCAN_DIRS:
        for root, dirs, files in os.walk(os.path.join(REPO, d)):
            dirs[:] = [x for x in dirs if not x.startswith((".", "__pycache__"))]
            out.extend(os.path.join(root, f) for f in files if f.endswith(".py"))
    out.extend(os.path.join(REPO, f) for f in _TOP_FILES
               if os.path.exists(os.path.join(REPO, f)))
    return sorted(out)


def check_compile(files: list[str]) -> list[str]:
    errors = []
    for f in files:
        # quiet=2 silences listings; failure prints to stderr AND returns False
        if not compileall.compile_file(f, quiet=2, force=False):
            errors.append(f"{os.path.relpath(f, REPO)}: failed to byte-compile")
    return errors


def _pyflakes_check(files: list[str]) -> list[str] | None:
    """Full pyflakes run when the tool is importable; None = unavailable."""
    try:
        from pyflakes.api import checkPath
        from pyflakes.reporter import Reporter
    except ImportError:
        return None
    import io

    out, err = io.StringIO(), io.StringIO()
    rep = Reporter(out, err)
    n = 0
    for f in files:
        n += checkPath(f, rep)
    if n == 0:
        return []
    lines = [ln for ln in (out.getvalue() + err.getvalue()).splitlines() if ln]
    # only unused-import findings gate; other pyflakes classes are advisory
    return [ln for ln in lines if "imported but unused" in ln]


def _fallback_dead_imports(path: str, src: str) -> list[str]:
    """Names bound by import statements that the file never mentions again."""
    if os.path.basename(path) == "__init__.py":
        return []  # re-export surface: unused-looking imports are the point
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []  # the compile pass reports this
    lines = src.splitlines()
    findings = []
    bound: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound.append(((a.asname or a.name.split(".")[0]), node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound.append(((a.asname or a.name), node.lineno))
    for name, lineno in bound:
        line = lines[lineno - 1] if lineno - 1 < len(lines) else ""
        if "noqa" in line:
            continue
        # a name is "used" if it appears anywhere else in the file at all
        # (code, strings, __all__, docstrings) — maximally conservative
        uses = len(re.findall(rf"\b{re.escape(name)}\b", src))
        if uses <= 1:
            findings.append(f"{os.path.relpath(path, REPO)}:{lineno}: "
                            f"'{name}' imported but unused")
    return findings


def check_dead_imports(files: list[str]) -> list[str]:
    via_pyflakes = _pyflakes_check(files)
    if via_pyflakes is not None:
        return via_pyflakes
    findings = []
    for f in files:
        with open(f, encoding="utf-8") as fh:
            findings.extend(_fallback_dead_imports(f, fh.read()))
    return findings


_METRIC_FACTORIES = ("counter", "gauge", "histogram")
_OBS_DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")


def collect_metric_names(files: list[str] | None = None
                         ) -> list[tuple[str, str]]:
    """[(metric name, relpath)] for every literal-named
    counter()/gauge()/histogram() registration inside the package.

    Matches both the module conveniences (`metrics.counter("x", ...)`) and
    registry methods (`REGISTRY.counter(...)`, `reg.gauge(...)`) by the
    ATTRIBUTE name; bare-name calls (`counter(...)` after a from-import)
    are matched by function name. Non-literal first arguments are skipped —
    there are none today, and a dynamic name would need its own doc story
    anyway. Scope is the package only: tests and perf register bench-only
    scratch metrics that never reach a production /metrics."""
    if files is None:
        files = [f for f in repo_py_files()
                 if os.path.relpath(f, REPO).startswith(
                     "distributed_llama_tpu" + os.sep)]
    out = []
    for path in files:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue  # the compile pass reports this
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name not in _METRIC_FACTORIES:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value,
                                                              str):
                out.append((first.value, os.path.relpath(path, REPO)))
    return sorted(set(out))


def check_metric_docs() -> list[str]:
    """Every registered metric name must appear in docs/OBSERVABILITY.md —
    as a DELIMITED token, not a substring: a bare `in` test would let a new
    metric ride on any documented name it happens to prefix (e.g.
    `prefix_cache_hit` passing via `prefix_cache_hit_tokens_total`)."""
    try:
        with open(_OBS_DOC, encoding="utf-8") as fh:
            doc = fh.read()
    except OSError:
        return [f"{os.path.relpath(_OBS_DOC, REPO)}: missing — the metric "
                "inventory has nowhere to live"]
    return [f"{path}: metric '{name}' is not documented in "
            "docs/OBSERVABILITY.md (add it to the inventory)"
            for name, path in collect_metric_names()
            if not re.search(r"(?<![A-Za-z0-9_])" + re.escape(name)
                             + r"(?![A-Za-z0-9_])", doc)]


def main() -> int:
    files = repo_py_files()
    errors = (check_compile(files) + check_dead_imports(files)
              + check_metric_docs())
    for e in errors:
        print(e, file=sys.stderr)
    print(f"smoke_lint: {len(files)} files, {len(errors)} finding(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
