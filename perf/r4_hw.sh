#!/bin/bash
# Round-4 hardware runbook — the full post-recovery measurement sequence,
# serialized (concurrent TPU jobs wedge the axon tunnel; see PROFILE.md).
#   bash perf/r4_hw.sh [outfile]
cd "$(dirname "$0")/.."
OUT="${1:-perf/r4_hw_results.jsonl}"
: > "$OUT"

note() { python -c "import json,sys;print(json.dumps({'section':'cmd','argv':sys.argv[1]}))" "$*" | tee -a "$OUT"; }
run() {
    note "$*"
    local line
    if line=$(timeout 1500 "$@" 2>/dev/null | tail -1) && [ -n "$line" ]; then
        echo "$line" | tee -a "$OUT"
    else
        python -c "import json,sys;print(json.dumps({'section':'error','argv':sys.argv[1],'error':'failed/hung/empty'}))" "$*" | tee -a "$OUT"
    fi
}

# 1. headline with the deferred cache discipline (new default)
run python bench.py --steps 32
# 2. cache-write A/B: the carry-copy question
run python bench.py --steps 32 --cache-write inscan
# 3. device-loop amortization
run python bench.py --steps 32 --device-loop 8
run python bench.py --steps 64 --device-loop 32
# 4. forced-failure fallback drill (must print an i8 line with fallback_reason)
note "DLT_FORCE_I4P_FAILURE=1 python bench.py --steps 4"
line=$(DLT_FORCE_I4P_FAILURE=1 timeout 1500 python bench.py --steps 4 2>/dev/null | tail -1)
if [ -z "$line" ]; then
    line='{"section":"error","argv":"drill","error":"failed/hung/empty"}'
fi
echo "$line" | tee -a "$OUT"
# 5. the full sweep (window sweep, prefill, other archs, microbench, collectives)
bash perf/sweep.sh
echo "r4 hw runbook complete -> $OUT + perf/sweep_results.jsonl"
