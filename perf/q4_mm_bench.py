#!/usr/bin/env python
"""Batched fused Q40 dequant-matmul microbench — the serving-shape evidence
for ops/pallas_q4_mm.py (decode, verify, drafter rows; perf/PROFILE.md
"Batched fused Q40 cost model").

A fused dispatch should move only

    packed weights   n*(k/2) + 2*n*(k/32)     (0.5625 B/weight)
  + activations      m*k*2                    (bf16 rows)
  + output           m*n*4                    (f32 accumulator writeback)
  [+ residual        m*n*2                    (residual epilogue)]
  [+ second stream   n*(k/2) + 2*n*(k/32)     (gated silu·mul pair)]

per matmul — never a dequantized (n, k) bf16 image, which alone is 3.56x
the packed bytes. Sections time the kernels against the XLA dequant+dot
oracle at the M-row buckets the batched runtime actually dispatches
(decode M=B, verify M=B*(1+k), drafter M=B at the draft model's geometry)
and ALWAYS emit the analytic byte model, so the achieved-GB/s number can
be read against the theoretical floor. On CPU the kernels run in interpret
mode: timings are meaningless there (labeled backend="cpu"), but the byte
model and the bit-consistency section are backend-independent — the tier-1
smoke wrapper (tests/test_fused_matmul.py) asserts both without timing.

Each result prints as one JSON line (the microbench.py idiom).

Usage: python perf/q4_mm_bench.py [--section model|consistency|time] [--quick]
"""

import argparse
import functools
import json
import os
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_llama_tpu.quants import QK, FloatType, QTensor  # noqa: E402

# serving M-row buckets (runtime/batch_engine.py defaults): decode scans
# dispatch M=B rows, verify blocks M=B*(1+k) with k=4 drafts per row, the
# drafter free-runs M=B at its own (smaller) geometry. Shapes are the
# Llama-2-7B hot matmuls; the drafter rows use a TinyLlama-1.1B-class dim.
B, K_DRAFTS = 8, 4
TARGET_SHAPES = ((4096, 4096), (11008, 4096), (4096, 11008))
DRAFTER_SHAPES = ((2048, 2048), (5632, 2048), (2048, 5632))
BUCKETS = (
    ("decode", B, TARGET_SHAPES),
    ("verify", B * (1 + K_DRAFTS), TARGET_SHAPES),
    ("drafter", B, DRAFTER_SHAPES),
)
# small tileable shapes for the interpret-mode consistency pass (kh must
# admit a {512,256,128} K-tile: k % 256 == 0)
SMALL_SHAPES = ((8, 256, 512), (40, 512, 256), (8, 384, 256))


def fence(x):
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(x)[0].ravel()[0]))


def timed(fn, *args, reps=10):
    fence(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn(*args)
    fence(out)
    return (time.perf_counter() - t0) / reps


def emit(**kw):
    print(json.dumps(kw))


def hbm_model(m: int, n: int, k: int, *, residual: bool = False,
              gated: bool = False) -> dict:
    """Analytic per-dispatch HBM traffic of the fused kernel family —
    every operand it reads or writes, and nothing else (the dequantized
    image never exists outside VMEM). `ratio` is total/packed: the
    fused-path acceptance bar is ratio <= 2.0 at every serving shape
    (weights dominate; a ratio blowing past 2 means the shape is
    activation-bound and the kernel is the wrong tool)."""
    packed = n * (k // 2) + 2 * n * (k // QK)  # nibbles + f16-bit scales
    weights = packed * (2 if gated else 1)
    total = weights + m * k * 2 + m * n * 4  # bf16 x rows, f32 out
    if residual:
        total += m * n * 2  # bf16 residual read folded into the epilogue
    return {"packed_bytes": weights, "total_bytes": total,
            "density": round(weights / (n * k * (2 if gated else 1)), 4),
            "ratio": round(total / weights, 3)}


def _rand_q40(n, k, seed=0):
    rng = np.random.RandomState(seed)
    return QTensor.from_float((rng.randn(n, k) * 0.05).astype(np.float32),
                              FloatType.Q40)


def _i4p(n, k, seed=0):
    return jax.tree_util.tree_map(
        jnp.asarray, _rand_q40(n, k, seed).to_i4p_layout())


def sec_model():
    """The analytic byte model at every serving bucket x op — no device
    work; this is the section the tier-1 smoke test replays."""
    for bucket, m, shapes in BUCKETS:
        for n, k in shapes:
            for op, kw in (("mm", {}), ("mm+res", {"residual": True}),
                           ("gated", {"gated": True})):
                rec = hbm_model(m, n, k, **kw)
                emit(section="model", bucket=bucket, op=op, m=m, n=n, k=k,
                     **rec)


def check_consistency(shapes=SMALL_SHAPES, seed=0) -> list[str]:
    """Interpret-mode kernels vs the XLA dequant+dot oracle on every fused
    variant: f32 closeness AND per-row argmax identity (the greedy-pick
    bar the serving identity suite holds end-to-end). Returns a list of
    failure strings — empty means consistent."""
    from distributed_llama_tpu.ops.pallas_q4_mm import (q4_gated_matmul,
                                                        q4_gated_supported,
                                                        q4_matmul,
                                                        q4_mm_supported)

    problems: list[str] = []
    for m, n, k in shapes:
        wl = _i4p(n, k, seed)
        w3 = _i4p(n, k, seed + 1)
        assert q4_mm_supported(wl, m) and q4_gated_supported(wl, w3, m), \
            (m, n, k)
        rng = np.random.RandomState(seed + 2)
        x = jnp.asarray(rng.randn(m, k) * 0.1, jnp.bfloat16)
        res = jnp.asarray(rng.randn(m, n) * 0.1, jnp.bfloat16)
        wd = np.asarray(wl.dequantize(dtype=jnp.float32))
        w3d = np.asarray(w3.dequantize(dtype=jnp.float32))
        xf = np.asarray(x, np.float32)

        def close(name, got, want):
            got = np.asarray(got, np.float32)
            if not np.allclose(got, want, atol=1e-2, rtol=5e-2):
                err = np.abs(got - want).max()
                problems.append(f"{name} m={m} n={n} k={k}: max err {err}")
            if not np.array_equal(got.argmax(-1), want.argmax(-1)):
                problems.append(f"{name} m={m} n={n} k={k}: argmax drift")

        close("mm", q4_matmul(x, wl, out_dtype=jnp.float32, interpret=True),
              xf @ wd.T)
        close("mm+res",
              q4_matmul(x, wl, out_dtype=jnp.float32, residual=res,
                        interpret=True),
              np.asarray(res, np.float32) + xf @ wd.T)
        h1, h3 = xf @ wd.T, xf @ w3d.T
        close("gated",
              q4_gated_matmul(x, wl, w3, act="silu", out_dtype=jnp.float32,
                              interpret=True),
              (h1 / (1.0 + np.exp(-h1))) * h3)
    return problems


def sec_consistency():
    problems = check_consistency()
    emit(section="consistency", shapes=len(SMALL_SHAPES), ok=not problems,
         problems=problems)


def sec_time(reps):
    """Kernel vs oracle wall time per bucket (TPU numbers are the real
    ones; CPU interpret timings are labeled and only prove liveness). On
    CPU the weight n is shrunk so interpret mode stays tractable."""
    from distributed_llama_tpu.ops.matmul import qmatmul
    from distributed_llama_tpu.ops.pallas_q4_mm import (q4_gated_matmul,
                                                        q4_matmul,
                                                        q4_mm_supported)

    on_tpu = jax.default_backend() == "tpu"
    for bucket, m, shapes in BUCKETS:
        for n, k in shapes:
            n_eff = n if on_tpu else min(n, 512)
            k_eff = k if on_tpu else min(k, 512)
            wl = _i4p(n_eff, k_eff)
            w3 = _i4p(n_eff, k_eff, seed=1)
            if not q4_mm_supported(wl, m):
                emit(section="time", bucket=bucket, m=m, n=n_eff, k=k_eff,
                     skipped="shape outside kernel support")
                continue
            x = jnp.ones((m, k_eff), jnp.bfloat16)
            res = jnp.ones((m, n_eff), jnp.bfloat16)
            packed = wl.data.nbytes + wl.scales.nbytes
            runs = (
                ("mm", functools.partial(q4_matmul, interpret=not on_tpu),
                 (x, wl), packed),
                ("mm+res", lambda x, wl, res: q4_matmul(
                    x, wl, residual=res, interpret=not on_tpu),
                 (x, wl, res), packed),
                ("gated", lambda x, wl, w3: q4_gated_matmul(
                    x, wl, w3, act="silu", interpret=not on_tpu),
                 (x, wl, w3), 2 * packed),
                ("xla", functools.partial(qmatmul, use_pallas=False),
                 (x, wl), packed),
            )
            for op, fn, args, weight_bytes in runs:
                dt = timed(jax.jit(fn), *args, reps=reps)
                emit(section="time", backend=jax.default_backend(),
                     bucket=bucket, op=op, m=m, n=n_eff, k=k_eff,
                     ms=round(dt * 1e3, 3),
                     weight_gbps=round(weight_bytes / 1e9 / dt, 1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--section", default=None,
                    choices=["model", "consistency", "time"])
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    reps = 3 if args.quick else 10
    emit(section="meta", backend=jax.default_backend(),
         device=str(jax.devices()[0]))
    if args.section in (None, "model"):
        sec_model()
    if args.section in (None, "consistency"):
        sec_consistency()
    if args.section in (None, "time"):
        sec_time(reps)


if __name__ == "__main__":
    main()
