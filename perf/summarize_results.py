#!/usr/bin/env python
"""Summarize a bench-results JSONL (warm runner or sweep) into a markdown table.

    python perf/summarize_results.py [perf/r5_hw_results.jsonl]

Groups each result under its preceding {"section":"cmd"} marker, skips meta/
heartbeat records, flags errors and profiler-instrumented rows, and prints the
table PROFILE.md's round sections are built from. Pure stdlib — safe anywhere.
"""

import json
import sys


def rows(path):
    cmd = None
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            yield cmd, {"error": f"unparseable line: {line[:80]}"}
            continue
        sec = rec.get("section")
        if sec == "cmd":
            cmd = rec.get("argv", "?")
        elif sec == "error":
            yield rec.get("argv", cmd), {"error": rec.get("error", "?")[:80]}
        elif sec == "meta":
            continue
        elif "metric" in rec:
            yield cmd, rec


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else "perf/r5_hw_results.jsonl"
    seen = []
    for cmd, rec in rows(path):
        seen.append((cmd, rec))
    if not seen:
        print(f"(no results in {path})")
        return
    print("| config | tok/s | ms/tok | GB/s | layout | notes |")
    print("|---|---|---|---|---|---|")
    for cmd, rec in seen:
        cfg = (cmd or "?").replace("bench.py ", "")
        if "error" in rec:
            print(f"| `{cfg}` | — | — | — | — | ERROR: {rec['error']} |")
            continue
        notes = []
        if rec.get("profiled"):
            notes.append("profiled (not comparable)")
        if rec.get("fallback_reason"):
            notes.append(f"fallback: {rec['fallback_reason'][:50]}")
        if rec.get("provenance"):
            notes.append(f"{rec['provenance']} age={rec.get('age_s')}s")
        if rec.get("cache_write") == "inscan":
            notes.append("inscan")
        if rec.get("prologue"):
            notes.append("prologue")
        if "prefill_kernel" in rec:
            notes.append(f"prefill_kernel={rec['prefill_kernel']}"
                         + (f" cov={rec['prefill_kernel_coverage']}"
                            if "prefill_kernel_coverage" in rec else ""))
        ms = rec.get("ms_per_token", rec.get("ms_per_chunk", ""))
        print(f"| `{cfg}` | {rec.get('value', '')} | {ms} | "
              f"{rec.get('achieved_gbps', '')} | {rec.get('layout', '')} | "
              f"{'; '.join(notes)} |")


if __name__ == "__main__":
    main()
