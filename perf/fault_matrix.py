#!/usr/bin/env python
"""Fault-injection matrix: every runtime injection point x fault kind against
live CPU-mesh engines (wired into tier-1 via tests/test_fault_matrix.py).

For each (point, kind) cell the harness installs a deterministic FaultSpec,
drives a workload through the family that owns the point, uninstalls, and
then asserts the INVARIANTS the resilience layer promises (docs/ROBUSTNESS.md):

- the BatchEngine scheduler thread NEVER dies: a fault-free probe request
  must complete normally after every cell;
- no slot leak: every slot is free, the queue is empty, and no prefix-cache
  lease stays pinned once the cell's requests are done;
- the sequential / paged Engine stays usable: reset + a short fault-free
  generation succeeds after every cell;
- the fleet router (fleet/router.py over two model-free stub replicas)
  survives `router.proxy` / `router.health` chaos: the membership poller
  thread stays alive, ejected replicas rejoin on the next clean poll, a
  fault-free probe request proxies end-to-end, and no router-side inflight
  count leaks.

Individual requests inside a cell MAY fail — that is the point of an
injected error — the matrix only fails when the process-level invariants
break. Run directly (`python perf/fault_matrix.py [--skip-paged]`): exit 0
clean, 1 with failing cells on stderr, one JSON summary line on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_llama_tpu.models.params import init_random_params  # noqa: E402
from distributed_llama_tpu.models.spec import (ArchType, ModelSpec,  # noqa: E402
                                               RopeType)
from distributed_llama_tpu.quants import FloatType  # noqa: E402
from distributed_llama_tpu.resilience import faults  # noqa: E402
from distributed_llama_tpu.resilience.faults import FaultSpec  # noqa: E402
from distributed_llama_tpu.runtime.sampler import Sampler  # noqa: E402

KINDS = ("error", "transient", "latency")
BATCH_POINTS = ("batch.submit", "batch.cache_seed", "batch.prefill",
                "batch.dispatch", "batch.emit",
                "device_loop.batched_dispatch")
# speculation family (docs/SERVING.md "Speculative decoding"): the same
# blast-radius promises under batched draft-verify super-steps — faults
# mid-verify-dispatch and mid-accept-delivery, spec-enabled engines,
# pipelined AND serialized. batch.emit rides along because with spec on it
# fires inside the ACCEPT delivery loop (victim-only cells whose survivors
# must additionally stay token-identical to a fault-free run).
SPEC_POINTS = ("batch.verify", "device_loop.verify_dispatch", "batch.emit")
ENGINE_POINTS = ("engine.dispatch", "device_loop.dispatch")
PAGED_POINTS = ("paged.append", "paged.cold_attend")
ROUTER_POINTS = ("router.proxy", "router.health")
# api.request is HTTP-layer; its shed/validation/drain behavior is asserted
# against a live server in tests/test_resilience.py, not here.

# Durability family (ISSUE 9, docs/FLEET.md "Resume protocol"): mid-stream
# replica kill (a wedged engine failing all in-flight — the supervisor
# escalation shape) through the REAL durable router over two REAL in-process
# replicas, crossed over {stream, non-stream} × {pipelined, speculative}
# engines × {resume on, off}. Resume-on cells assert ZERO client-visible
# failures and byte-identical output vs a fault-free reference; resume-off
# cells assert the failure semantics the PR-6 router promised (mid-stream
# SSE error surfaced honestly for streams; pre-output failures retried).
DURABILITY_ENGINES = ("pipelined", "speculative")
DURABILITY_CELLS = len(DURABILITY_ENGINES) * 2 * 2  # × stream × resume
SUPERVISOR_CELLS = 1  # fault-injected hang -> supervisor recovery

# Disaggregation family (ISSUE 13, docs/DISAGG.md): a role-split fleet
# (prefill replica + decode replica behind the real router with the
# splitter armed) where the prefill replica "dies" mid-transfer — every
# fetch (decode side) or export chunk (prefill side) errors — crossed over
# {stream, non-stream} × {Q80 wire on, off}. Every cell asserts the
# documented degradation: the decode replica falls back to a LOCAL prefill
# with ZERO client-visible failures and byte-identical output (greedy AND
# seeded-stochastic) vs the monolithic reference, and afterwards neither
# replica leaks a device block-pool reference, slot, or lease.
DISAGG_POINTS = ("disagg.fetch", "disagg.export")
# planner-leg points: a failing plan POST (router side) or /v1/kv prefill
# admission (replica side) must route the request MONOLITHIC, untouched —
# one cell each on the raw fleet (wire mode is irrelevant before transfer)
DISAGG_PLAN_POINTS = ("disagg.plan", "disagg.prefill")
DISAGG_CELLS = 2 * len(DISAGG_POINTS) * 2 + len(DISAGG_PLAN_POINTS)

# Fairness/starvation family (ISSUE 11, docs/SERVING.md "Multi-tenant
# serving"): an adversarial flooding tenant saturates the engine's wait
# queue under ~4x-slots overload while two weighted tenants submit
# interactive and batch work AFTER the flood, crossed over {no fault,
# chaos-transient, chaos-error, failover} × {pipelined, serialized}.
# Every cell asserts EVERY tenant makes progress (>= 1 completed request
# each — the weighted-fair queue must reorder past the flood), the
# scheduler thread survives, a fault-free probe completes, and no
# slot/lease/queue entry leaks. The failover scenario recover_wedged()s
# the engine mid-overload and re-submits the retriably-failed requests
# (the durable-router stand-in) — tenants must still progress.
FAIRNESS_SCENARIOS = ("none", "chaos-transient", "chaos-error", "failover")
FAIRNESS_CELLS = len(FAIRNESS_SCENARIOS) * 2  # × {pipelined, serialized}

# Gray-failure family (ISSUE 14, docs/FLEET.md "Gray-failure resilience"):
# one replica of a REAL two-replica fleet under a SUSTAINED latency
# injection (api.request latency matched to the victim — it keeps answering
# healthz ok while serving slow, the gray shape) across resilience modes ×
# {stream, nonstream}. Modes: "route" = outlier detection + probation only,
# "timeout" = + adaptive pre-first-byte timeout (tries to the victim are
# cut and failed over), "hedge" = + budget-bounded duplicate tries. Every
# cell asserts 0 client-visible failures with byte-identical output
# (greedy AND pinned-seed), the victim observed ENTERING probation while
# slow and REJOINING after the injection clears (canary-driven), rotation
# recovered, and — hedge mode — hedge spend within the configured budget.
GRAY_MODES = ("route", "timeout", "hedge")
GRAY_CELLS = len(GRAY_MODES) * 2  # × {stream, nonstream}

# Drafter family (ISSUE 15, docs/SERVING.md "Model-based drafting"): a
# failing model drafter must DEGRADE — to n-gram drafting for rows prompt
# lookup can serve, to plain decode for the rest — and never surface to a
# client: byte-identity is the verify path's contract regardless of where
# proposals come from. draft.load cells build the engine under injection
# (error -> the drafter is dropped at construction, n-gram-only engine);
# draft.propose / draft.dispatch cells inject into a live drafter's
# proposal turns (error -> that dispatch's rows fall back to n-gram, the
# ProposerMux failure counter advances — asserted, so the cells can't go
# vacuous). Kinds: error + latency (a transient drafter is just a slow
# one — retries are not part of the proposal path, degradation is).
DRAFT_POINTS = ("draft.load", "draft.propose", "draft.dispatch")
DRAFT_KINDS = ("error", "latency")
DRAFT_CELLS = len(DRAFT_POINTS) * len(DRAFT_KINDS) * 2  # × {pipe, serial}

# Fused-kernel family (ISSUE 16, docs/SERVING.md "Kernel selection"): the
# `matmul.kernel_select` point fires at TRACE time inside the fused matmul
# dispatch (ops/matmul.py), BEFORE the shape gate — a raising kernel path
# must degrade that call site to the XLA lowering (bit-identical by the
# oracle contract) without killing co-batched rows or the engine. Cells
# build a FRESH --fused-matmul engine UNDER injection, so kernel selection
# actually happens while the fault is armed: every output must equal the
# kernel-off reference byte-for-byte whether the kernel path served or
# degraded, and fs.fired is asserted > 0 (non-vacuous).
FUSED_POINT = "matmul.kernel_select"
FUSED_CELLS = len(KINDS) * 2  # × {pipelined, serialized}


def _spec(seq_len=128):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=seq_len, rope_type=RopeType.LLAMA).resolved()


def _greedy(spec):
    return Sampler(spec.vocab_size, temperature=0.0)


def build_batch_engine(pipeline: bool = True, speculative: int = 0):
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    return spec, BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                             pipeline=pipeline, speculative=speculative)


# n-gram-dense prompts: greedy decode on the seed-11 tiny model enters a
# repetitive attractor, so verify dispatches engage within a few tokens —
# spec_reference() asserts that, keeping the family non-vacuous
SPEC_PAT = [7, 31, 5, 102, 9, 31, 5, 77]
SPEC_PROMPTS = ([1] + SPEC_PAT * 3, [1, 2] + SPEC_PAT * 3)
SPEC_GEN = 24


def spec_reference(spec, be) -> dict:
    """Fault-free reference outputs for the speculation family (also warms
    every program the cells will hit). Keyed by prompt tuple so a cell can
    check any completed request — victims excluded — against the tokens the
    fault-free scheduler emits (survivor token-identity)."""
    refs = {}
    v0 = be.verify_steps
    reqs = [(p, be.submit(list(p), SPEC_GEN, _greedy(spec)))
            for p in SPEC_PROMPTS]
    for p, r in reqs:
        refs[tuple(p)] = r.wait(timeout=120)
    assert be.verify_steps > v0, (
        "speculation family is vacuous: no verify dispatch in the fault-free "
        "reference run")
    return refs


def run_spec_cell(spec, be, point: str, kind: str, refs: dict) -> list[str]:
    """One speculation cell: inject at `point` while spec-enabled requests
    decode through verify dispatches, then assert the batch invariants PLUS
    survivor token-identity — any request that completed without error must
    have emitted exactly the fault-free reference tokens (rejected-draft
    rollback and mid-accept faults must never corrupt a survivor)."""
    problems: list[str] = []
    # mid-accept-delivery faults target ONE slot so the cell always has a
    # genuine victim/survivor split (an unmatched emit fault's first two
    # fires would kill both co-batched requests, making survivor identity
    # vacuous); dispatch-level faults stay unmatched — their engine blast
    # radius is exactly what the cell probes
    fs = _spec_for(point, kind)
    if point == "batch.emit":
        fs.match = {"slot": 0}
    with faults.active(fs):
        reqs = [(p, be.submit(list(p), SPEC_GEN, _greedy(spec)))
                for p in SPEC_PROMPTS]
        for p, r in reqs:
            try:
                out = r.wait(timeout=120)
            except TimeoutError:
                problems.append(f"{point}/{kind}: request hung (stuck slot)")
                continue
            except Exception:
                continue  # the injected victim — expected
            if out != refs[tuple(p)]:
                problems.append(
                    f"{point}/{kind}: survivor diverged from fault-free "
                    f"reference ({out[:6]}... vs {refs[tuple(p)][:6]}...)")
    faults.uninstall()
    if not be.scheduler_alive():
        problems.append(f"{point}/{kind}: scheduler thread DIED")
        return problems
    try:
        probe = be.submit(list(SPEC_PROMPTS[0]), SPEC_GEN, _greedy(spec))
        out = probe.wait(timeout=120)
        if out != refs[tuple(SPEC_PROMPTS[0])] or probe.error is not None:
            problems.append(f"{point}/{kind}: probe degraded "
                            f"({len(out)} tokens, err={probe.error!r})")
    except Exception as e:
        problems.append(f"{point}/{kind}: probe failed: {e!r}")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with be._plock:
            leaked = [s for s in be._slots
                      if s.req is not None or s.lease is not None]
        if not leaked and not be._pending and be._queue.empty():
            break
        time.sleep(0.01)
    else:
        problems.append(f"{point}/{kind}: slot/lease leak after probe")
    return problems


def build_draft_engine(pipeline: bool):
    """Target engine + a small RANDOM co-resident drafter (its drafts
    mostly miss — irrelevant here: the family tests degradation, not
    speedup; byte-identity holds for any proposal content)."""
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    dspec = ModelSpec(arch_type=ArchType.LLAMA, dim=32, hidden_dim=64,
                      n_layers=1, n_heads=2, n_kv_heads=2, vocab_size=256,
                      seq_len=128, rope_type=RopeType.LLAMA).resolved()
    dparams = init_random_params(dspec, FloatType.Q40, seed=5)
    be = BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                     pipeline=pipeline, speculative=4,
                     draft_model=(dspec, dparams))
    return spec, be


# one repetition-heavy prompt (n-gram can serve it when the drafter dies)
# and one structureless prompt (prompt lookup is dry there — a dead drafter
# leaves it PLAIN DECODE, the second rung of the degradation ladder)
DRAFT_PROMPTS = ([1] + SPEC_PAT * 3,
                 [1, 17, 93, 4, 55, 201, 8, 41, 113, 29])
DRAFT_GEN = 24


def run_draft_cell(spec, be, point: str, kind: str, refs: dict,
                   tag: str) -> list[str]:
    """One live-drafter cell: inject at `point` while drafter-backed
    requests decode. NO client-visible failure is acceptable — a drafter
    is an accelerator: its faults cost proposals (mux degrades that
    dispatch to n-gram), never correctness — and every output must equal
    the fault-free reference byte-for-byte."""
    problems: list[str] = []
    errs0 = be.proposer.errors
    with faults.active(_spec_for(point, kind)):
        reqs = [(p, be.submit(list(p), DRAFT_GEN, _greedy(spec)))
                for p in DRAFT_PROMPTS]
        for p, r in reqs:
            try:
                out = r.wait(timeout=120)
            except Exception as e:
                problems.append(f"draft {tag} {point}/{kind}: "
                                f"client-visible failure {e!r}")
                continue
            if r.error is not None:
                problems.append(f"draft {tag} {point}/{kind}: request "
                                f"errored {r.error!r}")
            elif out != refs[tuple(p)]:
                problems.append(f"draft {tag} {point}/{kind}: output "
                                f"diverged from fault-free reference")
    faults.uninstall()
    if kind == "error" and be.proposer.errors == errs0:
        problems.append(f"draft {tag} {point}/{kind}: fault never reached "
                        "the drafter (vacuous cell)")
    if be.proposer.disabled:
        problems.append(f"draft {tag} {point}/{kind}: bounded fault "
                        "disabled the drafter permanently")
    if not be.scheduler_alive():
        problems.append(f"draft {tag} {point}/{kind}: scheduler DIED")
        return problems
    try:
        probe = be.submit(list(DRAFT_PROMPTS[0]), DRAFT_GEN, _greedy(spec))
        out = probe.wait(timeout=120)
        if out != refs[tuple(DRAFT_PROMPTS[0])] or probe.error is not None:
            problems.append(f"draft {tag} {point}/{kind}: probe degraded")
    except Exception as e:
        problems.append(f"draft {tag} {point}/{kind}: probe failed: {e!r}")
    with be._plock:
        leaked = [s for s in be._slots
                  if s.req is not None or s.lease is not None]
    if leaked:
        problems.append(f"draft {tag} {point}/{kind}: slot/lease leak")
    return problems


def run_draft_load_cell(pipeline: bool, kind: str, refs: dict,
                        tag: str) -> list[str]:
    """draft.load cell: the engine is CONSTRUCTED under injection. An
    error must drop the drafter (n-gram-only engine, outputs unchanged);
    latency must merely delay construction."""
    problems: list[str] = []
    with faults.active(FaultSpec("draft.load", kind=kind, count=1,
                                 delay_ms=10)):
        spec, be = build_draft_engine(pipeline)
    faults.uninstall()
    try:
        if kind == "error" and be.drafter is not None:
            problems.append(f"draft {tag} load/{kind}: drafter survived an "
                            "injected load failure (vacuous cell)")
        if kind == "latency" and be.drafter is None:
            problems.append(f"draft {tag} load/{kind}: a slow load dropped "
                            "the drafter")
        for p in DRAFT_PROMPTS:
            r = be.submit(list(p), DRAFT_GEN, _greedy(spec))
            out = r.wait(timeout=120)
            if r.error is not None:
                problems.append(f"draft {tag} load/{kind}: request errored "
                                f"{r.error!r}")
            elif out != refs[tuple(p)]:
                problems.append(f"draft {tag} load/{kind}: output diverged "
                                "from fault-free reference")
    except Exception as e:
        problems.append(f"draft {tag} load/{kind}: {e!r}")
    finally:
        be.close()
    return problems


def run_draft_family() -> tuple[int, list[str]]:
    cells = 0
    problems: list[str] = []
    for pipeline in (True, False):
        tag = "pipelined" if pipeline else "serialized"
        spec, be = build_draft_engine(pipeline)
        try:
            refs = {}
            for p in DRAFT_PROMPTS:
                refs[tuple(p)] = be.submit(list(p), DRAFT_GEN,
                                           _greedy(spec)).wait(timeout=120)
            for point in ("draft.propose", "draft.dispatch"):
                for kind in DRAFT_KINDS:
                    cells += 1
                    problems += run_draft_cell(spec, be, point, kind, refs,
                                               tag)
        finally:
            be.close()
        for kind in DRAFT_KINDS:
            cells += 1
            problems += run_draft_load_cell(pipeline, kind, refs, tag)
    return cells, problems


def build_fused_engine(pipeline: bool):
    """A --fused-matmul batched engine (use_pallas upgraded to "fused",
    ops/matmul.py): every M>1 matmul the programs trace runs the kernel
    dispatch, so `matmul.kernel_select` fires while the cell's fault is
    armed and the except-path degrades that call site to XLA."""
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    return spec, BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                             pipeline=pipeline, speculative=4,
                             use_pallas=True, fused_matmul=True)


def run_fused_cell(pipeline: bool, kind: str, refs: dict) -> list[str]:
    """One fused-kernel cell: construct the engine (and trace its first
    programs — where kernel selection happens) UNDER injection. A failing
    kernel path is a TRACE-time event: it must cost only the kernel (that
    call site lowers via XLA), never a request — every output must equal
    the kernel-off reference byte-for-byte, co-batched rows included."""
    problems: list[str] = []
    tag = "fused-pipelined" if pipeline else "fused-serialized"
    name = f"[{tag}] {FUSED_POINT}/{kind}"
    fs = FaultSpec(FUSED_POINT, kind=kind, count=4, delay_ms=10)
    be = None
    try:
        with faults.active(fs):
            spec, be = build_fused_engine(pipeline)
            reqs = [(p, be.submit(list(p), SPEC_GEN, _greedy(spec)))
                    for p in SPEC_PROMPTS]
            for p, r in reqs:
                try:
                    out = r.wait(timeout=120)
                except Exception as e:
                    problems.append(f"{name}: client-visible failure {e!r}")
                    continue
                if r.error is not None:
                    problems.append(f"{name}: request errored {r.error!r}")
                elif out != refs[tuple(p)]:
                    problems.append(f"{name}: output diverged from the "
                                    "kernel-off reference "
                                    f"({out[:6]}... vs "
                                    f"{refs[tuple(p)][:6]}...)")
        faults.uninstall()
        if fs.fired == 0:
            problems.append(f"{name}: fault never reached kernel selection "
                            "(vacuous cell)")
        if not be.scheduler_alive():
            problems.append(f"{name}: scheduler thread DIED")
            return problems
        try:
            probe = be.submit(list(SPEC_PROMPTS[0]), SPEC_GEN, _greedy(spec))
            out = probe.wait(timeout=120)
            if out != refs[tuple(SPEC_PROMPTS[0])] or probe.error is not None:
                problems.append(f"{name}: probe degraded "
                                f"({len(out)} tokens, err={probe.error!r})")
        except Exception as e:
            problems.append(f"{name}: probe failed: {e!r}")
        with be._plock:
            leaked = [s for s in be._slots
                      if s.req is not None or s.lease is not None]
        if leaked:
            problems.append(f"{name}: slot/lease leak")
    finally:
        faults.uninstall()
        if be is not None:
            be.close()
    return problems


def run_fused_family() -> tuple[int, list[str]]:
    cells = 0
    problems: list[str] = []
    # kernel-off reference (the XLA oracle, use_pallas=False): fused cells
    # must emit exactly these tokens whether the kernel path served or
    # degraded mid-trace
    spec, be = build_batch_engine(pipeline=True, speculative=4)
    try:
        refs = {tuple(p): be.submit(list(p), SPEC_GEN,
                                    _greedy(spec)).wait(timeout=120)
                for p in SPEC_PROMPTS}
    finally:
        be.close()
    for pipeline in (True, False):
        for kind in KINDS:
            cells += 1
            problems += run_fused_cell(pipeline, kind, refs)
    return cells, problems


# ----------------------------------------------------------------------
# grammar-constrained decoding family (constrain/, docs/SERVING.md
# "Constrained decoding"; docs/ROBUSTNESS.md): the documented degradation
# ladder under injected faults, × {pipelined, serialized}.
#
#   constrain.compile — fires at the EDGE (constrain/compiler.py), before
#     any queue work: an injected error surfaces to the caller (the api
#     maps it to an honest 400 invalid_request_error) and the ENGINE never
#     sees the request — co-batched service is untouched, byte-for-byte.
#   constrain.mask — fires on the engine's masking paths (host sample +
#     masked dispatch state upload): an error DEGRADES that row to
#     unconstrained decoding (constrain_degraded_total, flight event) and
#     the request completes without a client-visible failure; latency
#     merely delays. Co-batched unconstrained survivors stay
#     token-identical to the fault-free reference in every cell.
# ----------------------------------------------------------------------

CONSTRAIN_PROMPT = [1, 5, 9]
CONSTRAIN_GEN = 30
CONSTRAIN_POINTS = ("constrain.compile", "constrain.mask")
CONSTRAIN_KINDS = ("error", "latency")
CONSTRAIN_CELLS = (len(CONSTRAIN_POINTS) * len(CONSTRAIN_KINDS)
                   * 2)  # × {pipelined, serialized}


def _constrain_grammar():
    from distributed_llama_tpu.constrain import byte_vocab, compile_grammar

    cv = byte_vocab(256)
    aut, gh = compile_grammar(
        "json_schema",
        {"type": "object", "properties": {
            "name": {"enum": ["alpha", "beta"]},
            "ok": {"type": "boolean"}}}, cv, eos_id=2)
    return cv, aut, gh


def run_constrain_cell(spec, be, point: str, kind: str, refs: dict,
                       aut, gh: str, cv, tag: str) -> list[str]:
    from distributed_llama_tpu.constrain import compile_grammar

    name = f"constrain {tag} {point}/{kind}"
    problems: list[str] = []
    deg0 = be.constrain_degraded
    fs = _spec_for(point, kind)
    with faults.active(fs):
        if point == "constrain.compile":
            # the edge path: compile fails/stalls BEFORE any queue work —
            # the engine never sees the request (honest 400 at the api)
            try:
                compile_grammar("regex", "[0-9]{4}", cv, eos_id=2)
                compiled = True
            except Exception:
                compiled = False
            if kind == "error" and compiled:
                problems.append(f"{name}: injected compile fault vanished")
            if kind == "latency" and not compiled:
                problems.append(f"{name}: latency injection failed the "
                                "compile")
        # engine-side service under the armed fault: one constrained row
        # co-batched with one plain row (speculation on — grammar drafts
        # on the constrained row, n-gram on the repetitive plain row)
        rc = be.submit(list(CONSTRAIN_PROMPT), CONSTRAIN_GEN, _greedy(spec),
                       constraint=aut, constraint_hash=gh)
        rp = be.submit(list(DRAFT_PROMPTS[0]), DRAFT_GEN, _greedy(spec))
        for label, r, ref in (("constrained", rc, refs["constrained"]),
                              ("plain", rp, refs["plain"])):
            try:
                out = r.wait(timeout=120)
            except Exception as e:
                problems.append(f"{name}: client-visible {label} failure "
                                f"{e!r}")
                continue
            if r.error is not None:
                problems.append(f"{name}: {label} request errored "
                                f"{r.error!r}")
                continue
            if label == "plain" and out != ref:
                # the blast-radius promise: an unconstrained co-batched
                # survivor is token-identical in EVERY cell
                problems.append(f"{name}: co-batched plain row diverged "
                                "from fault-free reference")
            if label == "constrained" and out != ref and not (
                    point == "constrain.mask" and kind == "error"):
                # mask/error legitimately degrades the victim to
                # unconstrained output; every other cell must emit the
                # fault-free constrained tokens exactly
                problems.append(f"{name}: constrained output diverged "
                                "from fault-free reference")
    faults.uninstall()
    if fs.fired == 0:
        problems.append(f"{name}: fault never fired (vacuous cell)")
    if (point == "constrain.mask" and kind == "error"
            and be.constrain_degraded == deg0):
        problems.append(f"{name}: mask fault did not degrade the "
                        "constrained row (vacuous cell)")
    if not be.scheduler_alive():
        problems.append(f"{name}: scheduler thread DIED")
        return problems
    # post-fault probe: constrained service fully restored
    try:
        probe = be.submit(list(CONSTRAIN_PROMPT), CONSTRAIN_GEN,
                          _greedy(spec), constraint=aut, constraint_hash=gh)
        out = probe.wait(timeout=120)
        if out != refs["constrained"] or probe.error is not None:
            problems.append(f"{name}: probe degraded "
                            f"({len(out)} tokens, err={probe.error!r})")
    except Exception as e:
        problems.append(f"{name}: probe failed: {e!r}")
    with be._plock:
        leaked = [s for s in be._slots
                  if s.req is not None or s.lease is not None]
    if leaked:
        problems.append(f"{name}: slot/lease leak")
    if be.constrain_table is not None and be.constrain_table.active_rows:
        problems.append(f"{name}: constraint-table region leak")
    return problems


def run_constrain_family() -> tuple[int, list[str]]:
    cv, aut, gh = _constrain_grammar()
    cells = 0
    problems: list[str] = []
    for pipeline in (True, False):
        tag = "pipelined" if pipeline else "serialized"
        spec, be = build_batch_engine(pipeline=pipeline, speculative=4)
        try:
            refs = {
                "constrained": be.submit(
                    list(CONSTRAIN_PROMPT), CONSTRAIN_GEN, _greedy(spec),
                    constraint=aut, constraint_hash=gh).wait(timeout=120),
                "plain": be.submit(
                    list(DRAFT_PROMPTS[0]), DRAFT_GEN,
                    _greedy(spec)).wait(timeout=120),
            }
            for point in CONSTRAIN_POINTS:
                for kind in CONSTRAIN_KINDS:
                    cells += 1
                    problems += run_constrain_cell(spec, be, point, kind,
                                                   refs, aut, gh, cv, tag)
        finally:
            be.close()
    return cells, problems


def build_engine(paged: bool = False):
    from distributed_llama_tpu.runtime.engine import Engine

    spec = _spec(seq_len=256 if paged else 128)
    params = init_random_params(spec, FloatType.Q40, seed=11)
    kw = (dict(kv_cache_storage="host", kv_cache_resident=64) if paged
          else {})
    return spec, Engine(spec, params, tp=1, **kw)


def _spec_for(point: str, kind: str) -> FaultSpec:
    # count=2 bounds every cell: the fault fires, the stack reacts, and the
    # cell's own workload can still make progress afterwards
    return FaultSpec(point, kind=kind, count=2, delay_ms=10)


def run_batch_cell(spec, be, point: str, kind: str) -> list[str]:
    problems: list[str] = []
    with faults.active(_spec_for(point, kind)):
        reqs = []
        for i in range(2):
            try:
                reqs.append(be.submit([1, 7 + i, 23, 5] + list(range(2, 12)),
                                      8, _greedy(spec)))
            except Exception:
                pass  # batch.submit faults reject synchronously — expected
        for r in reqs:
            try:
                r.wait(timeout=120)
            except TimeoutError:
                problems.append(f"{point}/{kind}: request hung (stuck slot)")
            except Exception:
                pass  # injected failure surfaced to the client — expected
    faults.uninstall()
    # invariants: scheduler alive, probe completes, nothing leaked
    if not be.scheduler_alive():
        problems.append(f"{point}/{kind}: scheduler thread DIED")
        return problems
    try:
        probe = be.submit([1, 2, 3], 4, _greedy(spec))
        out = probe.wait(timeout=120)
        if len(out) != 4 or probe.error is not None:
            problems.append(f"{point}/{kind}: probe degraded "
                            f"({len(out)} tokens, err={probe.error!r})")
    except Exception as e:
        problems.append(f"{point}/{kind}: probe failed: {e!r}")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with be._plock:
            leaked = [s for s in be._slots
                      if s.req is not None or s.lease is not None]
        if not leaked and not be._pending and be._queue.empty():
            break
        time.sleep(0.01)
    else:
        problems.append(f"{point}/{kind}: slot/lease leak after probe")
    return problems


def run_engine_cell(spec, eng, point: str, kind: str,
                    paged: bool = False) -> list[str]:
    problems: list[str] = []
    prompt = ([1] + list(range(2, 82))) if paged else [1, 7, 23, 5]
    with faults.active(_spec_for(point, kind)):
        try:
            eng.reset()
            if point == "device_loop.dispatch":
                eng.generate_with(list(prompt), 6, _greedy(spec),
                                  device_loop_chunk=4)
            else:
                eng.generate(list(prompt), 6, _greedy(spec))
        except Exception:
            pass  # the request may fail; the ENGINE must survive
    faults.uninstall()
    try:
        eng.reset()
        out, _ = eng.generate(list(prompt), 2, _greedy(spec))
        if len(out) != 2:
            problems.append(f"{point}/{kind}: probe generated {len(out)}/2")
    except Exception as e:
        problems.append(f"{point}/{kind}: engine unusable after fault: {e!r}")
    return problems


def build_router_fleet():
    """Fleet-tier family harness: the REAL router over two model-free stub
    replicas (stdlib HTTP servers answering /healthz and completions) — the
    router's fault points live entirely in its proxy/poll paths, so the cells
    need no engine. Returns (router_server, stub_servers)."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from distributed_llama_tpu.fleet.router import serve_router

    class StubReplica(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *a):
            pass

        def do_GET(self):
            body = _json.dumps({"status": "ok", "replica": {
                "id": "stub", "model_hash": "deadbeef0000", "slots": 2,
                "free_slots": 2, "queue_depth": 0, "draining": False,
            }}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            body = _json.dumps({"choices": [{"message": {
                "role": "assistant", "content": "ok"},
                "finish_reason": "stop", "index": 0}]}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    stubs = []
    for _ in range(2):
        srv = ThreadingHTTPServer(("127.0.0.1", 0), StubReplica)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        stubs.append(srv)
    router = serve_router(
        [f"127.0.0.1:{s.server_address[1]}" for s in stubs],
        host="127.0.0.1", port=0, poll_interval=0.2, poll_timeout=2.0,
        retries=2, try_timeout=10.0)
    threading.Thread(target=router.serve_forever, daemon=True).start()
    return router, stubs


def run_router_cell(router, point: str, kind: str) -> list[str]:
    """One fleet cell: inject at `point`, drive proxied requests + a poll,
    then assert the fleet-level invariants — the membership poller thread
    survives, a fault-free probe request completes end-to-end, rotation
    recovers to both stubs, and no router-side inflight count leaks."""
    import http.client
    import json as _json

    state = router.router_state
    problems: list[str] = []

    def post():
        conn = http.client.HTTPConnection(
            "127.0.0.1", router.server_address[1], timeout=30)
        try:
            conn.request("POST", "/v1/chat/completions",
                         _json.dumps({"messages": [
                             {"role": "user", "content": f"{point}/{kind}"}],
                             "max_tokens": 2}),
                         {"Content-Type": "application/json"})
            return conn.getresponse().status
        finally:
            conn.close()

    with faults.active(_spec_for(point, kind)):
        state.membership.poll_once()
        for _ in range(2):
            try:
                post()  # MAY 503 under injected proxy errors — that is the cell
            except Exception:
                pass
    faults.uninstall()
    if not state.membership._thread.is_alive():
        problems.append(f"{point}/{kind}: membership poller thread DIED")
        return problems
    state.membership.poll_once()  # clean poll: ejected stubs must rejoin
    if len(state.membership.in_rotation()) != 2:
        problems.append(f"{point}/{kind}: rotation did not recover "
                        f"({[r.snapshot() for r in state.membership.replicas]})")
    try:
        status = post()
        if status != 200:
            problems.append(f"{point}/{kind}: fault-free probe got {status}")
    except Exception as e:
        problems.append(f"{point}/{kind}: fault-free probe failed: {e!r}")
    # the probe client returns on response HEADERS; the handler thread
    # decrements inflight in its finally a beat later — poll, don't race it
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        leaked = [r.id for r in state.membership.replicas if r.inflight != 0]
        if not leaked:
            break
        time.sleep(0.01)
    else:
        problems.append(f"{point}/{kind}: router inflight leak on {leaked}")
    return problems


def run_supervisor_cell() -> list[str]:
    """Hung-engine supervision (resilience/supervisor.py): a deterministic
    fault-injected hang (latency fault parking the scheduler in a 600 s
    sleep at batch.dispatch — the BENCH_r03/r04 backend-outage stand-in)
    must be recovered within the supervisor's escalation threshold: the
    in-flight request fails with the RETRIABLE EngineWedged, the backend
    re-initializes, and a fault-free probe completes on the fresh scheduler
    while the zombie thread is still asleep."""
    from distributed_llama_tpu.resilience.errors import EngineWedged
    from distributed_llama_tpu.resilience.supervisor import EngineSupervisor

    problems: list[str] = []
    spec, be = build_batch_engine(pipeline=True)
    sup = EngineSupervisor(be, threshold=1.0, poll=0.1)
    try:
        # warm the shapes so the hang is the only slow thing in the cell
        be.generate([1, 7, 23, 5], 4, _greedy(spec))
        with faults.active(FaultSpec("batch.dispatch", kind="latency",
                                     delay_ms=600_000, count=1)):
            req = be.submit([1, 9, 9, 2], 8, _greedy(spec))
            t0 = time.monotonic()
            while be.dispatch_age() <= 1.0 and time.monotonic() - t0 < 30:
                time.sleep(0.02)
            t_esc = time.monotonic()
            sup.check_once()
            try:
                req.wait(timeout=10)
                problems.append("supervisor: wedged request COMPLETED "
                                "(hang never engaged?)")
            except EngineWedged:
                pass  # the retriable failure the escalation promises
            except Exception as e:
                problems.append(f"supervisor: wedged request failed with "
                                f"{e!r}, want EngineWedged")
            if time.monotonic() - t_esc > 5.0:
                problems.append("supervisor: escalation took "
                                f"{time.monotonic() - t_esc:.1f}s")
        faults.uninstall()
        if not sup.healthy:
            problems.append(f"supervisor: state {sup.state} after recovery")
        if sup.recoveries != 1:
            problems.append(f"supervisor: {sup.recoveries} recoveries, want 1")
        try:
            probe = be.submit([1, 2, 3], 4, _greedy(spec))
            out = probe.wait(timeout=120)
            if len(out) != 4:
                problems.append(f"supervisor: probe generated {len(out)}/4 "
                                "after recovery")
        except Exception as e:
            problems.append(f"supervisor: probe failed after recovery: {e!r}")
    finally:
        faults.uninstall()
        sup.stop()
        be.close()
    return problems


# ----------------------------------------------------------------------
# fairness family: flooding tenant, weighted survivors, chaos + failover
# ----------------------------------------------------------------------

def build_fair_engine(pipeline: bool):
    from distributed_llama_tpu.resilience.tenancy import TenantRegistry
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine

    spec = _spec()
    params = init_random_params(spec, FloatType.Q40, seed=11)
    reg = TenantRegistry.parse("alpha:weight=3;beta:weight=2;flood:weight=1")
    return spec, BatchEngine(spec, params, slots=2, tp=1, superstep=4,
                             pipeline=pipeline, tenants=reg)


def run_fairness_cell(spec, be, scenario: str, tag: str) -> list[str]:
    from distributed_llama_tpu.resilience.errors import EngineWedged

    problems: list[str] = []
    name = f"[{tag}] fairness/{scenario}"
    gen = 10
    fs = None
    if scenario == "chaos-transient":
        fs = FaultSpec("batch.dispatch", kind="transient", count=3,
                       delay_ms=5)
    elif scenario == "chaos-error":
        fs = FaultSpec("batch.emit", kind="error", count=2)
    reqs = []  # (tenant, prompt, BatchRequest)

    def sub(tenant, klass, salt):
        prompt = [1, salt, 23, 5]
        return (tenant, prompt,
                be.submit(list(prompt), gen, _greedy(spec), tenant=tenant,
                          klass=klass))

    done: dict = {}
    ctx = faults.active(fs) if fs is not None else None
    if ctx is not None:
        ctx.__enter__()
    try:
        # the flood lands FIRST: a FIFO queue would serve all 8 before any
        # later tenant — the weighted-fair queue must not
        for i in range(8):
            reqs.append(sub("flood", "batch", 40 + i))
        for i in range(2):
            reqs.append(sub("alpha", "interactive", 60 + i))
            reqs.append(sub("beta", "interactive", 80 + i))
        reqs.append(sub("alpha", "batch", 90))
        reqs.append(sub("beta", "batch", 91))
        if scenario == "failover":
            # mid-overload wedge: everything in flight/queued fails
            # RETRIABLE; re-submit each failure once, as a durable router
            # would, and the tenants must still make progress
            time.sleep(0.05)
            be.recover_wedged()
        resubmit = []
        for tenant, prompt, r in reqs:
            try:
                r.wait(timeout=120)
                done[tenant] = done.get(tenant, 0) + 1
            except EngineWedged:
                resubmit.append((tenant, prompt))
            except TimeoutError:
                problems.append(f"{name}: {tenant} request hung")
            except Exception:
                pass  # injected victim — expected under chaos-error
        for tenant, prompt in resubmit:
            try:
                be.submit(list(prompt), gen, _greedy(spec), tenant=tenant,
                          klass="batch").wait(timeout=120)
                done[tenant] = done.get(tenant, 0) + 1
            except Exception as e:
                problems.append(f"{name}: {tenant} resubmit failed: {e!r}")
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
        faults.uninstall()
    for tenant in ("alpha", "beta", "flood"):
        if not done.get(tenant):
            problems.append(f"{name}: tenant {tenant} STARVED "
                            f"(completions: {done})")
    if not be.scheduler_alive():
        problems.append(f"{name}: scheduler thread DIED")
        return problems
    try:
        probe = be.submit([1, 2, 3], 4, _greedy(spec))
        out = probe.wait(timeout=120)
        if len(out) != 4 or probe.error is not None:
            problems.append(f"{name}: probe degraded "
                            f"({len(out)} tokens, err={probe.error!r})")
    except Exception as e:
        problems.append(f"{name}: probe failed: {e!r}")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with be._plock:
            leaked = [s for s in be._slots
                      if s.req is not None or s.lease is not None]
            qleft = len(be._pending)
        if not leaked and not qleft and be._queue.empty():
            break
        time.sleep(0.01)
    else:
        problems.append(f"{name}: slot/lease/queue leak after probe")
    return problems


def run_fairness_family() -> tuple[int, list[str]]:
    cells = 0
    problems: list[str] = []
    for pipeline in (True, False):
        tag = "fair-pipelined" if pipeline else "fair-serialized"
        spec, be = build_fair_engine(pipeline)
        try:
            be.generate([1, 7, 23, 5], 4, _greedy(spec))  # warm the shapes
            for scenario in FAIRNESS_SCENARIOS:
                cells += 1
                problems += run_fairness_cell(spec, be, scenario, tag)
        finally:
            be.close()
    return cells, problems


# ----------------------------------------------------------------------
# durability family: real replicas, real router, mid-stream kill
# ----------------------------------------------------------------------

_FLEET_MODEL: tuple | None = None


def _fleet_model_files():
    """Tiny real checkpoint + byte-fallback tokenizer, written once per run
    (the durability family needs full api_server replicas, which load from
    files)."""
    global _FLEET_MODEL
    if _FLEET_MODEL is not None:
        return _FLEET_MODEL
    import tempfile

    from distributed_llama_tpu.formats.mfile import (params_file_order,
                                                     write_model)
    from distributed_llama_tpu.formats.tfile import (TokenizerData,
                                                     write_tokenizer)

    tmp = tempfile.mkdtemp(prefix="dlt_durability_")
    spec = _spec(seq_len=192)
    params = init_random_params(spec, FloatType.F32, seed=21)
    mpath = os.path.join(tmp, "m.m")
    write_model(mpath, spec, params_file_order(spec, params), FloatType.F32)
    vocab = ([b"<unk>", b"<s>", b"</s>"] + [bytes([i]) for i in range(251)]
             + [b"<|im_start|>", b"<|im_end|>"])
    scores = [0.0] * 254 + [-1.0, -1.0]
    tpath = os.path.join(tmp, "t.t")
    write_tokenizer(tpath, TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=254,
        max_token_length=12, chat_template="{{<|im_start|>}}"))
    _FLEET_MODEL = (mpath, tpath)
    return _FLEET_MODEL


def build_durable_fleet(speculative: int = 0, router_kwargs: dict = None):
    """Two REAL in-process api_server replicas (tiny checkpoint, batched
    engines) fronted by the REAL durable router. Returns
    (replicas=[(engine, server, port)], router, rport, close).
    `router_kwargs` extends serve_router (the gray family's GrayConfig)."""
    import threading

    from distributed_llama_tpu.apps.api_server import serve
    from distributed_llama_tpu.fleet.router import close_router, serve_router
    from distributed_llama_tpu.formats.mfile import load_model
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.tokenizer import TemplateType
    from distributed_llama_tpu.tokenizer.bpe import Tokenizer

    mpath, tpath = _fleet_model_files()
    reps = []
    for _ in range(2):
        lspec, lparams = load_model(mpath, 0)
        be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), slots=2,
                         tp=1, superstep=4, speculative=speculative)
        srv = serve(None, host="127.0.0.1", port=0,
                    template_type=TemplateType.CHATML, batch_engine=be)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        reps.append((be, srv, srv.server_address[1]))
    router = serve_router([f"127.0.0.1:{p}" for _, _, p in reps],
                          host="127.0.0.1", port=0, poll_interval=0.15,
                          block_bytes=16, retries=2, try_timeout=60.0,
                          **(router_kwargs or {}))
    threading.Thread(target=router.serve_forever, daemon=True).start()

    def close():
        close_router(router)
        for be, srv, _p in reps:
            srv.shutdown()
            srv.server_close()
            be.close()

    return reps, router, router.server_address[1], close


def _durability_request(rport: int, stream: bool) -> dict:
    """One completion through the router; returns the shared driver's
    outcome dict (fleet/client.py — text/error/status are what the cells
    assert on). The repetitive content makes n-gram drafts engage on spec
    engines."""
    from distributed_llama_tpu.fleet.client import completion_request

    body = {"messages": [
        {"role": "system", "content": "shared fleet system prompt abcb abcb"},
        {"role": "user", "content": "ab ab ab ab ab ab ab ab"}],
        "max_tokens": 48, "temperature": 0.8, "seed": 4242, "stream": stream}
    return completion_request(rport, body, timeout=120)


def _start_killer(reps, min_tokens: int = 3):
    """Background thread that wedges (recover_wedged: fail in-flight
    retriable, re-init backend — the supervisor escalation body) whichever
    replica is observed serving a request with >= min_tokens generated.
    Returns (thread, fired: list)."""
    import threading

    fired: list[str] = []

    def run():
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not fired:
            for be, _srv, port in reps:
                with be._plock:
                    busy = any(s.req is not None
                               and len(s.req.out) >= min_tokens
                               for s in be._slots)
                if busy:
                    fired.append(str(port))
                    be.recover_wedged()
                    return
            time.sleep(0.002)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, fired


def run_durability_cell(reps, router, rport: int, stream: bool,
                        resume_on: bool, ref_text: str,
                        tag: str) -> list[str]:
    """One mid-stream-kill cell. Resume ON: zero client-visible failures and
    byte-identical output. Resume OFF (the PR-6 router semantics): a stream
    that lost its replica mid-flight surfaces an honest SSE error; a
    non-stream request either completes identically via the pre-output
    retry path or surfaces an honest error status — never a hang, and the
    router/poller must survive either way."""
    from distributed_llama_tpu.obs import metrics as obs_metrics

    problems: list[str] = []
    name = (f"{tag}/{'stream' if stream else 'nonstream'}/"
            f"resume={'on' if resume_on else 'off'}")
    state = router.router_state
    state.durable = resume_on
    resumed0 = (obs_metrics.snapshot()
                .get("router_resumed_requests_total") or 0)
    killer, fired = _start_killer(reps)
    try:
        res = _durability_request(rport, stream)
    finally:
        killer.join(timeout=60)
        state.durable = True
    if not fired:
        problems.append(f"{name}: the kill never engaged (request finished "
                        "before any replica had 3 tokens in flight)")
        return problems
    if resume_on:
        if res["error"] is not None or res["status"] != 200:
            problems.append(f"{name}: client-visible failure {res!r}")
        elif res["text"] != ref_text:
            problems.append(f"{name}: output diverged from fault-free "
                            f"reference ({res['text'][:40]!r} vs "
                            f"{ref_text[:40]!r})")
        resumed = (obs_metrics.snapshot()
                   .get("router_resumed_requests_total") or 0)
        if stream and resumed <= resumed0:
            problems.append(f"{name}: no resume recorded — the cell was "
                            "vacuous")
    else:
        if stream:
            # honest surfacing: the client must see the SSE error event
            # (never a silent truncation or a double-delivered splice)
            if res["error"] is None and res["text"] != ref_text:
                problems.append(f"{name}: stream neither errored nor "
                                f"matched the reference: {res!r}")
        elif res["status"] not in (200, 500, 502, 503):
            problems.append(f"{name}: unexpected status {res!r}")
        elif res["status"] == 200 and res["text"] != ref_text:
            # pre-output retry completed it: identity holds (pinned seed
            # comes from the request body here)
            problems.append(f"{name}: retried non-stream diverged: {res!r}")
    # fleet must recover for the next cell: wedged engine serves again
    # (recover_wedged re-initialized it) once the poller sees it healthy
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        state.membership.poll_once()
        if len(state.membership.in_rotation()) == len(reps):
            break
        time.sleep(0.05)
    else:
        problems.append(f"{name}: rotation did not recover after the kill")
    return problems


def run_durability_family() -> tuple[int, list[str]]:
    cells = 0
    problems: list[str] = []
    for tag in DURABILITY_ENGINES:
        spec_k = 4 if tag == "speculative" else 0
        reps, router, rport, close = build_durable_fleet(speculative=spec_k)
        try:
            refs = {}
            for stream in (True, False):
                ref = _durability_request(rport, stream)
                if ref["error"] is not None:
                    problems.append(f"{tag}: fault-free reference failed: "
                                    f"{ref!r}")
                    cells += 4
                    break
                refs[stream] = ref["text"]
            else:
                if refs[True] != refs[False]:
                    problems.append(f"{tag}: stream vs non-stream reference "
                                    "mismatch")
                for stream in (True, False):
                    for resume_on in (True, False):
                        cells += 1
                        problems += run_durability_cell(
                            reps, router, rport, stream, resume_on,
                            refs[stream], tag)
        finally:
            close()
    return cells, problems


# ----------------------------------------------------------------------
# disaggregation family: role-split fleet, prefill death mid-transfer
# ----------------------------------------------------------------------

def build_disagg_fleet(q80: bool):
    """Prefill-role + decode-role replicas (REAL in-process api_servers)
    behind the REAL router with the splitter armed. Returns
    (replicas=[(engine, server, port, role)], router, rport, close)."""
    import threading

    from distributed_llama_tpu.apps.api_server import serve
    from distributed_llama_tpu.fleet.router import close_router, serve_router
    from distributed_llama_tpu.formats.mfile import load_model
    from distributed_llama_tpu.runtime.batch_engine import BatchEngine
    from distributed_llama_tpu.tokenizer import TemplateType
    from distributed_llama_tpu.tokenizer.bpe import Tokenizer

    mpath, tpath = _fleet_model_files()
    reps = []
    for role in ("prefill", "decode"):
        lspec, lparams = load_model(mpath, 0)
        be = BatchEngine(lspec, lparams, Tokenizer.load(tpath), slots=2,
                         tp=1, superstep=4)
        srv = serve(None, host="127.0.0.1", port=0,
                    template_type=TemplateType.CHATML, batch_engine=be,
                    role=role, kv_wire_q80=q80)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        reps.append((be, srv, srv.server_address[1], role))
    router = serve_router([f"127.0.0.1:{p}" for _, _, p, _ in reps],
                          host="127.0.0.1", port=0, poll_interval=0.15,
                          block_bytes=16, retries=2, try_timeout=60.0,
                          disagg_threshold=24, disagg_timeout=30.0)
    threading.Thread(target=router.serve_forever, daemon=True).start()

    def close():
        close_router(router)
        for be, srv, _p, _r in reps:
            srv.shutdown()
            srv.server_close()
            be.close()

    return reps, router, router.server_address[1], close


def _disagg_request(rport: int, stream: bool, seed=None,
                    salt: str = "") -> dict:
    """One long-prompt completion (over the split threshold) through the
    router; {text, error, status}. `seed` switches to pinned-seed
    stochastic sampling (the seeded half of the byte-identity bar).
    `salt` makes the prompt unique per cell: a Q80-wire split leaves
    BOUNDED-ERROR KV in the decode replica's directory by design, so a
    later same-prompt request would legitimately decode from degraded
    rows — byte-identity cells must not share prompts across wire modes."""
    from distributed_llama_tpu.fleet.client import completion_request

    body = {"messages": [
        {"role": "system", "content": "s" * 64},
        {"role": "user", "content": f"tell me something {salt}"}],
        "max_tokens": 10, "temperature": 0, "stream": stream}
    if seed is not None:
        body.update(temperature=0.9, seed=seed)
    return completion_request(rport, body, timeout=120)


def _disagg_leak_check(be, tag: str) -> list[str]:
    """Post-family invariants for one replica engine: slots/leases/queue
    quiesce empty and the device block pool's refcounts BALANCE — every
    reference is attributable to the pinned scratch block, a slot table
    entry, or a directory dev node (an imported/exported transfer must not
    leave a stray pool reference on either side)."""
    problems: list[str] = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with be._plock:
            leaked = [s for s in be._slots
                      if s.req is not None or s.lease is not None]
        if not leaked and not be._pending and be._queue.empty():
            break
        time.sleep(0.01)
    else:
        problems.append(f"{tag}: slot/lease leak after disagg family")
        return problems
    if be.kv_pool is not None:
        total = int(be.kv_pool.refcounts().sum())
        slots = sum(len(s.blocks) for s in be._slots)
        dev_nodes = (be.prefix_cache.stats()["dev_blocks"]
                     if be.prefix_cache is not None else 0)
        want = 1 + slots + dev_nodes  # scratch + tables + directory
        if total != want:
            problems.append(
                f"{tag}: block-pool refcount leak (total {total}, "
                f"accounted {want} = 1 scratch + {slots} slot-table + "
                f"{dev_nodes} directory)")
    return problems


def run_disagg_family() -> tuple[int, list[str]]:
    from distributed_llama_tpu.obs import metrics as obs_metrics

    cells = 0
    problems: list[str] = []
    for q80 in (False, True):
        tag = f"disagg-{'q80' if q80 else 'raw'}"
        reps, router, rport, close = build_disagg_fleet(q80)
        state = router.router_state
        try:
            # non-vacuity: a fault-free request must actually SPLIT (and on
            # the bit-exact raw wire, still match the monolithic reference)
            s0 = (obs_metrics.snapshot()
                  .get("router_disagg_requests_total") or {})
            r = _disagg_request(rport, stream=False, salt=f"warm-{tag}")
            s1 = (obs_metrics.snapshot()
                  .get("router_disagg_requests_total") or {})
            key = '{outcome="split"}'
            if (s1.get(key, 0) or 0) <= (s0.get(key, 0) or 0):
                problems.append(f"{tag}: family vacuous — the fault-free "
                                "request never split")
            if not q80:
                state.disagg.threshold = 0
                ref = _disagg_request(rport, stream=False,
                                      salt=f"warm-{tag}")
                state.disagg.threshold = 24
                if r["text"] != ref["text"]:
                    problems.append(
                        f"{tag}: raw-wire split output diverged "
                        f"({r['text']!r:.40} vs {ref['text']!r:.40})")
            for point in DISAGG_POINTS:
                for stream in (True, False):
                    cells += 1
                    name = (f"{tag}/{point}/"
                            f"{'stream' if stream else 'nonstream'}")
                    for seed in (None, 777):
                        # per-cell prompt (see _disagg_request salt note);
                        # the FAULTED request runs first — its import dies,
                        # the local-prefill fallback commits BIT-EXACT rows
                        # — then the monolithic reference, so the identity
                        # comparison is degraded-path vs clean-path, not
                        # cache-warmth luck. count=64 outlives every
                        # per-chunk retry: the prefill replica is
                        # effectively dead for the whole transfer.
                        salt = (f"{point[7]}{int(stream)}"
                                f"{0 if seed is None else 1}{int(q80)}")
                        with faults.active(FaultSpec(point, kind="error",
                                                     count=64)):
                            res = _disagg_request(rport, stream, seed,
                                                  salt=salt)
                        faults.uninstall()
                        if (res["error"] is not None
                                or res["status"] != 200):
                            problems.append(f"{name}: client-visible "
                                            f"failure {res!r}")
                            continue
                        state.disagg.threshold = 0
                        ref = _disagg_request(rport, stream=False,
                                              seed=seed, salt=salt)
                        state.disagg.threshold = 24
                        if res["text"] != ref["text"]:
                            problems.append(
                                f"{name}: fallback output diverged "
                                f"(seed={seed}, {res['text']!r:.40} vs "
                                f"{ref['text']!r:.40})")
            if not q80:
                # planner-leg cells: the split must fail CLOSED into the
                # monolithic path — same client answer, prefill_error
                # counted (non-vacuity)
                for point in DISAGG_PLAN_POINTS:
                    cells += 1
                    name = f"{tag}/{point}"
                    salt = f"p{point[7]}"
                    e0 = (obs_metrics.snapshot()
                          .get("router_disagg_requests_total") or {})
                    with faults.active(FaultSpec(point, kind="error",
                                                 count=4)):
                        res = _disagg_request(rport, False, None, salt=salt)
                    faults.uninstall()
                    e1 = (obs_metrics.snapshot()
                          .get("router_disagg_requests_total") or {})
                    ekey = '{outcome="prefill_error"}'
                    if res["error"] is not None or res["status"] != 200:
                        problems.append(f"{name}: client-visible failure "
                                        f"{res!r}")
                        continue
                    if (e1.get(ekey, 0) or 0) <= (e0.get(ekey, 0) or 0):
                        problems.append(f"{name}: vacuous — no "
                                        "prefill_error counted")
                    state.disagg.threshold = 0
                    ref = _disagg_request(rport, stream=False, salt=salt)
                    state.disagg.threshold = 24
                    if res["text"] != ref["text"]:
                        problems.append(
                            f"{name}: monolithic-fallback output diverged "
                            f"({res['text']!r:.40} vs {ref['text']!r:.40})")
            for be, _srv, port, role in reps:
                problems += _disagg_leak_check(be, f"{tag}/{role}:{port}")
        finally:
            faults.uninstall()
            close()
    return cells, problems


# ----------------------------------------------------------------------
# gray-failure family: sustained-slow replica, probation, hedging
# ----------------------------------------------------------------------

def _gray_request(rport: int, stream: bool, seed=None, salt: str = "",
                  scatter: str = "") -> dict:
    """One short completion through the router; {text, error, status}.
    `scatter` (when set) replaces the shared system prompt with a UNIQUE
    one: affinity would otherwise pin every request to one replica and the
    victim would never see the traffic detection needs — a cold prefix
    falls back to least-loaded with round-robin ties, alternating replicas.
    The unique part must LEAD the prompt (the affinity key is block-
    granular: a shared 16-byte prefix block still pins). Scattered requests
    are liveness probes only (their text depends on the prompt, so identity
    is asserted on the fixed-prompt requests)."""
    from distributed_llama_tpu.fleet.client import completion_request

    body = {"messages": [
        {"role": "system", "content": scatter or "gray fleet system prompt"},
        {"role": "user", "content": f"ab ab {salt}"}],
        "max_tokens": 6, "temperature": 0, "stream": stream}
    if seed is not None:
        body.update(temperature=0.9, seed=seed)
    return completion_request(rport, body, timeout=120)


def run_gray_mode(state, reps, rport: int, victim, mode: str,
                  refs: dict) -> list[str]:
    """One gray-failure mode over the shared fleet: configure the
    resilience layer for `mode`, sustain-slow the victim, and assert the
    family's invariants (module docstring at GRAY_MODES)."""
    from distributed_llama_tpu.fleet.latency import TokenBudget
    from distributed_llama_tpu.obs import metrics as obs_metrics

    problems: list[str] = []
    name = f"gray/{mode}"
    g = state.gray
    # mode wiring (fields mutated in place — the detector and membership
    # hold the same GrayConfig object)
    g.hedge = mode == "hedge"
    if mode == "timeout":
        # adaptive pre-first-byte timeout armed TIGHT: tries to the victim
        # are cut (censored-sample recorded) and failed over
        g.min_lat_samples = 8
        g.ttfb_floor, g.ttfb_cap, g.ttfb_mult = 0.2, None, 2.0
        delay_ms = 1200.0
    elif mode == "hedge":
        # fixed timeout (floor == cap) isolates hedging as the mechanism;
        # fixed hedge delay — with one of two replicas slow, HALF the
        # samples are slow and an adaptive p95 delay would defer itself
        g.min_lat_samples = 8
        g.ttfb_floor = g.ttfb_cap = 60.0
        g.hedge_delay = 0.2
        g.hedge_pct = 0.25
        state.hedge_budget = TokenBudget(g.hedge_pct, g.hedge_burst)
        delay_ms = 600.0
    else:  # "route": detection + probation only, timeouts/hedging at caps
        g.min_lat_samples = 10 ** 9
        g.ttfb_floor, g.ttfb_cap = 5.0, None
        delay_ms = 500.0
    # hedge-spend baseline from the LAUNCH-SITE counter, not the budget's
    # own ledger (gating stats()["spent"] against cap + rate*noted would be
    # tautological — TokenBudget enforces that internally by construction;
    # a regression that launches without spending must still fail the gate)
    h0 = (obs_metrics.snapshot().get("router_hedges_total") or {}).get(
        '{outcome="launched"}', 0)
    i = 0
    with faults.active(FaultSpec("api.request", kind="latency",
                                 delay_ms=delay_ms,
                                 match={"replica": victim.id})):
        # identity drive: fixed prompt, stream x {greedy, pinned-seed} —
        # every response client-clean and byte-identical to the reference
        for stream in (True, False):
            for seed in (None, 777):
                res = _gray_request(rport, stream, seed)
                tag = (f"{name}/{'stream' if stream else 'nonstream'}"
                       f"/seed={seed}")
                if res["error"] is not None or res["status"] != 200:
                    problems.append(f"{tag}: client-visible failure {res!r}")
                elif res["text"] != refs[(stream, seed)]:
                    problems.append(f"{tag}: diverged ({res['text']!r:.40} "
                                    f"vs {refs[(stream, seed)]!r:.40})")
        # probation entry: scattered probes keep outcome samples flowing to
        # BOTH replicas until the detector flags the victim. The budget is
        # generous: hedged rounds leave the victim's (losing) attempts
        # holding inflight counts, so least-loaded picks it only when idle
        # — its sampling rate is a fraction of the probe rate.
        deadline = time.monotonic() + 60
        while not victim.degraded and time.monotonic() < deadline:
            res = _gray_request(rport, i % 2 == 0, salt=str(i),
                                scatter=f"p{i:04d} {name} probe")
            if res["error"] is not None or res["status"] != 200:
                problems.append(f"{name}: probe failure {res!r}")
                break
            i += 1
            state.membership.poll_once()
        if not victim.degraded:
            problems.append(f"{name}: victim never entered probation "
                            f"({victim.snapshot()})")
    faults.uninstall()
    # probation exit: the injection cleared — canary traffic must rejoin
    # the victim within probation_exits in-band outcomes
    deadline = time.monotonic() + 30
    while victim.degraded and time.monotonic() < deadline:
        res = _gray_request(rport, i % 2 == 0, salt=str(i),
                            scatter=f"c{i:04d} {name} canary")
        if res["error"] is not None or res["status"] != 200:
            problems.append(f"{name}: canary failure {res!r}")
            break
        i += 1
        state.membership.poll_once()
    if victim.degraded:
        problems.append(f"{name}: victim never rejoined after the "
                        "injection cleared")
    state.membership.poll_once()
    if len(state.membership.in_rotation()) != len(reps):
        problems.append(f"{name}: rotation did not recover")
    if mode == "hedge":
        st = state.hedge_budget.stats()
        launched = (obs_metrics.snapshot().get("router_hedges_total")
                    or {}).get('{outcome="launched"}', 0) - h0
        allowance = st["cap"] + g.hedge_pct * st["noted"]
        if launched < 1:
            problems.append(f"{name}: vacuous — no hedge launched")
        if launched > allowance:
            problems.append(f"{name}: hedge spend {launched} over budget "
                            f"(allowance {allowance:.1f})")
    # no router-side inflight leak (hedge losers must release their counts)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        leaked = [r.id for r in state.membership.replicas if r.inflight != 0]
        if not leaked:
            break
        time.sleep(0.02)
    else:
        problems.append(f"{name}: router inflight leak on {leaked}")
    return problems


def run_gray_family() -> tuple[int, list[str]]:
    from distributed_llama_tpu.fleet.latency import GrayConfig

    cells = 0
    problems: list[str] = []
    cfg = GrayConfig(eject_multiple=3.0, min_samples=4, probation_exits=2,
                     quorum_frac=0.5, canary_every=2,
                     min_lat_samples=10 ** 9, hedge=False)
    reps, router, rport, close = build_durable_fleet(
        router_kwargs={"gray": cfg})
    state = router.router_state
    victim = state.membership.by_id(f"127.0.0.1:{reps[0][2]}")
    try:
        refs = {}
        for stream in (True, False):
            for seed in (None, 777):
                r = _gray_request(rport, stream, seed)
                if r["error"] is not None:
                    problems.append(
                        f"gray: fault-free reference failed: {r!r}")
                    return GRAY_CELLS, problems
                refs[(stream, seed)] = r["text"]
        if refs[(True, None)] != refs[(False, None)]:
            problems.append("gray: stream vs non-stream reference mismatch")
        for mode in GRAY_MODES:
            cells += 2  # the mode drives stream AND nonstream cells
            problems += run_gray_mode(state, reps, rport, victim, mode, refs)
    finally:
        faults.uninstall()
        close()
    return cells, problems


def run_matrix(include_paged: bool = True,
               kinds=KINDS) -> tuple[int, list[str]]:
    cells = 0
    problems: list[str] = []
    # the batch family runs TWICE — pipelined (the default: overlapped
    # dispatches, speculative chains that faults must flush cleanly) and
    # serialized — so every cell's invariants hold under both schedulers
    for pipeline in (True, False):
        bspec, be = build_batch_engine(pipeline=pipeline)
        tag = "pipelined" if pipeline else "serialized"
        try:
            for point in BATCH_POINTS:
                for kind in kinds:
                    cells += 1
                    problems += [f"[{tag}] {p}"
                                 for p in run_batch_cell(bspec, be, point,
                                                         kind)]
        finally:
            be.close()
    # speculation family: same invariants with batched draft-verify
    # super-steps engaged, plus survivor token-identity, under both
    # schedulers (docs/SERVING.md "Speculative decoding")
    for pipeline in (True, False):
        bspec, be = build_batch_engine(pipeline=pipeline, speculative=4)
        tag = "spec-pipelined" if pipeline else "spec-serialized"
        try:
            refs = spec_reference(bspec, be)
            for point in SPEC_POINTS:
                for kind in kinds:
                    cells += 1
                    problems += [f"[{tag}] {p}"
                                 for p in run_spec_cell(bspec, be, point,
                                                        kind, refs)]
        finally:
            be.close()
    espec, eng = build_engine()
    for point in ENGINE_POINTS:
        for kind in kinds:
            cells += 1
            problems += run_engine_cell(espec, eng, point, kind)
    if include_paged:
        pspec, peng = build_engine(paged=True)
        for point in PAGED_POINTS:
            for kind in kinds:
                cells += 1
                problems += run_engine_cell(pspec, peng, point, kind,
                                            paged=True)
    router, stubs = build_router_fleet()
    try:
        for point in ROUTER_POINTS:
            for kind in kinds:
                cells += 1
                problems += run_router_cell(router, point, kind)
    finally:
        from distributed_llama_tpu.fleet.router import close_router

        close_router(router)
        for s in stubs:
            s.shutdown()
            s.server_close()
    # hung-engine supervision + durable mid-stream failover (ISSUE 9)
    cells += SUPERVISOR_CELLS
    problems += run_supervisor_cell()
    d_cells, d_problems = run_durability_family()
    cells += d_cells
    problems += d_problems
    # multi-tenant starvation/fairness under overload × chaos/failover
    # (ISSUE 11, docs/SERVING.md "Multi-tenant serving")
    f_cells, f_problems = run_fairness_family()
    cells += f_cells
    problems += f_problems
    # prefill/decode disaggregation: prefill death mid-transfer must
    # degrade to a byte-identical local prefill (ISSUE 13, docs/DISAGG.md)
    g_cells, g_problems = run_disagg_family()
    cells += g_cells
    problems += g_problems
    # gray failures: sustained-slow replica -> probation + adaptive
    # timeouts + bounded hedging (ISSUE 14, docs/FLEET.md)
    y_cells, y_problems = run_gray_family()
    cells += y_cells
    problems += y_problems
    # model drafter: load/propose/dispatch failures degrade to n-gram
    # then plain decode, never a client failure (ISSUE 15,
    # docs/SERVING.md "Model-based drafting")
    d_cells, d_problems = run_draft_family()
    cells += d_cells
    problems += d_problems
    # fused dequant-matmul kernels: a failing kernel path degrades that
    # call site to the XLA lowering, token-identical, engine intact
    # (ISSUE 16, docs/SERVING.md "Kernel selection")
    k_cells, k_problems = run_fused_family()
    cells += k_cells
    problems += k_problems
    # grammar-constrained decoding: compile faults stop at the edge
    # (honest 400, no queue work), mask faults degrade that row to
    # unconstrained decoding, co-batched survivors token-identical
    # (ISSUE 17, docs/SERVING.md "Constrained decoding")
    c_cells, c_problems = run_constrain_family()
    cells += c_cells
    problems += c_problems
    return cells, problems


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-paged", action="store_true",
                    help="skip the paged-engine family (its per-layer host "
                         "callbacks dominate the matrix wall time)")
    args = ap.parse_args()
    t0 = time.perf_counter()
    cells, problems = run_matrix(include_paged=not args.skip_paged)
    for p in problems:
        print(p, file=sys.stderr)
    print(json.dumps({"metric": "fault_matrix_cells", "value": cells,
                      "unit": "cells", "vs_baseline": None,
                      "failures": len(problems),
                      "seconds": round(time.perf_counter() - t0, 1)}))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
