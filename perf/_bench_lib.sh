# Shared helpers for the perf shell runbooks (sourced by sweep.sh / r5_hw.sh).
# Requires $OUT to be set by the sourcing script. Every emitted line is valid
# JSON; a command that dies leaves an explicit {"section":"error",...} record
# carrying the tail of its stderr (diagnosable, not just 'failed/hung').

note() {
    python -c "import json,sys;print(json.dumps({'section':'cmd','argv':sys.argv[1]}))" "$*" | tee -a "$OUT"
}

err_record() {  # $1=argv  $2=stderr-file
    python - "$1" "$2" <<'PY' | tee -a "$OUT"
import json, sys
tail = ""
try:
    with open(sys.argv[2], errors="replace") as f:
        tail = " | ".join(l.strip() for l in f.readlines()[-3:] if l.strip())[:500]
except OSError:
    pass
print(json.dumps({"section": "error", "argv": sys.argv[1],
                  "error": "command failed, hung (watchdog), or produced no output",
                  "stderr_tail": tail}))
PY
}

# pause the warm runner for any TPU job this script launches (microbench etc.
# don't write the sentinel themselves; concurrent jobs wedge the tunnel).
# The path mirrors bench.py's SENTINEL constant — keep the two in sync.
touch_sentinel() {
    python -c "import time;open('perf/.driver_bench_active','w').write(str(time.time()))" 2>/dev/null || true
}

# watchdog: must budget for bench.py's pre-measurement waits (busy-wait for the
# warm runner to yield, up to DLT_BUSY_WAIT=1500s, + probe up to
# DLT_PROBE_TIMEOUT=600s) on top of the measurement itself
WATCHDOG_S=3600

# run CMD...: emit cmd record, run under the watchdog, record the LAST stdout
# line (bench.py's JSON) or an error record with stderr tail
run() {
    note "$*"
    touch_sentinel
    local line etmp
    etmp=$(mktemp)
    if line=$(timeout "$WATCHDOG_S" "$@" 2>"$etmp" | tail -1) && [ -n "$line" ]; then
        echo "$line" | tee -a "$OUT"
    else
        err_record "$*" "$etmp"
    fi
    rm -f "$etmp"
}

# run_all CMD...: same, but records EVERY stdout line (multi-record sections)
run_all() {
    note "$*"
    touch_sentinel
    local out etmp
    etmp=$(mktemp)
    if out=$(timeout "$WATCHDOG_S" "$@" 2>"$etmp") && [ -n "$out" ]; then
        echo "$out" | tee -a "$OUT"
    else
        err_record "$*" "$etmp"
    fi
    rm -f "$etmp"
}
