#!/usr/bin/env python
"""Persistent bench runner for the flapping-tunnel regime.

The axon tunnel's half-alive mode makes backend INIT the hard part: a cold process
can spend minutes (or forever) initializing, and by the time a shell-looped bench
process starts, the window is gone. This runner keeps ONE process alive: it retries
a tiny fenced op until the backend comes up, then runs the whole bench matrix
in-process against the already-warm backend, appending each JSON line to the
results file as it lands (so a mid-matrix wedge still leaves everything earlier).

    python perf/persistent_bench.py [outfile] [max_wait_minutes]
"""

import io
import json
import os
import sys
import time
from contextlib import redirect_stdout

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import numpy as np  # noqa: E402

OUT = sys.argv[1] if len(sys.argv) > 1 else "perf/r4_hw_results.jsonl"
MAX_WAIT_MIN = float(sys.argv[2]) if len(sys.argv) > 2 else 240.0

CONFIGS = [
    ["--steps", "32"],
    ["--steps", "32", "--cache-write", "inscan"],
    ["--steps", "32", "--layout", "i8"],
    ["--steps", "32", "--device-loop", "8"],
    ["--steps", "64", "--device-loop", "32"],
    ["--steps", "64", "--window", "2048"],
    ["--prefill", "64", "--steps", "16"],
    ["--arch", "tinyllama_1_1b", "--steps", "32"],
    ["--arch", "llama3_8b", "--steps", "32"],
    ["--arch", "mixtral_8x7b_l8", "--steps", "16"],
    ["--arch", "grok1_l2", "--steps", "16"],
]
DRILL = ["--steps", "4"]


def emit(path, obj_or_line):
    line = obj_or_line if isinstance(obj_or_line, str) else json.dumps(obj_or_line)
    print(line, flush=True)
    with open(path, "a") as f:
        f.write(line + "\n")


def wait_for_backend() -> bool:
    """In the dead mode the fenced op HANGS (never raises), so it must run on a
    watchdog thread: the main thread heartbeats while a single probe thread blocks
    in backend init; when the tunnel recovers, that same blocked call completes and
    flips the event. A raised error restarts the probe thread."""
    import threading

    import jax.numpy as jnp

    t0 = time.time()
    done = threading.Event()
    state = {}

    def probe():
        try:
            np.asarray(jnp.ones((4,)) + 1)  # fenced: device->host
            state["ok"] = True
        except Exception as e:
            state["err"] = str(e)[:120]
        done.set()

    threading.Thread(target=probe, daemon=True).start()
    beats = 0
    while time.time() - t0 < MAX_WAIT_MIN * 60:
        if done.wait(timeout=60):
            if state.get("ok"):
                emit(OUT, {"section": "meta", "event": "backend_up",
                           "waited_s": round(time.time() - t0, 1)})
                return True
            emit(OUT, {"section": "meta", "event": "probe_error",
                       "error": state.get("err", "?")})
            try:
                # a FAILED init is cached per process; reset so the retry re-inits
                import jax.extend.backend

                jax.extend.backend.clear_backends()
            except Exception:
                pass
            done.clear()
            state.clear()
            time.sleep(20)
            threading.Thread(target=probe, daemon=True).start()
        else:
            beats += 1
            if beats % 10 == 0:
                emit(OUT, {"section": "meta", "event": "still_waiting",
                           "waited_s": round(time.time() - t0, 1)})
    return False


def run_config(argv, env=None):
    import bench

    old_argv, old_env = sys.argv, {}
    for k, v in (env or {}).items():
        old_env[k] = os.environ.get(k)
        os.environ[k] = v
    sys.argv = ["bench.py"] + argv
    buf = io.StringIO()
    try:
        with redirect_stdout(buf):
            bench.main()
    except SystemExit:
        pass
    except Exception as e:
        emit(OUT, {"section": "error", "argv": " ".join(argv),
                   "error": f"{type(e).__name__}: {e}"[:300]})
        return
    finally:
        sys.argv = old_argv
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        import gc

        gc.collect()
    lines = [l for l in buf.getvalue().splitlines() if l.strip()]
    emit(OUT, {"section": "cmd", "argv": "bench.py " + " ".join(argv)})
    if lines:
        emit(OUT, lines[-1])
    else:
        emit(OUT, {"section": "error", "argv": " ".join(argv), "error": "no output"})


def main():
    open(OUT, "a").close()
    emit(OUT, {"section": "meta", "event": "runner_start",
               "time": time.strftime("%H:%M:%S")})
    if not wait_for_backend():
        emit(OUT, {"section": "error", "error": "backend never came up"})
        sys.exit(1)
    # the tunnel is warm in THIS process: run the whole matrix here
    for argv in CONFIGS:
        run_config(argv)
    run_config(DRILL, env={"DLT_FORCE_I4P_FAILURE": "1"})
    emit(OUT, {"section": "meta", "event": "runner_done",
               "time": time.strftime("%H:%M:%S")})


if __name__ == "__main__":
    main()
