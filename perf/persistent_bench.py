#!/usr/bin/env python
"""Persistent bench runner for the flapping-tunnel regime.

The axon tunnel's half-alive mode makes backend INIT the hard part: a cold process
can spend minutes (or forever) initializing, and by the time a shell-looped bench
process starts, the window is gone. This runner keeps ONE process alive: it retries
a tiny fenced op until the backend comes up, then runs the whole bench matrix
in-process against the already-warm backend, appending each JSON line to the
results file as it lands (so a mid-matrix wedge still leaves everything earlier).

    python perf/persistent_bench.py [outfile] [max_wait_minutes]

Driver handoff: every time the HEADLINE config (the bench.py defaults) completes,
the result is atomically written to BENCH_LATEST (repo root) with a capture
timestamp. When the driver's own fresh `python bench.py` can't init the backend
(tunnel flapped between this runner's window and the driver's capture), bench.py
reports that file's number with explicit provenance/age fields instead of 0.0 —
so a hardware number captured in ANY window this round survives to BENCH_r05.json.
After the matrix, the runner stays alive re-running the headline config every
REFRESH_MIN minutes to keep the handoff file fresh, pausing whenever a foreign
bench process announces itself via the ACTIVE sentinel (the tunnel wedges under
concurrent jobs — see perf/PROFILE.md).
"""

import io
import json
import os
import sys
import time
from contextlib import contextmanager, redirect_stdout

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from bench import (  # noqa: E402  — single source of truth for the protocol
    BUSY_MARKER, HANDOFF_LATEST as BENCH_LATEST, HANDOFF_TRACKED,
    SENTINEL as ACTIVE, SENTINEL_EXPIRY_S)

# argv belongs to this script only when it IS the script — under pytest (which
# imports this module for _git_commit_path) argv holds pytest's own arguments
_IS_SCRIPT = os.path.basename(sys.argv[0] or "").startswith("persistent_bench")
OUT = (sys.argv[1] if _IS_SCRIPT and len(sys.argv) > 1
       else "perf/r5_hw_results.jsonl")
MAX_WAIT_MIN = float(sys.argv[2]) if _IS_SCRIPT and len(sys.argv) > 2 else 600.0
REFRESH_MIN = 20.0
KEEP_FRESH_HOURS = 14.0

HEADLINE = ["--steps", "32"]
# Ordered by next-window value: the 01:09 window closed after ~8 usable
# minutes, so the never-yet-measured judge deliverables (prefill tok/s —
# VERDICT r4 item 5; per-arch sweep — item 6) come before the comparison
# levers that already have one window of data (no-fuse/prologue/inscan) and
# the lower-stakes A/Bs (device-loop, window, i8). Resume markers key on argv,
# not position, so reordering composes with a mid-matrix restart.
CONFIGS = [
    HEADLINE,
    ["--prefill", "64", "--steps", "16"],
    ["--prefill", "128", "--steps", "16"],
    ["--prefill", "64", "--steps", "16", "--prefill-kernel"],
    ["--prefill", "128", "--steps", "16", "--prefill-kernel"],
    ["--arch", "tinyllama_1_1b", "--steps", "32"],
    ["--arch", "llama3_8b", "--steps", "32"],
    ["--arch", "mixtral_8x7b_l8", "--steps", "16"],
    ["--arch", "grok1_l2", "--steps", "16"],
    ["--steps", "32", "--no-fuse"],
    ["--steps", "32", "--prologue"],
    ["--steps", "32", "--cache-write", "inscan"],
    ["--steps", "32", "--layout", "i8"],
    ["--steps", "32", "--device-loop", "8"],
    ["--steps", "64", "--device-loop", "32"],
    ["--steps", "64", "--window", "2048"],
    # post-deferred profiler trace (VERDICT r4 item 4: where does the residual
    # non-kernel time go once the carry copies are gone?)
    ["--steps", "8", "--profile-dir", "perf/r5_trace"],
    # LAST on purpose: the paged rung is the first pure_callback ever run over
    # the tunnel — if host callbacks wedge, only the supervisor's stall budget
    # is lost, not the jobs behind it
    ["--steps", "8", "--kv-paged", "1024"],
]
DRILL = ["--steps", "4"]


_last_foreign_active = 0.0
FOREIGN_GRACE_S = 180.0


def foreign_bench_active() -> bool:
    """True while another process (the driver's bench.py) holds the sentinel, and
    for a FOREIGN_GRACE_S tail after it disappears — a driver runbook issues
    back-to-back bench invocations, and each gap (atexit removes the sentinel,
    the next python takes seconds to recreate it) must not let the runner slip a
    20-min config in between (concurrent jobs wedge the tunnel). Stale sentinels
    from a crashed process expire after 30 min."""
    global _last_foreign_active
    try:
        if time.time() - os.path.getmtime(ACTIVE) < SENTINEL_EXPIRY_S:
            _last_foreign_active = time.time()
            return True
    except OSError:
        pass
    return time.time() - _last_foreign_active < FOREIGN_GRACE_S


def pause_for_foreign(event: str) -> float:
    """Block while a foreign (driver) bench holds the sentinel; returns the
    seconds spent paused so callers can exclude it from their own deadlines."""
    if not foreign_bench_active():
        return 0.0
    t0 = time.time()
    emit(OUT, {"section": "meta", "event": event})
    beats = 0
    while foreign_bench_active():
        time.sleep(30)
        beats += 1
        if beats % 20 == 0:  # ~10 min: keep the supervisor's stall
            # detector from killing a runner that is correctly yielding
            emit(OUT, {"section": "meta", "event": "still_paused",
                       "paused_s": round(time.time() - t0, 1)})
    return time.time() - t0


def emit(path, obj_or_line):
    line = obj_or_line if isinstance(obj_or_line, str) else json.dumps(obj_or_line)
    # the REAL stdout: emit fires from inside run_inprocess's redirect_stdout
    # (via _LineTee), where print() to sys.stdout would recurse into the tee
    print(line, file=sys.__stdout__, flush=True)
    with open(path, "a") as f:
        f.write(line + "\n")


def wait_for_backend(max_wait_min: float | None = None) -> bool:
    """In the dead mode the fenced op HANGS (never raises), so it must run on a
    watchdog thread: the main thread heartbeats while a single probe thread blocks
    in backend init; when the tunnel recovers, that same blocked call completes and
    flips the event. A raised error restarts the probe thread."""
    import threading

    import jax.numpy as jnp

    t0 = time.time()
    paused = 0.0  # time yielded to a foreign bench; not charged to the budget
    done = threading.Event()
    state = {}

    def probe():
        try:
            np.asarray(jnp.ones((4,)) + 1)  # fenced: device->host
            state["ok"] = True
        except Exception as e:
            state["err"] = str(e)[:120]
        done.set()

    paused += pause_for_foreign("probe_paused_for_foreign_bench")
    threading.Thread(target=probe, daemon=True).start()
    beats = 0
    budget_min = MAX_WAIT_MIN if max_wait_min is None else max_wait_min
    while time.time() - t0 - paused < budget_min * 60:
        if done.wait(timeout=60):
            if state.get("ok"):
                emit(OUT, {"section": "meta", "event": "backend_up",
                           "waited_s": round(time.time() - t0, 1)})
                return True
            emit(OUT, {"section": "meta", "event": "probe_error",
                       "error": state.get("err", "?")})
            try:
                # a FAILED init is cached per process; reset so the retry re-inits
                import jax.extend.backend

                jax.extend.backend.clear_backends()
            except Exception:
                pass
            done.clear()
            state.clear()
            time.sleep(20)
            # do not spawn fresh init attempts while the driver's bench.py is
            # probing (its sentinel is up): concurrent inits step on each other
            # in the half-alive mode. The already-stuck thread (dead mode) just
            # lingers — it never issues new connection attempts.
            paused += pause_for_foreign("probe_paused_for_foreign_bench")
            threading.Thread(target=probe, daemon=True).start()
        else:
            beats += 1
            if beats % 10 == 0:
                emit(OUT, {"section": "meta", "event": "still_waiting",
                           "waited_s": round(time.time() - t0, 1)})
    return False


def purge_device_memory():
    """Free EVERYTHING on the device between in-process configs. The first r5
    matrix run proved gc alone is not enough: each bench.main() leaves buffers
    pinned by jit-cache constants, so by the --layout i8 config (7.4 GB weights)
    HBM was full, and every later config — including a 4-element probe — died
    RESOURCE_EXHAUSTED. Each config rebuilds all its arrays, so force-deleting
    every live array (and dropping the jit caches that pin them) is safe here."""
    import gc

    jax.clear_caches()
    gc.collect()
    try:
        arrays = list(jax.live_arrays())
    except Exception:
        arrays = []
    for a in arrays:
        try:
            a.delete()
        except Exception:
            pass  # already deleted/donated; keep freeing the rest
    gc.collect()
    # NOTE: clear_caches() forces a re-trace on the next run of even an
    # identical config (the keep-fresh headline). The persistent on-disk
    # compilation cache makes that a cache load, not a recompile — an
    # acceptable price for starting every config from empty HBM.


def config_failed(result) -> bool:
    """A config that produced no JSON line, an explicit error, a 0.0 value, or
    a handoff-fallback payload (bench.py serves the OLD BENCH_latest result
    with value>0 and no 'error' when its own probe fails — provenance marks
    it) leaves the backend suspect."""
    return (result is None or "error" in result or "provenance" in result
            or not result.get("value", 0) > 0)


@contextmanager
def busy_marker():
    """Two-way handshake: a driver bench.py that starts while an in-process job
    runs waits for the busy marker to clear instead of probing into a busy
    tunnel. Refreshed every 5 min so a >30-min job isn't mistaken for a crashed
    runner by bench.py's staleness check."""
    import threading

    busy_stop = threading.Event()

    def _busy_keepalive():
        while not busy_stop.is_set():
            try:
                with open(BUSY_MARKER, "w") as f:
                    f.write(str(time.time()))
            except OSError:
                pass
            busy_stop.wait(300)

    threading.Thread(target=_busy_keepalive, daemon=True).start()
    try:
        yield
    finally:
        busy_stop.set()
        try:
            os.path.exists(BUSY_MARKER) and os.remove(BUSY_MARKER)
        except OSError:
            pass


def run_inprocess(label, argv, call, env=None, emit_all=False):
    """Run one in-process job with the busy handshake, env swap, stdout capture
    and post-run device purge. Returns the captured non-empty stdout lines (or
    None on failure). The cmd marker is emitted BEFORE the run so a wedge or
    exception still leaves the attempt attributable in the JSONL stream."""
    emit(OUT, {"section": "cmd", "argv": _job_key(label, argv, env)})
    old_argv, old_env = sys.argv, {}
    for k, v in (env or {}).items():
        old_env[k] = os.environ.get(k)
        os.environ[k] = v
    # in-process runs are the runner's own, not a foreign job
    old_env.setdefault("DLT_WARM_RUNNER", os.environ.get("DLT_WARM_RUNNER"))
    os.environ["DLT_WARM_RUNNER"] = "1"
    sys.argv = [label] + argv

    class _LineTee(io.TextIOBase):
        """Captures lines AND (for emit_all jobs) appends each to the results
        file as it lands, so a mid-job wedge or kill still leaves every
        completed line on disk (the runner's append-as-it-lands contract)."""

        def __init__(self):
            self.lines, self._cur = [], ""

        def write(self, text):
            self._cur += text
            while "\n" in self._cur:
                line, self._cur = self._cur.split("\n", 1)
                self._emit_line(line)
            return len(text)

        def _emit_line(self, line):
            if line.strip():
                self.lines.append(line)
                if emit_all:
                    emit(OUT, line)

        def close_tail(self):
            """Promote a final line with no trailing newline (the old
            splitlines() contract)."""
            self._emit_line(self._cur)
            self._cur = ""

    buf = _LineTee()
    try:
        with busy_marker(), redirect_stdout(buf):
            call()
        buf.close_tail()
    except SystemExit:
        buf.close_tail()
    except Exception as e:
        emit(OUT, {"section": "error", "argv": " ".join(argv),
                   "error": f"{type(e).__name__}: {e}"[:300]})
        return None
    finally:
        sys.argv = old_argv
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        purge_device_memory()
    if not buf.lines:
        emit(OUT, {"section": "error", "argv": " ".join(argv), "error": "no output"})
        return None
    return buf.lines


def run_config(argv, env=None):
    """One bench.py invocation in-process; returns the parsed result dict."""
    import bench

    lines = run_inprocess("bench.py", argv, bench.main, env=env)
    if lines is None:
        return None
    emit(OUT, lines[-1])
    try:
        return json.loads(lines[-1])
    except ValueError:
        return None


def _job_key(label, argv, env=None):
    key = label + " " + " ".join(argv)
    if env:
        key += " [env:" + " ".join(f"{k}={v}" for k, v in sorted(env.items())) + "]"
    return key


def mark_job_done(label, argv, env=None):
    """Completed-job marker consumed by completed_jobs() after a supervisor
    restart (a dead-mode hang inside a config can only be cleared by killing
    the process — perf/runner_supervisor.sh — and the fresh runner must not
    redo the configs that already landed)."""
    emit(OUT, {"section": "meta", "event": "job_done",
               "argv": _job_key(label, argv, env)})


def completed_jobs() -> set:
    """job_done markers since the last COMPLETED matrix: a matrix_done event
    clears the set, so re-running the supervisor after a finished round redoes
    every config (fresh numbers) while a mid-matrix restart resumes."""
    done = set()
    try:
        with open(OUT) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if rec.get("event") == "matrix_done":
                    done.clear()
                elif rec.get("event") == "job_done":
                    done.add(rec.get("argv"))
    except OSError:
        pass
    return done


def publish_latest(result, argv):
    """Atomic handoff write: bench.py falls back to this file when its own
    backend probe fails at driver-capture time."""
    # never re-publish a result that itself came from the handoff file (bench.py's
    # fallback fires even in-process when the runner's backend dies) — that would
    # recycle a stale number under an ever-fresh timestamp
    if config_failed(result):  # single definition of "suspect result"
        return
    payload = {"result": result, "captured_unix": time.time(),
               "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
               "argv": "bench.py " + " ".join(argv)}
    for path in (BENCH_LATEST, HANDOFF_TRACKED):
        if not path:
            continue  # tests run with DLT_HANDOFF_PATH: no tracked mirror
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    commit_tracked_handoff()
    emit(OUT, {"section": "meta", "event": "published_latest",
               "value": result.get("value")})


def commit_tracked_handoff():
    """Commit ONLY the tracked mirror (pathspec commit: staged-but-uncommitted
    builder work is untouched). The 2026-07-31 03:15 container restart proved
    gitignored files don't survive restarts — an uncommitted handoff is one
    restart away from being the round-4 `value: 0.0` failure again. Best-effort:
    a concurrent builder commit holding index.lock just means the next publish
    retries."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not HANDOFF_TRACKED or not HANDOFF_TRACKED.startswith(repo + os.sep):
        return  # test scratch paths live outside the repo: nothing to commit
    try:
        ok, detail = _git_commit_path(repo, HANDOFF_TRACKED)
        if not ok:
            # a dead defense must be visible in the results stream, not
            # discovered after the next restart has destroyed the evidence
            emit(OUT, {"section": "meta", "event": "handoff_commit_failed",
                       "detail": detail[:200]})
    except Exception as e:
        try:  # never let git plumbing take down the runner
            emit(OUT, {"section": "meta", "event": "handoff_commit_failed",
                       "detail": f"{type(e).__name__}: {e}"[:200]})
        except Exception:
            pass


def _git_commit_path(repo, path):
    """Commit ONE path's working-tree state; returns (ok, detail). The file
    starts life UNTRACKED, and a pathspec commit rejects untracked files — it
    must be `git add`ed first. Unchanged-since-last-commit counts as ok."""
    import subprocess

    diff = subprocess.run(
        ["git", "-C", repo, "status", "--porcelain", "--", path],
        capture_output=True, text=True, timeout=30)
    if not diff.stdout.strip():
        return True, "unchanged"
    add = subprocess.run(["git", "-C", repo, "add", "--", path],
                         capture_output=True, text=True, timeout=30)
    commit_cmd = ["git", "-C", repo, "commit", "-m",
                  "Publish warm-runner bench handoff", "--", path]
    com = subprocess.run(commit_cmd, capture_output=True, text=True, timeout=30)
    if com.returncode and "Author identity unknown" in com.stderr:
        # no user.name/email in this environment: fall back to an explicit
        # identity rather than losing the handoff commit
        com = subprocess.run(
            ["git", "-c", "user.name=dlt-runner",
             "-c", "user.email=runner@localhost"] + commit_cmd[1:],
            capture_output=True, text=True, timeout=30)
    if add.returncode or com.returncode:
        return False, f"rc={add.returncode}/{com.returncode}: " + (
            add.stderr + com.stderr).strip()
    return True, "committed"


def main():
    open(OUT, "a").close()
    emit(OUT, {"section": "meta", "event": "runner_start",
               "time": time.strftime("%H:%M:%S")})
    if not wait_for_backend():
        emit(OUT, {"section": "error", "error": "backend never came up"})
        sys.exit(1)
    # the tunnel is warm in THIS process: headline FIRST (publish the handoff
    # file as early as possible), then the rest of the matrix. EVERY config —
    # including the first — yields to a driver bench already in flight.
    done_before = completed_jobs()
    if done_before:
        emit(OUT, {"section": "meta", "event": "resuming",
                   "already_done": len(done_before)})
    pause_for_foreign("paused_for_foreign_bench")
    res = run_config(HEADLINE)
    publish_latest(res, HEADLINE)
    suspect = config_failed(res)
    # one job list, one copy of the serialize/reprobe discipline: the bench
    # matrix, the forced-failure drill, then the extras — the prologue-crash
    # bisect (which kernel kills the Mosaic remote compile?) and the microbench
    # sections the bench.py-only matrix never captured (raw-read stream probes
    # etc. — PROFILE "pending hardware items").
    jobs = [("bench.py", c, None, False) for c in CONFIGS[1:]]
    jobs.append(("bench.py", DRILL, {"DLT_FORCE_I4P_FAILURE": "1"}, True))
    jobs.append(("probe_prologue.py", [], None, False))
    jobs.extend(("microbench.py", ["--section", sec, "--quick"], None, False)
                for sec in ("dispatch", "stream", "matvec", "prefill_mm",
                            "prologue", "attention"))
    for label, argv, env, is_drill in jobs:
        if _job_key(label, argv, env) in done_before:
            continue
        if suspect:
            # the failed job may have wedged the in-process backend (OOM,
            # tunnel drop). Memory is already purged; verify the backend
            # answers a fenced op before burning the next job's attempt.
            emit(OUT, {"section": "meta", "event": "reprobe_after_failure"})
            if not wait_for_backend():
                emit(OUT, {"section": "error",
                           "error": "backend lost mid-matrix; giving up"})
                sys.exit(1)
        pause_for_foreign("paused_for_foreign_bench")
        if label == "bench.py":
            res = run_config(argv, env=env)
            suspect = config_failed(res)
            # the forced-failure DRILL is done once it RAN — its whole point
            # is recording the degrade, so even an error record completes it
            # (otherwise every supervisor restart would re-run and re-flag it)
            if not suspect or is_drill:
                mark_job_done(label, argv, env)
        else:
            import importlib

            try:
                mod = importlib.import_module(label[:-3])
            except Exception as e:
                # an import failure is a code problem, not a wedged backend:
                # record it and move on without a reprobe
                emit(OUT, {"section": "error", "argv": label,
                           "error": f"import: {type(e).__name__}: {e}"[:300]})
                continue
            suspect = run_inprocess(label, argv, mod.main,
                                    emit_all=True) is None
            if not suspect:
                mark_job_done(label, argv, env)
    emit(OUT, {"section": "meta", "event": "matrix_done",
               "time": time.strftime("%H:%M:%S")})
    # keep-fresh: periodically re-run the headline so the handoff file stays
    # recent; yield whenever the driver's own bench announces itself
    t_end = time.time() + KEEP_FRESH_HOURS * 3600
    while time.time() < t_end:
        time.sleep(REFRESH_MIN * 60)
        if foreign_bench_active():
            emit(OUT, {"section": "meta", "event": "skip_refresh_foreign_bench"})
            continue
        if suspect:
            emit(OUT, {"section": "meta", "event": "reprobe_after_failure"})
            # short per-tick budget: the startup MAX_WAIT_MIN (hours) would
            # block past t_end and make this retry loop unreachable
            if not wait_for_backend(max_wait_min=REFRESH_MIN):
                continue  # keep trying on the next refresh tick
        res = run_config(HEADLINE)
        publish_latest(res, HEADLINE)
        suspect = config_failed(res)
    emit(OUT, {"section": "meta", "event": "runner_done",
               "time": time.strftime("%H:%M:%S")})


if __name__ == "__main__":
    main()
