#!/usr/bin/env python
"""Smoke: verify-block dispatch cost must stay near-flat in block length T
(ISSUE 8 CI gate) — the roofline argument batched speculative decoding rests
on.

A (B, T) verify dispatch streams the quantized weights ONCE for all T
positions, so on a bandwidth-bound chip (and on this CPU mesh, where the
tiny model's per-dispatch overhead dominates the extra matmul columns) the
cost of T = 1+k must sit well under T times the cost of T = 2. If this ratio
regresses, the verify program stopped amortizing the weight stream — e.g. a
lowering change serialized the block positions — and the default --speculative
K stops paying for itself exactly when accept rates are high.

Measures the REAL program the BatchEngine compiles
(runtime/device_loop.py make_batched_verify_loop) at every block bucket the
scheduler uses (2, 3, 5, 9 for k=8), median of repeated timed dispatches
with the token block fetched to host (the scheduler's sync point).

Run: JAX_PLATFORMS=cpu python perf/spec_amortize.py
Prints one JSON line (bench.py convention); exit 0 pass, 1 fail.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from distributed_llama_tpu.models.params import init_random_params  # noqa: E402
from distributed_llama_tpu.models.spec import (ArchType, ModelSpec,  # noqa: E402
                                               RopeType)
from distributed_llama_tpu.quants import FloatType  # noqa: E402

B = 4  # batch rows
K = 8  # draft cap: blocks 2, 3, 5, 9 (the scheduler's _verify_block_for)
BLOCKS = (2, 3, 5, 9)
REPS = 30
GATE = 2.5  # median cost(T=1+K) must stay under GATE x median cost(T=2)


def _spec(seq_len=128):
    return ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=seq_len, rope_type=RopeType.LLAMA).resolved()


def measure(spec=None, params=None) -> dict[int, float]:
    """Median seconds per verify dispatch at each block length."""
    from distributed_llama_tpu.runtime.device_loop import \
        make_batched_verify_loop
    from distributed_llama_tpu.runtime.engine import Engine

    spec = spec or _spec()
    if params is None:
        params = init_random_params(spec, FloatType.Q40, seed=11)
    eng = Engine(spec, params, tp=1, batch=B)
    kc, vc = eng.k_cache, eng.v_cache
    rng = np.zeros((B, 2), np.uint32)
    temps = [0.0] * B
    topps = [0.9] * B
    out: dict[int, float] = {}
    pos0 = 32  # mid-cache: every block bucket fits under seq_len
    for t in BLOCKS:
        loop = make_batched_verify_loop(spec, eng.mesh, eng.params, t,
                                        mode="greedy", dtype=eng.dtype,
                                        donate_cache=True)
        props = [[(7 * (i + j)) % spec.vocab_size for j in range(t)]
                 for i in range(B)]
        ndraft = [t - 1] * B
        starts = [pos0] * B

        def dispatch():
            nonlocal kc, vc
            toks, acc, tok, pos, r, kc, vc = loop(
                eng.params, eng.rope, props, kc, vc, starts, rng, temps,
                topps, ndraft)
            np.asarray(toks)  # host sync: the scheduler's delivery point

        dispatch()  # compile
        times = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            dispatch()
            times.append(time.perf_counter() - t0)
        out[t] = statistics.median(times)
    return out


def main() -> int:
    costs = measure()
    ratio = costs[BLOCKS[-1]] / costs[BLOCKS[0]]
    ok = ratio <= GATE
    print(json.dumps({
        "metric": "spec_verify_amortization",
        "value": round(ratio, 3), "unit": "xT2_cost", "vs_baseline": None,
        "gate": GATE, "ok": ok,
        "cost_ms": {str(t): round(c * 1e3, 4) for t, c in costs.items()},
        "blocks": list(BLOCKS), "batch": B, "reps": REPS,
    }))
    if not ok:
        print(f"❌ verify block T={BLOCKS[-1]} costs {ratio:.2f}x T=2 "
              f"(gate {GATE}x): the verify program stopped amortizing the "
              f"weight stream", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
