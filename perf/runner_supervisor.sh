#!/bin/bash
# Supervises perf/persistent_bench.py against the axon tunnel's dead mode.
#
# A hang can strike INSIDE a config (a blocked XLA call during compile/synth —
# observed 01:32 UTC on a fresh i8 bench): no in-process watchdog can interrupt
# it, so the only recovery is killing the process. This loop restarts the
# runner whenever (a) it exits nonzero, or (b) the results file stops growing
# for STALL_MIN minutes mid-job (wait_for_backend heartbeats every 10 min, so
# a healthy wait never trips this). The restarted runner skips configs that
# already landed (job_done markers — persistent_bench.completed_jobs).
#
#   bash perf/runner_supervisor.sh [outfile] [stall_minutes]
set -u
OUT="${1:-perf/r5_hw_results.jsonl}"
STALL_MIN="${2:-45}"
cd "$(dirname "$0")/.."
pid=""
trap '[ -n "$pid" ] && kill "$pid" 2>/dev/null' EXIT  # no orphaned runners
while true; do
    python perf/persistent_bench.py "$OUT" 600 &
    pid=$!
    while kill -0 "$pid" 2>/dev/null; do
        sleep 60
        # a missing file (runner still importing) counts as fresh, not stalled
        mtime=$(stat -c %Y "$OUT" 2>/dev/null || date +%s)
        age=$(( $(date +%s) - mtime ))
        if [ "$age" -gt $((STALL_MIN * 60)) ]; then
            echo "{\"section\": \"meta\", \"event\": \"supervisor_restart\", \"stalled_s\": $age}" >> "$OUT"
            kill -9 "$pid" 2>/dev/null
            sleep 5
            break
        fi
    done
    wait "$pid" 2>/dev/null
    rc=$?
    if [ "$rc" -eq 0 ]; then
        break  # runner_done: clean exit after the keep-fresh window
    fi
    sleep 30
done
