#!/usr/bin/env python
"""Prefix-cache seeding speed gate (ISSUE 3 satellite).

Asserts that admitting a request whose 512-token prefix is already in the
block pool (one KV copy-in + a tail prefill) is at least 5x faster than
recomputing that prefill from scratch on the CPU mesh. Both measurements run
on the SAME BatchEngine with every compiled shape warmed, against prompts of
identical length — the only variable is whether the 512-token prefix hits the
radix index.

Run: python perf/prefix_seed_bench.py     (exit 0 pass / 1 fail, one JSON line)

Standalone perf gate, not tier-1: wall-clock ratios on a shared CI host are
too noisy for the main suite (same policy as perf/obs_overhead.py), but the
5x bar has ~an order of magnitude of slack — a real regression (seeding
re-running prefill, a copy-in gone quadratic) blows straight through it.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_llama_tpu.models.params import init_random_params  # noqa: E402
from distributed_llama_tpu.models.spec import (  # noqa: E402
    ArchType, ModelSpec, RopeType)
from distributed_llama_tpu.quants import FloatType  # noqa: E402
from distributed_llama_tpu.runtime.batch_engine import BatchEngine  # noqa: E402
from distributed_llama_tpu.runtime.sampler import Sampler  # noqa: E402

PREFIX = 512
MIN_SPEEDUP = 5.0


def main() -> int:
    spec = ModelSpec(arch_type=ArchType.LLAMA, dim=64, hidden_dim=128,
                     n_layers=2, n_heads=4, n_kv_heads=4, vocab_size=256,
                     seq_len=1024, rope_type=RopeType.LLAMA).resolved()
    params = init_random_params(spec, FloatType.Q40, seed=3)
    be = BatchEngine(spec, params, slots=2, tp=1, prefix_cache=True,
                     prefix_block_tokens=16)

    def prefix(seed: int) -> list[int]:
        import random

        r = random.Random(seed)
        return [1] + [r.randrange(2, spec.vocab_size) for _ in range(PREFIX - 1)]

    def run(prompt) -> float:
        t0 = time.perf_counter()
        be.generate(list(prompt), 1, Sampler(spec.vocab_size, temperature=0.0))
        return time.perf_counter() - t0

    try:
        # Warm every compiled shape and the RADIX seed path itself. The
        # unrelated runs in between dirty the slot that holds the prefix:
        # without them the repeat lands on its own slot and the same-slot
        # rewind (copy-free fast path) would serve it — the gate must time
        # the pool copy-in, not the rewind.
        run(prefix(0) + [9])                      # prefill shapes + insert
        run([1] + list(range(5, 25)))             # dirty the slot
        run(prefix(0) + [11])                     # radix-seed path warm
        # cold: a never-seen 512-token prefix pays full prefill
        t_cold = run(prefix(1) + [9])
        run([1] + list(range(30, 50)))            # dirty the slot again
        # seeded: cached prefix, different tail, slot history unrelated ->
        # the 512 rows are copied in from the pool and only the tail prefills
        base = be.prefilled_tokens
        hits0 = be.prefix_cache.hits
        t_seed = run(prefix(1) + [11])
        seeded_prefill = be.prefilled_tokens - base
        radix_applied = be.prefix_cache.hits - hits0
        st = be.prefix_cache.stats()
    finally:
        be.close()

    speedup = t_cold / max(t_seed, 1e-9)
    # radix_applied proves the timed run took the pool copy-in, not the
    # same-slot rewind (which would trivially pass the ratio)
    ok = speedup >= MIN_SPEEDUP and seeded_prefill <= 8 and radix_applied == 1
    print(json.dumps({
        "metric": "prefix_seed_admission_speedup",
        "value": round(speedup, 2), "unit": "x",
        "threshold": MIN_SPEEDUP, "pass": ok,
        "prefix_tokens": PREFIX,
        "cold_prefill_s": round(t_cold, 4),
        "seeded_admission_s": round(t_seed, 4),
        "seeded_prefill_tokens": seeded_prefill,
        "radix_seed_applied": radix_applied,
        "hit_tokens": st["hit_tokens"],
    }))
    if not ok:
        print(f"FAIL: cache-seeded admission only {speedup:.2f}x faster than "
              f"recomputing the {PREFIX}-token prefill (need >= {MIN_SPEEDUP}x; "
              f"seeded path prefilled {seeded_prefill} tokens)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
