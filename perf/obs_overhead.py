#!/usr/bin/env python
"""Microbench: the DISABLED observability hot path must cost <1% of a decode
dispatch (ISSUE 2 acceptance gate for always-on instrumentation; ISSUE 7
extends the bundle with the request-tracing hooks).

The per-dispatch instrumentation on runtime/engine.py / batch_engine.py is
exactly:

    1 disabled trace.span() (global check + shared no-op context manager)
    1 inline args dict build
    2 time.perf_counter() calls
    1 Histogram.observe() (bisect + lock + 3 adds)
    1 Counter.inc()
    1 disabled flight.event() (global check; kwargs dict built at call site)
    1 reqctx.use() enter/exit (contextvar set + reset — the scheduler's
      per-request trace re-entry)
    1 constrain-disabled scan (ISSUE 17: every masked-capable dispatch asks
      "is any co-batched row constrained?" — B attribute loads returning
      None — before picking the unmasked program)

This script times that exact bundle standalone, times a real T=1 decode
dispatch of the tiny CI model shape on the current backend, and asserts
bundle < 1% of dispatch. Prints one JSON line (bench.py convention).

Run: JAX_PLATFORMS=cpu python perf/obs_overhead.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

from distributed_llama_tpu.models.params import init_random_params
from distributed_llama_tpu.models.spec import ArchType, ModelSpec
from distributed_llama_tpu.obs import flight, metrics, reqctx, trace
from distributed_llama_tpu.parallel.mesh import make_mesh
from distributed_llama_tpu.parallel.tp import (init_sharded_kv_cache,
                                               make_sharded_forward,
                                               shard_params)
from distributed_llama_tpu.ops.rope import RopeTables
from distributed_llama_tpu.quants import FloatType

SMALL = dict(arch_type=ArchType.LLAMA, dim=512, hidden_dim=1408, n_layers=4,
             n_heads=8, n_kv_heads=8, vocab_size=32000, seq_len=256)


def bench_instrumentation_bundle(n: int = 200_000) -> float:
    """Seconds per disabled-path bundle (span + dict + 2 clocks + observe +
    inc + disabled flight event + trace-context re-entry) — the marginal
    cost one decode dispatch now pays."""
    trace.uninstall()
    flight.uninstall()
    hist = metrics.histogram("obs_overhead_bench_seconds", "bench-only")
    ctr = metrics.counter("obs_overhead_bench_total", "bench-only")
    ctx = reqctx.new_context("req-bench")

    class _Slot:  # the constrain-disabled scan: B rows, constraint None
        __slots__ = ("constraint",)

        def __init__(self):
            self.constraint = None

    slots = [_Slot() for _ in range(8)]
    t_start = time.perf_counter()
    for i in range(n):
        with reqctx.use(ctx):
            with trace.span("engine.dispatch", {"t": 1, "pos": i}):
                pass
            t0 = time.perf_counter()
            dt = time.perf_counter() - t0
            hist.observe(dt)
            ctr.inc()
            flight.event("req-bench", "super_step", k=8, delivered=8)
            masked = False
            for s in slots:  # batch_engine._constrained(rows)
                sc = s.constraint
                if sc is not None and not sc.degraded:
                    masked = True
                    break
            assert not masked
    return (time.perf_counter() - t_start) / n


def bench_decode_dispatch(steps: int = 32) -> float:
    """Seconds per T=1 decode dispatch of the tiny CI shape (compiled once,
    host-fenced like the engine's hot loop)."""
    spec = ModelSpec(**SMALL).resolved()
    mesh = make_mesh(tp=1)
    params = shard_params(init_random_params(spec, FloatType.F32, seed=7),
                          mesh, spec)
    rope = RopeTables.create(spec)
    kc, vc = init_sharded_kv_cache(spec, mesh, batch=1, dtype=jnp.float32)
    step = make_sharded_forward(spec, mesh, params, dtype=jnp.float32,
                                use_pallas=False, donate_cache=True)
    tok = jnp.asarray([[1]], jnp.int32)
    for i in range(3):  # compile + warm
        logits, kc, vc = step(params, rope, tok, kc, vc, jnp.int32(i))
    np.asarray(logits[0, 0, 0])
    t0 = time.perf_counter()
    for i in range(steps):
        logits, kc, vc = step(params, rope, tok, kc, vc, jnp.int32(3 + i))
        np.asarray(logits[0, 0, 0])  # per-dispatch fence, like Engine._infer
    return (time.perf_counter() - t0) / steps


def main() -> int:
    bundle_s = bench_instrumentation_bundle()
    dispatch_s = bench_decode_dispatch()
    ratio = bundle_s / dispatch_s
    ok = ratio < 0.01
    print(json.dumps({
        "metric": "obs_disabled_overhead_ratio",
        "value": round(ratio, 6), "unit": "fraction",
        "pass": ok, "threshold": 0.01,
        "bundle_us": round(bundle_s * 1e6, 3),
        "dispatch_ms": round(dispatch_s * 1e3, 3),
        "backend": jax.default_backend(),
    }))
    if not ok:
        print(f"FAIL: disabled-path bundle {bundle_s * 1e6:.2f} µs is "
              f"{ratio:.2%} of a {dispatch_s * 1e3:.2f} ms decode dispatch "
              "(budget 1%)", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
