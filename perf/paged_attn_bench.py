"""Paged-attention kernel microbench + parity oracle (ISSUE 12 satellite).

Measures achieved GB/s of ops/pallas_paged_attention.paged_attention at the
two production shapes — decode (T=1, the K-step scan's per-step read) and
speculative verify (T=1+k) — against the bytes the kernel must move per
call (the table's KV blocks + the chunk), and checks three parities:

- XLA-vs-dense BIT-EXACTNESS: paged_attention_xla (gather + gqa_attention)
  must equal the dense contiguous-window gqa_attention to the last bit when
  the gathered width equals the dense window — the structural property the
  paged engine's token-identity rests on (tests/test_paged_kv.py).
- kernel-vs-oracle numeric parity: the Pallas kernel's blockwise online
  softmax against the one-shot XLA softmax, gated at a tight f32 tolerance.
- greedy-pick agreement: argmax over a projected vocab row must match —
  the token-level consequence of the numeric gap staying far below logit
  spacing.

CPU runs use interpret mode (correctness numbers only; GB/s on interpret
mode measures the interpreter, and the JSON says so). On TPU, append the
result row to a perf/r*_hw_results.jsonl-style artifact with --json.

Usage: python perf/paged_attn_bench.py [--json out.json] [--iters N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _mk(rng, shape):
    import jax.numpy as jnp

    return jnp.asarray(rng.normal(size=shape).astype(np.float32))


def bench_shape(t: int, *, L=8, N=64, hk=8, g=4, bt=64, hs=128, B=4,
                iters=20, interpret=None, seed=0):
    """One (decode or verify) shape: returns the parity + GB/s row."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.attention import gqa_attention
    from distributed_llama_tpu.ops.pallas_paged_attention import (
        paged_attention, paged_attention_xla)

    rng = np.random.default_rng(seed)
    hq = hk * g
    kc = _mk(rng, (L, N, hk, bt, hs))
    vc = _mk(rng, (L, N, hk, bt, hs))
    q = _mk(rng, (B, t, hq, hs))
    kn = _mk(rng, (B, hk, t, hs))
    vn = _mk(rng, (B, hk, t, hs))
    nb = (N - 1) // B  # read blocks per row (disjoint tables, block 0 scratch)
    tables = np.zeros((B, nb), np.int32)
    ids = np.arange(1, B * nb + 1)
    rng.shuffle(ids)
    tables[:] = ids.reshape(B, nb)
    tables = jnp.asarray(tables)
    lengths = jnp.asarray(
        rng.integers(bt, nb * bt + 1, size=B).astype(np.int32))
    layer = min(3, L - 1)

    out_k = paged_attention(q, kc, vc, kn, vn, tables, lengths, layer,
                            n_read=nb, interpret=interpret)
    out_x = paged_attention_xla(q, kc, vc, kn, vn, tables, lengths, layer,
                                n_read=nb)
    kernel_max_abs = float(jnp.max(jnp.abs(out_k - out_x)))

    # XLA-vs-dense bit-exactness: materialize the virtual contiguous cache
    # and run the dense deferred-window computation (same masks/sentinels)
    kl = np.asarray(kc)[layer]
    vl = np.asarray(vc)[layer]
    tbl = np.asarray(tables)
    kwin = np.stack([kl[tbl[b]].transpose(1, 0, 2, 3).reshape(
        hk, nb * bt, hs) for b in range(B)])
    vwin = np.stack([vl[tbl[b]].transpose(1, 0, 2, 3).reshape(
        hk, nb * bt, hs) for b in range(B)])
    win = nb * bt
    slot = np.arange(win)
    ln = np.asarray(lengths)
    slot_pos = np.where(slot[None, :] < ln[:, None], slot[None, :], win + 1)
    key_pos = np.concatenate([slot_pos, ln[:, None] + np.arange(t)[None, :]],
                             axis=1)
    positions = ln[:, None] + np.arange(t, dtype=np.int32)[None, :]
    dense = gqa_attention(
        q, jnp.concatenate([jnp.asarray(kwin), kn], axis=2),
        jnp.concatenate([jnp.asarray(vwin), vn], axis=2),
        jnp.asarray(positions), key_positions=jnp.asarray(key_pos))
    xla_vs_dense_bits = bool(jnp.array_equal(
        out_x.reshape(B, t, hq * hs).astype(dense.dtype), dense))

    # greedy-pick agreement through a projection head
    wproj = _mk(rng, (hs * hq, 512))
    pick_k = jnp.argmax(out_k.reshape(B, t, hq * hs) @ wproj, axis=-1)
    pick_x = jnp.argmax(out_x.reshape(B, t, hq * hs) @ wproj, axis=-1)
    greedy_agree = bool(jnp.array_equal(pick_k, pick_x))

    # timing: bytes = the KV blocks the table forces through HBM + chunk
    fn = jax.jit(lambda *a: paged_attention(*a, n_read=nb,
                                            interpret=interpret))
    args = (q, kc, vc, kn, vn, tables, lengths, layer)
    fn(*args)[0].block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    itemsize = np.dtype(np.float32).itemsize
    bytes_moved = 2 * B * nb * hk * bt * hs * itemsize \
        + 2 * B * hk * t * hs * itemsize
    import jax as _jax

    return {
        "shape": "decode_t1" if t == 1 else f"verify_t{t}",
        "B": B, "T": t, "layers_pool": L, "pool_blocks": N, "hk": hk,
        "g": g, "block_tokens": bt, "head_size": hs, "read_blocks": nb,
        "kernel_max_abs_err": kernel_max_abs,
        "xla_vs_dense_bit_exact": xla_vs_dense_bits,
        "greedy_pick_agree": greedy_agree,
        "ms_per_call": round(dt * 1e3, 4),
        "achieved_gbps": round(bytes_moved / dt / 1e9, 2),
        "bytes_per_call": bytes_moved,
        "backend": _jax.default_backend(),
        "interpret": bool(interpret if interpret is not None
                          else _jax.default_backend() != "tpu"),
    }


def run(iters: int = 20, small: bool = False, interpret=None):
    kw = dict(iters=iters)
    if small:  # tier-1 smoke geometry: seconds, not minutes, on CPU
        kw.update(L=2, N=12, hk=2, g=2, bt=8, hs=16, B=2, iters=3)
    rows = [bench_shape(1, **kw), bench_shape(5, **kw)]
    for r in rows:
        assert r["xla_vs_dense_bit_exact"], (
            "paged gather path diverged bitwise from the dense window path")
        assert r["kernel_max_abs_err"] < 2e-5, r["kernel_max_abs_err"]
        assert r["greedy_pick_agree"], "kernel numeric gap flipped an argmax"
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", metavar="OUT", default=None)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--small", action="store_true",
                    help="tiny smoke geometry (the tier-1 gate's shapes)")
    args = ap.parse_args(argv)
    rows = run(iters=args.iters, small=args.small)
    out = {"bench": "paged_attention", "results": rows}
    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(out, fh, indent=2)
            fh.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
