"""Block quantization formats (Q40 / Q80), TPU-native layout.

Byte-compatible with the reference `.m` tensor encoding (reference: src/quants.hpp:17-25,
src/quants.cpp:137-288, converter/writer.py:29-74) but stored on device as *planar* arrays
instead of 18/34-byte interleaved structs:

    Q40 tensor of shape (rows, n):  packed uint8 (rows, n//32, 16)  + scales f16 (rows, n//32)
    Q80 tensor of shape (rows, n):  values int8  (rows, n//32, 32)  + scales f16 (rows, n//32)

Planar layout is what TPU wants: the packed nibbles land in HBM as a dense uint8 array that
Pallas kernels / XLA can tile onto (32, 128)-shaped int8 registers, while the f16 scales form
a small separate array that broadcasts over each 32-element block. The interleaved struct
layout of the reference exists only at file I/O boundaries (`*_to_bytes` / `*_from_bytes`).

Nibble semantics match the reference exactly (src/quants.cpp:178-182): byte j of a block
holds element j in its low nibble and element j+16 in its high nibble; value = (nibble-8)*d.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

QK = 32  # block size for both Q40 and Q80 (reference: src/quants.hpp:14-15)
Q40_BLOCK_BYTES = 18  # f16 delta + 16 nibble-pair bytes
Q80_BLOCK_BYTES = 34  # f16 delta + 32 int8

_Q40_STRUCT = np.dtype([("d", "<f2"), ("qs", "u1", (QK // 2,))])
_Q80_STRUCT = np.dtype([("d", "<f2"), ("qs", "i1", (QK,))])


class FloatType(enum.IntEnum):
    """Wire/storage float types (reference: src/quants.hpp:6-12)."""

    F32 = 0
    F16 = 1
    Q40 = 2
    Q80 = 3


def batch_bytes(ftype: FloatType, n: int, d: int = 1) -> int:
    """Bytes for a (d, n) tensor in the given storage type (reference: src/quants.cpp:28-51)."""
    count = n * d
    if ftype == FloatType.F32:
        return count * 4
    if ftype == FloatType.F16:
        return count * 2
    if ftype == FloatType.Q40:
        assert n % QK == 0, (n, d)
        return (count // QK) * Q40_BLOCK_BYTES
    if ftype == FloatType.Q80:
        assert n % QK == 0, (n, d)
        return (count // QK) * Q80_BLOCK_BYTES
    raise ValueError(f"unknown float type {ftype}")


# ---------------------------------------------------------------------------
# Q40: 4-bit blocks, asymmetric-ish (min/max) scaling with +8.5 offset
# ---------------------------------------------------------------------------


def quantize_q40(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Quantize float array (..., n) to Q40 planar (packed, scales).

    Matches converter/writer.py:29-53: delta = extremum/-8 in f16, q = clip(x/delta+8.5, 0, 15).

    Returns (packed uint8 (..., n//32, 16), scales float16 (..., n//32)).
    """
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[-1]
    assert n % QK == 0, n
    g = x.reshape(*x.shape[:-1], n // QK, QK)
    gmax = g.max(axis=-1)
    gmin = g.min(axis=-1)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    deltas16 = deltas.astype(np.float16)
    inv = np.divide(1.0, deltas, out=np.zeros_like(deltas), where=deltas != 0).astype(np.float32)
    q = np.clip(g * inv[..., None] + 8.5, 0, 15).astype(np.uint8)
    packed = q[..., : QK // 2] | (q[..., QK // 2 :] << 4)
    return packed, deltas16


def dequantize_q40(packed: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Planar Q40 -> float32 (..., n). Matches src/quants.cpp:170-183."""
    lo = (packed & 0x0F).astype(np.int8) - 8
    hi = (packed >> 4).astype(np.int8) - 8
    vals = np.concatenate([lo, hi], axis=-1).astype(np.float32)
    out = vals * scales[..., None].astype(np.float32)
    return out.reshape(*packed.shape[:-2], packed.shape[-2] * QK)


def q40_to_bytes(packed: np.ndarray, scales: np.ndarray) -> bytes:
    """Planar Q40 -> reference interleaved block stream (BlockQ40[])."""
    nb = int(np.prod(packed.shape[:-1]))
    out = np.empty(nb, dtype=_Q40_STRUCT)
    out["d"] = scales.reshape(nb)
    out["qs"] = packed.reshape(nb, QK // 2)
    return out.tobytes()


def q40_from_bytes(buf: bytes, shape: tuple[int, ...]) -> tuple[np.ndarray, np.ndarray]:
    """Reference BlockQ40[] stream -> planar (packed, scales) for logical shape (..., n)."""
    n = shape[-1]
    assert n % QK == 0, shape
    nb_shape = (*shape[:-1], n // QK)
    nb = int(np.prod(nb_shape))
    from . import native

    nat = native.q40_deinterleave(buf, nb)
    if nat is not None:
        qs, d = nat
        return qs.reshape(*nb_shape, QK // 2), d.reshape(nb_shape)
    arr = np.frombuffer(buf, dtype=_Q40_STRUCT, count=nb)
    return arr["qs"].reshape(*nb_shape, QK // 2).copy(), arr["d"].reshape(nb_shape).copy()


# ---------------------------------------------------------------------------
# Q80: int8 blocks, symmetric absmax/127 scaling
# ---------------------------------------------------------------------------


def quantize_q80(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Quantize (..., n) to Q80 planar (values int8 (..., n//32, 32), scales f16 (..., n//32)).

    Matches converter/writer.py:55-74 / src/quants.cpp:186-268 (round-to-nearest-even).
    """
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[-1]
    assert n % QK == 0, n
    g = x.reshape(*x.shape[:-1], n // QK, QK)
    absmax = np.abs(g).max(axis=-1)
    deltas = absmax / 127.0
    deltas16 = deltas.astype(np.float16)
    inv = np.divide(1.0, deltas, out=np.zeros_like(deltas), where=deltas != 0).astype(np.float32)
    q = np.round(g * inv[..., None]).astype(np.int8)
    return q, deltas16


def dequantize_q80(values: np.ndarray, scales: np.ndarray) -> np.ndarray:
    out = values.astype(np.float32) * scales[..., None].astype(np.float32)
    return out.reshape(*values.shape[:-2], values.shape[-2] * QK)


def q80_to_bytes(values: np.ndarray, scales: np.ndarray) -> bytes:
    nb = int(np.prod(values.shape[:-1]))
    out = np.empty(nb, dtype=_Q80_STRUCT)
    out["d"] = scales.reshape(nb)
    out["qs"] = values.reshape(nb, QK)
    return out.tobytes()


def q80_from_bytes(buf: bytes, shape: tuple[int, ...]) -> tuple[np.ndarray, np.ndarray]:
    n = shape[-1]
    assert n % QK == 0, shape
    nb_shape = (*shape[:-1], n // QK)
    nb = int(np.prod(nb_shape))
    from . import native

    nat = native.q80_deinterleave(buf, nb)
    if nat is not None:
        qs, d = nat
        return qs.reshape(*nb_shape, QK), d.reshape(nb_shape)
    arr = np.frombuffer(buf, dtype=_Q80_STRUCT, count=nb)
    return arr["qs"].reshape(*nb_shape, QK).copy(), arr["d"].reshape(nb_shape).copy()


# ---------------------------------------------------------------------------
# On-device (jnp) dequantization — the XLA-path used outside Pallas kernels
# ---------------------------------------------------------------------------


def jnp_dequantize_q40(packed: jax.Array, scales: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    """Dequantize planar Q40 on device: (..., nb, 16) u8 + (..., nb) f16 -> (..., nb*32)."""
    lo = (packed & 0x0F).astype(jnp.int8) - 8
    hi = (packed >> 4).astype(jnp.int8) - 8
    vals = jnp.concatenate([lo, hi], axis=-1).astype(dtype)
    out = vals * scales[..., None].astype(dtype)
    return out.reshape(*packed.shape[:-2], packed.shape[-2] * QK)


def jnp_dequantize_i8(values: jax.Array, scales: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    """Dequantize the int8-plane layout: (..., K) i8 + (..., K//32) f32 -> (..., K).

    Same math as Q80 planar dequant after regrouping the flat K axis into blocks.
    """
    k = values.shape[-1]
    nb = scales.shape[-1]
    assert nb * QK == k, (values.shape, scales.shape)
    return jnp_dequantize_q80(values.reshape(*values.shape[:-1], nb, QK), scales, dtype)


def jnp_dequantize_q80(values: jax.Array, scales: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    out = values.astype(dtype) * scales[..., None].astype(dtype)
    return out.reshape(*values.shape[:-2], values.shape[-2] * QK)


def jnp_quantize_q80(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """On-device Q80 quantization (..., n) -> (int8 (..., nb, 32), f16 scales).

    TPU-native descendant of the reference's wire compression (src/tasks.cpp:96-135):
    used for int8-compressed collectives instead of socket payloads.
    """
    n = x.shape[-1]
    g = x.reshape(*x.shape[:-1], n // QK, QK).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(g), axis=-1)
    deltas = (absmax / 127.0).astype(jnp.float16)
    inv = jnp.where(absmax > 0, 127.0 / absmax, 0.0)
    q = jnp.round(g * inv[..., None]).astype(jnp.int8)
    return q, deltas


# ---------------------------------------------------------------------------
# QTensor: a quantized-or-not weight tensor as a pytree
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
@dataclass
class QTensor:
    """A weight tensor, stored dense or block-quantized.

    For Q40/Q80 the block axis is the LAST logical axis (the contraction axis `n` of the
    reference's (d, n) row-major weights; reference blocks run along n — src/commands.cpp:22-39).
    Registered as a pytree so QTensors flow through jit/scan/shard_map and can carry per-leaf
    shardings. `shape` is derived from `data`, so it stays correct when transforms (scan
    unstacking, vmap, gathers) reshape the leaves.
    """

    ftype: FloatType
    data: jax.Array | np.ndarray  # dense values, Q40 packed u8, or Q80 int8
    # per-block scales for Q40/Q80: f16 (planar), f32 (i8), int16 f16-bit-patterns (i4p)
    scales: jax.Array | np.ndarray | None = None
    # "planar" | "i8" (int8 planes, to_i8_layout) | "i4p" (split-plane packed nibbles,
    # to_i4p_layout — true Q40 HBM density for the pallas_q4 decode kernel)
    layout: str = "planar"
    # i4p only: number of column groups the split-plane pack was applied within
    # (= the TP degree for in-axis-sharded tensors, so each shard's slice is a
    # self-contained pack). 1 elsewhere.
    groups: int = 1
    # fused matvec groups only (models/params.py fuse_matvec_groups): the
    # TP-group count the member ROWS were interleaved with at fuse time. Carried
    # through layout conversion so shard time can verify the placement matches
    # the interleave (a mismatch would silently scramble the member split). 1
    # for unfused tensors.
    row_groups: int = 1

    @property
    def shape(self) -> tuple[int, ...]:
        """Logical (dequantized) shape."""
        if self.ftype in (FloatType.F32, FloatType.F16):
            return tuple(self.data.shape)
        if self.layout == "i8":
            return tuple(self.data.shape)
        if self.layout == "i4p":
            return (*self.data.shape[:-1], self.data.shape[-1] * 2)
        if self.ftype in (FloatType.Q40, FloatType.Q80):
            return (*self.data.shape[:-2], self.data.shape[-2] * QK)
        raise ValueError(self.ftype)

    def tree_flatten(self):
        aux = (self.ftype, self.scales is not None, self.layout, self.groups,
               self.row_groups)
        if self.scales is None:
            return (self.data,), aux
        return (self.data, self.scales), aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        ftype, has_scales, layout, groups, row_groups = aux
        if has_scales:
            data, scales = children
        else:
            (data,) = children
            scales = None
        return cls(ftype=ftype, data=data, scales=scales, layout=layout,
                   groups=groups, row_groups=row_groups)

    def to_i8_layout(self) -> "QTensor":
        """Expand planar Q40/Q80 into int8 planes for the MXU matvec kernel (pallas_q8).

        data int8 (..., K) holding (nibble - 8) for Q40 / raw int8 for Q80, natural
        column order; scales f32 (..., K//32). Costs 2x (Q40) the packed HBM bytes but
        removes every per-weight VPU op from decode; both axes slice cleanly for TP
        (blocks stay 32-aligned), so no per-shard segmenting is needed.
        """
        assert self.layout == "planar", self.layout
        if self.ftype == FloatType.Q40:
            from . import native

            nat = native.q40_to_i8(np.asarray(self.data), np.asarray(self.scales))
            if nat is not None:
                return QTensor(self.ftype, nat[0], nat[1], layout="i8",
                               row_groups=self.row_groups)
            packed = np.asarray(self.data)
            lo = (packed & 0x0F).astype(np.int8) - 8  # elements 0..15 of each block
            hi = (packed >> 4).astype(np.int8) - 8  # elements 16..31
            vals = np.concatenate([lo, hi], axis=-1)  # (..., nb, 32)
        elif self.ftype == FloatType.Q80:
            vals = np.asarray(self.data, dtype=np.int8)
        else:
            raise ValueError(self.ftype)
        k = vals.shape[-2] * QK
        data = vals.reshape(*vals.shape[:-2], k)
        scales32 = np.asarray(self.scales, dtype=np.float32)
        return QTensor(self.ftype, data, scales32, layout="i8",
                       row_groups=self.row_groups)

    def to_i4p_layout(self, col_groups: int = 1) -> "QTensor":
        """Repack planar Q40 into split-plane nibbles for the 4-bit MXU matvec kernel
        (ops/pallas_q4.py): data uint8 (..., K/2) with byte j = q[j] | (q[j+K/2] << 4)
        where q = nibble+8; scales stored as int16 BIT PATTERNS of the file's f16
        deltas (bit-exact, same 2 B/block) because Mosaic on this toolchain cannot
        lower f16 refs — the kernel decodes f16-bits -> f32 with exact integer math
        (pallas_q4._f16_bits_to_f32) and dequantize()/to_numpy() bitcast back.

        Both unpacked planes land in natural element order, so the kernel needs no
        cross-lane shuffles. Same HBM bytes as the reference's BlockQ40 stream
        (src/quants.hpp:17-20).

        col_groups: split-plane pack WITHIN each of `col_groups` equal column groups —
        required for in-axis (ColMatmulSlice) TP sharding, where each shard must receive
        a self-contained split-plane pack of its own K/col_groups columns. Row-sharded
        tensors use col_groups=1. Each group's K_local must satisfy K_local % 64 == 0
        so the plane boundary stays on a quant-block boundary.
        """
        assert self.layout == "planar" and self.ftype == FloatType.Q40, (
            self.layout, self.ftype)
        packed = np.asarray(self.data)  # (..., nb, 16)
        from . import native

        scales16 = np.ascontiguousarray(
            np.asarray(self.scales, dtype=np.float16)).view(np.int16)
        nat = native.q40_to_i4p(packed, col_groups)
        if nat is not None:
            return QTensor(self.ftype, nat, scales16, layout="i4p",
                           groups=col_groups, row_groups=self.row_groups)
        lo = (packed & 0x0F).astype(np.uint8)  # block elements 0..15
        hi = (packed >> 4).astype(np.uint8)  # block elements 16..31
        q = np.concatenate([lo, hi], axis=-1)  # (..., nb, 32) natural order, in [0,16)
        k = q.shape[-2] * QK
        lead = q.shape[:-2]
        kl = k // col_groups
        assert k % col_groups == 0 and kl % 64 == 0, (k, col_groups)
        q = q.reshape(*lead, col_groups, kl)
        data = q[..., : kl // 2] | (q[..., kl // 2 :] << 4)
        data = data.reshape(*lead, k // 2)
        return QTensor(self.ftype, data, scales16, layout="i4p",
                       groups=col_groups, row_groups=self.row_groups)

    def _i4p_unpack(self, xp):
        """Split-plane nibbles -> natural-order values (..., K) minus the 8 offset."""
        wp = self.data
        kh = wp.shape[-1]
        g = self.groups
        wp = wp.reshape(*wp.shape[:-1], g, kh // g)
        lo = xp.asarray((wp & 0x0F), dtype=xp.int8) - 8
        hi = xp.asarray((wp >> 4), dtype=xp.int8) - 8
        out = xp.concatenate([lo, hi], axis=-1)  # (..., g, K/g) natural within group
        return out.reshape(*out.shape[:-2], kh * 2)

    @classmethod
    def from_float(cls, x: np.ndarray, ftype: FloatType) -> "QTensor":
        x = np.asarray(x)
        if ftype == FloatType.F32:
            return cls(ftype, x.astype(np.float32))
        if ftype == FloatType.F16:
            return cls(ftype, x.astype(np.float16))
        if ftype == FloatType.Q40:
            packed, scales = quantize_q40(x)
            return cls(ftype, packed, scales)
        if ftype == FloatType.Q80:
            vals, scales = quantize_q80(x)
            return cls(ftype, vals, scales)
        raise ValueError(ftype)

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        """Materialize logical values on device (jnp path; Pallas kernels bypass this)."""
        if self.ftype in (FloatType.F32, FloatType.F16):
            return jnp.asarray(self.data).astype(dtype)
        if self.layout == "i8":
            return jnp_dequantize_i8(jnp.asarray(self.data), jnp.asarray(self.scales),
                                     dtype)
        if self.layout == "i4p":
            vals = self._i4p_unpack(jnp)
            nb = self.scales.shape[-1]
            g = vals.reshape(*vals.shape[:-1], nb, QK)
            scales = jax.lax.bitcast_convert_type(jnp.asarray(self.scales), jnp.float16)
            return jnp_dequantize_q80(g, scales, dtype)
        if self.ftype == FloatType.Q40:
            return jnp_dequantize_q40(jnp.asarray(self.data), jnp.asarray(self.scales), dtype)
        if self.ftype == FloatType.Q80:
            return jnp_dequantize_q80(jnp.asarray(self.data), jnp.asarray(self.scales), dtype)
        raise ValueError(self.ftype)

    def to_numpy(self) -> np.ndarray:
        if self.ftype in (FloatType.F32, FloatType.F16):
            return np.asarray(self.data, dtype=np.float32)
        if self.layout == "i8":
            nb = self.scales.shape[-1]
            g = np.asarray(self.data).reshape(*self.data.shape[:-1], nb, QK)
            return dequantize_q80(g, np.asarray(self.scales))
        if self.layout == "i4p":
            vals = self._i4p_unpack(np)
            nb = self.scales.shape[-1]
            g = vals.reshape(*vals.shape[:-1], nb, QK)
            return dequantize_q80(g, np.asarray(self.scales).view(np.float16))
        if self.ftype == FloatType.Q40:
            return dequantize_q40(np.asarray(self.data), np.asarray(self.scales))
        if self.ftype == FloatType.Q80:
            return dequantize_q80(np.asarray(self.data), np.asarray(self.scales))
        raise ValueError(self.ftype)

    def nbytes(self) -> int:
        n = self.data.nbytes
        if self.scales is not None:
            n += self.scales.nbytes
        return n
